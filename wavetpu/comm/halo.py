"""Halo exchange over the ICI mesh: cyclic `ppermute` on all three axes.

The TPU-native replacement for the reference's entire L3 layer - the
pack / MPI_Sendrecv / unpack machinery (mpi_sol.cpp:196-285,
mpi_new.cpp:181-269) and the CUDA D2H -> MPI -> H2D staging path
(cuda_sol.cpp:230-312, cuda_sol_kernels.cu:91-177).  Ghost planes move
HBM-to-HBM over ICI; nothing is packed and nothing touches the host.

Why *cyclic* on every axis (not just periodic x): the fundamental-domain
state (see wavetpu.core.problem) makes the global neighbor relation a cyclic
shift on all three axes - x because the domain is periodic, y/z because the
wrap delivers the stored zero Dirichlet plane.  So one permutation pattern
serves all axes, the analog of the reference's periods={1,0,0} Cartesian
topology (mpi_sol.cpp:409-410) collapsing into uniform code.

Uneven-grid seam arithmetic: with zero-padding (core/grid.py), the last
shard along an axis owns r_last < block real planes.  Two index shifts keep
the exchange exact, the moral counterpart of the reference's seam-skip
invariant (sending plane X-1 / plane 2 from the x-edge ranks,
mpi_sol.cpp:201-202, SURVEY.md section 3.4):

 * the forward send ships the last *real* plane (r_last - 1, not block - 1);
 * the wrapped ghost received by the last shard lands at ext position
   r_last + 1, so the last real cell's +1 neighbor read hits it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from wavetpu.core.grid import AXIS_NAMES, Topology


def _fwd_perm(m: int):
    """shard i -> shard i+1 (cyclic): receiver gets its lower ghost."""
    return [(i, (i + 1) % m) for i in range(m)]


def _bwd_perm(m: int):
    """shard i -> shard i-1 (cyclic): receiver gets its upper ghost."""
    return [(i, (i - 1) % m) for i in range(m)]


def _place(ext, ghost, axis: int, pos):
    """Write a ghost plane into `ext` at index `pos` along `axis` (offset 1
    on the other axes; the unused ext corners stay zero)."""
    starts = [pos if a == axis else 1 for a in range(3)]
    return lax.dynamic_update_slice(ext, ghost, starts)


def collect_ghosts(u: jax.Array, topo: Topology):
    """Exchange the 6 face ghost planes; no placement.

    Must run inside `shard_map` over the (x, y, z) mesh.  Returns
    ((xlo, xhi), (ylo, yhi), (zlo, zhi)) where `lo` is this shard's lower
    ghost (the -1 neighbour of its plane 0) and `hi` its upper ghost (the
    +1 neighbour of its last *real* plane).  The ppermute side of the
    reference's `exchange(n)` (mpi_new.cpp:181-269); placement into an
    extended array (`halo_extend`) or into the Pallas kernel's operand
    slots (`solver.sharded`) is the caller's choice.
    """
    ghosts = []
    for axis, name in enumerate(AXIS_NAMES):
        m = topo.mesh_shape[axis]
        b = topo.block[axis]
        r = topo.r_last[axis]
        if m == 1:
            # Single shard on this axis: the "exchange" is the local cyclic
            # wrap (a ppermute would be a self-copy; skipping it statically
            # removes real HBM traffic on every 1-dim mesh axis).  No pad
            # exists when m == 1, so b == r.
            ghost_lo = lax.slice_in_dim(u, b - 1, b, axis=axis)
            ghost_hi = lax.slice_in_dim(u, 0, 1, axis=axis)
            ghosts.append((ghost_lo, ghost_hi))
            continue
        is_last = lax.axis_index(name) == m - 1
        # Forward: my last real plane becomes the next shard's lower ghost.
        send_fwd = lax.dynamic_slice_in_dim(
            u, jnp.where(is_last, r - 1, b - 1), 1, axis
        )
        ghost_lo = lax.ppermute(send_fwd, name, _fwd_perm(m))
        # Backward: my first plane becomes the previous shard's upper ghost.
        send_bwd = lax.slice_in_dim(u, 0, 1, axis=axis)
        ghost_hi = lax.ppermute(send_bwd, name, _bwd_perm(m))
        ghosts.append((ghost_lo, ghost_hi))
    return tuple(ghosts)


def place_ghosts(u: jax.Array, ghosts, topo: Topology) -> jax.Array:
    """Build the (bx+2, by+2, bz+2) extension from pre-exchanged ghosts."""
    ext = jnp.pad(u, 1)
    for axis, (ghost_lo, ghost_hi) in enumerate(ghosts):
        m = topo.mesh_shape[axis]
        b = topo.block[axis]
        r = topo.r_last[axis]
        is_last = lax.axis_index(AXIS_NAMES[axis]) == m - 1
        ext = _place(ext, ghost_lo, axis, 0)
        ext = _place(ext, ghost_hi, axis, jnp.where(is_last, r + 1, b + 1))
    return ext


def halo_extend(u: jax.Array, topo: Topology) -> jax.Array:
    """Exchange 6 face ghosts and return the (bx+2, by+2, bz+2) extension.

    Must run inside `shard_map` over the (x, y, z) mesh.  Replaces
    `exchange(n)` + ghost-plane unpack of the reference (mpi_new.cpp:181-269);
    `kernels.stencil_ref.laplacian_ext` consumes the result.
    """
    return place_ghosts(u, collect_ghosts(u, topo), topo)


def absorb_hi_ghosts(u: jax.Array, ghosts, topo: Topology) -> jax.Array:
    """Write each axis's `hi` ghost into the first pad plane of `u` on the
    last shard of that axis (uneven shards only).

    The Pallas sharded kernel reads the +1 neighbour of local plane p from
    plane p+1 of its operand block, so for an unevenly sharded axis (where
    the last shard's last real plane r-1 is followed by pad, not by the
    ghost) the ghost must live *inside* the block at plane r - the in-block
    counterpart of `place_ghosts` writing ext position r+1.  Axes that
    divide evenly are untouched (their hi ghost rides the kernel's explicit
    ghost operand instead).  Pad planes of the *output* are re-zeroed by the
    kernel's global mask, so the invariant "carry state has zero pad" holds.
    """
    for axis, (_, ghost_hi) in enumerate(ghosts):
        b = topo.block[axis]
        r = topo.r_last[axis]
        if r == b:
            continue  # even split: no pad plane on this axis
        m = topo.mesh_shape[axis]
        is_last = lax.axis_index(AXIS_NAMES[axis]) == m - 1
        # Non-last shards overwrite their (real) plane r with itself.
        own = lax.slice_in_dim(u, r, r + 1, axis=axis)
        plane = jnp.where(is_last, ghost_hi, own)
        starts = [r if a == axis else 0 for a in range(3)]
        u = lax.dynamic_update_slice(u, plane, starts)
    return u
