"""Halo exchange over the ICI mesh: cyclic `ppermute` on all three axes.

The TPU-native replacement for the reference's entire L3 layer - the
pack / MPI_Sendrecv / unpack machinery (mpi_sol.cpp:196-285,
mpi_new.cpp:181-269) and the CUDA D2H -> MPI -> H2D staging path
(cuda_sol.cpp:230-312, cuda_sol_kernels.cu:91-177).  Ghost planes move
HBM-to-HBM over ICI; nothing is packed and nothing touches the host.

Why *cyclic* on every axis (not just periodic x): the fundamental-domain
state (see wavetpu.core.problem) makes the global neighbor relation a cyclic
shift on all three axes - x because the domain is periodic, y/z because the
wrap delivers the stored zero Dirichlet plane.  So one permutation pattern
serves all axes, the analog of the reference's periods={1,0,0} Cartesian
topology (mpi_sol.cpp:409-410) collapsing into uniform code.

Uneven-grid seam arithmetic: with zero-padding (core/grid.py), the last
shard along an axis owns r_last < block real planes.  Two index shifts keep
the exchange exact, the moral counterpart of the reference's seam-skip
invariant (sending plane X-1 / plane 2 from the x-edge ranks,
mpi_sol.cpp:201-202, SURVEY.md section 3.4):

 * the forward send ships the last *real* plane (r_last - 1, not block - 1);
 * the wrapped ghost received by the last shard lands at ext position
   r_last + 1, so the last real cell's +1 neighbor read hits it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from wavetpu.core.grid import AXIS_NAMES, Topology


def _fwd_perm(m: int):
    """shard i -> shard i+1 (cyclic): receiver gets its lower ghost."""
    return [(i, (i + 1) % m) for i in range(m)]


def _bwd_perm(m: int):
    """shard i -> shard i-1 (cyclic): receiver gets its upper ghost."""
    return [(i, (i - 1) % m) for i in range(m)]


def _place(ext, ghost, axis: int, pos):
    """Write a ghost plane into `ext` at index `pos` along `axis` (offset 1
    on the other axes; the unused ext corners stay zero)."""
    starts = [pos if a == axis else 1 for a in range(3)]
    return lax.dynamic_update_slice(ext, ghost, starts)


def halo_extend(u: jax.Array, topo: Topology) -> jax.Array:
    """Exchange 6 face ghosts and return the (bx+2, by+2, bz+2) extension.

    Must run inside `shard_map` over the (x, y, z) mesh.  Replaces
    `exchange(n)` + ghost-plane unpack of the reference (mpi_new.cpp:181-269);
    `kernels.stencil_ref.laplacian_ext` consumes the result.
    """
    ext = jnp.pad(u, 1)
    for axis, name in enumerate(AXIS_NAMES):
        m = topo.mesh_shape[axis]
        b = topo.block[axis]
        r = topo.r_last[axis]
        idx = lax.axis_index(name)
        is_last = idx == m - 1
        # Forward: my last real plane becomes the next shard's lower ghost.
        send_fwd = lax.dynamic_slice_in_dim(
            u, jnp.where(is_last, r - 1, b - 1), 1, axis
        )
        ghost_lo = lax.ppermute(send_fwd, name, _fwd_perm(m))
        # Backward: my first plane becomes the previous shard's upper ghost.
        send_bwd = lax.slice_in_dim(u, 0, 1, axis=axis)
        ghost_hi = lax.ppermute(send_bwd, name, _bwd_perm(m))
        ext = _place(ext, ghost_lo, axis, 0)
        ext = _place(ext, ghost_hi, axis, jnp.where(is_last, r + 1, b + 1))
    return ext
