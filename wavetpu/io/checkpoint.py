"""Checkpoint / resume: dump the live solver state, re-enter the scan.

The reference has no checkpointing; SURVEY.md section 5 flags it as the
trivial-win auxiliary because the full solver state is just two rolling
buffers plus the step index (the three-buffer rotation of mpi_new.cpp:131
collapses to (u^{n-1}, u^n) in the functional solver).  A checkpoint is a
single `.npz` holding those two (N, N, N) fields, the step index, and the
Problem spec; `resume_solve` feeds them back into `leapfrog.resume`, whose
per-step operation sequence is identical to an uninterrupted run's - so the
resumed final state is bitwise-equal (pinned by tests/test_checkpoint.py).

Sharded states are gathered to host before saving (this image is
single-host; a multi-host deployment would shard the .npz per host the way
the reference writes per-rank state, but the format here stays one file).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from wavetpu.core.problem import Problem
from wavetpu.solver import leapfrog
from wavetpu.solver.leapfrog import SolveResult

_FORMAT_VERSION = 1


def _encode_field(arr) -> Tuple[np.ndarray, str]:
    """(storable array, dtype tag) for one state field.

    `np.savez` silently stores ml_dtypes' bfloat16 as raw void bytes (|V2)
    that `jnp.asarray` then rejects on load, so bf16 travels as a uint16
    bit-view plus a dtype tag and is re-viewed on the way back - the
    round-trip is bitwise (the invariant tests/test_checkpoint.py pins).
    Native numpy dtypes pass through untouched.
    """
    arr = np.asarray(arr)
    if arr.dtype.name == "bfloat16":
        return arr.view(np.uint16), "bfloat16"
    if arr.dtype.kind == "V":
        # Some other ml_dtypes custom dtype (fp8, ...): a uint16 view would
        # silently reshape/corrupt it, and np.savez would store raw void
        # bytes - refuse at save time instead.
        raise ValueError(
            f"cannot checkpoint dtype {arr.dtype.name}: only native numpy "
            f"dtypes and bfloat16 are supported"
        )
    return arr, arr.dtype.name


def _decode_field(arr: np.ndarray, tag: Optional[str]) -> np.ndarray:
    """Inverse of `_encode_field`; also recovers legacy untagged checkpoints
    whose bf16 fields were stored as void |V2 (same raw bytes)."""
    if tag == "bfloat16" or (tag is None and arr.dtype.kind == "V"):
        import ml_dtypes

        return arr.view(np.uint16).view(ml_dtypes.bfloat16)
    return arr


def save_checkpoint(path: str, result: SolveResult) -> str:
    """Write (u_prev, u_cur, step, problem) from a (possibly partial) solve.

    `result.final_step` (set by solve/resume) is the layer index `u_cur`
    holds; a full-run result checkpoints its final state.
    """
    p = result.problem
    step = (
        result.final_step if result.final_step is not None else p.timesteps
    )
    u_prev, prev_tag = _encode_field(result.u_prev)
    u_cur, cur_tag = _encode_field(result.u_cur)
    np.savez(
        path,
        format_version=_FORMAT_VERSION,
        step=step,
        u_prev=u_prev,
        u_cur=u_cur,
        u_prev_dtype=prev_tag,
        u_cur_dtype=cur_tag,
        **{
            f"problem_{k}": v
            for k, v in dataclasses.asdict(p).items()
        },
    )
    return path if path.endswith(".npz") else path + ".npz"


def _problem_from_npz(z) -> Problem:
    return Problem(
        N=int(z["problem_N"]),
        Np=int(z["problem_Np"]),
        Lx=float(z["problem_Lx"]),
        Ly=float(z["problem_Ly"]),
        Lz=float(z["problem_Lz"]),
        T=float(z["problem_T"]),
        timesteps=int(z["problem_timesteps"]),
    )


def load_checkpoint(path: str) -> Tuple[Problem, np.ndarray, np.ndarray, int]:
    """Read a checkpoint back as (problem, u_prev, u_cur, step)."""
    with np.load(path) as z:
        version = int(z["format_version"])
        if version != _FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {version} != supported {_FORMAT_VERSION}"
            )
        problem = _problem_from_npz(z)

        def tag(name):
            return str(z[name]) if name in z.files else None

        u_prev = _decode_field(z["u_prev"], tag("u_prev_dtype"))
        u_cur = _decode_field(z["u_cur"], tag("u_cur_dtype"))
        return problem, u_prev, u_cur, int(z["step"])


def resume_solve(
    path: str,
    dtype=None,
    step_fn=None,
    compute_errors: bool = True,
) -> SolveResult:
    """Load a checkpoint and march from its step to `problem.timesteps`.

    `dtype` defaults to the stored arrays' dtype.
    """
    problem, u_prev, u_cur, step = load_checkpoint(path)
    if dtype is None:
        import jax.numpy as jnp

        dtype = jnp.dtype(u_cur.dtype)
    return leapfrog.resume(
        problem,
        u_prev,
        u_cur,
        start_step=step,
        dtype=dtype,
        step_fn=step_fn,
        compute_errors=compute_errors,
    )
