"""Checkpoint / resume: dump the live solver state, re-enter the scan.

The reference has no checkpointing; SURVEY.md section 5 flags it as the
trivial-win auxiliary because the full solver state is just two rolling
buffers plus the step index (the three-buffer rotation of mpi_new.cpp:131
collapses to (u^{n-1}, u^n) in the functional solver).  A checkpoint is a
single `.npz` holding those two (N, N, N) fields, the step index, and the
Problem spec; `resume_solve` feeds them back into `leapfrog.resume`, whose
per-step operation sequence is identical to an uninterrupted run's - so the
resumed final state is bitwise-equal (pinned by tests/test_checkpoint.py).

Sharded states are gathered to host before saving (this image is
single-host; a multi-host deployment would shard the .npz per host the way
the reference writes per-rank state, but the format here stays one file).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from wavetpu.core.problem import Problem
from wavetpu.solver import leapfrog
from wavetpu.solver.leapfrog import SolveResult

_FORMAT_VERSION = 1


def save_checkpoint(path: str, result: SolveResult) -> str:
    """Write (u_prev, u_cur, step, problem) from a (possibly partial) solve.

    `result.final_step` (set by solve/resume) is the layer index `u_cur`
    holds; a full-run result checkpoints its final state.
    """
    p = result.problem
    step = (
        result.final_step if result.final_step is not None else p.timesteps
    )
    np.savez(
        path,
        format_version=_FORMAT_VERSION,
        step=step,
        u_prev=np.asarray(result.u_prev),
        u_cur=np.asarray(result.u_cur),
        **{
            f"problem_{k}": v
            for k, v in dataclasses.asdict(p).items()
        },
    )
    return path if path.endswith(".npz") else path + ".npz"


def load_checkpoint(path: str) -> Tuple[Problem, np.ndarray, np.ndarray, int]:
    """Read a checkpoint back as (problem, u_prev, u_cur, step)."""
    with np.load(path) as z:
        version = int(z["format_version"])
        if version != _FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {version} != supported {_FORMAT_VERSION}"
            )
        problem = Problem(
            N=int(z["problem_N"]),
            Np=int(z["problem_Np"]),
            Lx=float(z["problem_Lx"]),
            Ly=float(z["problem_Ly"]),
            Lz=float(z["problem_Lz"]),
            T=float(z["problem_T"]),
            timesteps=int(z["problem_timesteps"]),
        )
        return problem, z["u_prev"], z["u_cur"], int(z["step"])


def resume_solve(
    path: str,
    dtype=None,
    step_fn=None,
    compute_errors: bool = True,
) -> SolveResult:
    """Load a checkpoint and march from its step to `problem.timesteps`.

    `dtype` defaults to the stored arrays' dtype.
    """
    problem, u_prev, u_cur, step = load_checkpoint(path)
    if dtype is None:
        import jax.numpy as jnp

        dtype = jnp.dtype(u_cur.dtype)
    return leapfrog.resume(
        problem,
        u_prev,
        u_cur,
        start_step=step,
        dtype=dtype,
        step_fn=step_fn,
        compute_errors=compute_errors,
    )
