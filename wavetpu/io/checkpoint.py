"""Checkpoint / resume: dump the live solver state, re-enter the scan.

The reference has no checkpointing; SURVEY.md section 5 flags it as the
trivial-win auxiliary because the full solver state is just two rolling
buffers plus the step index (the three-buffer rotation of mpi_new.cpp:131
collapses to (u^{n-1}, u^n) in the functional solver).  A checkpoint is a
single `.npz` holding those two (N, N, N) fields, the step index, and the
Problem spec; `resume_solve` feeds them back into `leapfrog.resume`, whose
per-step operation sequence is identical to an uninterrupted run's - so the
resumed final state is bitwise-equal (pinned by tests/test_checkpoint.py).

Sharded runs use the per-shard format instead (`save_sharded_checkpoint`):
one meta file plus one .npz per shard, written and read only by the process
that owns the shard - the scalable counterpart of the reference writing
per-rank state, with no host gather anywhere.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from wavetpu.core.problem import Problem
from wavetpu.solver import leapfrog
from wavetpu.solver.leapfrog import SolveResult

_FORMAT_VERSION = 1


def _encode_field(arr) -> Tuple[np.ndarray, str]:
    """(storable array, dtype tag) for one state field.

    `np.savez` silently stores ml_dtypes' bfloat16 as raw void bytes (|V2)
    that `jnp.asarray` then rejects on load, so bf16 travels as a uint16
    bit-view plus a dtype tag and is re-viewed on the way back - the
    round-trip is bitwise (the invariant tests/test_checkpoint.py pins).
    Native numpy dtypes pass through untouched.
    """
    arr = np.asarray(arr)
    if arr.dtype.name == "bfloat16":
        return arr.view(np.uint16), "bfloat16"
    if arr.dtype.kind == "V":
        # Some other ml_dtypes custom dtype (fp8, ...): a uint16 view would
        # silently reshape/corrupt it, and np.savez would store raw void
        # bytes - refuse at save time instead.
        raise ValueError(
            f"cannot checkpoint dtype {arr.dtype.name}: only native numpy "
            f"dtypes and bfloat16 are supported"
        )
    return arr, arr.dtype.name


def _decode_field(arr: np.ndarray, tag: Optional[str]) -> np.ndarray:
    """Inverse of `_encode_field`; also recovers legacy untagged checkpoints
    whose bf16 fields were stored as void |V2 (same raw bytes)."""
    if tag == "bfloat16" or (tag is None and arr.dtype.kind == "V"):
        import ml_dtypes

        return arr.view(np.uint16).view(ml_dtypes.bfloat16)
    return arr


def _record_io(op: str, kind: str, nbytes: float, seconds: float) -> None:
    """Checkpoint I/O telemetry (bytes / seconds / op counters into the
    process registry, plus a span when tracing is on - emitted by the
    caller).  Never lets an obs failure break a checkpoint."""
    try:
        from wavetpu.obs import metrics as _obs

        _obs.record_checkpoint_io(op, kind, nbytes, seconds)
    except Exception:
        pass


def _tree_bytes(path_dir: str) -> int:
    """Directory byte total for telemetry - best-effort: a file another
    process renames/removes mid-walk (concurrent multi-host writers
    cleaning tmp debris) must not fail a checkpoint op that already
    succeeded."""
    import os

    total = 0
    try:
        entries = os.listdir(path_dir)
    except OSError:
        return 0
    for e in entries:
        try:
            p = os.path.join(path_dir, e)
            if os.path.isfile(p):
                total += os.path.getsize(p)
        except OSError:
            pass
    return total


def _file_bytes(path: str) -> int:
    import os

    try:
        return os.path.getsize(path) if os.path.exists(path) else 0
    except OSError:
        return 0


def save_checkpoint(path: str, result: SolveResult) -> str:
    """Write (u_prev, u_cur, step, problem) from a (possibly partial) solve.

    `result.final_step` (set by solve/resume) is the layer index `u_cur`
    holds; a full-run result checkpoints its final state.
    """
    import time as _time

    from wavetpu.obs import tracing

    t0 = _time.perf_counter()
    p = result.problem
    step = (
        result.final_step if result.final_step is not None else p.timesteps
    )
    u_prev, prev_tag = _encode_field(result.u_prev)
    u_cur, cur_tag = _encode_field(result.u_cur)
    extra = {}
    if result.comp_v is not None:
        # Compensated-scheme state is three buffers: u, the increment v,
        # and the Kahan carry (u_prev is still stored for uniformity /
        # inspection, but the bitwise resume re-enters from (u, v, carry)).
        # The carry-less increment form (bf16 v) stores zeros: a zero
        # carry is a valid Kahan start, and the bf16 v dtype marks the
        # mode for resume dispatch (cli.py).
        import jax.numpy as jnp

        comp_v, v_tag = _encode_field(result.comp_v)
        comp_carry, c_tag = _encode_field(
            result.comp_carry if result.comp_carry is not None
            else jnp.zeros_like(result.u_cur)
        )
        extra = dict(
            scheme="compensated",
            comp_v=comp_v,
            comp_carry=comp_carry,
            comp_v_dtype=v_tag,
            comp_carry_dtype=c_tag,
        )
    np.savez(
        path,
        format_version=_FORMAT_VERSION,
        step=step,
        u_prev=u_prev,
        u_cur=u_cur,
        u_prev_dtype=prev_tag,
        u_cur_dtype=cur_tag,
        **extra,
        **{
            f"problem_{k}": v
            for k, v in dataclasses.asdict(p).items()
        },
    )
    out = path if path.endswith(".npz") else path + ".npz"
    seconds = _time.perf_counter() - t0
    nbytes = _file_bytes(out)
    _record_io("save", "single", nbytes, seconds)
    tracing.event("checkpoint.save", kind="single", step=step,
                  bytes=nbytes, seconds=round(seconds, 6), path=out)
    return out


def _problem_from_npz(z) -> Problem:
    return Problem(
        N=int(z["problem_N"]),
        Np=int(z["problem_Np"]),
        Lx=float(z["problem_Lx"]),
        Ly=float(z["problem_Ly"]),
        Lz=float(z["problem_Lz"]),
        T=float(z["problem_T"]),
        timesteps=int(z["problem_timesteps"]),
    )


def load_checkpoint(path: str) -> Tuple[Problem, np.ndarray, np.ndarray, int]:
    """Read a checkpoint back as (problem, u_prev, u_cur, step)."""
    import time as _time

    t0 = _time.perf_counter()
    with np.load(path) as z:
        version = int(z["format_version"])
        if version != _FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {version} != supported {_FORMAT_VERSION}"
            )
        problem = _problem_from_npz(z)

        def tag(name):
            return str(z[name]) if name in z.files else None

        u_prev = _decode_field(z["u_prev"], tag("u_prev_dtype"))
        u_cur = _decode_field(z["u_cur"], tag("u_cur_dtype"))
        step = int(z["step"])
    _record_io("load", "single", _file_bytes(path),
               _time.perf_counter() - t0)
    return problem, u_prev, u_cur, step


def _shard_filename(starts) -> str:
    return f"shard_{starts[0]}_{starts[1]}_{starts[2]}.wts"


def _legacy_shard_filename(starts) -> str:
    return f"shard_{starts[0]}_{starts[1]}_{starts[2]}.npz"


def _legacy_shard_has_step(legacy_path: str, step: int) -> bool:
    """True iff a legacy .npz shard exists AND explicitly records `step`.

    Used to gate the WTS-mismatch fallback: a step-less legacy shard
    (the ancient layout) is loadable as a whole-directory legacy
    checkpoint but must never be mixed into a partially written WTS one.
    """
    import os

    if not os.path.exists(legacy_path):
        return False
    with np.load(legacy_path) as z:
        return "step" in z.files and int(z["step"]) == step


def save_sharded_checkpoint(path_dir: str, result: SolveResult) -> str:
    """Write a sharded solve's state as one file per shard plus a meta file.

    The scalable counterpart of `save_checkpoint`: nothing is gathered - on
    a multi-host deployment each process writes only its addressable shards
    (the moral equivalent of the reference writing per-rank state), so the
    host-memory and file-size cost per process is O(state / n_processes)
    instead of one dense ~68 GB .npz at the N=2048 stretch config.
    Layout: `meta.npz` (problem, step, mesh shape, state dtype; process 0
    only) + `shard_{x0}_{y0}_{z0}.wts` (WTS1 containers, io/nativeio.py)
    keyed by global start offsets.

    Crash consistency: every file is written to a temp name and renamed
    (atomic per file), each shard carries a CRC32 footer and the step it
    belongs to, and the loader rejects any shard whose CRC fails or whose
    step disagrees with meta - so a preemption mid-way through OVERWRITING
    an older checkpoint cannot be silently resumed as mixed-step or torn
    state.  (On multi-host, rank 0's meta write is not ordered after other
    hosts' shard writes; a deployment wanting cross-host atomicity should
    save each checkpoint to a fresh directory and flip a pointer at the
    orchestration layer - which is exactly what the supervised-run
    rotation does: run/supervisor.py's CheckpointRotation saves every
    periodic checkpoint into a fresh `step-XXXXXXXX` entry and atomically
    updates a `latest` pointer afterwards.)  Stale `*.tmp-<pid>*` files
    left by a crashed writer are removed before each shard is rewritten
    (and are ignored by the loader, which opens exact filenames only).

    IO path: shards are WTS1 containers streamed by the native async
    writer (io/nativeio.py: C++ background thread, CRC32, atomic rename) -
    the disk write of shard i overlaps assembling shard i+1, and a pure-
    Python fallback produces byte-identical files where no compiler
    exists.  Legacy .npz shard checkpoints remain loadable.
    """
    import os
    import time as _time

    import jax

    from wavetpu.io import nativeio
    from wavetpu.obs import tracing

    t0 = _time.perf_counter()
    p = result.problem
    step = (
        result.final_step if result.final_step is not None else p.timesteps
    )
    u_prev, u_cur = result.u_prev, result.u_cur
    mesh = u_cur.sharding.mesh
    from wavetpu.core.grid import AXIS_NAMES

    mesh_shape = tuple(int(mesh.shape[n]) for n in AXIS_NAMES)
    os.makedirs(path_dir, exist_ok=True)

    def clean_stale_tmps(filename):
        # A writer killed mid-save (the preemption case --ckpt-every
        # exists for) leaves `<file>.tmp-<pid>*` behind; unbounded runs
        # would leak one per crash into a rotated checkpoint directory.
        # Each process cleans only the temp names of files IT is about to
        # write, so a concurrent multi-host save never removes another
        # live writer's in-flight temp.
        prefix = f"{filename}.tmp-"
        for e in os.listdir(path_dir):
            if e.startswith(prefix):
                try:
                    os.remove(os.path.join(path_dir, e))
                except OSError:
                    pass

    def atomic_savez(filename, **arrays):
        path = os.path.join(path_dir, filename)
        clean_stale_tmps(filename)
        # np.savez appends .npz to names without it, so the temp name must
        # already carry the suffix for the rename to find it.
        tmp = f"{path}.tmp-{os.getpid()}.npz"
        try:
            np.savez(tmp, **arrays)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    def starts_of(index):
        return tuple(int(sl.start or 0) for sl in index)

    compensated = result.comp_v is not None

    def by_start(arr):
        return {starts_of(s.index): s.data for s in arr.addressable_shards}

    prev_by_start = by_start(u_prev)
    if compensated and result.comp_carry is None:
        # Carry-less increment form: store a zero carry (a valid Kahan
        # start; the bf16 v dtype marks the mode for resume dispatch).
        import jax.numpy as jnp

        carry_src = by_start(jnp.zeros_like(result.u_cur))
    elif compensated:
        carry_src = by_start(result.comp_carry)
    aux_by_start = (
        (by_start(result.comp_v), carry_src) if compensated else None
    )
    in_flight = []
    try:
        for sc in u_cur.addressable_shards:
            starts = starts_of(sc.index)
            fields = dict(
                u_prev=_encode_field(prev_by_start[starts]),
                u_cur=_encode_field(sc.data),
            )
            if compensated:
                fields["comp_v"] = _encode_field(aux_by_start[0][starts])
                fields["comp_carry"] = _encode_field(aux_by_start[1][starts])
            clean_stale_tmps(_shard_filename(starts))
            in_flight.append(nativeio.write_container(
                os.path.join(path_dir, _shard_filename(starts)),
                fields,
                meta={"step": step},
            ))
        for w in in_flight:
            nativeio.finish_container(w)
    except Exception:
        for w in in_flight:
            w.abort()
        raise
    if jax.process_index() == 0:
        atomic_savez(
            "meta.npz",
            format_version=_FORMAT_VERSION,
            step=step,
            mesh_shape=np.asarray(mesh_shape),
            state_dtype=np.asarray(u_cur.dtype.name),
            scheme=np.asarray(
                "compensated" if compensated else "standard"
            ),
            **{
                f"problem_{k}": v
                for k, v in dataclasses.asdict(p).items()
            },
        )
    seconds = _time.perf_counter() - t0
    # Directory total (this process's shards + meta; a reused directory
    # also counts prior files - rotation entries are always fresh).
    nbytes = _tree_bytes(path_dir)
    _record_io("save", "sharded", nbytes, seconds)
    tracing.event("checkpoint.save", kind="sharded", step=step,
                  bytes=nbytes, seconds=round(seconds, 6), path=path_dir)
    return path_dir


def load_sharded_meta(path_dir: str):
    """Read only a per-shard checkpoint's meta file (numpy, no jax):
    (problem, step, mesh_shape, state_dtype_name).  Lets callers (the CLI)
    inspect the checkpoint - e.g. to enable x64 for an f64 state - before
    the jax platform is configured."""
    import os

    with np.load(os.path.join(path_dir, "meta.npz")) as z:
        version = int(z["format_version"])
        if version != _FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {version} != supported {_FORMAT_VERSION}"
            )
        problem = _problem_from_npz(z)
        step = int(z["step"])
        mesh_shape = tuple(int(v) for v in z["mesh_shape"])
        state_dtype = (
            str(z["state_dtype"]) if "state_dtype" in z.files else None
        )
        scheme = str(z["scheme"]) if "scheme" in z.files else "standard"
    return problem, step, mesh_shape, state_dtype, scheme


def load_sharded_checkpoint(path_dir: str, devices=None):
    """Load a per-shard checkpoint back onto a device mesh.

    Returns (problem, u_prev, u_cur, step, mesh_shape, scheme, aux) with
    u_* global jax.Arrays sharded P("x","y","z") over a mesh rebuilt from
    the stored shape; `scheme` is "standard" or "compensated" and `aux` is
    the compensated (comp_v, comp_carry) pair or None.  Each process reads only the shard files its devices own
    (jax.make_array_from_single_device_arrays), so the load path is as
    multi-host-scalable as the save path.
    """
    import os
    import time as _time

    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from wavetpu.core.grid import AXIS_NAMES, Topology, build_mesh

    t0 = _time.perf_counter()
    problem, step, mesh_shape, _, scheme = load_sharded_meta(path_dir)
    topo = Topology(N=problem.N, mesh_shape=mesh_shape)
    if devices is None:
        devices = jax.devices()
    mesh = build_mesh(mesh_shape, devices[: topo.n_devices])
    sharding = NamedSharding(mesh, P(*AXIS_NAMES))
    imap = sharding.addressable_devices_indices_map(topo.padded)
    compensated = scheme == "compensated"
    buffers = {"u_prev": [], "u_cur": []}
    if compensated:
        buffers.update(comp_v=[], comp_carry=[])
    from wavetpu.io import nativeio

    for dev, idx in imap.items():
        starts = tuple(int(sl.start or 0) for sl in idx)
        wts_path = os.path.join(path_dir, _shard_filename(starts))
        legacy_path = os.path.join(
            path_dir, _legacy_shard_filename(starts)
        )
        if os.path.exists(wts_path):
            fields, shard_meta = nativeio.read_container(wts_path)
            if shard_meta.get("step") != step:
                # A WTS1 save overwriting a legacy .npz checkpoint was
                # preempted mid-way: the stale meta still describes the
                # legacy files.  Fall back to the legacy shard ONLY when
                # it explicitly carries the step meta describes - a
                # step-less (ancient) .npz here could predate meta
                # entirely and must not be assembled into a mixed-step
                # state.
                if not _legacy_shard_has_step(legacy_path, step):
                    raise ValueError(
                        f"shard {_shard_filename(starts)} holds step "
                        f"{shard_meta.get('step')} but meta says {step}: "
                        f"checkpoint was interrupted mid-save; discard it "
                        f"(if this directory held an older .npz checkpoint, "
                        f"its shards may still be intact and recoverable)"
                    )
            else:
                for key, bufs in buffers.items():
                    arr, dt = fields[key]
                    bufs.append(
                        jax.device_put(_decode_field(arr, dt), dev)
                    )
                continue
        # Legacy .npz shard layout (pre-WTS1 checkpoints).  A checkpoint
        # with NEITHER file is reported against the current format's name,
        # not the legacy one.
        if not os.path.exists(legacy_path):
            raise FileNotFoundError(
                f"checkpoint shard missing: {wts_path}"
            )
        with np.load(legacy_path) as z:
            if "step" in z.files and int(z["step"]) != step:
                raise ValueError(
                    f"shard {_legacy_shard_filename(starts)} holds step "
                    f"{int(z['step'])} but meta says {step}: checkpoint "
                    f"was interrupted mid-save; discard it"
                )

            def tag(name):
                return str(z[name]) if name in z.files else None

            for key, bufs in buffers.items():
                bufs.append(
                    jax.device_put(
                        _decode_field(z[key], tag(f"{key}_dtype")), dev
                    )
                )

    def assemble(bufs):
        return jax.make_array_from_single_device_arrays(
            topo.padded, sharding, bufs
        )

    u_prev = assemble(buffers["u_prev"])
    u_cur = assemble(buffers["u_cur"])
    aux = None
    if compensated:
        aux = (assemble(buffers["comp_v"]), assemble(buffers["comp_carry"]))
    _record_io("load", "sharded", _tree_bytes(path_dir),
               _time.perf_counter() - t0)
    return problem, u_prev, u_cur, step, mesh_shape, scheme, aux


def resume_sharded_solve(
    path_dir: str,
    dtype=None,
    kernel: str = "roll",
    overlap: bool = False,
    compute_errors: bool = True,
) -> SolveResult:
    """Load a per-shard checkpoint and march to problem.timesteps on the
    mesh it was saved from, under the scheme it was saved with."""
    from wavetpu.solver import sharded

    problem, u_prev, u_cur, step, mesh_shape, scheme, aux = (
        load_sharded_checkpoint(path_dir)
    )
    if dtype is None:
        import jax.numpy as jnp

        dtype = jnp.dtype(u_cur.dtype)
    comp_v, comp_carry = aux if aux is not None else (None, None)
    return sharded.resume_sharded(
        problem,
        u_prev,
        u_cur,
        start_step=step,
        mesh_shape=mesh_shape,
        dtype=dtype,
        kernel=kernel,
        overlap=overlap if scheme == "standard" else False,
        compute_errors=compute_errors,
        scheme=scheme,
        comp_v=comp_v,
        comp_carry=comp_carry,
    )


def load_checkpoint_aux(path: str):
    """The compensated-scheme auxiliary state (v, carry) of a single-file
    checkpoint, or None for a standard-scheme one."""
    with np.load(path) as z:
        if "comp_v" not in z.files:
            return None

        def tag(name):
            return str(z[name]) if name in z.files else None

        return (
            _decode_field(z["comp_v"], tag("comp_v_dtype")),
            _decode_field(z["comp_carry"], tag("comp_carry_dtype")),
        )


def checkpoint_scheme(path: str) -> str:
    """The time-integration scheme a single-file checkpoint was saved
    under: "compensated" or "standard" (numpy-only; no jax)."""
    with np.load(path) as z:
        return str(z["scheme"]) if "scheme" in z.files else "standard"


def resume_solve(
    path: str,
    dtype=None,
    step_fn=None,
    comp_step_fn=None,
    compute_errors: bool = True,
) -> SolveResult:
    """Load a checkpoint and march from its step to `problem.timesteps`.

    Dispatches on the stored scheme: a compensated checkpoint re-enters
    the compensated scan from (u, v, carry) - `comp_step_fn` then selects
    its kernel and `step_fn` is ignored (and vice versa for standard).
    `dtype` defaults to the stored arrays' dtype.
    """
    import jax.numpy as jnp

    if checkpoint_scheme(path) == "compensated":
        with np.load(path) as z:
            def tag(name):
                return str(z[name]) if name in z.files else None

            problem = _problem_from_npz(z)
            step = int(z["step"])
            u_cur = _decode_field(z["u_cur"], tag("u_cur_dtype"))
            v = _decode_field(z["comp_v"], tag("comp_v_dtype"))
            carry = _decode_field(z["comp_carry"], tag("comp_carry_dtype"))
        if dtype is None:
            dtype = jnp.dtype(u_cur.dtype)
        return leapfrog.resume_compensated(
            problem,
            u_cur,
            v,
            carry,
            start_step=step,
            dtype=dtype,
            comp_step_fn=comp_step_fn,
            compute_errors=compute_errors,
        )
    problem, u_prev, u_cur, step = load_checkpoint(path)
    if dtype is None:
        dtype = jnp.dtype(u_cur.dtype)
    return leapfrog.resume(
        problem,
        u_prev,
        u_cur,
        start_step=step,
        dtype=dtype,
        step_fn=step_fn,
        compute_errors=compute_errors,
    )
