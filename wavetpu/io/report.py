"""Run-report writer, format-compatible with the reference's output files.

The reference's rank 0 writes `output_N{N}_Np{procs}[_..]_{variant}.txt`
containing init time, solve wall time, per-layer L-inf abs/rel errors, and
(new/cuda variants) a timing breakdown (openmp_sol.cpp:229, mpi_new.cpp:454,
lines written at mpi_new.cpp:474,356-371 and cuda_sol.cpp:427-442).  The
layer-error lines here are verbatim-compatible ("max abs and rel errors on
layer n: A R") so outputs diff cleanly against reference runs; the timing
labels name the TPU phases honestly (ICI exchange, not MPI).  A structured
JSON sidecar carries the same data plus throughput for machines.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from wavetpu.solver.leapfrog import SolveResult


def _fmt(x: float) -> str:
    """C++ ostream default formatting: 6 significant digits, shortest form."""
    s = f"{x:.6g}"
    return s


def report_filename(
    N: int, n_procs: int, variant: str = "TPU", n_threads: Optional[int] = None
) -> str:
    """Reference naming convention (SURVEY.md section 0 output contract):
    output_N{N}_Np{procs}[_Nt{threads}]_{variant}.txt."""
    parts = [f"output_N{N}", f"Np{n_procs}"]
    if n_threads is not None:
        parts.append(f"Nt{n_threads}")
    return "_".join(parts) + f"_{variant}.txt"


def format_report(
    result: SolveResult,
    exchange_seconds: Optional[float] = None,
    loop_seconds: Optional[float] = None,
    errors_computed: bool = True,
    probe_steps: Optional[int] = None,
) -> str:
    """Render the text report body (reference line layout).

    With `errors_computed=False` (a --no-errors run) the layer lines are
    replaced by an explicit marker rather than emitting all-zero errors that
    would read as a perfect run.
    """
    lines = [
        f"grids initialized in {int(result.init_seconds * 1000)}ms",
        f"numerical solution calculated in {int(result.solve_seconds * 1000)}ms",
    ]
    if errors_computed:
        for n, (a, r) in enumerate(zip(result.abs_errors, result.rel_errors)):
            lines.append(
                f"max abs and rel errors on layer {n}: {_fmt(a)} {_fmt(r)}"
            )
    else:
        lines.append("errors not computed (run without --no-errors to verify)")
    if exchange_seconds is not None:
        lines.append(
            f"total ICI exchange time: {int(exchange_seconds * 1000)}ms"
        )
    if loop_seconds is not None:
        lines.append(f"total loop time: {int(loop_seconds * 1000)}ms")
    if probe_steps is not None and (
        exchange_seconds is not None or loop_seconds is not None
    ):
        # Honesty label: unlike the reference's per-step host timers
        # (mpi_new.cpp:200-240), these come from a probe scan of the
        # production step body extrapolated to the full solve length.
        lines.append(
            f"(phase times probe-extrapolated from {probe_steps} steps)"
        )
    return "\n".join(lines) + "\n"


def write_report(
    result: SolveResult,
    out_dir: str = ".",
    n_procs: int = 1,
    variant: str = "TPU",
    exchange_seconds: Optional[float] = None,
    loop_seconds: Optional[float] = None,
    json_sidecar: bool = True,
    errors_computed: bool = True,
    probe_steps: Optional[int] = None,
    run_config: Optional[dict] = None,
) -> str:
    """Write the text report (+ JSON sidecar); returns the text-file path.

    `run_config` (JSON-serializable) records how the run was produced -
    backend, kernel, scheme, fuse_steps, mesh, dtype - so a sidecar is
    self-describing (the reference encodes this in the BINARY it ran;
    the runtime-selected equivalent must travel with the output).
    """
    p = result.problem
    name = report_filename(p.N, n_procs, variant)
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, name)
    with open(path, "w") as f:
        f.write(
            format_report(
                result, exchange_seconds, loop_seconds, errors_computed,
                probe_steps,
            )
        )
    if json_sidecar:
        side = {
            "problem": dataclasses.asdict(p),
            "courant": p.courant,
            "variant": variant,
            "n_procs": n_procs,
            "init_seconds": result.init_seconds,
            "solve_seconds": result.solve_seconds,
            "gcells_per_second": result.gcells_per_second,
            "cells_per_step": p.cells_per_step,
            "errors_computed": errors_computed,
            "max_abs_error": (
                float(result.abs_errors.max()) if errors_computed else None
            ),
            "abs_errors": (
                [float(x) for x in result.abs_errors] if errors_computed else None
            ),
            "rel_errors": (
                [float(x) for x in result.rel_errors] if errors_computed else None
            ),
            "exchange_seconds": exchange_seconds,
            "loop_seconds": loop_seconds,
            "phase_probe_steps": probe_steps,
            "run_config": run_config,
        }
        # Derive the sidecar from `name` (not `path`): out_dir may itself
        # contain ".txt".
        with open(os.path.join(out_dir, name[:-4] + ".json"), "w") as f:
            json.dump(side, f, indent=1)
    return path
