// Native checkpoint IO: asynchronous file writer with CRC32 and atomic
// rename.  The runtime-side counterpart of the reference's C++ IO layer
// (every reference variant is a C++ binary doing its own file IO,
// openmp_sol.cpp:216-243); here the hot path is JAX/XLA and this library
// carries the *runtime* concern: getting multi-GB shard state to disk
// without stalling the solver loop or leaving torn files behind on
// preemption.
//
// Contract (C ABI, driven from Python via ctypes - wavetpu/io/nativeio.py):
//   w = ckpt_writer_open(tmp_path)      open the temp file
//   ckpt_writer_write(w, buf, len)      enqueue a chunk (ZERO-COPY: the
//                                       caller must keep buf alive and
//                                       unmodified until finish/abort)
//   ckpt_writer_finish(w, final_path,   drain the queue, fsync, atomically
//                      &crc)            rename tmp -> final, return the
//                                       CRC32 of the whole stream
//   ckpt_writer_abort(w)                drop the queue, unlink the temp
//   ckpt_crc32(buf, len, seed)          standalone CRC32 (load-side verify)
//
// A single background thread per writer consumes the queue, so the Python
// caller overlaps device->host transfer of the next shard with the disk
// write of the current one.  CRC32 is the standard reflected polynomial
// 0xEDB88320 (zlib-compatible: crc32(data) == zlib.crc32(data)), computed
// slice-by-8.
//
// Build: g++ -O3 -shared -fPIC -pthread ckptio.cc -o _ckptio.so

#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

#include <fcntl.h>
#include <unistd.h>

namespace {

// ---- CRC32 (reflected 0xEDB88320, zlib-compatible), slice-by-8 ----------

uint32_t g_crc_tab[8][256];

void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c >> 1) ^ (0xEDB88320u & (-(c & 1u)));
    g_crc_tab[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int t = 1; t < 8; t++)
      g_crc_tab[t][i] =
          (g_crc_tab[t - 1][i] >> 8) ^ g_crc_tab[0][g_crc_tab[t - 1][i] & 0xff];
}

struct CrcInitOnce {
  CrcInitOnce() { crc_init(); }
} g_crc_init_once;

uint32_t crc32_update(uint32_t crc, const uint8_t* p, size_t n) {
  crc = ~crc;
  while (n >= 8) {
    crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
    uint32_t hi = (uint32_t)p[4] | ((uint32_t)p[5] << 8) |
                  ((uint32_t)p[6] << 16) | ((uint32_t)p[7] << 24);
    crc = g_crc_tab[7][crc & 0xff] ^ g_crc_tab[6][(crc >> 8) & 0xff] ^
          g_crc_tab[5][(crc >> 16) & 0xff] ^ g_crc_tab[4][crc >> 24] ^
          g_crc_tab[3][hi & 0xff] ^ g_crc_tab[2][(hi >> 8) & 0xff] ^
          g_crc_tab[1][(hi >> 16) & 0xff] ^ g_crc_tab[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ g_crc_tab[0][(crc ^ *p++) & 0xff];
  return ~crc;
}

// ---- async writer --------------------------------------------------------

struct Chunk {
  const uint8_t* data;
  size_t len;
};

struct Writer {
  int fd = -1;
  std::string tmp_path;
  std::deque<Chunk> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::thread worker;
  bool closing = false;   // no more chunks will arrive
  int io_errno = 0;       // first write error, reported at finish
  uint32_t crc = 0;

  void run() {
    for (;;) {
      Chunk c;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return !queue.empty() || closing; });
        if (queue.empty()) return;
        c = queue.front();
        queue.pop_front();
      }
      if (io_errno == 0) {
        const uint8_t* p = c.data;
        size_t left = c.len;
        while (left > 0) {
          ssize_t w = ::write(fd, p, left);
          if (w < 0) {
            if (errno == EINTR) continue;
            io_errno = errno;
            break;
          }
          p += w;
          left -= (size_t)w;
        }
        if (io_errno == 0) crc = crc32_update(crc, c.data, c.len);
      }
      cv.notify_all();  // finish() waits for the queue to drain
    }
  }
};

}  // namespace

extern "C" {

uint64_t ckpt_crc32(const void* buf, uint64_t len, uint64_t seed) {
  return crc32_update((uint32_t)seed, (const uint8_t*)buf, (size_t)len);
}

void* ckpt_writer_open(const char* tmp_path) {
  int fd = ::open(tmp_path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return nullptr;
  Writer* w = new Writer();
  w->fd = fd;
  w->tmp_path = tmp_path;
  w->worker = std::thread([w] { w->run(); });
  return w;
}

int ckpt_writer_write(void* handle, const void* buf, uint64_t len) {
  Writer* w = (Writer*)handle;
  {
    std::lock_guard<std::mutex> lk(w->mu);
    if (w->closing) return -1;
    w->queue.push_back(Chunk{(const uint8_t*)buf, (size_t)len});
  }
  w->cv.notify_all();
  return 0;
}

// Drain, fsync, rename to final_path; *crc_out gets the stream CRC32.
// Returns 0 on success, -errno on the first IO failure (temp unlinked).
int ckpt_writer_finish(void* handle, const char* final_path,
                       uint64_t* crc_out) {
  Writer* w = (Writer*)handle;
  {
    std::lock_guard<std::mutex> lk(w->mu);
    w->closing = true;
  }
  w->cv.notify_all();
  w->worker.join();
  int err = w->io_errno;
  if (err == 0 && ::fsync(w->fd) != 0) err = errno;
  ::close(w->fd);
  if (err == 0 && ::rename(w->tmp_path.c_str(), final_path) != 0) err = errno;
  if (err != 0) ::unlink(w->tmp_path.c_str());
  if (crc_out) *crc_out = w->crc;
  delete w;
  return err == 0 ? 0 : -err;
}

int ckpt_writer_abort(void* handle) {
  Writer* w = (Writer*)handle;
  {
    std::lock_guard<std::mutex> lk(w->mu);
    w->queue.clear();
    w->closing = true;
  }
  w->cv.notify_all();
  w->worker.join();
  ::close(w->fd);
  ::unlink(w->tmp_path.c_str());
  delete w;
  return 0;
}

}  // extern "C"
