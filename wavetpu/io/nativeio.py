"""Native-accelerated checkpoint IO: async writer, CRC32, shard container.

The C++ side (`native/ckptio.cc`) is a background-thread file writer with
zlib-compatible CRC32 and atomic temp-file rename - the runtime IO layer
the reference keeps in C++ (its variants are C++ binaries writing their own
output files, openmp_sol.cpp:216-243).  It is compiled on first use with
the toolchain's g++ (no pip deps, ctypes binding, ~1 s); when no compiler
is available every entry point falls back to a pure-Python implementation
that produces byte-identical files, so the container format below is THE
format, not "the native format".

Shard container ("WTS1"): the per-shard checkpoint file written by
io/checkpoint.py's sharded path.  Layout:

    8  bytes   magic  b"WTSCKPT1"
    4  bytes   u32 little-endian header length H
    H  bytes   UTF-8 JSON: {"arrays": [{name, dtype, shape, nbytes}...],
                            "meta": {...}}   (offsets implicit, in order)
    payloads   raw C-order array bytes, in header order
    12 bytes   footer: u32 CRC32 of everything before the footer + b"WTSEND\x00\x00"

One CRC covers header+payloads, so a torn or bit-flipped file is detected
at load; the atomic rename means a file with the final name is always
complete (reader double-checks via the footer magic + CRC anyway).
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import subprocess
import sys
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

_MAGIC = b"WTSCKPT1"
_FOOTER_MAGIC = b"WTSEND\x00\x00"

_here = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_here, "native", "ckptio.cc")
_LIB_PATH = os.path.join(_here, "native", "_ckptio.so")

_lib = None
_lib_tried = False


def _load_native():
    """Compile (once) and dlopen the native library; None if unavailable.

    Build failures are demoted to the Python fallback with a one-line
    stderr note - checkpointing must never be the thing that kills a run.
    """
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if not os.path.exists(_LIB_PATH) or (
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
        ):
            tmp = f"{_LIB_PATH}.build-{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-pthread", _SRC,
                 "-o", tmp],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, _LIB_PATH)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.ckpt_writer_open.argtypes = [ctypes.c_char_p]
        lib.ckpt_writer_open.restype = ctypes.c_void_p
        lib.ckpt_writer_write.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
        ]
        lib.ckpt_writer_write.restype = ctypes.c_int
        lib.ckpt_writer_finish.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.ckpt_writer_finish.restype = ctypes.c_int
        lib.ckpt_writer_abort.argtypes = [ctypes.c_void_p]
        lib.ckpt_writer_abort.restype = ctypes.c_int
        lib.ckpt_crc32.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64
        ]
        lib.ckpt_crc32.restype = ctypes.c_uint64
        _lib = lib
    except Exception as e:  # missing g++, sandboxed fs, bad toolchain, ...
        print(f"wavetpu: native ckpt IO unavailable ({e}); "
              f"using Python fallback", file=sys.stderr)
        _lib = None
    return _lib


def native_available() -> bool:
    return _load_native() is not None


def crc32(data, seed: int = 0) -> int:
    """zlib-compatible CRC32 (native slice-by-8 when available)."""
    lib = _load_native()
    mv = memoryview(data).cast("B")
    if lib is None or len(mv) == 0:
        return zlib.crc32(mv, seed) & 0xFFFFFFFF
    arr = np.frombuffer(mv, dtype=np.uint8)  # raw address, no copy
    return int(lib.ckpt_crc32(
        arr.ctypes.data_as(ctypes.c_void_p), len(mv), seed
    )) & 0xFFFFFFFF


class AsyncFileWriter:
    """Background-thread file writer with CRC32 and atomic rename.

    ZERO-COPY: every buffer passed to `write` must stay alive and
    unmodified until `finish`/`abort` returns (this class keeps Python
    references to enforce the lifetime half of that contract).  Falls back
    to synchronous Python IO when the native library is unavailable - the
    bytes on disk and the returned CRC are identical either way.
    """

    def __init__(self, final_path: str):
        self.final_path = final_path
        self.tmp_path = f"{final_path}.tmp-{os.getpid()}"
        self._bufs = []           # lifetime anchors for zero-copy chunks
        self._lib = _load_native()
        self._handle = None
        self._file = None
        self._crc = 0
        if self._lib is not None:
            self._handle = self._lib.ckpt_writer_open(
                self.tmp_path.encode()
            )
        if self._handle is None:
            self._lib = None
            self._file = open(self.tmp_path, "wb")

    def write(self, data) -> None:
        if self._lib is not None and self._handle is None:
            # finish()/abort() already ran; the native call would
            # dereference a NULL handle (SIGSEGV, not an exception).
            raise IOError("write after finish/abort")
        mv = memoryview(data).cast("B")
        if not mv.nbytes:
            return
        if self._lib is not None:
            # ctypes needs a raw address; a numpy view provides it without
            # copying (works for writable and read-only buffers alike).
            arr = np.frombuffer(mv, dtype=np.uint8)
            self._bufs.append(arr)  # lifetime anchor until finish/abort
            rc = self._lib.ckpt_writer_write(
                self._handle, arr.ctypes.data_as(ctypes.c_void_p), mv.nbytes
            )
            if rc != 0:
                raise IOError("ckpt_writer_write after close")
        else:
            self._file.write(mv)
            self._crc = zlib.crc32(mv, self._crc) & 0xFFFFFFFF

    def sync(self) -> int:
        """Drain and fsync the TEMP file (no rename); returns the stream
        CRC32.  The temp file stays on disk until `commit` renames it -
        callers that cross-check the CRC (finish_container) do so between
        the two phases, so a detected corruption can discard the temp file
        without having replaced the previous good file at `final_path`."""
        if self._lib is not None:
            crc = ctypes.c_uint64(0)
            # Renaming the temp file onto itself is a POSIX no-op, so the
            # native finish becomes drain+fsync+close with the temp kept.
            rc = self._lib.ckpt_writer_finish(
                self._handle, self.tmp_path.encode(), ctypes.byref(crc)
            )
            self._handle = None
            self._bufs.clear()
            if rc != 0:
                raise IOError(
                    f"native checkpoint write failed: errno {-rc} "
                    f"({os.strerror(-rc)})"
                )
            return int(crc.value) & 0xFFFFFFFF
        try:
            self._file.flush()
            os.fsync(self._file.fileno())
            self._file.close()
        except Exception:
            # Never leave the temp file behind.
            if not self._file.closed:
                self._file.close()
            if os.path.exists(self.tmp_path):
                os.remove(self.tmp_path)
            raise
        self._bufs.clear()
        return self._crc

    def commit(self) -> None:
        """Atomically rename the synced temp file to `final_path`."""
        try:
            os.replace(self.tmp_path, self.final_path)
        except Exception:
            # Keep the never-leave-a-temp-behind invariant on rename
            # failure (abort() is a no-op once sync() has closed the file).
            self.discard()
            raise

    def discard(self) -> None:
        """Remove the synced temp file (CRC cross-check failed)."""
        try:
            os.remove(self.tmp_path)
        except OSError:
            pass

    def finish(self) -> int:
        """Drain, fsync, atomically rename; returns the stream CRC32."""
        crc = self.sync()
        self.commit()
        return crc

    def abort(self) -> None:
        if self._lib is not None and self._handle is not None:
            self._lib.ckpt_writer_abort(self._handle)
            self._handle = None
        elif self._file is not None and not self._file.closed:
            self._file.close()
            if os.path.exists(self.tmp_path):
                os.remove(self.tmp_path)
        self._bufs.clear()


def write_container(
    path: str,
    arrays: Dict[str, Tuple[np.ndarray, str]],
    meta: Optional[dict] = None,
) -> "AsyncFileWriter":
    """Start writing a WTS1 container; returns the in-flight writer.

    `arrays` maps name -> (C-contiguous array, dtype tag); `meta` is small
    JSON-serializable data (e.g. the step index).  The caller overlaps
    further work with the disk write and completes the file with
    `finish_container` (or uses `write_container_sync`).  All chunks -
    including the CRC footer - are enqueued here; `finish_container` just
    drains, fsyncs, renames, and cross-checks the stream CRC.
    """
    entries = []
    payloads = []
    for name, (arr, tag) in arrays.items():
        arr = np.ascontiguousarray(arr)
        entries.append(dict(
            name=name, dtype=tag, shape=list(arr.shape),
            nbytes=int(arr.nbytes),
        ))
        payloads.append(arr)
    header = json.dumps(
        {"arrays": entries, "meta": meta or {}}, sort_keys=True
    ).encode()
    head = _MAGIC + struct.pack("<I", len(header)) + header

    w = AsyncFileWriter(path)
    try:
        # Enqueue everything first, THEN compute the footer CRC: the host
        # CRC pass runs concurrently with the writer thread's disk IO (the
        # thread computes its own stream CRC; finish cross-checks the two).
        w.write(head)
        for p in payloads:
            w.write(p)
        crc = crc32(head)
        for p in payloads:
            crc = crc32(p, crc)
        w.write(struct.pack("<I", crc) + _FOOTER_MAGIC)
    except Exception:
        w.abort()
        raise
    w._expected_crc = crc  # cross-checked in finish_container
    return w


def finish_container(w: "AsyncFileWriter") -> int:
    """Complete a `write_container` writer, verifying the stream CRC the
    writer thread computed against the host-side one BEFORE the rename.

    On a mismatch the temp file is discarded and the previous good file at
    the final name (if any) is left intact - a corrupt container never
    replaces a good shard."""
    stream_crc = w.sync()
    expected = crc32(
        struct.pack("<I", w._expected_crc) + _FOOTER_MAGIC, w._expected_crc
    )
    if stream_crc != expected:
        w.discard()
        raise IOError(
            f"checkpoint writer CRC mismatch on {w.final_path}: a buffer "
            f"was modified during the asynchronous write"
        )
    w.commit()
    return w._expected_crc


def write_container_sync(path, arrays, meta=None) -> int:
    return finish_container(write_container(path, arrays, meta))


def read_container(path: str, verify: bool = True):
    """Read a WTS1 container -> (dict name -> (array, dtype_tag), meta).

    With `verify`, the CRC footer is checked over the raw bytes - a torn
    or corrupted shard raises instead of resuming garbage.
    """
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < len(_MAGIC) + 4 + 12 or blob[:len(_MAGIC)] != _MAGIC:
        raise ValueError(f"{path}: not a WTS1 checkpoint container")
    if blob[-8:] != _FOOTER_MAGIC:
        raise ValueError(f"{path}: truncated checkpoint (no footer)")
    stored_crc = struct.unpack("<I", blob[-12:-8])[0]
    if verify:
        actual = crc32(memoryview(blob)[:-12])
        if actual != stored_crc:
            raise ValueError(
                f"{path}: checkpoint CRC mismatch "
                f"(stored {stored_crc:#010x}, actual {actual:#010x}) - "
                f"the file is corrupt; discard it"
            )
    hlen = struct.unpack("<I", blob[len(_MAGIC):len(_MAGIC) + 4])[0]
    hstart = len(_MAGIC) + 4
    payload_end = len(blob) - 12  # footer: u32 CRC + 8-byte magic
    # Structural bounds checks run even with verify=False (the documented
    # forensic mode): a malformed file must surface as this module's own
    # errors, not a raw json/numpy exception downstream.
    if hstart + hlen > payload_end:
        raise ValueError(
            f"{path}: truncated checkpoint (header length {hlen} exceeds "
            f"file payload)"
        )
    try:
        header = json.loads(blob[hstart:hstart + hlen].decode())
        entries = header["arrays"]
        meta = header["meta"]
        # Schema-check every field the loop below will access, so a
        # corrupt-but-parseable header also surfaces as this module's error.
        total = sum(int(e["nbytes"]) for e in entries)
        for e in entries:
            e["name"], list(e["shape"])
            if e["dtype"] != "bfloat16":
                np.dtype(e["dtype"])  # TypeError here, not in the loop
    except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
        raise ValueError(
            f"{path}: corrupt checkpoint header ({e})"
        ) from None
    if hstart + hlen + total != payload_end:
        raise ValueError(
            f"{path}: truncated checkpoint (arrays declare {total} payload "
            f"bytes, file carries {payload_end - hstart - hlen})"
        )
    out = {}
    off = hstart + hlen
    for e in entries:
        nbytes = int(e["nbytes"])
        dtype = (
            np.dtype(np.uint16) if e["dtype"] == "bfloat16"
            else np.dtype(e["dtype"])
        )
        try:
            arr = np.frombuffer(
                blob, dtype=dtype, count=nbytes // dtype.itemsize, offset=off
            ).reshape(e["shape"])
        except ValueError as err:
            raise ValueError(
                f"{path}: corrupt checkpoint array {e.get('name')!r} ({err})"
            ) from None
        off += nbytes
        out[e["name"]] = (arr, e["dtype"])
    return out, meta
