"""`wavetpu fleet roll` - zero-cold-compile rolling deploys.

Replace one fleet member with a successor WITHOUT paying a single
client-visible error or a single fresh XLA compile:

  1. Build a warmup manifest from the fleet's shared compile ledger
     (`--ledger DIR`, the telemetry dir every replica appends to; or
     hand one in with `--manifest FILE`) - the exact key set the fleet
     has ever compiled, in the shape `wavetpu serve --warmup-manifest`
     consumes.
  2. Spawn the successor (everything after `--` is its command line,
     e.g. `wavetpu serve --port 8078 --program-cache-dir /shared`)
     with `--warmup-manifest MANIFEST` appended, so it answers
     `ready: false` while it pre-adopts every program - from the
     SHARED persistent program cache where possible (disk adoption,
     not compilation: `--max-cold-compiles 0` stays green).
  3. Wait for the successor's /healthz to flip ready.
  4. Join it to the router (`POST /admin/join`) and wait until the
     router reports it `up` - the fleet now has N+1 serving members,
     every warm key still has a live holder.
  5. Leave the predecessor (`POST /admin/leave`): the router drains it
     (503 + Retry-After absorbed by the router's own member retry),
     snapshots its final counters (frozen into the fleet /metrics
     aggregate - loadgen deltas across the roll stay monotonic), and
     retires it.  With `--solve-state-dir` shared across replicas, the
     drain CHECKPOINTS any in-flight chunked long solve and answers a
     503 + resume_token; the router re-injects the token on its member
     retry, so the successor resumes the march from the last completed
     chunk - the roll hands half-done solves over instead of burning
     them (docs/robustness.md "Preemptible solves").  The driver reads
     the router's `resume_handoffs_total` across the cutover and logs
     how many solves were handed off.

Usage:

    wavetpu fleet roll --router URL --old URL --new URL
        (--ledger DIR | --manifest FILE) [--timeout-s S]
        [--no-spawn] -- SUCCESSOR ARGV...

`--no-spawn` skips step 2 (the successor is already running - e.g. a
container orchestrator started it); steps 3-5 still gate and cut over.
Exit codes: 0 rolled; 1 the roll FAILED SAFE (successor never became
ready / never joined - the predecessor keeps serving untouched);
2 usage errors.

Stdlib-only; never imports jax.  Runbook: docs/fleet.md.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

from wavetpu.core.flags import split_flags

_USAGE = (
    "usage: wavetpu fleet roll --router URL --old URL --new URL "
    "(--ledger DIR | --manifest FILE) [--timeout-s S] [--no-spawn] "
    "-- SUCCESSOR ARGV..."
)


def _get_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _post_json(url: str, body: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode())


def build_manifest(ledger_dir: str, out_path: Optional[str] = None
                   ) -> str:
    """Ledger dir (or file) -> warmup manifest file; returns its path.
    An empty ledger still writes a valid zero-key manifest (a brand-new
    fleet has nothing to warm - the roll proceeds, trivially)."""
    from wavetpu.obs import ledger as ledger_mod

    path = ledger_mod.resolve_ledger_path(ledger_dir)
    records = ledger_mod.load_ledger(path) if os.path.exists(path) else []
    manifest = ledger_mod.warmup_manifest(records)
    if out_path is None:
        fd, out_path = tempfile.mkstemp(
            prefix="wavetpu-roll-manifest-", suffix=".json"
        )
        os.close(fd)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    return out_path


def _router_handoffs(router_url: str) -> int:
    """The router's resume_handoffs_total counter (0 when unreadable -
    the handoff log line is best-effort, never a roll failure)."""
    try:
        snap = _get_json(router_url.rstrip("/") + "/metrics",
                         timeout=5.0)
        return int(snap.get("resume_handoffs_total", 0))
    except (OSError, ValueError, urllib.error.URLError):
        return 0


def wait_ready(base_url: str, timeout_s: float,
               interval_s: float = 0.25) -> bool:
    """Poll /healthz until ready (True) or the budget is gone (False).
    Transport errors are just 'not yet' - the successor may still be
    binding its port."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            health = _get_json(base_url.rstrip("/") + "/healthz",
                               timeout=5.0)
            if (health.get("status") == "ok"
                    and health.get("ready") is not False):
                return True
        except (OSError, ValueError, urllib.error.URLError):
            pass
        time.sleep(interval_s)
    return False


def wait_member_state(router_url: str, member_url: str, state: str,
                      timeout_s: float, interval_s: float = 0.25
                      ) -> bool:
    """Poll the router's /healthz member summary until `member_url`
    reports `state`."""
    member_url = member_url.rstrip("/")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            health = _get_json(router_url.rstrip("/") + "/healthz",
                               timeout=5.0)
            for m in health.get("members", ()):
                if m.get("url") == member_url and m.get("state") == state:
                    return True
        except (OSError, ValueError, urllib.error.URLError):
            pass
        time.sleep(interval_s)
    return False


def roll(router_url: str, old_url: str, new_url: str,
         spawn_argv: Optional[Sequence[str]] = None,
         manifest_path: Optional[str] = None,
         timeout_s: float = 300.0,
         leave_sync: bool = False,
         log=print) -> int:
    """The deploy sequence (module docstring).  Returns an exit code;
    fails SAFE - the predecessor is only drained AFTER the successor is
    ready and routed."""
    # HA guard: admin mutations against a STANDBY router land in state
    # the next promotion overwrites from the control-plane store - the
    # join would silently vanish.  Fail before touching anything.
    try:
        router_health = _get_json(router_url.rstrip("/") + "/healthz")
    except (OSError, ValueError, urllib.error.URLError) as e:
        log(f"roll: FAILED - cannot reach router {router_url}: {e}",
            file=sys.stderr)
        return 1
    if router_health.get("role") == "standby":
        log(f"roll: FAILED - {router_url} is a STANDBY router (not the "
            f"lease holder); a join/leave there would be overwritten "
            f"on promotion.  Point --router at the active.",
            file=sys.stderr)
        return 1
    proc = None
    if spawn_argv:
        argv = list(spawn_argv)
        if manifest_path is not None:
            argv += ["--warmup-manifest", manifest_path]
        log(f"roll: spawning successor: {' '.join(argv)}")
        proc = subprocess.Popen(argv)
    try:
        log(f"roll: waiting for {new_url} to become ready "
            f"(warmup runs now, budget {timeout_s:g}s)")
        if not wait_ready(new_url, timeout_s):
            log(f"roll: FAILED - {new_url} never became ready; "
                f"predecessor untouched", file=sys.stderr)
            if proc is not None:
                proc.terminate()
            return 1
        log(f"roll: joining {new_url} to router {router_url}")
        _post_json(router_url.rstrip("/") + "/admin/join",
                   {"url": new_url})
        if not wait_member_state(router_url, new_url, "up", timeout_s):
            log(f"roll: FAILED - router never admitted {new_url}; "
                f"predecessor untouched", file=sys.stderr)
            return 1
        log(f"roll: draining + retiring predecessor {old_url}")
        handoffs_before = _router_handoffs(router_url)
        _post_json(router_url.rstrip("/") + "/admin/leave",
                   {"url": old_url, "drain": True, "sync": leave_sync})
        if not wait_member_state(router_url, old_url, "left",
                                 timeout_s):
            log(f"roll: WARNING - {old_url} did not reach 'left' in "
                f"{timeout_s:g}s (drain may still be flushing)",
                file=sys.stderr)
        handed = _router_handoffs(router_url) - handoffs_before
        if handed > 0:
            log(f"roll: {handed} in-flight long solve(s) handed off "
                f"to the successor via resume tokens")
        log(f"roll: done - {new_url} serving, {old_url} retired")
        return 0
    except (OSError, urllib.error.URLError) as e:
        log(f"roll: FAILED - {e}", file=sys.stderr)
        if proc is not None:
            proc.terminate()
        return 1


def _log(msg, file=None):
    print(msg, file=file or sys.stdout, flush=True)


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    spawn_argv: Optional[Sequence[str]] = None
    if "--" in argv:
        cut = argv.index("--")
        argv, spawn_argv = argv[:cut], argv[cut + 1:]
    try:
        _, flags = split_flags(
            argv,
            known=("router", "old", "new", "ledger", "manifest",
                   "timeout-s", "no-spawn"),
            valueless=("no-spawn",),
            allow_positionals=False,
        )
        for need in ("router", "old", "new"):
            if need not in flags:
                raise ValueError(f"fleet roll needs --{need} URL")
        if ("ledger" in flags) == ("manifest" in flags):
            raise ValueError(
                "fleet roll needs exactly one of --ledger DIR / "
                "--manifest FILE"
            )
        timeout_s = float(flags.get("timeout-s", "300"))
        if "no-spawn" in flags:
            if spawn_argv:
                raise ValueError("--no-spawn and a `-- ARGV` conflict")
            spawn_argv = None
        elif not spawn_argv:
            raise ValueError(
                "missing successor command after `--` "
                "(or pass --no-spawn for an already-running successor)"
            )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2
    manifest_path = flags.get("manifest")
    if manifest_path is None:
        manifest_path = build_manifest(flags["ledger"])
        with open(manifest_path, encoding="utf-8") as f:
            n_keys = len(json.load(f).get("keys", []))
        print(f"roll: warmup manifest from {flags['ledger']}: "
              f"{n_keys} key(s) -> {manifest_path}")
    return roll(
        flags["router"], flags["old"], flags["new"],
        spawn_argv=spawn_argv, manifest_path=manifest_path,
        timeout_s=timeout_s, log=_log,
    )


if __name__ == "__main__":
    sys.exit(main())
