"""Crash-safe control-plane store for the router tier.

Everything the router knows that is not re-derivable from a poll -
quota-bucket levels, per-member affinity tables, membership
state-machine positions (including LEFT members' frozen Prometheus
snapshots and mid-flight joiners' baselines), last-observed brownout
rungs, and the router's own monotonic counters - lives in one process
today, so a router crash loses it: quotas reopen full (a restart is a
free flood), fleet /metrics deltas go backwards, and N routers behind
an L4 balancer each admit the full per-tenant limit.  This module is
the durable home for that state, shared by every `wavetpu router
--control-plane-dir DIR` pointed at the same directory.

Layout (all under the control-plane dir):

    snapshot.json   the last compacted full state - atomic tmp +
                    `os.replace` write with a whole-payload sha256 in
                    the header, the progcache/checkpoint discipline
    wal.jsonl       append-only JSONL records SINCE the snapshot; each
                    line carries `{"seq", "section", "data", "sha"}`
                    with a per-line sha256 over the canonical record
    lease.json /    single-writer lease + its mutation lock
    lease.lock      (fleet/ha.py owns these; listed for the runbook)

`load()` is snapshot-base + WAL-replay, latest-seq-wins per section.
Corruption anywhere - a flipped byte, a torn tail from a killed
writer, a snapshot that fails its checksum - is a COUNTED recoverable
miss (`corrupt_lines_total` / `corrupt_snapshots_total`), never a
crash: the store degrades to whatever prefix still verifies, exactly
like a progcache miss degrades to a recompile.  `compact()` folds the
WAL into a fresh snapshot and truncates it, bounding replay time.

Stdlib-only; NEVER imports jax (this module runs in router processes
on hosts with no accelerator stack).  Contract and failover runbook:
docs/fleet.md "Control plane & router HA".
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Dict, Optional

SNAPSHOT_NAME = "snapshot.json"
WAL_NAME = "wal.jsonl"
SNAPSHOT_MAGIC = "wavetpu-control-plane-v1"


def _canonical(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


def _record_sha(seq: int, section: str, data) -> str:
    return hashlib.sha256(
        _canonical({"seq": seq, "section": section, "data": data})
    ).hexdigest()[:16]


class ControlPlaneStore:
    """One router's handle on the shared durable state.

    Thread-safe; every instance keeps its own miss/append counters
    (exposed by the router as `wavetpu_store_*` samples - a corruption
    that recovered silently would make the chaos drills unfalsifiable).
    `fault_plan` is the optional WAVETPU_FAULT router plan
    (run/faults.py `router_plan_from_env`): a `store-corrupt` injection
    truncates the WAL tail just before a load, driving the real
    per-line checksum rejection branch."""

    def __init__(self, root: str, fault_plan=None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.snapshot_path = os.path.join(root, SNAPSHOT_NAME)
        self.wal_path = os.path.join(root, WAL_NAME)
        self.fault_plan = fault_plan
        self._lock = threading.Lock()
        self._seq = 0
        # wavetpu_store_* counter sources (see prom_samples()).
        self.appends_total = 0
        self.compactions_total = 0
        self.loads_total = 0
        self.corrupt_lines_total = 0
        self.corrupt_snapshots_total = 0

    # ---- write path ----

    def append(self, section: str, data: dict) -> int:
        """Append one section's latest state to the WAL (flushed, not
        fsynced - the flusher cadence bounds loss to one interval, the
        per-line checksum bounds a torn tail to one skipped record).
        Returns the record's sequence number."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            rec = {
                "seq": seq,
                "section": section,
                "data": data,
                "sha": _record_sha(seq, section, data),
            }
            with open(self.wal_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
                f.flush()
            self.appends_total += 1
        return seq

    def compact(self, state: Dict[str, dict]) -> None:
        """Fold `state` (the full current section map) into a fresh
        snapshot - tmp + os.replace so a crash mid-write leaves the old
        snapshot intact - then truncate the WAL it supersedes."""
        payload = {
            "magic": SNAPSHOT_MAGIC,
            "seq": self._seq,
            "state": state,
            "sha": hashlib.sha256(_canonical(state)).hexdigest(),
        }
        with self._lock:
            tmp = self.snapshot_path + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snapshot_path)
            with open(self.wal_path, "w", encoding="utf-8"):
                pass  # truncate: the snapshot now owns this history
            self.compactions_total += 1

    # ---- read path ----

    def _load_snapshot(self) -> Dict[str, dict]:
        """The checksummed snapshot base, or {} (missing/corrupt - a
        counted miss; the WAL replay may still recover newer state)."""
        try:
            with open(self.snapshot_path, encoding="utf-8") as f:
                payload = json.load(f)
        except FileNotFoundError:
            return {}
        except (OSError, ValueError):
            self.corrupt_snapshots_total += 1
            return {}
        state = payload.get("state")
        if (
            payload.get("magic") != SNAPSHOT_MAGIC
            or not isinstance(state, dict)
            or payload.get("sha")
            != hashlib.sha256(_canonical(state)).hexdigest()
        ):
            self.corrupt_snapshots_total += 1
            return {}
        try:
            self._seq = max(self._seq, int(payload.get("seq") or 0))
        except (TypeError, ValueError):
            pass
        return state

    def load(self) -> Dict[str, dict]:
        """Snapshot base + WAL replay, latest-wins per section.  Every
        line that fails to parse or verify is counted and SKIPPED (a
        torn tail from a killed writer costs its last record, nothing
        else); the store never raises on corruption."""
        if self.fault_plan is not None \
                and self.fault_plan.fire("store-corrupt") is not None:
            self._corrupt_wal_tail()
        with self._lock:
            self.loads_total += 1
            state = self._load_snapshot()
            try:
                with open(self.wal_path, encoding="utf-8") as f:
                    lines = f.readlines()
            except FileNotFoundError:
                lines = []
            except OSError:
                self.corrupt_lines_total += 1
                lines = []
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    seq = int(rec["seq"])
                    section = rec["section"]
                    data = rec["data"]
                    if rec["sha"] != _record_sha(seq, section, data):
                        raise ValueError("checksum mismatch")
                except (ValueError, KeyError, TypeError):
                    self.corrupt_lines_total += 1
                    continue
                state[section] = data
                self._seq = max(self._seq, seq)
            return state

    def _corrupt_wal_tail(self) -> None:
        """The store-corrupt chaos injection: chop bytes off the WAL
        (or, with no WAL yet, flip a snapshot byte) so the NEXT load
        exercises the real rejection branch."""
        try:
            if os.path.getsize(self.wal_path) > 0:
                with open(self.wal_path, "r+b") as f:
                    f.truncate(max(0, os.path.getsize(self.wal_path) - 9))
                return
        except OSError:
            pass
        try:
            size = os.path.getsize(self.snapshot_path)
            with open(self.snapshot_path, "r+b") as f:
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([b[0] ^ 0x01]))
        except OSError:
            pass

    # ---- observability ----

    def prom_samples(self) -> Dict[str, float]:
        """The store's Prometheus samples, merged into the router's
        own block (docs/observability.md catalogs each)."""
        return {
            "wavetpu_store_appends_total": self.appends_total,
            "wavetpu_store_compactions_total": self.compactions_total,
            "wavetpu_store_loads_total": self.loads_total,
            "wavetpu_store_corrupt_lines_total": self.corrupt_lines_total,
            "wavetpu_store_corrupt_snapshots_total":
                self.corrupt_snapshots_total,
        }

    def snapshot_counters(self) -> dict:
        return {
            "appends": self.appends_total,
            "compactions": self.compactions_total,
            "loads": self.loads_total,
            "corrupt_lines": self.corrupt_lines_total,
            "corrupt_snapshots": self.corrupt_snapshots_total,
        }
