"""The router's warm-key table: which replica already compiled what.

An XLA compile is seconds; a warm batched solve is milliseconds.  The
single highest-leverage routing decision in a wavetpu fleet is landing
a request where its program is ALREADY compiled, so this table maps
affinity keys (`wavetpu.progkey.AFFINITY_FIELDS` - the program identity
minus the server-chosen batch bucket and server-config flags) to the
set of member urls known to hold them, learned from two sources:

 * **Polls**: each membership poll reads the replica's /metrics
   `program_cache.warm_keys` block (memory LRU + disk `.wtpc` entries)
   and REPLACES that member's warm set - the authoritative bootstrap,
   and how a restarted-on-a-shared-cache replica advertises its disk
   inheritance before serving a single request.
 * **Responses**: every proxied /solve response's `Server-Timing:
   warm;desc=` label updates the table at traffic speed - `true`
   (memory hit), `disk` (adopted from the persistent cache), and
   `false` (it JUST paid the compile - warm from now on) all mark the
   serving member a holder; `fallback` marks nothing (no batched
   program was built).

Routing (`choose`): warm holders win; among several holders (or for a
cold key) the least-loaded of TWO RANDOM CHOICES takes it - the
power-of-two-choices bound on max load without a global scan, using
router-side inflight + last-polled queue depth as the load signal.
Decisions are counted (hits / rerouted / cold) and exposed at the
router's /metrics; `hit_rate = hits / (hits + rerouted)` is the
acceptance-drill number (how often a warm-keyed request actually
landed on a holder).

Stdlib-only, thread-safe, no jax.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Dict, Optional, Sequence, Set

from wavetpu.progkey import warm_keys_to_affinity

# Server-Timing warm labels that prove the serving member now holds the
# compiled program (see ServeEngine batch_info["warm"]).
_HOLDER_LABELS = ("true", "disk", "false")


class AffinityTable:
    """affinity key -> set of member urls holding the program."""

    def __init__(self, rng: Optional[random.Random] = None):
        self._lock = threading.Lock()
        self._holders: Dict[str, Set[str]] = {}
        self._rng = rng if rng is not None else random.Random()
        # Routing decision counters (monotonic).
        self.hits = 0         # warm key routed onto a holder
        self.rerouted = 0     # warm key, but no routable holder
        self.cold = 0         # key nobody holds yet
        self.unkeyed = 0      # body did not parse to an identity

    # ---- learning ----

    def observe_warm_keys(self, member_url: str, warm_keys: dict) -> int:
        """Poll-driven REPLACE of one member's warm set from its
        /metrics warm_keys block; returns how many keys it holds."""
        member_url = member_url.rstrip("/")
        keys = warm_keys_to_affinity(warm_keys)
        with self._lock:
            for holders in self._holders.values():
                holders.discard(member_url)
            for ak in keys:
                self._holders.setdefault(ak, set()).add(member_url)
            self._gc()
        return len(keys)

    def observe_response(self, member_url: str, affinity_key: str,
                         warm_label: Optional[str]) -> None:
        """Response-driven ADD: the member served (or just compiled)
        this key, so it holds the program now."""
        if not affinity_key or warm_label not in _HOLDER_LABELS:
            return
        with self._lock:
            self._holders.setdefault(
                affinity_key, set()
            ).add(member_url.rstrip("/"))

    def forget_member(self, member_url: str) -> None:
        member_url = member_url.rstrip("/")
        with self._lock:
            for holders in self._holders.values():
                holders.discard(member_url)
            self._gc()

    def _gc(self) -> None:
        # under self._lock
        for ak in [k for k, v in self._holders.items() if not v]:
            del self._holders[ak]

    # ---- views ----

    def holders(self, affinity_key: str) -> Set[str]:
        with self._lock:
            return set(self._holders.get(affinity_key, ()))

    def known_keys(self) -> int:
        with self._lock:
            return len(self._holders)

    # ---- persistence (fleet/store.py) ----

    def export_state(self) -> dict:
        """Durable view: the holder table plus the decision counters
        (restored so affinity hit rates stay monotonic across a router
        restart/failover)."""
        with self._lock:
            return {
                "holders": {
                    ak: sorted(urls)
                    for ak, urls in self._holders.items()
                },
                "hits": self.hits,
                "rerouted": self.rerouted,
                "cold": self.cold,
                "unkeyed": self.unkeyed,
            }

    def restore_state(self, data: dict) -> int:
        """UNION-merge persisted holders into the live table (the
        successor may already have fresher poll data - never discard
        it) and max-merge the counters.  Returns keys adopted."""
        if not isinstance(data, dict):
            return 0
        holders = data.get("holders")
        adopted = 0
        with self._lock:
            if isinstance(holders, dict):
                for ak, urls in holders.items():
                    if not isinstance(urls, (list, tuple)):
                        continue
                    self._holders.setdefault(ak, set()).update(
                        str(u).rstrip("/") for u in urls
                    )
                    adopted += 1
            for field in ("hits", "rerouted", "cold", "unkeyed"):
                try:
                    v = int(data.get(field) or 0)
                except (TypeError, ValueError):
                    continue
                setattr(self, field, max(getattr(self, field), v))
        return adopted

    def stats(self) -> dict:
        with self._lock:
            routed = self.hits + self.rerouted
            return {
                "known_keys": len(self._holders),
                "hits": self.hits,
                "rerouted": self.rerouted,
                "cold": self.cold,
                "unkeyed": self.unkeyed,
                "hit_rate": (
                    round(self.hits / routed, 4) if routed else None
                ),
            }

    # ---- routing ----

    def _load(self, url: str, load: Callable[[str], float]) -> float:
        try:
            return float(load(url))
        except Exception:
            return 0.0

    def _p2c(self, candidates: Sequence[str],
             load: Callable[[str], float]) -> str:
        """Least-loaded of two random choices (the whole list when it
        is that short)."""
        if len(candidates) == 1:
            return candidates[0]
        pair = self._rng.sample(list(candidates), 2)
        return min(pair, key=lambda u: self._load(u, load))

    def choose(self, affinity_key: Optional[str],
               candidates: Sequence[str],
               load: Callable[[str], float]) -> str:
        """Pick the member for one request.  `candidates` is the
        routable-url list (non-empty - the router 503s before calling
        with an empty rotation); `load(url)` returns the comparable
        load figure (inflight + queue depth).  Counts the decision."""
        candidates = [c.rstrip("/") for c in candidates]
        if not candidates:
            raise ValueError("choose() needs at least one candidate")
        if affinity_key is None:
            with self._lock:
                self.unkeyed += 1
            return self._p2c(candidates, load)
        with self._lock:
            holders = self._holders.get(affinity_key, set())
            live_holders = [c for c in candidates if c in holders]
            if live_holders:
                self.hits += 1
            elif holders:
                self.rerouted += 1
            else:
                self.cold += 1
        if live_holders:
            return self._p2c(live_holders, load)
        return self._p2c(candidates, load)


def warm_label_from_server_timing(header: Optional[str]) -> Optional[str]:
    """Extract the `warm;desc=LABEL` entry from a Server-Timing header
    (None when absent/unparseable - e.g. --no-server-timing replicas,
    whose affinity then learns from polls alone)."""
    if not header:
        return None
    for part in header.split(","):
        name, _, params = part.strip().partition(";")
        if name.strip() != "warm":
            continue
        for p in params.split(";"):
            k, _, v = p.strip().partition("=")
            if k == "desc":
                return v.strip() or None
    return None
