"""Per-tenant quotas for the router tier: token buckets + tenant QoS
config parsed from the --api-keys-file schema.

The router is the AUTHORITATIVE quota point (replicas keep only a
defensive per-tenant in-flight cap - serve/api.py): every authenticated
/solve spends from its tenant's two token buckets BEFORE routing:

 * requests/s  - each request costs 1 token.  Caps call rate.
 * cells/s     - each request costs its MODEL-PRICED cell volume:
   `cells_per_step x timesteps`, weighted by the request path's HBM
   bytes-per-cell from the shared cost model (obs/perf.py
   `model_bytes_per_cell`, normalized to the roll stencil's baseline),
   so one giant fused solve spends proportionally more than a hundred
   tiny ones and a cheap path spends less than an expensive one.

Exhausting EITHER bucket answers 429 with `Retry-After` set to the
MEASURED refill time - `(cost - tokens) / rate` - not a constant: the
client (WavetpuClient honors Retry-After over its own backoff) returns
exactly when the bucket can afford the request again.

Priority-class policy also lives in the tenant config: each tenant has
a default class (applied when a request declares none) and a CEILING
(the highest class its requests may claim; the router clamps and stamps
`X-Priority`, stripping the inbound header like it strips tenant
claims, so a tenant can never self-promote past its contract).

Stdlib-only; NEVER imports jax (this module runs in the router
process).  The class ladder here must stay identical to
serve/scheduler.py's - tests/test_qos.py pins the two tuples equal.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from wavetpu.obs.perf import model_bytes_per_cell

# Highest-to-lowest, identical to serve/scheduler.py PRIORITY_CLASSES
# (pinned by tests; duplicated because the router must not import the
# jax-transitively-loaded serve package).
PRIORITY_CLASSES = ("interactive", "batch", "best_effort")
DEFAULT_PRIORITY = "batch"

# cells/s pricing is normalized so the roll stencil costs exactly its
# geometric cell count: weight = model_bytes_per_cell(path) / this.
_BASELINE_BYTES_PER_CELL = model_bytes_per_cell("roll") or 12.0


def normalize_priority(value, default: str = DEFAULT_PRIORITY) -> str:
    """Lenient class parse (same contract as the scheduler's): strip +
    lower; anything unknown (None, junk, empty) maps to `default`, so a
    bad label degrades to policy rather than erroring a request."""
    if isinstance(value, str):
        v = value.strip().lower()
        if v in PRIORITY_CLASSES:
            return v
    return default


def clamp_priority(requested: str, ceiling: str) -> str:
    """The effective class: `requested` demoted to `ceiling` when it
    outranks it (lower index = higher class).  Both args must already
    be normalized class names."""
    if PRIORITY_CLASSES.index(requested) < PRIORITY_CLASSES.index(ceiling):
        return ceiling
    return requested


@dataclass
class TenantConfig:
    """One tenant's QoS contract from the --api-keys-file schema.

    `priority` is the default class stamped when a request declares
    none; `priority_ceiling` the highest class it may claim.  The four
    quota fields are all optional - None means "no limit on this axis"
    (a plain-string api-keys entry gets all-None: the historical
    identity-only behavior, bit-for-bit)."""

    tenant: str
    priority: str = DEFAULT_PRIORITY
    priority_ceiling: str = PRIORITY_CLASSES[0]  # interactive = no cap
    rps: Optional[float] = None
    burst: Optional[float] = None
    cells_per_s: Optional[float] = None
    cells_burst: Optional[float] = None

    def effective_priority(self, requested: Optional[str]) -> str:
        """Default-then-clamp: the class the router stamps forward."""
        if requested is None:
            return clamp_priority(self.priority, self.priority_ceiling)
        return clamp_priority(
            normalize_priority(requested, default=self.priority),
            self.priority_ceiling,
        )


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill toward a `burst`
    cap.  `try_take(cost)` either spends and returns (True, 0.0) or
    leaves the bucket untouched and returns (False, retry_after_s) with
    the measured time until `cost` tokens exist - the 429's
    Retry-After.  Thread-safe; monotonic clock."""

    def __init__(self, rate: float, burst: Optional[float] = None):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else float(rate)
        if self.burst <= 0:
            raise ValueError(f"burst must be > 0, got {self.burst}")
        self._tokens = self.burst  # start full: first burst is free
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:
        self._tokens = min(
            self.burst, self._tokens + (now - self._t) * self.rate
        )
        self._t = now

    def try_take(self, cost: float = 1.0) -> Tuple[bool, float]:
        now = time.monotonic()
        with self._lock:
            self._refill_locked(now)
            if self._tokens >= cost:
                self._tokens -= cost
                return True, 0.0
            return False, (cost - self._tokens) / self.rate

    def refund(self, cost: float) -> None:
        """Return `cost` tokens (capped at burst).  Used when a spend
        turns out to have priced work that never happened - a /solve
        the replica answered from its result cache or coalesced onto an
        in-flight march costs near-zero cells, not the analytic model's
        full volume."""
        if cost <= 0:
            return
        now = time.monotonic()
        with self._lock:
            self._refill_locked(now)
            self._tokens = min(self.burst, self._tokens + cost)

    def tokens(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._refill_locked(now)
            return self._tokens

    # ---- persistence (fleet/store.py) ----

    def export_state(self) -> dict:
        """Durable view of this bucket.  The internal clock is
        monotonic (meaningless across processes), so the export pairs
        the refreshed level with a UNIX stamp; restore refills for the
        wall time that elapsed in between - a restarted router neither
        reopens a drained bucket nor double-charges the downtime."""
        return {
            "rate": self.rate,
            "burst": self.burst,
            "tokens": round(self.tokens(), 6),
            "unix": round(time.time(), 6),
        }

    @classmethod
    def restore(cls, data: dict) -> "TokenBucket":
        """A bucket rebuilt from `export_state` output, refilled for
        the wall time since export.  ValueError/KeyError on a
        malformed record (callers treat that as a counted miss)."""
        b = cls(float(data["rate"]), float(data["burst"]))
        elapsed = max(0.0, time.time() - float(data["unix"]))
        b._tokens = min(
            b.burst, float(data["tokens"]) + elapsed * b.rate
        )
        b._t = time.monotonic()
        return b


def price_cells(body: Optional[dict]) -> float:
    """Model-priced cell volume of a /solve body: geometric cell
    updates (`(N+1)^3 x timesteps`, the BASELINE.md throughput
    definition) weighted by the path's HBM traffic relative to the roll
    stencil.  Unparseable bodies price 0 (the replica 400s them; they
    never reach a scheduler slot, so they spend only the rps bucket)."""
    if not isinstance(body, dict):
        return 0.0
    try:
        n = int(body.get("N", 0))
        timesteps = int(body.get("timesteps", 20))
        if n <= 0 or timesteps <= 0:
            return 0.0
        cells = float((n + 1) ** 3 * timesteps)
    except (ValueError, TypeError):
        return 0.0
    path = body.get("path") or body.get("kernel") or "roll"
    try:
        bpc = model_bytes_per_cell(
            str(path), k=int(body.get("k", 1) or 1)
        )
    except (ValueError, TypeError):
        bpc = None
    weight = (bpc / _BASELINE_BYTES_PER_CELL) if bpc else 1.0
    return cells * weight


class QuotaManager:
    """Per-tenant bucket pairs, lazily built from TenantConfig (plus
    router-wide defaults for tenants whose config leaves an axis
    unset).  `admit(cfg, cells)` spends both buckets atomically-enough:
    the rps bucket first (cheap), then cells - on a cells refusal the
    rps token is NOT refunded (the request did arrive; refunding would
    let a flood of oversized requests probe for free)."""

    def __init__(self, default_rps: Optional[float] = None,
                 default_burst: Optional[float] = None,
                 default_cells_per_s: Optional[float] = None,
                 default_cells_burst: Optional[float] = None):
        self.default_rps = default_rps
        self.default_burst = default_burst
        self.default_cells_per_s = default_cells_per_s
        self.default_cells_burst = default_cells_burst
        self._lock = threading.Lock()
        self._rps: Dict[str, TokenBucket] = {}
        self._cells: Dict[str, TokenBucket] = {}
        self.rejected_per_tenant: Dict[str, int] = {}

    @property
    def enforces_anything(self) -> bool:
        return any(v is not None for v in (
            self.default_rps, self.default_cells_per_s,
        ))

    def _bucket(self, pool: Dict[str, TokenBucket], tenant: str,
                rate: Optional[float],
                burst: Optional[float]) -> Optional[TokenBucket]:
        if rate is None:
            return None
        b = pool.get(tenant)
        if b is None:
            b = TokenBucket(rate, burst if burst is not None else rate)
            pool[tenant] = b
        return b

    def admit(self, cfg: TenantConfig,
              cells: float) -> Tuple[bool, float]:
        """(admitted, retry_after_s).  retry_after_s is the measured
        refill wait of whichever bucket refused (0.0 on admit)."""
        with self._lock:
            rps = self._bucket(
                self._rps, cfg.tenant,
                cfg.rps if cfg.rps is not None else self.default_rps,
                cfg.burst if cfg.burst is not None else self.default_burst,
            )
            cb = self._bucket(
                self._cells, cfg.tenant,
                cfg.cells_per_s if cfg.cells_per_s is not None
                else self.default_cells_per_s,
                cfg.cells_burst if cfg.cells_burst is not None
                else self.default_cells_burst,
            )
        if rps is not None:
            ok, retry = rps.try_take(1.0)
            if not ok:
                self._note_rejected(cfg.tenant)
                return False, retry
        if cb is not None and cells > 0:
            # A request larger than the burst can NEVER pass; answer
            # with one full-bucket refill rather than a precise-but-
            # unreachable wait (the client would retry forever).
            cost = min(cells, cb.burst)
            ok, retry = cb.try_take(cost)
            if not ok:
                self._note_rejected(cfg.tenant)
                return False, retry
        return True, 0.0

    def refund_cells(self, tenant: str, cells: float) -> None:
        """Return model-priced cells to a tenant's bucket after the
        fleet learned the request was answered WITHOUT marching (result
        -cache hit or singleflight ride): the tenant keeps paying the
        1-token request rate - every request is individually charged -
        but the cells price collapses to the measured near-zero cost of
        a cache lookup.  No-op for tenants with no cells bucket."""
        if cells <= 0:
            return
        with self._lock:
            cb = self._cells.get(tenant)
        if cb is not None:
            cb.refund(min(cells, cb.burst))

    def _note_rejected(self, tenant: str) -> None:
        with self._lock:
            self.rejected_per_tenant[tenant] = (
                self.rejected_per_tenant.get(tenant, 0) + 1
            )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "quota_rejected_per_tenant":
                    dict(self.rejected_per_tenant),
            }

    def levels(self) -> Dict[str, dict]:
        """Live per-tenant bucket levels (the /metrics `quota_buckets`
        block - what the failover-parity drill compares)."""
        with self._lock:
            tenants = set(self._rps) | set(self._cells)
            out: Dict[str, dict] = {}
            for t in sorted(tenants):
                row: Dict[str, float] = {}
                if t in self._rps:
                    row["rps_tokens"] = round(self._rps[t].tokens(), 4)
                if t in self._cells:
                    row["cells_tokens"] = round(
                        self._cells[t].tokens(), 4
                    )
                out[t] = row
            return out

    # ---- persistence (fleet/store.py) ----

    def export_state(self) -> dict:
        """Everything a successor router needs to RESUME enforcement:
        each tenant's bucket levels (with rate/burst/unix, so restore
        can refill for downtime) plus the rejection counters."""
        with self._lock:
            return {
                "rps": {
                    t: b.export_state() for t, b in self._rps.items()
                },
                "cells": {
                    t: b.export_state() for t, b in self._cells.items()
                },
                "rejected_per_tenant": dict(self.rejected_per_tenant),
            }

    def restore_state(self, data: dict) -> int:
        """Adopt persisted bucket levels (malformed per-bucket records
        are skipped - a corrupt entry costs ONE tenant one fresh
        bucket, never the restore).  Rejection counters restore as a
        max-merge so they stay monotonic.  Returns buckets adopted."""
        if not isinstance(data, dict):
            return 0
        adopted = 0
        for field, pool in (("rps", self._rps), ("cells", self._cells)):
            entries = data.get(field)
            if not isinstance(entries, dict):
                continue
            for tenant, rec in entries.items():
                try:
                    bucket = TokenBucket.restore(rec)
                except (KeyError, TypeError, ValueError):
                    continue
                with self._lock:
                    pool[tenant] = bucket
                adopted += 1
        rejected = data.get("rejected_per_tenant")
        if isinstance(rejected, dict):
            with self._lock:
                for tenant, n in rejected.items():
                    try:
                        n = int(n)
                    except (TypeError, ValueError):
                        continue
                    self.rejected_per_tenant[tenant] = max(
                        self.rejected_per_tenant.get(tenant, 0), n
                    )
        return adopted


def parse_tenant_entry(key: str, value) -> TenantConfig:
    """One --api-keys-file entry -> TenantConfig.  A plain string is
    the PR-12 schema (identity only, no quotas, default classes); an
    object grows the QoS fields.  ValueError on anything else."""
    if isinstance(value, str) and value:
        return TenantConfig(tenant=value)
    if isinstance(value, dict):
        tenant = value.get("tenant")
        if not isinstance(tenant, str) or not tenant:
            raise ValueError(
                f"api key {key!r}: object entries need a non-empty "
                f'"tenant" label'
            )
        prio = normalize_priority(value.get("priority"))
        ceiling = normalize_priority(
            value.get("priority_ceiling"),
            default=PRIORITY_CLASSES[0],
        )
        cfg = TenantConfig(
            tenant=tenant,
            # A declared default above the ceiling is clamped at parse
            # time, so the pair is always consistent.
            priority=clamp_priority(prio, ceiling),
            priority_ceiling=ceiling,
        )
        for fname in ("rps", "burst", "cells_per_s", "cells_burst"):
            raw = value.get(fname)
            if raw is None:
                continue
            try:
                fv = float(raw)
            except (ValueError, TypeError):
                raise ValueError(
                    f"api key {key!r}: {fname} must be a number, "
                    f"got {raw!r}"
                ) from None
            if fv <= 0:
                raise ValueError(
                    f"api key {key!r}: {fname} must be > 0, got {fv}"
                )
            setattr(cfg, fname, fv)
        return cfg
    raise ValueError(
        f"api key {key!r}: value must be a tenant-label string or a "
        f"config object, got {type(value).__name__}"
    )


def load_api_keys(path: str) -> Dict[str, TenantConfig]:
    """Parse an --api-keys-file.  Two value shapes per key:

        {"KEY": "tenant-label"}                      (PR-12 schema)
        {"KEY": {"tenant": "label",                  (QoS schema)
                 "priority": "batch",
                 "priority_ceiling": "interactive",
                 "rps": 50, "burst": 100,
                 "cells_per_s": 2.0e8, "cells_burst": 1.0e9}}

    Keys terminate AT the router (replicas never see them); the mapped
    tenant label travels on as X-Wavetpu-Tenant and the effective
    (defaulted, ceiling-clamped) class as X-Priority."""
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    return parse_api_keys(raw, source=path)


def parse_api_keys(raw, source: str = "api-keys") \
        -> Dict[str, TenantConfig]:
    """Schema validation for an already-loaded api-keys object (the
    build_router path accepts plain dicts from tests/embedding)."""
    if not isinstance(raw, dict) or not raw:
        raise ValueError(
            f"{source}: want a non-empty JSON object "
            f'{{"API_KEY": "tenant-label" | {{config}}, ...}}'
        )
    out: Dict[str, TenantConfig] = {}
    for k, v in raw.items():
        if not isinstance(k, str) or not k:
            raise ValueError(
                f"{source}: API keys must be non-empty strings"
            )
        try:
            out[k] = parse_tenant_entry(k, v)
        except ValueError as e:
            raise ValueError(f"{source}: {e}") from None
    return out
