"""Fleet tier: N `wavetpu serve` replicas behind one affinity router.

One `wavetpu serve` process is one scheduler worker in front of one
accelerator; a fleet is N of them behind `wavetpu router` - a stdlib
ThreadingHTTPServer front (same discipline as serve/api.py) that:

 * derives each /solve body's program identity with the SAME shared
   key-derivation the engine uses (`wavetpu.progkey` - the module
   factored out of serve/engine.py so router and engine cannot drift),
 * routes warm keys to the replica that already holds the compiled
   program (warm-key tables learned from replica `/metrics`
   `program_cache.warm_keys` polls plus every proxied response's
   `Server-Timing: warm;desc=` label),
 * falls back to least-loaded power-of-two-choices for cold keys,
 * health-gates membership on `/healthz` polls (`ready: false` or
   repeated transport failures eject; recovery re-admits),
 * absorbs a draining replica's 503s by retrying on a live member, and
 * aggregates member Prometheus counters (including frozen snapshots
   of departed members) so `wavetpu loadgen` pointed at the router
   sees fleet-wide monotonic deltas across a rolling deploy.

`wavetpu fleet roll` is the zero-cold-compile deploy driver: start the
successor with `--warmup-manifest` built from the fleet's shared
compile ledger, wait for readiness, join it to the router, then drain
and remove the predecessor - clients retrying through `WavetpuClient`
(or the router's own retry) never see the cutover.

Modules (all stdlib-only, never import jax - the router runs on hosts
with no accelerator stack):

  membership.py  health-gated member table + poll loop
  affinity.py    warm-key table + hit/rerouted/cold routing decisions
  router.py      the HTTP proxy tier (`wavetpu router`)
  roll.py        the rolling-deploy driver (`wavetpu fleet roll`)

Contract and runbook: docs/fleet.md.
"""

from wavetpu.fleet.affinity import AffinityTable  # noqa: F401
from wavetpu.fleet.membership import Member, MembershipTable  # noqa: F401
