"""Router edge result cache: answer repeats with ZERO replica I/O.

The outermost layer of the fleet result tier (docs/fleet.md "Edge
result cache"): the router keyed every /solve by the shared jax-free
`wavetpu.progkey.result_key` already (it routes by the same identity),
so a repeat of a cached answer can be served AT the router - no
forward, no replica queue slot, no batch executed (the drill pins the
replica batch counter unchanged across an edge hit).

Entries are stored from real replica responses: a replica that stored
a payload into ITS result cache stamps `X-Wavetpu-Cache: store;fp=H`
(H = a short hash of its environment fingerprint), and the router
adopts the exact response bytes under that fingerprint tag.  A store
carrying a NEW fingerprint flushes every entry of the old one - the
edge must never outlive a fleet upgrade.  Each entry carries a sha256
digest verified on every hit; corruption is a counted miss that falls
through to the replicas, never a wrong answer.

The index rides the PR 16 control plane: `export_state()` /
`restore_state()` round-trip the full entry map as the `edge_cache`
section of the ControlPlaneStore WAL, so a router restart - or an HA
standby's promotion - inherits the warm edge, and the first request
after a failover can still be answered without touching a chip.

Stdlib-only; never imports jax (routers run on accelerator-less
hosts).
"""

from __future__ import annotations

import base64
import hashlib
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

DEFAULT_MAX_BYTES = 32 << 20
DEFAULT_TTL_S = 600.0


def _digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


class EdgeCache:
    """Thread-safe bounded LRU of /solve success payloads at the
    router.  Keys are `progkey.result_key` digests; values are the
    exact replica response bytes + the headers a hit must replay."""

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES,
                 ttl_s: float = DEFAULT_TTL_S,
                 clock=time.time):
        if max_bytes <= 0:
            raise ValueError(f"max_bytes must be > 0, got {max_bytes}")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._lock = threading.Lock()
        # key -> {payload, content_type, server_timing, fp, digest,
        #         created}; insertion order is LRU order.
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._bytes = 0
        self._fp: Optional[str] = None  # the fleet fingerprint tag
        self.hits_total = 0
        self.misses_total = 0
        self.stores_total = 0
        self.evicted_total = 0
        self.corrupt_total = 0
        self.fingerprint_flushes_total = 0

    # ---- internals (call under lock) ----

    def _drop(self, key: str) -> None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self._bytes -= len(entry["payload"])

    def _flush_all(self) -> None:
        self._entries.clear()
        self._bytes = 0

    # ---- data path ----

    def get(self, key: str) -> Optional[Tuple[bytes, str,
                                              Optional[str]]]:
        """(payload, content_type, server_timing) for a live verified
        entry, else None (counted miss; TTL-expired, corrupt, and
        fingerprint-flushed entries all land here)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses_total += 1
                return None
            if self._clock() - entry["created"] > self.ttl_s:
                self._drop(key)
                self.evicted_total += 1
                self.misses_total += 1
                return None
            if _digest(entry["payload"]) != entry["digest"]:
                self._drop(key)
                self.corrupt_total += 1
                self.misses_total += 1
                return None
            self._entries.move_to_end(key)
            self.hits_total += 1
            return (entry["payload"], entry["content_type"],
                    entry["server_timing"])

    def put(self, key: str, payload: bytes, content_type: str,
            server_timing: Optional[str], fp: Optional[str]) -> bool:
        """Adopt one replica success payload under fingerprint tag
        `fp`.  A NEW fp flushes every old-fp entry first (the fleet
        upgraded under us); an oversized payload is refused."""
        if len(payload) > self.max_bytes:
            return False
        with self._lock:
            if fp != self._fp:
                if self._entries:
                    self.fingerprint_flushes_total += 1
                self._flush_all()
                self._fp = fp
            self._drop(key)
            self._entries[key] = {
                "payload": payload,
                "content_type": content_type,
                "server_timing": server_timing,
                "fp": fp,
                "digest": _digest(payload),
                "created": self._clock(),
            }
            self._bytes += len(payload)
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                old_key = next(iter(self._entries))
                if old_key == key:
                    break
                self._drop(old_key)
                self.evicted_total += 1
            self.stores_total += 1
            return True

    # ---- control-plane persistence (the `edge_cache` store section) ----

    def export_state(self) -> dict:
        """The WAL-persistable index: payload bytes base64'd (the store
        is JSON), counters included so a promoted standby's /metrics
        stay monotonic."""
        with self._lock:
            return {
                "fp": self._fp,
                "entries": [
                    {
                        "key": k,
                        "payload": base64.b64encode(
                            e["payload"]
                        ).decode("ascii"),
                        "content_type": e["content_type"],
                        "server_timing": e["server_timing"],
                        "fp": e["fp"],
                        "digest": e["digest"],
                        "created": e["created"],
                    }
                    for k, e in self._entries.items()
                ],
                "counters": {
                    "hits_total": self.hits_total,
                    "misses_total": self.misses_total,
                    "stores_total": self.stores_total,
                    "evicted_total": self.evicted_total,
                    "corrupt_total": self.corrupt_total,
                    "fingerprint_flushes_total":
                        self.fingerprint_flushes_total,
                },
            }

    def restore_state(self, state: dict) -> None:
        """Adopt a predecessor's persisted index (router restart or
        standby promotion).  Entries that fail to decode or verify are
        silently skipped - a corrupt WAL record must cost at most its
        own entry; counters max-merge for monotonic /metrics."""
        if not isinstance(state, dict):
            return
        with self._lock:
            fp = state.get("fp")
            self._fp = fp if isinstance(fp, str) or fp is None else None
            self._flush_all()
            for e in state.get("entries") or ():
                if not isinstance(e, dict):
                    continue
                try:
                    key = e["key"]
                    payload = base64.b64decode(e["payload"])
                    if _digest(payload) != e["digest"]:
                        self.corrupt_total += 1
                        continue
                    created = float(e["created"])
                except (KeyError, TypeError, ValueError):
                    continue
                if len(payload) > self.max_bytes:
                    continue
                self._entries[key] = {
                    "payload": payload,
                    "content_type": str(
                        e.get("content_type") or "application/json"
                    ),
                    "server_timing": e.get("server_timing"),
                    "fp": e.get("fp"),
                    "digest": e["digest"],
                    "created": created,
                }
                self._bytes += len(payload)
            while self._bytes > self.max_bytes and self._entries:
                self._drop(next(iter(self._entries)))
            counters = state.get("counters")
            if isinstance(counters, dict):
                for field in ("hits_total", "misses_total",
                              "stores_total", "evicted_total",
                              "corrupt_total",
                              "fingerprint_flushes_total"):
                    try:
                        v = int(counters.get(field) or 0)
                    except (TypeError, ValueError):
                        continue
                    setattr(self, field,
                            max(getattr(self, field), v))

    # ---- observability ----

    def prom_samples(self) -> Dict[str, float]:
        with self._lock:
            return {
                "wavetpu_router_edgecache_hits_total": self.hits_total,
                "wavetpu_router_edgecache_misses_total":
                    self.misses_total,
                "wavetpu_router_edgecache_stores_total":
                    self.stores_total,
                "wavetpu_router_edgecache_evicted_total":
                    self.evicted_total,
                "wavetpu_router_edgecache_corrupt_total":
                    self.corrupt_total,
                "wavetpu_router_edgecache_bytes": self._bytes,
                "wavetpu_router_edgecache_entries":
                    len(self._entries),
            }

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "ttl_s": self.ttl_s,
                "fingerprint": self._fp,
                "hits": self.hits_total,
                "misses": self.misses_total,
                "stores": self.stores_total,
                "evicted": self.evicted_total,
                "corrupt": self.corrupt_total,
                "fingerprint_flushes": self.fingerprint_flushes_total,
            }
