"""`wavetpu router` - the ProgramKey-affinity fleet front tier.

A stdlib ThreadingHTTPServer (the serve/api.py discipline: handler
threads block on upstream I/O, one shared state object on the server)
that proxies /solve across N `wavetpu serve` replicas:

  POST /solve       derive the body's program identity with the SHARED
                    key module (`wavetpu.progkey` - the same derivation
                    the engine caches under, so router and engine
                    cannot drift), land it on a replica that already
                    holds the compiled program (fleet/affinity.py),
                    else least-loaded power-of-two-choices.  A
                    transport failure or a 503 (draining / breaker /
                    crashed-worker replica) is RETRIED on a different
                    live member before the client ever sees it; only
                    when every member refused does the router answer
                    503 + Retry-After + retriable (which WavetpuClient
                    absorbs with backoff).  The response carries
                    `X-Wavetpu-Member` naming the replica that served.
                    `X-Deadline-Ms` is forwarded DECREMENTED by the
                    router-side wall already burned, and retries stop
                    when the remaining budget drops below
                    --min-retry-budget-ms (a doomed retry wastes a
                    replica slot).  A 503 carrying `resume_token` (a
                    draining replica checkpointed a chunked long
                    solve) has the token re-injected into the retried
                    body, so the next member resumes the march -
                    cross-replica solve handoff.  With
                    --api-keys-file, /solve requires a mapped API key
                    (Authorization: Bearer or X-Api-Key; else 401) and
                    the router stamps the mapped tenant label as
                    X-Wavetpu-Tenant, stripping any caller-supplied
                    value.  The key's entry may also carry a QoS
                    config (fleet/quota.py): a default priority class
                    + ceiling (the router clamps and stamps
                    X-Priority, stripping the inbound claim) and
                    per-tenant token buckets - requests/s AND
                    model-priced cells/s - enforced HERE, before
                    routing; exhaustion answers 429 with Retry-After
                    set to the measured bucket refill time.  With
                    --proxy-token the router stamps
                    X-Wavetpu-Proxy-Token on every forwarded request,
                    so replicas started with the same secret accept
                    tenant/priority headers ONLY from this router.
                    With --telemetry-dir the router writes its OWN
                    trace.jsonl (obs/tracing.py records): a
                    `router.request` span per proxied /solve with
                    `router.attempt` children per member try plus
                    `router.retry` / `router.drain_handoff` events -
                    adopting the client's W3C `traceparent` as remote
                    parent and minting a fresh per-attempt context for
                    the replica, so `wavetpu trace-report --dir ...`
                    joins router and replica spans into ONE fleet
                    trace (docs/observability.md "Distributed
                    tracing").  The trace context is echoed on every
                    /solve response.
  GET /healthz      router liveness + readiness (`ready` = at least
                    one routable member) + per-member state summary.
  GET /metrics      JSON (default): router counters, affinity stats
                    (hit/rerouted/cold + hit_rate), per-member summary
                    and proxied counts.  `Accept: text/plain`: the
                    FLEET-WIDE Prometheus cut - sample-wise sum over
                    every member ever seen (departed members contribute
                    frozen snapshots; mid-flight joiners contribute
                    growth since join, their warmup history baselined
                    away - so `wavetpu loadgen` pointed at the router
                    sees monotonic, roll-clean deltas across a rolling
                    deploy) plus the router's own wavetpu_router_*
                    samples.
  POST /admin/join  {"url": U} - add a member (admitted to rotation
                    when its /healthz says ready).
  POST /admin/leave {"url": U} - drain U (POST its /admin/drain),
                    keep polling its counters while it flushes, then
                    retire it with counters frozen.  The roll driver's
                    cutover primitive.

Stdlib-only; NEVER imports jax (routers run on hosts with no
accelerator stack).  Contract and runbook: docs/fleet.md.
"""

from __future__ import annotations

import http.client
import json
import os
import random
import sys
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Sequence, Tuple

from wavetpu import progkey
from wavetpu.core.flags import split_flags
from wavetpu.fleet import ha as fleet_ha
from wavetpu.fleet import quota
from wavetpu.fleet.affinity import (
    AffinityTable,
    warm_label_from_server_timing,
)
from wavetpu.fleet.edgecache import EdgeCache
from wavetpu.fleet.membership import LEFT, MembershipTable
from wavetpu.fleet.store import ControlPlaneStore
from wavetpu.obs import tracing
from wavetpu.obs.telemetry import (
    DEFAULT_MAX_BYTES,
    ROTATE_KEEP,
    TRACE_FILENAME,
)

_USAGE = (
    "usage: wavetpu router --member URL [--member URL2 ...] "
    "[--host H] [--port P] [--poll-interval-s S] [--fail-threshold K] "
    "[--proxy-timeout-s S] [--max-body-bytes B] "
    "[--min-retry-budget-ms MS] [--api-keys-file FILE.json] "
    "[--quota-default-rps R] [--quota-default-burst B] "
    "[--quota-default-cells-per-s C] [--quota-default-cells-burst CB] "
    "[--proxy-token SECRET] [--telemetry-dir DIR] "
    "[--control-plane-dir DIR] [--lease-ttl-s S] "
    "[--store-flush-interval-s S] "
    "[--edge-cache] [--edge-cache-max-bytes B] [--edge-cache-ttl-s S]"
)

# Response headers worth forwarding verbatim from replica to client
# (the rest are hop-by-hop or recomputed by the router's send path).
# `traceparent` is the replica's trace-context echo; a TRACED router
# overwrites it with its own outer-hop context before answering.
_FORWARD_RESPONSE_HEADERS = (
    "X-Request-Id", "Server-Timing", "Retry-After", "traceparent",
    "X-Wavetpu-Cache",
)
# Request headers forwarded replica-ward.  X-Wavetpu-Tenant and
# X-Priority pass through only on an UNauthenticated router (trusted
# internal callers); with --api-keys-file the router strips the inbound
# values and stamps its own - the tenant from the key map, the class
# defaulted + ceiling-clamped by the tenant's config - so neither label
# is forgeable.  `traceparent` passes through verbatim on an UNtraced
# router (the client's context still reaches the replica); a traced
# router replaces it with a fresh per-attempt context under the same
# trace id.
_FORWARD_REQUEST_HEADERS = (
    "Content-Type", "X-Request-Id", "X-Deadline-Ms",
    "X-Wavetpu-Tenant", "X-Priority", "traceparent",
)


def _server_timing_total_ms(header: Optional[str]) -> Optional[float]:
    """The `total;dur=` milliseconds from a replica's Server-Timing
    header - the replica-side wall for the per-hop attribution counters
    (router wall vs replica wall).  None when absent/unparseable."""
    if not header:
        return None
    for part in header.split(","):
        name, _, params = part.strip().partition(";")
        if name.strip() != "total":
            continue
        for p in params.split(";"):
            k, _, v = p.strip().partition("=")
            if k == "dur":
                try:
                    return float(v)
                except ValueError:
                    return None
    return None


def load_api_keys(path: str) -> Dict[str, quota.TenantConfig]:
    """Parse an --api-keys-file into key -> TenantConfig.  Two value
    shapes: the PR-12 plain tenant-label string (identity only), or a
    QoS config object (tenant + priority default/ceiling + per-tenant
    token-bucket rates) - fleet/quota.py `load_api_keys` holds the
    schema.  Keys terminate AT the router (replicas never see them)."""
    return quota.load_api_keys(path)


class _ProxyConns:
    """Thread-local kept-alive upstream connections, one per (handler
    thread, member) - the router pays the TCP handshake once per
    member per thread, not once per proxied request (the replicas
    speak HTTP/1.1)."""

    def __init__(self):
        self._local = threading.local()

    def _pool(self) -> Dict[str, http.client.HTTPConnection]:
        pool = getattr(self._local, "pool", None)
        if pool is None:
            pool = {}
            self._local.pool = pool
        return pool

    def request(self, base_url: str, method: str, path: str,
                body: Optional[bytes], headers: Dict[str, str],
                timeout: float) -> Tuple[int, bytes, Dict[str, str]]:
        """One exchange on the kept-alive connection to `base_url`;
        raises OSError/http.client errors on transport failure (after
        dropping the dead connection so the next try reconnects)."""
        pool = self._pool()
        conn = pool.get(base_url)
        if conn is None:
            parts = urllib.parse.urlsplit(base_url)
            conn = http.client.HTTPConnection(
                parts.hostname, parts.port or 80, timeout=timeout
            )
            pool[base_url] = conn
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
        except Exception:
            try:
                conn.close()
            except Exception:
                pass
            pool.pop(base_url, None)
            raise
        if resp.will_close:
            try:
                conn.close()
            except Exception:
                pass
            pool.pop(base_url, None)
        return resp.status, raw, dict(resp.headers)

    def drop(self, base_url: str) -> None:
        conn = self._pool().pop(base_url, None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass


class RouterState:
    """Shared router state: membership + affinity + counters."""

    def __init__(self, table: MembershipTable, affinity: AffinityTable,
                 proxy_timeout: float = 120.0,
                 max_body_bytes: Optional[int] = None,
                 min_retry_budget_ms: float = 50.0,
                 api_keys: Optional[Dict] = None,
                 quotas: Optional[quota.QuotaManager] = None,
                 proxy_token: Optional[str] = None):
        self.table = table
        self.affinity = affinity
        self.proxy_timeout = proxy_timeout
        self.max_body_bytes = max_body_bytes
        # Deadline-budget floor for cross-member retries: when the
        # remaining client budget is below this, a second attempt
        # cannot finish in time - surface the last answer instead of
        # burning another replica's queue slot on doomed work.
        self.min_retry_budget_ms = min_retry_budget_ms
        # key -> TenantConfig; None = unauthenticated router (the
        # historical open mode).  Plain-string values (the PR-12 flat
        # map, still what tests/embedders hand build_router) are
        # normalized to identity-only configs here.
        self.api_keys: Optional[Dict[str, quota.TenantConfig]] = None
        if api_keys is not None:
            self.api_keys = {
                k: (v if isinstance(v, quota.TenantConfig)
                    else quota.parse_tenant_entry(k, v))
                for k, v in api_keys.items()
            }
        # Authoritative per-tenant token buckets (requests/s +
        # model-priced cells/s); default-constructed (enforcing
        # nothing) when the caller passes None so the admit path stays
        # branch-light.
        self.quotas = quotas if quotas is not None \
            else quota.QuotaManager()
        # Shared secret stamped as X-Wavetpu-Proxy-Token on every
        # forwarded request; replicas started with the same secret
        # accept tenant/priority headers only when it matches.
        self.proxy_token = proxy_token
        self.conns = _ProxyConns()
        self.started = time.time()
        self._lock = threading.Lock()
        self.requests_total = 0
        self.retried_requests = 0      # requests needing >1 member
        self.retries_total = 0         # extra member attempts
        self.exhausted_total = 0       # every member refused -> 503
        self.unparseable_total = 0     # body gave no identity (routed
        #                                anyway; the replica 400s it)
        self.auth_rejected_total = 0   # missing/unknown API key -> 401
        self.quota_rejected_total = 0  # bucket exhausted -> 429
        self.budget_stops_total = 0    # retries refused: budget floor
        self.resume_handoffs_total = 0  # 503-with-token retried with
        #                                 the token re-injected
        # Per-hop wall attribution: cumulative router-side wall per
        # proxied /solve vs the replica-side wall the members reported
        # (Server-Timing `total;dur=`).  The difference is the
        # network/queue/retry overhead the router tier added.
        self.proxy_wall_ms_total = 0.0
        self.upstream_wall_ms_total = 0.0
        # The router's OWN Tracer (--telemetry-dir), deliberately NOT
        # the module-level singleton: a test process may host this
        # router and N in-process replicas, each with its own trace
        # file - the router must not clobber theirs (or vice versa).
        self.tracer: Optional[tracing.Tracer] = None
        self.proxied_per_member: Dict[str, int] = {}
        self.requests_per_tenant: Dict[str, int] = {}
        # Control plane + HA (--control-plane-dir; both None without
        # it - the historical standalone-active router, bit-for-bit).
        self.store: Optional[ControlPlaneStore] = None
        self.ha: Optional[fleet_ha.HACoordinator] = None
        # Edge result cache (--edge-cache; fleet/edgecache.py, None =
        # off): repeats of a replica-stored answer are served AT the
        # router - zero replica I/O, pinned by an unchanged replica
        # batch counter.  Its index rides the control-plane store as
        # the `edge_cache` section, so restarts and HA promotions
        # inherit the warm edge.
        self.edge: Optional[EdgeCache] = None
        # Router-tier chaos plan (WAVETPU_FAULT router-*/store-* specs;
        # run/faults.py router_plan_from_env).  Shared with the store
        # and lease so count= budgets span the whole process.
        self.fault_plan = None
        self.standby_rejected_total = 0  # /solve answered standby-503
        self._poll_stop = threading.Event()
        self._poller: Optional[threading.Thread] = None

    # ---- HA role ----

    @property
    def role(self) -> str:
        """`active` (serving /solve) or `standby` (503s retriably until
        the lease is ours).  A router without a control plane is always
        active - there is nobody to defer to."""
        return fleet_ha.ACTIVE if self.ha is None else self.ha.role

    # ---- control-plane persistence (fleet/store.py sections) ----

    def export_state(self) -> dict:
        """The full durable section map the HA flusher persists."""
        with self._lock:
            counters = {
                "requests_total": self.requests_total,
                "retried_requests": self.retried_requests,
                "retries_total": self.retries_total,
                "exhausted_total": self.exhausted_total,
                "unparseable_total": self.unparseable_total,
                "auth_rejected_total": self.auth_rejected_total,
                "quota_rejected_total": self.quota_rejected_total,
                "budget_stops_total": self.budget_stops_total,
                "resume_handoffs_total": self.resume_handoffs_total,
                "standby_rejected_total": self.standby_rejected_total,
                "proxy_wall_ms_total": round(
                    self.proxy_wall_ms_total, 3
                ),
                "upstream_wall_ms_total": round(
                    self.upstream_wall_ms_total, 3
                ),
                "proxied_per_member": dict(self.proxied_per_member),
                "requests_per_tenant": dict(self.requests_per_tenant),
            }
        out = {
            "quota": self.quotas.export_state(),
            "affinity": self.affinity.export_state(),
            "membership": self.table.export_state(),
            "router_counters": counters,
        }
        if self.edge is not None:
            out["edge_cache"] = self.edge.export_state()
        return out

    def restore_state(self, state: dict) -> None:
        """Adopt a predecessor's persisted state (boot with a store, or
        a standby's promotion).  Counters max-merge so the router-own
        /metrics samples stay monotonic across the restart; quota
        levels restore refilled for downtime; membership restores
        frozen snapshots + baselines; affinity union-merges."""
        if not isinstance(state, dict):
            return
        self.quotas.restore_state(state.get("quota") or {})
        self.affinity.restore_state(state.get("affinity") or {})
        self.table.restore_state(state.get("membership") or {})
        if self.edge is not None:
            self.edge.restore_state(state.get("edge_cache") or {})
        counters = state.get("router_counters")
        if not isinstance(counters, dict):
            return
        with self._lock:
            for field in (
                "requests_total", "retried_requests", "retries_total",
                "exhausted_total", "unparseable_total",
                "auth_rejected_total", "quota_rejected_total",
                "budget_stops_total", "resume_handoffs_total",
                "standby_rejected_total",
            ):
                try:
                    v = int(counters.get(field) or 0)
                except (TypeError, ValueError):
                    continue
                setattr(self, field, max(getattr(self, field), v))
            for field in ("proxy_wall_ms_total",
                          "upstream_wall_ms_total"):
                try:
                    v = float(counters.get(field) or 0.0)
                except (TypeError, ValueError):
                    continue
                setattr(self, field, max(getattr(self, field), v))
            for field, pool in (
                ("proxied_per_member", self.proxied_per_member),
                ("requests_per_tenant", self.requests_per_tenant),
            ):
                persisted = counters.get(field)
                if not isinstance(persisted, dict):
                    continue
                for k, n in persisted.items():
                    try:
                        n = int(n)
                    except (TypeError, ValueError):
                        continue
                    pool[k] = max(pool.get(k, 0), n)

    # ---- load signal for power-of-two-choices ----

    def load_of(self, url: str) -> float:
        m = self.table.get(url)
        if m is None:
            return 0.0
        # Router-side inflight is fresh per request; queue depth is as
        # fresh as the last poll - together they bias p2c away from a
        # member that is busy RIGHT NOW or was backed up recently.
        return float(m.inflight + m.queue_depth)

    def note_proxied(self, url: str, retried: bool,
                     extra_attempts: int) -> None:
        with self._lock:
            self.proxied_per_member[url] = (
                self.proxied_per_member.get(url, 0) + 1
            )
            if retried:
                self.retried_requests += 1
            self.retries_total += extra_attempts

    # ---- background health poll ----

    def start_poller(self, interval_s: float) -> None:
        def _loop():
            while not self._poll_stop.wait(interval_s):
                try:
                    self.table.poll_once()
                except Exception:
                    pass  # a poll crash must never kill the loop

        self._poller = threading.Thread(
            target=_loop, name="wavetpu-router-poll", daemon=True
        )
        self._poller.start()

    def stop_poller(self) -> None:
        self._poll_stop.set()
        if self._poller is not None:
            self._poller.join(timeout=5.0)

    # ---- leave orchestration (the roll cutover primitive) ----

    def leave_member(self, url: str, drain: bool = True,
                     drain_wait_s: float = 30.0,
                     sync: bool = False) -> bool:
        """Mark `url` LEAVING (out of rotation now), drain it, keep
        snapshotting its counters while it flushes, then retire it
        (counters frozen).  Runs in the background unless sync=True
        (tests); returns whether the member existed."""
        m = self.table.leave(url)
        if m is None:
            return False

        def _drain_and_retire():
            if drain:
                try:
                    # A short-lived one-shot connection: the member is
                    # about to close every socket anyway.
                    self.conns.drop(m.base_url)
                    parts = urllib.parse.urlsplit(m.base_url)
                    conn = http.client.HTTPConnection(
                        parts.hostname, parts.port or 80, timeout=10.0
                    )
                    try:
                        conn.request("POST", "/admin/drain")
                        conn.getresponse().read()
                    finally:
                        conn.close()
                except Exception:
                    pass  # already down = already drained
            deadline = time.monotonic() + drain_wait_s
            while time.monotonic() < deadline:
                # Liveness probe FIRST: a drained replica stops
                # accepting the moment its serve loop exits, and
                # burning the metrics-fetch timeouts against a dead
                # socket would stall the cutover for nothing.
                try:
                    self.table._fetch(  # noqa: SLF001
                        m.base_url, "/healthz", 2.0, None
                    )
                except Exception:
                    break  # process gone: last snapshot is final
                try:
                    self.table.refresh_metrics(m)
                except Exception:
                    pass
                time.sleep(0.2)
            self.table.retire(m.base_url)

        if sync:
            _drain_and_retire()
        else:
            threading.Thread(
                target=_drain_and_retire,
                name="wavetpu-router-leave", daemon=True,
            ).start()
        return True

    # ---- fleet platform (for kernel:auto identity resolution) ----

    def platform(self) -> str:
        for m in self.table.routable_members():
            if m.backend:
                return m.backend
        for m in self.table.members():
            if m.backend:
                return m.backend
        return "cpu"

    # ---- metrics views ----

    def snapshot(self) -> dict:
        with self._lock:
            per_member = dict(self.proxied_per_member)
            snap = {
                "router": True,
                "uptime_seconds": round(time.time() - self.started, 3),
                "requests_total": self.requests_total,
                "retried_requests": self.retried_requests,
                "retries_total": self.retries_total,
                "exhausted_total": self.exhausted_total,
                "unparseable_total": self.unparseable_total,
                "auth_rejected_total": self.auth_rejected_total,
                "quota_rejected_total": self.quota_rejected_total,
                "budget_stops_total": self.budget_stops_total,
                "resume_handoffs_total": self.resume_handoffs_total,
                "standby_rejected_total": self.standby_rejected_total,
                "proxy_wall_ms_total": round(
                    self.proxy_wall_ms_total, 3
                ),
                "upstream_wall_ms_total": round(
                    self.upstream_wall_ms_total, 3
                ),
                "requests_per_tenant": dict(self.requests_per_tenant),
            }
        snap.update(self.quotas.snapshot())
        # Live bucket levels: what the failover-parity drill compares
        # between the pre-kill active and the promoted standby.
        snap["quota_buckets"] = self.quotas.levels()
        snap["role"] = self.role
        if self.ha is not None:
            snap["ha"] = self.ha.snapshot()
        if self.store is not None:
            snap["store"] = self.store.snapshot_counters()
        if self.edge is not None:
            snap["edge_cache"] = self.edge.snapshot()
        if self.fault_plan is not None:
            snap["fault_plan"] = self.fault_plan.snapshot()
        snap["affinity"] = self.affinity.stats()
        members = self.table.summary()
        for row in members:
            row["proxied_total"] = per_member.get(row["url"], 0)
        snap["members"] = members
        return snap

    def render_prometheus(self) -> str:
        """Fleet-wide text exposition: summed member samples (frozen
        snapshots included - monotonic across a roll) + router-own
        wavetpu_router_* samples."""
        agg = self.table.aggregate_prom(refresh=True)
        snap = self.snapshot()
        aff = snap["affinity"]
        own: Dict[str, float] = {
            "wavetpu_router_requests_total": snap["requests_total"],
            "wavetpu_router_retried_requests_total":
                snap["retried_requests"],
            "wavetpu_router_retries_total": snap["retries_total"],
            "wavetpu_router_exhausted_total": snap["exhausted_total"],
            "wavetpu_router_auth_rejected_total":
                snap["auth_rejected_total"],
            "wavetpu_router_quota_rejected_total":
                snap["quota_rejected_total"],
            "wavetpu_router_budget_stops_total":
                snap["budget_stops_total"],
            "wavetpu_router_resume_handoffs_total":
                snap["resume_handoffs_total"],
            "wavetpu_router_proxy_wall_ms_total":
                snap["proxy_wall_ms_total"],
            "wavetpu_router_upstream_wall_ms_total":
                snap["upstream_wall_ms_total"],
            'wavetpu_router_affinity_decisions_total{decision="hit"}':
                aff["hits"],
            'wavetpu_router_affinity_decisions_total{decision="rerouted"}':
                aff["rerouted"],
            'wavetpu_router_affinity_decisions_total{decision="cold"}':
                aff["cold"],
            "wavetpu_router_affinity_known_keys": aff["known_keys"],
        }
        for row in snap["members"]:
            url = row["url"]
            own[
                'wavetpu_router_member_proxied_total'
                f'{{member="{url}"}}'
            ] = row["proxied_total"]
        for tenant, n in sorted(snap["requests_per_tenant"].items()):
            own[
                'wavetpu_router_tenant_requests_total'
                f'{{tenant="{tenant}"}}'
            ] = n
        for tenant, n in sorted(
            snap["quota_rejected_per_tenant"].items()
        ):
            own[
                'wavetpu_router_tenant_quota_rejected_total'
                f'{{tenant="{tenant}"}}'
            ] = n
        by_state: Dict[str, int] = {}
        for row in snap["members"]:
            by_state[row["state"]] = by_state.get(row["state"], 0) + 1
        for state, n in sorted(by_state.items()):
            own[f'wavetpu_router_members{{state="{state}"}}'] = n
        own["wavetpu_router_standby_rejected_total"] = snap[
            "standby_rejected_total"
        ]
        if self.store is not None:
            own.update(self.store.prom_samples())
        if self.ha is not None:
            own.update(self.ha.prom_samples())
        if self.edge is not None:
            own.update(self.edge.prom_samples())
        if self.fault_plan is not None:
            for inj in self.fault_plan.snapshot():
                own[
                    'wavetpu_router_fault_injections_total'
                    f'{{kind="{inj["kind"]}"}}'
                ] = inj["fired"]
        lines = [f"{k} {float(v)}" for k, v in sorted(agg.items())]
        lines += [f"{k} {float(v)}" for k, v in sorted(own.items())]
        return "\n".join(lines) + "\n"


class _RouterHandler(BaseHTTPRequestHandler):
    # Same HTTP/1.1 + single-send-path discipline as serve/api.py: the
    # keep-alive WavetpuClient holds one socket to the router across a
    # whole replay; error paths that skip reading the request body
    # answer with Connection: close.
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 (quiet, like serve)
        pass

    @property
    def rstate(self) -> RouterState:
        return self.server.wavetpu_router

    def _send(self, code: int, payload: dict,
              headers: Optional[dict] = None) -> None:
        self._send_bytes(code, json.dumps(payload).encode(),
                         "application/json", headers)

    def _send_bytes(self, code: int, body: bytes, content_type: str,
                    headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    # ---- GET ----

    def do_GET(self) -> None:  # noqa: N802 (stdlib contract)
        st = self.rstate
        if self.path == "/healthz":
            members = st.table.summary()
            up = sum(1 for m in members if m["state"] == "up")
            payload = {
                "status": "ok",
                "router": True,
                # Preflight-compatible readiness: route here iff at
                # least one member can take traffic AND this router
                # holds the lease (a standby tells load balancers and
                # loadgen preflights NOT to point measured traffic at
                # it; the multi-endpoint client finds it on rotation).
                "ready": up > 0 and st.role == fleet_ha.ACTIVE,
                "draining": False,
                "role": st.role,
                "uptime_seconds": round(time.time() - st.started, 3),
                "members_up": up,
                "members": members,
            }
            if st.ha is not None:
                payload["ha"] = st.ha.snapshot()
            self._send(200, payload)
        elif self.path == "/metrics":
            accept = self.headers.get("Accept", "") or ""
            wants_text = (
                "application/json" not in accept
                and ("text/plain" in accept or "openmetrics" in accept)
            )
            if wants_text:
                self._send_bytes(
                    200, st.render_prometheus().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                self._send(200, st.snapshot())
        else:
            self._send(404, {"status": "error", "error": "not found"})

    # ---- POST ----

    def _read_body(self) -> Optional[bytes]:
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            return None
        limit = self.rstate.max_body_bytes
        if limit is not None and length > limit:
            return None
        return self.rfile.read(length) if length > 0 else b""

    def do_POST(self) -> None:  # noqa: N802
        st = self.rstate
        if self.path in ("/admin/join", "/admin/leave"):
            raw = self._read_body()
            try:
                body = json.loads(raw or b"{}")
                url = body["url"]
            except (ValueError, KeyError, TypeError):
                self._send(400, {
                    "status": "error",
                    "error": 'admin body must be {"url": "http://..."}',
                }, {"Connection": "close"})
                return
            if self.path == "/admin/join":
                # baseline=True: a mid-flight joiner's pre-join
                # counters (manifest warmup) must not show up as fleet
                # delta growth.
                m = st.table.add(url, baseline=True)
                # Admit without waiting for the next poll tick - the
                # roll driver polls router /healthz for the flip.
                st.table.poll_member(m)
                self._send(200, {"status": "ok", "member": m.summary()})
            else:
                found = st.leave_member(
                    url,
                    drain=bool(body.get("drain", True)),
                    drain_wait_s=float(body.get("drain_wait_s", 30.0)),
                    sync=bool(body.get("sync", False)),
                )
                if not found:
                    self._send(404, {
                        "status": "error",
                        "error": f"unknown member {url}",
                    })
                else:
                    self._send(200, {"status": "ok", "leaving": url})
            return
        if self.path != "/solve":
            self._send(404, {"status": "error", "error": "not found"},
                       {"Connection": "close"})
            return
        raw = self._read_body()
        if raw is None:
            self._send(413, {
                "status": "error",
                "error": "request body too large for this router",
            }, {"Connection": "close"})
            return
        self._proxy_solve(raw)

    # ---- the proxy data path ----

    def _affinity_key(self, raw: bytes) -> Optional[str]:
        """The request's routing identity, or None (unkeyed: malformed
        bodies are still FORWARDED - the replica owns the 400 contract;
        the router must stay transparent to error-shape tests).  Reuses
        the ONE body parse _proxy_solve did (quota pricing and routing
        identity share it)."""
        st = self.rstate
        body = self._body_obj
        try:
            if body is None:
                raise ValueError("unparseable body")
            return progkey.identity_from_body(
                body, platform=st.platform
            ).affinity_key()
        except (ValueError, TypeError, KeyError):
            with st._lock:  # noqa: SLF001
                st.unparseable_total += 1
            return None

    def _auth_tenant(self) -> Tuple[
        bool, Optional[str], Optional[quota.TenantConfig]
    ]:
        """API-key termination: (authorized, tenant_label, config).
        With no --api-keys-file every request is authorized with a
        pass-through tenant and no config (trusted internal mode); with
        one, the key must be in the map (Authorization: Bearer K, or
        X-Api-Key: K) and the MAPPED label replaces whatever tenant
        header the caller sent - a client can never self-assign a
        billing identity.  The returned TenantConfig carries the
        tenant's quota buckets + priority default/ceiling."""
        st = self.rstate
        if st.api_keys is None:
            return True, self.headers.get("X-Wavetpu-Tenant"), None
        key = self.headers.get("X-Api-Key")
        if not key:
            auth = self.headers.get("Authorization", "") or ""
            if auth.startswith("Bearer "):
                key = auth[len("Bearer "):].strip()
        cfg = st.api_keys.get(key) if key else None
        if cfg is None:
            return False, None, None
        return True, cfg.tenant, cfg

    def _echo_headers(self, base: Optional[dict] = None) -> dict:
        """Response headers + the trace-context echo (satellite of the
        traceparent contract: EVERY /solve answer names its fleet
        trace, so an outlier in a client-side report resolves to its
        trace with no translation table)."""
        out = dict(base or {})
        if self._echo_tp:
            out["traceparent"] = self._echo_tp
        return out

    def _proxy_solve(self, raw: bytes) -> None:
        st = self.rstate
        t0 = time.monotonic()
        with st._lock:  # noqa: SLF001
            st.requests_total += 1
        if st.role != fleet_ha.ACTIVE:
            # A standby must not admit (that would double every quota)
            # or proxy (split-brain routing).  The 503 is retriable and
            # carries `standby: true` so a multi-endpoint WavetpuClient
            # rotates to the active immediately instead of backing off
            # against this endpoint.
            with st._lock:  # noqa: SLF001
                st.standby_rejected_total += 1
            self._send(503, {
                "status": "error",
                "error": "standby router (not the lease holder)",
                "retriable": True,
                "standby": True,
            }, {"Retry-After": "1"})
            return
        if st.fault_plan is not None and st.fault_plan.fire(
                "router-crash") is not None:
            # The chaos drill's dead-active: a REAL SIGKILL of this
            # process, mid-request - no flush, no lease release, no
            # response.  The standby must take over within one TTL and
            # the client must see only a transport error it absorbs.
            import signal as _signal

            os.kill(os.getpid(), _signal.SIGKILL)
        authorized, tenant, cfg = self._auth_tenant()
        if not authorized:
            with st._lock:  # noqa: SLF001
                st.auth_rejected_total += 1
            self._send(401, {
                "status": "error",
                "error": "missing or unknown API key",
            }, {"Connection": "close",
                "WWW-Authenticate": "Bearer"})
            return
        if tenant:
            with st._lock:  # noqa: SLF001
                st.requests_per_tenant[tenant] = (
                    st.requests_per_tenant.get(tenant, 0) + 1
                )
        # ONE body parse, shared by quota pricing (here), the edge
        # result-cache key, and the affinity-key derivation
        # (_route_solve).
        self._body_obj = None
        try:
            self._body_obj = json.loads(raw)
        except (ValueError, TypeError):
            pass
        # Edge result cache (fleet/edgecache.py): same jax-free key
        # derivation the replica tier uses.  The key is computed even
        # under `Cache-Control: no-cache` (the fresh answer still
        # refreshes the edge); only the LOOKUP is bypassed.
        self._edge_key: Optional[str] = None
        self._priced_cells = 0.0
        edge_hit = None
        if st.edge is not None and isinstance(self._body_obj, dict) \
                and progkey.result_cache_eligible(self._body_obj):
            try:
                self._edge_key = progkey.result_key(
                    self._body_obj, platform=st.platform
                )
            except (ValueError, TypeError, KeyError):
                self._edge_key = None
        if self._edge_key is not None and "no-cache" not in (
                self.headers.get("Cache-Control") or "").lower():
            edge_hit = st.edge.get(self._edge_key)
        # Priority-class authority: on an authenticated router the
        # effective class is the tenant's config default (when the
        # request declares none) clamped at its ceiling - the inbound
        # X-Priority / body claim is an INPUT to the clamp, never
        # forwarded as-is.
        self._priority: Optional[str] = None
        if cfg is not None:
            requested = self.headers.get("X-Priority")
            if requested is None and isinstance(self._body_obj, dict):
                requested = self._body_obj.get("priority")
            self._priority = cfg.effective_priority(
                requested if isinstance(requested, str) else None
            )
        # Authoritative per-tenant quota spend (requests/s + model-
        # priced cells/s) BEFORE routing: an over-quota request never
        # occupies a replica slot.  Retry-After is the measured bucket
        # refill time for this request's cost.  On an open router
        # (--quota-default-* without --api-keys-file) pass-through
        # tenant labels spend the default buckets.
        if cfg is None and tenant and st.quotas.enforces_anything:
            cfg = quota.TenantConfig(tenant=tenant)
        if cfg is not None:
            # An edge hit is still individually charged its request-
            # rate token, but its cells price is the MEASURED cost of
            # answering - a dict lookup, near zero - not the analytic
            # model's full march volume.
            self._priced_cells = (
                0.0 if edge_hit is not None
                else quota.price_cells(self._body_obj)
            )
            ok, retry = st.quotas.admit(cfg, self._priced_cells)
            if not ok:
                with st._lock:  # noqa: SLF001
                    st.quota_rejected_total += 1
                self._send(429, {
                    "status": "error",
                    "error": (
                        f"tenant {tenant!r} quota exhausted"
                    ),
                    "retriable": True,
                    "retry_after_s": round(retry, 3),
                }, {"Retry-After": str(max(1, int(retry + 0.5)))})
                return
        # Distributed tracing (docs/observability.md): adopt the
        # client's W3C traceparent as the remote parent of a
        # `router.request` span (minting a fresh trace id for
        # context-less callers); per-attempt spans/events nest under it
        # on this handler thread.  An UNtraced router still forwards
        # the inbound context verbatim (it rides
        # _FORWARD_REQUEST_HEADERS) and echoes it back.
        inbound_tp = self.headers.get("traceparent")
        inbound = tracing.parse_traceparent(inbound_tp)
        self._trace_id: Optional[str] = None
        self._echo_tp: Optional[str] = inbound_tp if inbound else None
        span = None
        if st.tracer is not None:
            self._trace_id = (
                inbound[0] if inbound else tracing.mint_trace_id()
            )
            req_w3c = tracing.mint_span_id()
            self._echo_tp = tracing.format_traceparent(
                self._trace_id, req_w3c
            )
            span = st.tracer.begin(
                "router.request",
                {
                    "request_id": (
                        self.headers.get("X-Request-Id") or ""
                    ),
                    "tenant": tenant or "",
                    "w3c_id": req_w3c,
                },
                remote=(
                    self._trace_id, inbound[1] if inbound else None
                ),
            )
        status = 0
        try:
            if edge_hit is not None:
                status = self._serve_edge_hit(edge_hit, t0)
            else:
                status = self._route_solve(raw, t0, tenant)
        finally:
            with st._lock:  # noqa: SLF001
                st.proxy_wall_ms_total += (
                    (time.monotonic() - t0) * 1e3
                )
            if span is not None:
                st.tracer.end(span, status=status)

    def _serve_edge_hit(self, hit: Tuple[bytes, str, Optional[str]],
                        t0: float) -> int:
        """Answer a /solve from the edge index: the EXACT replica
        payload bytes, with ZERO replica I/O (no forward, no queue
        slot, no batch - the drill pins the replica batch counter
        unchanged)."""
        payload, content_type, _orig_timing = hit
        out = {
            "X-Wavetpu-Cache": "edge-hit",
            "Server-Timing": (
                f"cache;desc=edge-hit, "
                f"total;dur={(time.monotonic() - t0) * 1e3:.3f}"
            ),
        }
        self._send_bytes(200, payload, content_type,
                         self._echo_headers(out))
        return 200

    def _route_solve(self, raw: bytes, t0: float,
                     tenant: Optional[str]) -> int:
        """The member-retry routing loop; sends the response and
        returns the status it answered with (the wrapper's span/metric
        bookkeeping wants it)."""
        st = self.rstate
        rid = self.headers.get("X-Request-Id") or ""
        ak = self._affinity_key(raw)
        fwd_headers = {
            h: self.headers[h]
            for h in _FORWARD_REQUEST_HEADERS if self.headers.get(h)
        }
        fwd_headers.setdefault("Content-Type", "application/json")
        if st.api_keys is not None:
            # The router is the tenant AND class authority: stamp the
            # mapped label and the ceiling-clamped effective class,
            # never the caller's claims.
            fwd_headers.pop("X-Wavetpu-Tenant", None)
            fwd_headers.pop("X-Priority", None)
            if tenant:
                fwd_headers["X-Wavetpu-Tenant"] = tenant
            if self._priority:
                fwd_headers["X-Priority"] = self._priority
        if st.proxy_token is not None:
            # Replica-side trust: replicas started with the same
            # --proxy-token honor tenant/priority headers only when
            # this secret rides along.
            fwd_headers["X-Wavetpu-Proxy-Token"] = st.proxy_token
        # Client deadline budget (X-Deadline-Ms): each attempt forwards
        # the REMAINING budget - the original minus router-side
        # queue/retry wall already burned - so a replica never marches
        # against wall the client no longer has.
        budget_ms: Optional[float] = None
        raw_dl = self.headers.get("X-Deadline-Ms")
        if raw_dl is not None:
            try:
                budget_ms = float(raw_dl)
            except ValueError:
                budget_ms = None  # replica owns the 400 contract
        tried = []
        last: Optional[Tuple[int, bytes, Dict[str, str]]] = None
        while True:
            candidates = [
                u for u in st.table.routable_urls() if u not in tried
            ]
            if not candidates:
                break
            remaining_ms = None
            if budget_ms is not None:
                remaining_ms = (
                    budget_ms - (time.monotonic() - t0) * 1e3
                )
                if tried and remaining_ms < st.min_retry_budget_ms:
                    # A retry below the budget floor cannot finish in
                    # time: stop here and surface the last answer.
                    with st._lock:  # noqa: SLF001
                        st.budget_stops_total += 1
                    break
                if remaining_ms <= 0:
                    # Budget fully burned router-side: answer the 504
                    # ourselves rather than making a replica say it.
                    self._send(504, {
                        "status": "error",
                        "error": (
                            f"deadline_ms {budget_ms:g} expired at the "
                            f"router before any replica could serve"
                        ),
                        "deadline_ms": budget_ms,
                    }, self._echo_headers())
                    return 504
                fwd_headers["X-Deadline-Ms"] = (
                    f"{max(1.0, remaining_ms):.0f}"
                )
            if tried:
                url = self._retry_pick(candidates)
            else:
                url = st.affinity.choose(ak, candidates, st.load_of)
            member = st.table.get(url)
            if member is not None:
                with st.table._lock:  # noqa: SLF001
                    member.inflight += 1
            att_span = None
            if st.tracer is not None:
                # A fresh per-attempt wire context under the SAME trace
                # id: the replica's serve.request adopts it as remote
                # parent, so each attempt's replica tree hangs under
                # its own router.attempt span.
                att_w3c = tracing.mint_span_id()
                fwd_headers["traceparent"] = tracing.format_traceparent(
                    self._trace_id, att_w3c
                )
                att_span = st.tracer.begin(
                    "router.attempt",
                    {"request_id": rid, "member": url,
                     "attempt": len(tried) + 1, "w3c_id": att_w3c},
                )
            try:
                status, body, headers = st.conns.request(
                    url, "POST", "/solve", raw, fwd_headers,
                    st.proxy_timeout,
                )
                last = (status, body, headers)
            except (OSError, http.client.HTTPException):
                status, last = 0, None
            finally:
                if member is not None:
                    with st.table._lock:  # noqa: SLF001
                        member.inflight = max(0, member.inflight - 1)
            tried.append(url)
            replica_ms = None
            if last is not None and status != 0:
                replica_ms = _server_timing_total_ms(
                    last[2].get("Server-Timing")
                )
            if replica_ms is not None:
                with st._lock:  # noqa: SLF001
                    st.upstream_wall_ms_total += replica_ms
            if att_span is not None:
                extra = {"status": status}
                if replica_ms is not None:
                    extra["replica_ms"] = replica_ms
                st.tracer.end(att_span, **extra)
            if status == 200 and ak is not None:
                st.affinity.observe_response(
                    url, ak,
                    warm_label_from_server_timing(
                        (last[2] if last else {}).get("Server-Timing")
                    ),
                )
            # Transport failures and 503s (draining / breaker /
            # crashed worker) are MEMBER problems, not request
            # problems: try a different member before surfacing
            # anything.  Every other status is the request's answer.
            if status not in (0, 503):
                break
            if status == 503 and last is not None:
                # Cross-replica solve handoff: a draining replica's 503
                # may carry a resume_token (a checkpointed long solve).
                # Re-inject it into the body so the NEXT member picks
                # the march up from the last completed chunk instead of
                # restarting at layer 0.
                token = None
                try:
                    token = json.loads(last[1]).get("resume_token")
                except (ValueError, AttributeError):
                    pass
                if isinstance(token, str) and token:
                    try:
                        body_obj = json.loads(raw)
                        body_obj["resume_token"] = token
                        raw = json.dumps(body_obj).encode()
                        with st._lock:  # noqa: SLF001
                            st.resume_handoffs_total += 1
                        if st.tracer is not None:
                            st.tracer.event(
                                "router.drain_handoff",
                                request_id=rid, from_member=url,
                                resume_token=token,
                            )
                    except (ValueError, TypeError):
                        pass
            if st.tracer is not None:
                st.tracer.event(
                    "router.retry", request_id=rid,
                    from_member=url, status=status,
                )
        retried = len(tried) > 1
        if last is not None and last[0] not in (0, 503):
            status, body, headers = last
            cache_hdr = headers.get("X-Wavetpu-Cache") or ""
            if status == 200 and cache_hdr:
                if cache_hdr.startswith("store;fp=") \
                        and st.edge is not None \
                        and self._edge_key is not None:
                    # The replica just stored this answer in ITS tier:
                    # adopt the exact bytes at the edge under the
                    # replica's fingerprint tag (a NEW tag flushes the
                    # old fleet's entries).
                    st.edge.put(
                        self._edge_key, body,
                        headers.get("Content-Type", "application/json"),
                        headers.get("Server-Timing"),
                        fp=cache_hdr[len("store;fp="):],
                    )
                elif cache_hdr in ("hit", "coalesced") and tenant \
                        and self._priced_cells > 0:
                    # Replica-tier cache hit / singleflight ride: no
                    # march happened, so the analytic cells price
                    # collapses to measured near-zero (the rps token
                    # stays spent - every request is charged).
                    st.quotas.refund_cells(tenant, self._priced_cells)
            out = {
                h: headers[h]
                for h in _FORWARD_RESPONSE_HEADERS if headers.get(h)
            }
            out["X-Wavetpu-Member"] = tried[-1]
            st.note_proxied(tried[-1], retried, len(tried) - 1)
            self._send_bytes(
                status, body,
                headers.get("Content-Type", "application/json"),
                self._echo_headers(out),
            )
            return status
        # Exhausted: every member refused (or none exist).  Answer in
        # the replica's own retriable-503 shape so WavetpuClient backs
        # off and retries through the cutover exactly as it would
        # against a single draining replica.
        with st._lock:  # noqa: SLF001
            st.exhausted_total += 1
            if retried:
                st.retried_requests += 1
            st.retries_total += max(0, len(tried) - 1)
        if last is not None and last[0] == 503:
            out = {
                h: last[2][h]
                for h in _FORWARD_RESPONSE_HEADERS if last[2].get(h)
            }
            out.setdefault("Retry-After", "2")
            out["X-Wavetpu-Member"] = tried[-1]
            self._send_bytes(
                503, last[1],
                last[2].get("Content-Type", "application/json"),
                self._echo_headers(out),
            )
            return 503
        self._send(503, {
            "status": "error",
            "error": (
                "no live fleet member could serve the request"
                if tried else "fleet has no routable members"
            ),
            "retriable": True,
        }, self._echo_headers({"Retry-After": "2"}))
        return 503

    def _retry_pick(self, candidates) -> str:
        """Retry attempts skip the affinity counters (one request, one
        counted decision) and just take the least-loaded pair pick."""
        st = self.rstate
        if len(candidates) == 1:
            return candidates[0]
        pair = random.sample(list(candidates), 2)
        return min(pair, key=st.load_of)


def build_router(
    member_urls: Sequence[str],
    host: str = "127.0.0.1",
    port: int = 0,
    poll_interval_s: float = 2.0,
    fail_threshold: int = 3,
    proxy_timeout: float = 120.0,
    max_body_bytes: Optional[int] = None,
    fetch=None,
    rng: Optional[random.Random] = None,
    start_poller: bool = True,
    min_retry_budget_ms: float = 50.0,
    api_keys: Optional[Dict] = None,
    telemetry_dir: Optional[str] = None,
    quotas: Optional[quota.QuotaManager] = None,
    proxy_token: Optional[str] = None,
    control_plane_dir: Optional[str] = None,
    lease_ttl_s: float = 2.0,
    store_flush_interval_s: float = 0.5,
    ha_owner: Optional[str] = None,
    start_ha: bool = True,
    edge_cache: bool = False,
    edge_cache_max_bytes: Optional[int] = None,
    edge_cache_ttl_s: Optional[float] = None,
) -> Tuple[ThreadingHTTPServer, RouterState]:
    """Assemble membership + affinity + HTTP front (port 0 =
    ephemeral).  Does ONE synchronous poll before returning so the
    rotation is populated the moment the caller starts serving; the
    periodic poller (start_poller) keeps it fresh.  Returned httpd is
    not yet serving - call serve_forever() (main does) or drive it
    from a thread (tests do).  `telemetry_dir` turns on the router's
    own span tracing (DIR/trace.jsonl, rotated like a replica's).
    `api_keys` accepts either the PR-12 flat {key: label} map or
    {key: TenantConfig}; `quotas` carries the router-wide default
    bucket rates (--quota-default-*), and `proxy_token` is stamped on
    every forwarded request for replica-side tenant trust.

    `control_plane_dir` turns on the durable control plane + HA
    (fleet/store.py, fleet/ha.py): the router elects through the dir's
    single-writer lease (first election is SYNCHRONOUS - a lone router
    boots straight to active with persisted quota/membership/counter
    state restored, before serving a request; a second router over the
    same dir boots standby and answers retriable standby-503s until
    the lease frees).  `ha_owner` names this router in the lease
    (default host:port#pid); `start_ha=False` leaves the coordinator
    un-started for tests that drive ticks by hand.

    `edge_cache` (--edge-cache, default OFF) turns on the router edge
    result tier (fleet/edgecache.py): repeats of answers the replicas
    stamped `X-Wavetpu-Cache: store;fp=H` are served at the router with
    zero replica I/O, and with a control plane the index persists as
    the store's `edge_cache` section (restart/HA-promotion warm)."""
    from wavetpu.run.faults import router_plan_from_env

    fault_plan = router_plan_from_env()
    affinity = AffinityTable(rng=rng)
    table = MembershipTable(
        member_urls, fail_threshold=fail_threshold, fetch=fetch,
        affinity=affinity,
    )
    state = RouterState(
        table, affinity, proxy_timeout=proxy_timeout,
        max_body_bytes=max_body_bytes,
        min_retry_budget_ms=min_retry_budget_ms, api_keys=api_keys,
        quotas=quotas, proxy_token=proxy_token,
    )
    state.fault_plan = fault_plan
    if edge_cache:
        from wavetpu.fleet import edgecache as _edgecache

        # Built BEFORE the HA coordinator: the first (synchronous)
        # election restore adopts the persisted `edge_cache` section
        # into this instance.
        state.edge = EdgeCache(
            max_bytes=(edge_cache_max_bytes
                       or _edgecache.DEFAULT_MAX_BYTES),
            ttl_s=edge_cache_ttl_s or _edgecache.DEFAULT_TTL_S,
        )
    if telemetry_dir is not None:
        state.tracer = tracing.Tracer(
            os.path.join(telemetry_dir, TRACE_FILENAME),
            max_bytes=DEFAULT_MAX_BYTES, keep=ROTATE_KEEP,
        )
    table.poll_once()
    httpd = ThreadingHTTPServer((host, port), _RouterHandler)
    httpd.wavetpu_router = state
    if control_plane_dir is not None:
        state.store = ControlPlaneStore(
            control_plane_dir, fault_plan=fault_plan
        )
        bound = httpd.server_address
        owner = ha_owner or f"{bound[0]}:{bound[1]}#{os.getpid()}"
        lease = fleet_ha.LeaseManager(
            control_plane_dir, owner, ttl_s=lease_ttl_s,
            fault_plan=fault_plan,
        )
        state.ha = fleet_ha.HACoordinator(
            state.store, lease,
            export_state=state.export_state,
            restore_state=state.restore_state,
            flush_interval_s=store_flush_interval_s,
        )
        if start_ha:
            state.ha.start()
    if start_poller:
        state.start_poller(poll_interval_s)
    return httpd, state


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        pos, flags = split_flags(
            argv,
            known=("member", "host", "port", "poll-interval-s",
                   "fail-threshold", "proxy-timeout-s",
                   "max-body-bytes", "min-retry-budget-ms",
                   "api-keys-file", "quota-default-rps",
                   "quota-default-burst", "quota-default-cells-per-s",
                   "quota-default-cells-burst", "proxy-token",
                   "telemetry-dir", "control-plane-dir",
                   "lease-ttl-s", "store-flush-interval-s",
                   "edge-cache", "edge-cache-max-bytes",
                   "edge-cache-ttl-s"),
            valueless=("edge-cache",),
            allow_positionals=False,
            repeatable=("member",),
        )
        members = list(flags.get("member") or [])
        if not members:
            raise ValueError("router needs at least one --member URL")
        host = flags.get("host", "127.0.0.1")
        port = int(flags.get("port", "8070"))
        poll_interval_s = float(flags.get("poll-interval-s", "2"))
        fail_threshold = int(flags.get("fail-threshold", "3"))
        proxy_timeout = float(flags.get("proxy-timeout-s", "120"))
        max_body_bytes = (
            int(flags["max-body-bytes"])
            if "max-body-bytes" in flags else None
        )
        min_retry_budget_ms = float(
            flags.get("min-retry-budget-ms", "50")
        )
        api_keys = (
            load_api_keys(flags["api-keys-file"])
            if "api-keys-file" in flags else None
        )
        quotas = quota.QuotaManager(
            default_rps=(
                float(flags["quota-default-rps"])
                if "quota-default-rps" in flags else None
            ),
            default_burst=(
                float(flags["quota-default-burst"])
                if "quota-default-burst" in flags else None
            ),
            default_cells_per_s=(
                float(flags["quota-default-cells-per-s"])
                if "quota-default-cells-per-s" in flags else None
            ),
            default_cells_burst=(
                float(flags["quota-default-cells-burst"])
                if "quota-default-cells-burst" in flags else None
            ),
        )
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2
    try:
        lease_ttl_s = float(flags.get("lease-ttl-s", "2"))
        store_flush_interval_s = float(
            flags.get("store-flush-interval-s", "0.5")
        )
        edge_cache_max_bytes = (
            int(flags["edge-cache-max-bytes"])
            if "edge-cache-max-bytes" in flags else None
        )
        edge_cache_ttl_s = (
            float(flags["edge-cache-ttl-s"])
            if "edge-cache-ttl-s" in flags else None
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2
    httpd, state = build_router(
        members, host=host, port=port,
        poll_interval_s=poll_interval_s, fail_threshold=fail_threshold,
        proxy_timeout=proxy_timeout, max_body_bytes=max_body_bytes,
        min_retry_budget_ms=min_retry_budget_ms, api_keys=api_keys,
        telemetry_dir=flags.get("telemetry-dir"),
        quotas=quotas, proxy_token=flags.get("proxy-token"),
        control_plane_dir=flags.get("control-plane-dir"),
        lease_ttl_s=lease_ttl_s,
        store_flush_interval_s=store_flush_interval_s,
        edge_cache="edge-cache" in flags,
        edge_cache_max_bytes=edge_cache_max_bytes,
        edge_cache_ttl_s=edge_cache_ttl_s,
    )
    if state.edge is not None:
        print(
            f"edge cache: on ({state.edge.max_bytes >> 20} MiB, "
            f"ttl {state.edge.ttl_s:g}s)"
        )
    if api_keys is not None:
        n_tenants = len({c.tenant for c in api_keys.values()})
        n_quota = sum(
            1 for c in api_keys.values()
            if c.rps is not None or c.cells_per_s is not None
        )
        print(f"api keys: {len(api_keys)} key(s) -> "
              f"{n_tenants} tenant(s), {n_quota} with quotas")
    if state.tracer is not None:
        print(f"telemetry: router spans -> {state.tracer.path}")
    if state.ha is not None:
        print(
            f"control plane: {flags['control-plane-dir']} "
            f"(role {state.role}, lease ttl {lease_ttl_s:g}s, "
            f"flush every {store_flush_interval_s:g}s)"
        )
    bound = httpd.server_address
    up = len(state.table.routable_urls())
    print(
        f"wavetpu router on http://{bound[0]}:{bound[1]} "
        f"({up}/{len(members)} members up, poll every "
        f"{poll_interval_s:g}s, fail threshold {fail_threshold})"
    )
    for m in state.table.summary():
        print(f"  member {m['url']}: {m['state']}"
              + (f" [{m['backend']}]" if m["backend"] else ""))
    import signal

    def _shutdown(signum, frame):
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    try:
        httpd.serve_forever()
    finally:
        state.stop_poller()
        if state.ha is not None:
            # Orderly exit: final flush + lease release so a standby
            # promotes immediately instead of waiting out the TTL.
            state.ha.stop(release=True)
        httpd.server_close()
        if state.tracer is not None:
            state.tracer.close()
    print("wavetpu router: shut down")
    return 0


if __name__ == "__main__":
    sys.exit(main())
