"""Router high availability: single-writer lease + active/standby.

N `wavetpu router --control-plane-dir DIR` processes sharing one store
elect exactly ONE active router through a file-based lease:

 * `lease.json` names the current holder: `{"owner", "epoch",
   "acquired_unix", "renewed_unix", "ttl_s"}`.  A lease whose
   `renewed_unix` is more than `ttl_s` old is EXPIRED - the holder
   stopped renewing (crashed, partitioned, SIGKILLed) and any standby
   may take it.
 * Mutations (acquire / renew / release) happen under `lease.lock`, a
   bare O_CREAT|O_EXCL file - the only primitive the filesystem gives
   us that is atomic on every POSIX target.  A lock older than a few
   seconds is broken (its holder died mid-mutation).
 * `epoch` increments on every ACQUISITION (never on renewal): the
   fencing token.  A deposed active discovers the loss on its next
   renewal (owner/epoch mismatch) and demotes itself; it can never
   renew its way back into a lease someone else took.

`HACoordinator` runs the role loop in a daemon thread:

 * ACTIVE: renew the lease every tick, flush the router's exported
   state to the store every `flush_interval_s`, compact periodically.
   A failed renewal = the lease is lost -> demote to standby
   immediately (fail-safe direction: a false demotion costs one
   takeover gap; a false retention costs split-brain).
 * STANDBY: answer /solve with a retriable 503 (`"standby": true`, so
   the multi-endpoint WavetpuClient rotates instead of backing off),
   poll the lease each tick, and on expiry acquire it, RESTORE the
   persisted state into the router (quota-bucket levels, membership
   freeze/baselines, counters, affinity), and start serving - within
   about one lease TTL of the active's death.

Stdlib-only; never imports jax.  Runbook: docs/fleet.md.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

LEASE_NAME = "lease.json"
LOCK_NAME = "lease.lock"

# A lease.lock older than this is a dead mutator's leftover: break it.
_STALE_LOCK_S = 5.0

ACTIVE = "active"
STANDBY = "standby"


class LeaseManager:
    """The file lease: acquire / renew / release with epoch fencing.

    `clock` is injectable for deterministic tests.  All methods are
    safe to call from any thread of any process sharing the dir."""

    def __init__(self, root: str, owner: str, ttl_s: float = 2.0,
                 clock: Callable[[], float] = time.time,
                 fault_plan=None):
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.owner = owner
        self.ttl_s = float(ttl_s)
        self.path = os.path.join(root, LEASE_NAME)
        self.lock_path = os.path.join(root, LOCK_NAME)
        self._clock = clock
        self.fault_plan = fault_plan
        self.epoch = 0          # the epoch WE hold (0 = not holding)
        self.acquisitions_total = 0
        self.renew_failures_total = 0

    # ---- the on-disk lock (mutation critical section) ----

    def _take_lock(self) -> bool:
        try:
            fd = os.open(self.lock_path,
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            return True
        except FileExistsError:
            try:
                age = self._clock() - os.path.getmtime(self.lock_path)
            except OSError:
                return False  # racing remover; retry next tick
            if age > _STALE_LOCK_S:
                # The locker died mid-mutation: break the lock.  The
                # O_EXCL recreate below races fairly among breakers.
                try:
                    os.unlink(self.lock_path)
                except OSError:
                    pass
                try:
                    fd = os.open(self.lock_path,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.close(fd)
                    return True
                except OSError:
                    return False
            return False
        except OSError:
            return False

    def _drop_lock(self) -> None:
        try:
            os.unlink(self.lock_path)
        except OSError:
            pass

    # ---- reads ----

    def read(self) -> Optional[dict]:
        """The current lease record, or None (missing/corrupt - corrupt
        reads as absent so a torn lease write can only DELAY an
        acquisition by one tick, never wedge the fleet)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                lease = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(lease, dict):
            return None
        return lease

    def _expired(self, lease: dict) -> bool:
        try:
            renewed = float(lease["renewed_unix"])
            ttl = float(lease.get("ttl_s") or self.ttl_s)
        except (KeyError, TypeError, ValueError):
            return True  # unreadable fields = not a live claim
        return self._clock() - renewed > ttl

    def holder(self) -> Optional[str]:
        lease = self.read()
        if lease is None or self._expired(lease):
            return None
        return lease.get("owner")

    @property
    def held(self) -> bool:
        return self.epoch > 0

    # ---- mutations ----

    def _write(self, lease: dict) -> None:
        tmp = self.path + f".tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(lease, f)
            f.flush()
        os.replace(tmp, self.path)

    def try_acquire(self) -> bool:
        """Take the lease iff it is free, expired, or already ours.
        A NEW acquisition (not a reclaim of our own epoch) bumps the
        epoch - the fencing token every flush rides."""
        if not self._take_lock():
            return False
        try:
            now = self._clock()
            lease = self.read()
            if lease is not None and not self._expired(lease) \
                    and lease.get("owner") != self.owner:
                return False
            if lease is not None and lease.get("owner") == self.owner \
                    and not self._expired(lease) \
                    and int(lease.get("epoch") or 0) == self.epoch \
                    and self.epoch > 0:
                return True  # already ours and live
            try:
                prev_epoch = int((lease or {}).get("epoch") or 0)
            except (TypeError, ValueError):
                prev_epoch = 0
            self.epoch = prev_epoch + 1
            self.acquisitions_total += 1
            self._write({
                "owner": self.owner,
                "epoch": self.epoch,
                "acquired_unix": round(now, 3),
                "renewed_unix": round(now, 3),
                "ttl_s": self.ttl_s,
            })
            return True
        finally:
            self._drop_lock()

    def renew(self) -> bool:
        """Refresh our claim.  False = the lease is no longer ours
        (someone fenced us out, the file vanished, or a
        `store-stale-lease` chaos injection fired) - the caller MUST
        demote; it may try_acquire again next tick."""
        if self.epoch <= 0:
            return False
        if self.fault_plan is not None and self.fault_plan.fire(
                "store-stale-lease") is not None:
            # Chaos: this renewal "observes" a stale/foreign lease, the
            # exact thing a paused-then-resumed active would see.  The
            # holder must demote (and may re-acquire cleanly after).
            self.epoch = 0
            self.renew_failures_total += 1
            return False
        if not self._take_lock():
            # Could not enter the critical section this tick; the lease
            # record is untouched, so our claim stands until TTL.  Only
            # repeated failures (> TTL) cost the lease.
            return True
        try:
            lease = self.read()
            if (
                lease is None
                or lease.get("owner") != self.owner
                or int(lease.get("epoch") or 0) != self.epoch
            ):
                self.epoch = 0
                self.renew_failures_total += 1
                return False
            lease["renewed_unix"] = round(self._clock(), 3)
            self._write(lease)
            return True
        except (OSError, TypeError, ValueError):
            self.epoch = 0
            self.renew_failures_total += 1
            return False
        finally:
            self._drop_lock()

    def release(self) -> None:
        """Orderly handoff: mark our lease expired (renewed_unix 0, a
        time every clock agrees is past TTL) so a standby takes over
        immediately instead of waiting out the TTL.  The record - and
        its epoch - stays on disk: the fencing counter must be
        monotonic across releases, not just crashes."""
        if self.epoch <= 0:
            return
        if not self._take_lock():
            self.epoch = 0
            return
        try:
            lease = self.read()
            if lease is not None and lease.get("owner") == self.owner \
                    and int(lease.get("epoch") or 0) == self.epoch:
                lease["renewed_unix"] = 0.0
                lease["released"] = True
                try:
                    self._write(lease)
                except OSError:
                    pass
        finally:
            self.epoch = 0
            self._drop_lock()


class HACoordinator:
    """The role loop gluing a RouterState to the store + lease.

    `export_state()` / `restore_state(state)` are the router's
    callbacks (RouterState provides them); `on_promote` fires after a
    standby finishes restoring and flips active (tests hook it)."""

    def __init__(self, store, lease: LeaseManager,
                 export_state: Callable[[], dict],
                 restore_state: Callable[[dict], None],
                 flush_interval_s: float = 0.5,
                 compact_every: int = 64,
                 on_promote: Optional[Callable[[], None]] = None):
        self.store = store
        self.lease = lease
        self._export = export_state
        self._restore = restore_state
        self.flush_interval_s = max(0.01, float(flush_interval_s))
        self.compact_every = max(1, int(compact_every))
        self.on_promote = on_promote
        self.role = STANDBY
        self.takeovers_total = 0
        self.flushes_total = 0
        self.demotions_total = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._flushes_since_compact = 0

    # ---- lifecycle ----

    def start(self) -> None:
        """One synchronous election tick first (a lone router boots
        straight to active with its state restored, before it serves a
        single request), then the background loop."""
        self.tick()
        self._thread = threading.Thread(
            target=self._run, name="wavetpu-router-ha", daemon=True
        )
        self._thread.start()

    def stop(self, release: bool = True) -> None:
        """Orderly shutdown: final flush + lease release so a standby
        promotes immediately.  `release=False` simulates a crash
        (tests): the lease must expire on its own."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if release and self.role == ACTIVE:
            try:
                self.flush(compact=True)
            except Exception:
                pass
            self.lease.release()
        with self._lock:
            self.role = STANDBY

    def _run(self) -> None:
        # Tick fast enough that a renewal always lands well inside the
        # TTL and a standby notices expiry within ~half a TTL.
        interval = min(self.flush_interval_s, self.lease.ttl_s / 3.0)
        while not self._stop.wait(interval):
            try:
                self.tick()
            except Exception:
                pass  # the role loop must never die to one bad tick

    # ---- the role machine ----

    def tick(self) -> None:
        if self.role == ACTIVE:
            if not self.lease.renew():
                # Fenced out (or chaos said so): demote NOW.  Serving
                # one extra request as a deposed active is the
                # split-brain direction; a spurious demotion costs one
                # takeover gap.
                with self._lock:
                    self.role = STANDBY
                    self.demotions_total += 1
                return
            self.flush()
            return
        # standby
        if self.lease.try_acquire():
            state = self.store.load()
            if state:
                try:
                    self._restore(state)
                except Exception:
                    pass  # partial restore beats refusing to serve
            with self._lock:
                self.role = ACTIVE
                self.takeovers_total += 1
            if self.on_promote is not None:
                try:
                    self.on_promote()
                except Exception:
                    pass

    def flush(self, compact: bool = False) -> None:
        """Persist the router's current exported state (one WAL record
        per section), compacting every `compact_every` flushes."""
        state = self._export()
        for section, data in state.items():
            self.store.append(section, data)
        with self._lock:
            self.flushes_total += 1
            self._flushes_since_compact += 1
            due = self._flushes_since_compact >= self.compact_every
            if compact or due:
                self._flushes_since_compact = 0
        if compact or due:
            self.store.compact(state)

    # ---- views ----

    def snapshot(self) -> dict:
        lease = self.lease.read() or {}
        with self._lock:
            return {
                "role": self.role,
                "owner": self.lease.owner,
                "epoch": self.lease.epoch,
                "lease_owner": lease.get("owner"),
                "lease_epoch": lease.get("epoch"),
                "lease_ttl_s": self.lease.ttl_s,
                "takeovers_total": self.takeovers_total,
                "demotions_total": self.demotions_total,
                "flushes_total": self.flushes_total,
                "acquisitions_total": self.lease.acquisitions_total,
                "renew_failures_total":
                    self.lease.renew_failures_total,
            }

    def prom_samples(self) -> dict:
        snap = self.snapshot()
        return {
            "wavetpu_fleet_ha_takeovers_total": snap["takeovers_total"],
            "wavetpu_fleet_ha_demotions_total": snap["demotions_total"],
            "wavetpu_fleet_ha_flushes_total": snap["flushes_total"],
            "wavetpu_fleet_ha_renew_failures_total":
                snap["renew_failures_total"],
            "wavetpu_fleet_ha_lease_epoch": snap["epoch"],
            "wavetpu_fleet_ha_active":
                1.0 if snap["role"] == ACTIVE else 0.0,
        }
