"""Health-gated fleet membership: who is routable, right now.

A `Member` is one serve replica; the `MembershipTable` is the router's
authoritative view of the fleet.  The state machine (docs/fleet.md):

    joining --(healthz ready)--> up
    up --(ready:false / FAIL_THRESHOLD consecutive transport
          failures)--> ejected
    ejected --(healthz ready again)--> up          (re-admission)
    any --(/admin/leave)--> leaving --> left       (terminal)

Only `up` members receive new traffic.  `ejected` members stay in the
table and keep being polled - a replica that was draining, restarting,
or partitioned re-admits itself the moment its /healthz says ready
again, with no operator action.  `left` is terminal: the member's last
parsed Prometheus snapshot is kept FROZEN so the router's aggregated
/metrics stay monotonic across a rolling deploy (a loadgen delta
bracketing a roll must never see counters go backwards because a
replica left the fleet).

Every poll also refreshes the affinity inputs: the member's JSON
/metrics `program_cache.warm_keys` block (which programs it already
holds, memory and disk) and its `queue_depth` (the load half of
power-of-two-choices).

Transport is injectable (`fetch=`) so the state machine is testable
with zero sockets; the default fetch is a short-lived stdlib
urllib request per poll (polls are rare - keep-alive lives in the
proxy data path, not here).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# states
JOINING = "joining"
UP = "up"
EJECTED = "ejected"
LEAVING = "leaving"
LEFT = "left"

ROUTABLE = (UP,)

FetchFn = Callable[[str, str, float, Optional[str]], Tuple[int, str]]


def default_fetch(base_url: str, path: str, timeout: float,
                  accept: Optional[str] = None) -> Tuple[int, str]:
    """GET base_url+path -> (status, body text).  Raises OSError family
    on transport failure (the caller counts those toward ejection)."""
    req = urllib.request.Request(
        base_url.rstrip("/") + path,
        headers={"Accept": accept} if accept else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read().decode()


class Member:
    """One replica's membership record (mutated only under the table's
    lock; `inflight` is the router's own in-flight counter - the
    fresher load signal between metric polls)."""

    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")
        self.state = JOINING
        self.joined_unix = time.time()
        self.consecutive_failures = 0
        self.last_error: Optional[str] = None
        self.health: dict = {}
        self.backend: Optional[str] = None
        self.queue_depth: int = 0
        self.inflight: int = 0
        self.warm_key_count: int = 0
        # Last successfully parsed Prometheus cut {sample: value} -
        # frozen at departure for monotonic fleet aggregation.
        self.prom: Dict[str, float] = {}
        # Join-time snapshot of the member's CUMULATIVE samples,
        # subtracted from its aggregate contribution: a replica
        # admitted mid-flight (rolling deploy) must not inject its
        # pre-join history - e.g. manifest-warmup compiles - into a
        # loadgen delta bracketing the roll.  Empty for founding
        # members (their history IS the fleet's history).
        self.prom_baseline: Dict[str, float] = {}
        self.baseline_pending: bool = False
        self.last_poll_unix: Optional[float] = None
        self.transitions: List[dict] = []

    @property
    def routable(self) -> bool:
        return self.state in ROUTABLE

    def summary(self) -> dict:
        return {
            "url": self.base_url,
            "state": self.state,
            "backend": self.backend,
            "queue_depth": self.queue_depth,
            "inflight": self.inflight,
            "warm_keys": self.warm_key_count,
            "consecutive_failures": self.consecutive_failures,
            "last_error": self.last_error,
        }


def _is_cumulative(sample_name: str) -> bool:
    """True for counter/histogram samples (the ones join-baselining
    applies to); gauges must pass through absolute."""
    bare = sample_name.split("{", 1)[0]
    return bare.endswith(("_total", "_count", "_sum", "_bucket"))


def _parse_prometheus_text(text: str) -> Dict[str, float]:
    """Same minimal parser shape as loadgen/runner.py (duplicated by
    value, not import - loadgen is a peer tier, not a dependency)."""
    samples: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        if " # " in line:
            line = line.split(" # ", 1)[0]
        name, _, value = line.rpartition(" ")
        if not name:
            continue
        try:
            samples[name] = float(value.replace("+Inf", "inf"))
        except ValueError:
            continue
    return samples


class MembershipTable:
    """The fleet view: poll, admit, eject, re-admit, retire.

    `fail_threshold` transport failures in a row eject (one flaky poll
    must not empty the rotation); a single `ready: false` ejects
    immediately - the replica SAID do not route here (warming or
    draining), believing it is the whole point of readiness."""

    def __init__(self, member_urls: Sequence[str],
                 fail_threshold: int = 3,
                 poll_timeout: float = 5.0,
                 fetch: Optional[FetchFn] = None,
                 affinity=None):
        self._lock = threading.RLock()
        self._members: Dict[str, Member] = {}
        self.fail_threshold = max(1, int(fail_threshold))
        self.poll_timeout = poll_timeout
        self._fetch = fetch or default_fetch
        # AffinityTable (fleet/affinity.py), fed warm-key observations
        # from every metrics poll; optional so membership is testable
        # alone.
        self.affinity = affinity
        for url in member_urls:
            self.add(url)

    # ---- membership edits ----

    def add(self, base_url: str, baseline: bool = False) -> Member:
        """Join (or re-join) a member.  Re-adding a LEFT url starts a
        fresh record - the frozen counters of the old incarnation stay
        aggregated under a retired alias so deltas stay monotonic.

        `baseline=True` (the /admin/join path) snapshots the member's
        cumulative samples at its first metrics parse and subtracts
        them from its aggregate contribution: a mid-flight joiner's
        pre-join work (manifest-warmup compiles, direct traffic) is not
        fleet work and must not appear as delta growth to a scrape
        bracketing the join."""
        url = base_url.rstrip("/")
        with self._lock:
            existing = self._members.get(url)
            if existing is not None and existing.state != LEFT:
                return existing
            if existing is not None:
                # Retire the old incarnation under an alias key; its
                # frozen prom snapshot must keep contributing.
                alias = f"{url}#retired-{len(self._members)}"
                self._members[alias] = existing
            m = Member(url)
            m.baseline_pending = bool(baseline)
            self._record(m, JOINING, "joined")
            self._members[url] = m
            return m

    def leave(self, base_url: str) -> Optional[Member]:
        """Mark a member LEAVING (out of rotation immediately).  The
        caller (router leave handler / roll driver) is responsible for
        draining it and calling `retire` once its counters are final."""
        url = base_url.rstrip("/")
        with self._lock:
            m = self._members.get(url)
            if m is None:
                return None
            self._record(m, LEAVING, "leave requested")
            return m

    def retire(self, base_url: str) -> None:
        """LEAVING -> LEFT: the member's prom snapshot is now frozen."""
        url = base_url.rstrip("/")
        with self._lock:
            m = self._members.get(url)
            if m is not None and m.state != LEFT:
                self._record(m, LEFT, "retired (counters frozen)")
                if self.affinity is not None:
                    self.affinity.forget_member(url)

    def _record(self, m: Member, state: str, why: str) -> None:
        m.state = state
        m.transitions.append({
            "unix": round(time.time(), 3), "state": state, "why": why,
        })

    # ---- views ----

    def members(self) -> List[Member]:
        with self._lock:
            return list(self._members.values())

    def get(self, base_url: str) -> Optional[Member]:
        with self._lock:
            return self._members.get(base_url.rstrip("/"))

    def routable_members(self) -> List[Member]:
        with self._lock:
            return [m for m in self._members.values() if m.routable]

    def routable_urls(self) -> List[str]:
        return [m.base_url for m in self.routable_members()]

    def summary(self) -> List[dict]:
        with self._lock:
            return [m.summary() for m in self._members.values()]

    # ---- persistence (fleet/store.py) ----

    def export_state(self) -> dict:
        """Durable membership view: every record's state-machine
        position, frozen/last Prometheus snapshot, join baseline, and
        last health block (the per-member brownout rung rides in
        there).  Retired aliases export too - they carry the frozen
        counters that keep fleet deltas monotonic across deploys."""
        with self._lock:
            return {
                url: {
                    "state": m.state,
                    "base_url": m.base_url,
                    "joined_unix": round(m.joined_unix, 3),
                    "prom": dict(m.prom),
                    "prom_baseline": dict(m.prom_baseline),
                    "baseline_pending": m.baseline_pending,
                    "health": m.health if isinstance(m.health, dict)
                    else {},
                    "warm_keys": m.warm_key_count,
                }
                for url, m in self._members.items()
            }

    def restore_state(self, data: dict) -> int:
        """Adopt a predecessor's membership view.  LEFT records (and
        retired aliases) restore FROZEN - their snapshots keep
        aggregating, which is what makes fleet /metrics monotonic
        across a router restart.  Live records merge conservatively:
        unknown urls join as JOINING (the next poll decides
        routability - restoring UP outright could route to a corpse),
        known urls adopt the persisted baseline/prom only where the
        live record has none yet (a fresher poll always wins).
        Malformed entries are skipped.  Returns records adopted."""
        if not isinstance(data, dict):
            return 0
        adopted = 0
        for key, rec in data.items():
            if not isinstance(rec, dict):
                continue
            state = rec.get("state")
            prom = rec.get("prom")
            prom = prom if isinstance(prom, dict) else {}
            baseline = rec.get("prom_baseline")
            baseline = baseline if isinstance(baseline, dict) else {}
            with self._lock:
                m = self._members.get(key)
                if m is None:
                    m = Member(rec.get("base_url") or key)
                    if state in (LEFT, LEAVING):
                        # Frozen history: never polled again.
                        m.state = LEFT
                        m.prom = {
                            k: float(v) for k, v in prom.items()
                            if isinstance(v, (int, float))
                        }
                        m.prom_baseline = {
                            k: float(v) for k, v in baseline.items()
                            if isinstance(v, (int, float))
                        }
                    else:
                        m.state = JOINING
                        m.prom_baseline = {
                            k: float(v) for k, v in baseline.items()
                            if isinstance(v, (int, float))
                        }
                        m.baseline_pending = bool(
                            rec.get("baseline_pending")
                        )
                        if isinstance(rec.get("health"), dict):
                            m.health = rec["health"]
                    self._members[key] = m
                    adopted += 1
                    continue
                # Known url: fill only the gaps a fresh process has.
                if not m.prom_baseline and baseline:
                    m.prom_baseline = {
                        k: float(v) for k, v in baseline.items()
                        if isinstance(v, (int, float))
                    }
                    m.baseline_pending = False
                if not m.prom and prom and m.state == LEFT:
                    m.prom = {
                        k: float(v) for k, v in prom.items()
                        if isinstance(v, (int, float))
                    }
                if not m.health and isinstance(rec.get("health"), dict):
                    m.health = rec["health"]
                adopted += 1
        return adopted

    # ---- the poll ----

    def poll_member(self, m: Member) -> None:
        """One health + metrics poll of one member, applying the state
        machine.  LEFT members are never polled (frozen)."""
        if m.state == LEFT:
            return
        try:
            status, text = self._fetch(
                m.base_url, "/healthz", self.poll_timeout, None
            )
            health = json.loads(text)
        except Exception as e:  # transport/parse = one failure strike
            with self._lock:
                m.consecutive_failures += 1
                m.last_error = f"{type(e).__name__}: {e}"
                m.last_poll_unix = time.time()
                if (m.state in (UP, JOINING)
                        and m.consecutive_failures >= self.fail_threshold):
                    self._record(
                        m, EJECTED,
                        f"{m.consecutive_failures} consecutive "
                        f"transport failures",
                    )
            return
        with self._lock:
            m.consecutive_failures = 0
            m.last_error = None
            m.health = health
            m.last_poll_unix = time.time()
            m.backend = health.get("backend") or m.backend
            ready = (
                status == 200 and health.get("status") == "ok"
                and health.get("ready") is not False
            )
            if m.state in (JOINING, EJECTED) and ready:
                self._record(m, UP, "healthz ready")
            elif m.state == UP and not ready:
                self._record(
                    m, EJECTED,
                    "ready: false "
                    f"(warming={health.get('warming')}, "
                    f"draining={health.get('draining')})",
                )
        # Metrics refresh even for ejected/leaving members: a draining
        # replica's final counters and warm keys are still true, and a
        # recovering one should re-admit with a warm table, not a cold
        # one.
        self.refresh_metrics(m)

    def refresh_metrics(self, m: Member) -> None:
        """Best-effort refresh of one member's JSON metrics (warm keys,
        queue depth) and Prometheus cut (aggregation snapshot)."""
        if m.state == LEFT:
            return
        try:
            _, text = self._fetch(
                m.base_url, "/metrics", self.poll_timeout,
                "application/json",
            )
            snap = json.loads(text)
        except Exception:
            snap = None
        if isinstance(snap, dict):
            warm = (snap.get("program_cache") or {}).get("warm_keys")
            with self._lock:
                try:
                    m.queue_depth = int(snap.get("queue_depth") or 0)
                except (TypeError, ValueError):
                    pass
            if isinstance(warm, dict) and self.affinity is not None:
                n = self.affinity.observe_warm_keys(m.base_url, warm)
                with self._lock:
                    m.warm_key_count = n
        try:
            _, prom_text = self._fetch(
                m.base_url, "/metrics", self.poll_timeout, "text/plain"
            )
            prom = _parse_prometheus_text(prom_text)
        except Exception:
            return
        if prom:
            with self._lock:
                if m.baseline_pending:
                    m.prom_baseline = {
                        k: v for k, v in prom.items()
                        if _is_cumulative(k)
                    }
                    m.baseline_pending = False
                m.prom = prom

    def poll_once(self) -> None:
        for m in self.members():
            self.poll_member(m)

    # ---- aggregation ----

    def aggregate_prom(self, refresh: bool = True) -> Dict[str, float]:
        """Fleet-wide Prometheus cut: sample-wise sum of every member's
        last counters - LIVE members freshly fetched (refresh=True, the
        scrape path), departed/unreachable ones contributing their last
        (frozen) snapshot, mid-flight joiners contributing their growth
        SINCE join (cumulative samples minus the join baseline, clamped
        at zero in case the same URL restarted with reset counters).
        Deltas of the sum across a roll stay monotonic because no
        snapshot is ever dropped."""
        if refresh:
            for m in self.members():
                if m.state != LEFT:
                    self.refresh_metrics(m)
        out: Dict[str, float] = {}
        with self._lock:
            for m in self._members.values():
                for name, value in m.prom.items():
                    base = m.prom_baseline.get(name)
                    if base is not None:
                        value = max(0.0, value - base)
                    out[name] = out.get(name, 0.0) + value
        return out
