"""Fault-injection harness: break things on purpose, prove recovery fires.

io/checkpoint.py and io/nativeio.py carry carefully written rejection
branches (CRC footers, truncation checks, mixed-step detection) that no
test exercised until this module existed: a recovery path that has never
run is a liability, not a feature.  The injectors below are used by
tests/test_faults.py and tests/test_supervisor.py to drive every branch:

 * on-disk faults - `flip_byte` (CRC failure), `truncate_tail`
   (structural truncation), `rewrite_shard_step` (stale-step shard with a
   VALID CRC, i.e. the mixed-step fallback, not the checksum)
 * in-flight faults - chunk hooks for run/supervisor.py's fault port:
   `nan_at_step` (a NaN the watchdog must catch), `preempt_at_step` (a
   real SIGTERM/SIGINT delivered to this process mid-march - the
   kill-and-resume drill)

Chunk hooks have signature `hook(state, step) -> state` and run after a
chunk completes, BEFORE the health check and checkpoint save - exactly
where a hardware glitch would land.  `hook_from_env` wires the same
injectors to the `WAVETPU_FAULT` env var ("nan:STEP" | "preempt:STEP")
so CLI-level tests can drill the full exit-code path of a live process.
"""

from __future__ import annotations

import os
import signal
from typing import Optional


# ---------------------------------------------------------------- on disk


def flip_byte(path: str, offset: Optional[int] = None, xor: int = 0x01):
    """XOR one byte of `path` in place (default: mid-file, where a shard's
    array payload lives) - the minimal corruption a CRC must catch."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty; nothing to flip")
    if offset is None:
        offset = size // 2
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ (xor & 0xFF)]))
    return offset


def truncate_tail(path: str, drop_bytes: int = 16) -> int:
    """Chop `drop_bytes` off the end of `path` (a torn write / full disk /
    killed writer).  Returns the new size."""
    size = os.path.getsize(path)
    new = max(0, size - drop_bytes)
    with open(path, "r+b") as f:
        f.truncate(new)
    return new


def rewrite_shard_step(ckpt_dir: str, new_step: int,
                       shard_name: Optional[str] = None) -> str:
    """Rewrite one WTS shard of a sharded checkpoint with `new_step` in its
    meta - CRC-valid but disagreeing with meta.npz, i.e. the stale shard a
    preempted save-over-older-checkpoint leaves behind.  Returns the shard
    path."""
    from wavetpu.io import nativeio

    if shard_name is None:
        shards = sorted(
            f for f in os.listdir(ckpt_dir)
            if f.startswith("shard_") and f.endswith(".wts")
        )
        if not shards:
            raise FileNotFoundError(f"no .wts shards in {ckpt_dir}")
        shard_name = shards[0]
    path = os.path.join(ckpt_dir, shard_name)
    fields, meta = nativeio.read_container(path)
    meta = dict(meta, step=int(new_step))
    nativeio.write_container_sync(path, fields, meta)
    return path


# --------------------------------------------------------------- in flight


def nan_at_step(step: int, array_index: int = 1, once: bool = True):
    """Chunk hook: poison one element of state array `array_index` (default
    1 = u_cur for every path's state convention) with NaN at the first
    chunk boundary >= `step`.  With `once` (the transient-fault model) the
    second attempt after an auto-retry reload runs clean."""
    fired = [False]

    def hook(state, cur_step):
        if cur_step < step or (once and fired[0]):
            return state
        fired[0] = True
        import jax.numpy as jnp

        state = list(state)
        a = state[array_index]
        flat_nan = jnp.ravel(a).at[0].set(float("nan")).reshape(a.shape)
        state[array_index] = flat_nan.astype(a.dtype)
        return tuple(state)

    return hook


def preempt_at_step(step: int, sig: int = signal.SIGTERM, once: bool = True):
    """Chunk hook: deliver `sig` to THIS process at the first chunk
    boundary >= `step` - a deterministic stand-in for the scheduler's
    preemption notice.  The supervisor's handler must then finish the
    bookkeeping, save, and exit resumable (exit code 3)."""
    fired = [False]

    def hook(state, cur_step):
        if cur_step >= step and not (once and fired[0]):
            fired[0] = True
            os.kill(os.getpid(), sig)
        return state

    return hook


ENV_FAULT = "WAVETPU_FAULT"


def hook_from_env(env: Optional[dict] = None):
    """The CLI port of the harness: WAVETPU_FAULT="nan:STEP" or
    "preempt:STEP" returns the matching chunk hook (None when unset).
    Lets subprocess/CLI tests drill the watchdog-halt (exit 4) and
    kill-and-resume (exit 3) paths without timing races."""
    env = os.environ if env is None else env
    spec = env.get(ENV_FAULT)
    if not spec:
        return None
    kind, _, at = spec.partition(":")
    step = int(at)
    if kind == "nan":
        return nan_at_step(step)
    if kind == "preempt":
        return preempt_at_step(step)
    raise ValueError(
        f"{ENV_FAULT}={spec!r}: want 'nan:STEP' or 'preempt:STEP'"
    )
