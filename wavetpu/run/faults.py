"""Fault-injection harness: break things on purpose, prove recovery fires.

io/checkpoint.py and io/nativeio.py carry carefully written rejection
branches (CRC footers, truncation checks, mixed-step detection) that no
test exercised until this module existed: a recovery path that has never
run is a liability, not a feature.  The injectors below are used by
tests/test_faults.py and tests/test_supervisor.py to drive every branch:

 * on-disk faults - `flip_byte` (CRC failure), `truncate_tail`
   (structural truncation), `rewrite_shard_step` (stale-step shard with a
   VALID CRC, i.e. the mixed-step fallback, not the checksum)
 * in-flight faults - chunk hooks for run/supervisor.py's fault port:
   `nan_at_step` (a NaN the watchdog must catch), `preempt_at_step` (a
   real SIGTERM/SIGINT delivered to this process mid-march - the
   kill-and-resume drill)

Chunk hooks have signature `hook(state, step) -> state` and run after a
chunk completes, BEFORE the health check and checkpoint save - exactly
where a hardware glitch would land.  `hook_from_env` wires the same
injectors to the `WAVETPU_FAULT` env var ("nan:STEP" | "preempt:STEP")
so CLI-level tests can drill the full exit-code path of a live process.

Since the serving-resilience round the SAME env var also ports the
harness into `wavetpu serve`: semicolon-separated `serve-*` specs build
a `ServeFaultPlan` (`serve_plan_from_env`) that the engine, scheduler,
and HTTP layer consult at their seams -

 * `serve-compile-fail[:SELECTOR,count=N]` - program build/compile for
   matching ProgramKeys raises `InjectedFault` (drives the circuit
   breaker and the retrying client);
 * `serve-execute-nan[:SELECTOR,count=N]`  - a matching batch's final
   state is poisoned with NaN AFTER the solve, proving the per-lane
   watchdog 422s it;
 * `serve-slow-batch:seconds=S[,SELECTOR]` - the worker sleeps S before
   executing a matching batch, or before EACH CHUNK of a matching
   chunked long solve (deadline/queue-growth/preemption drills);
 * `serve-worker-crash[:after=N,count=K]`  - the scheduler worker
   raises mid-batch (its supervisor must restart it and fail in-flight
   futures with retriable 503s, never hang them);
 * `serve-conn-drop[:count=N]`             - the HTTP handler closes
   the connection without a response (client transport-retry drill);
 * `serve-progcache-truncate[:SELECTOR,count=N]` - a matching
   persistent program-cache entry is truncated ON DISK just before the
   read (serve/progcache.py), driving the real checksum/length
   rejection branch: a counted miss and a clean recompile;
 * `serve-progcache-fingerprint[:SELECTOR,count=N]` - the expected
   environment fingerprint is poisoned for one load, driving the real
   cross-version rejection branch the same way;
 * `serve-chunk-crash[:SELECTOR,after=K,count=N]` - the scheduler
   worker dies just before marching a chunk of a matching CHUNKED long
   solve (`after=K` lets a drill kill it at chunk K precisely); its
   supervisor restarts the worker and the march resumes from the last
   completed chunk with zero client-visible errors;
 * `serve-handoff-corrupt[:SELECTOR,count=N]` - the state-token
   checkpoint a resume presents is truncated on disk just before the
   load, driving the content-hash rejection branch: the resume must
   422 with `InvalidStateTokenError`, never a traceback, and the
   circuit breaker must never hear it (serve/preempt.py);
 * `serve-resultcache-corrupt[:SELECTOR,count=N]` - one payload byte
   of the matching RESULT-cache entry flips just before a lookup
   (serve/resultcache.py), driving the digest rejection branch: a
   counted miss and a clean recompute, never a wrong answer;
 * `serve-resultcache-stale-fingerprint[:SELECTOR,count=N]` - one
   result-cache lookup observes a poisoned environment fingerprint
   (the jaxlib-upgrade-under-a-warm-cache drill), driving the
   cross-version rejection branch the same way;
 * `serve-shadow-fail[:SELECTOR,count=N]` - a matching shadow solve
   (serve/shadow.py, `--shadow-sample-rate`) crashes in its worker
   before the reference twin runs, proving a shadow failure is
   counted, never touches the already-sent primary answer, and never
   feeds the circuit breaker.

SELECTOR is `field=value` pairs matched against the batch's program
identity (`n`, `timesteps`, `scheme`, `path`, `k`, `dtype`), so one
tier can be poisoned while its batchmates keep serving.  Every firing
is counted as `wavetpu_serve_fault_injections_total{kind=}` in the
server's registry - an injection that fired silently would make a chaos
drill unfalsifiable.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Dict, List, Optional


class InjectedFault(RuntimeError):
    """A deliberately-injected serve-path failure (compile/worker).  Its
    type matters only to tests; the serve stack treats it like any other
    compile/execute exception - that is the point."""


# ---------------------------------------------------------------- on disk


def flip_byte(path: str, offset: Optional[int] = None, xor: int = 0x01):
    """XOR one byte of `path` in place (default: mid-file, where a shard's
    array payload lives) - the minimal corruption a CRC must catch."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty; nothing to flip")
    if offset is None:
        offset = size // 2
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ (xor & 0xFF)]))
    return offset


def truncate_tail(path: str, drop_bytes: int = 16) -> int:
    """Chop `drop_bytes` off the end of `path` (a torn write / full disk /
    killed writer).  Returns the new size."""
    size = os.path.getsize(path)
    new = max(0, size - drop_bytes)
    with open(path, "r+b") as f:
        f.truncate(new)
    return new


def rewrite_shard_step(ckpt_dir: str, new_step: int,
                       shard_name: Optional[str] = None) -> str:
    """Rewrite one WTS shard of a sharded checkpoint with `new_step` in its
    meta - CRC-valid but disagreeing with meta.npz, i.e. the stale shard a
    preempted save-over-older-checkpoint leaves behind.  Returns the shard
    path."""
    from wavetpu.io import nativeio

    if shard_name is None:
        shards = sorted(
            f for f in os.listdir(ckpt_dir)
            if f.startswith("shard_") and f.endswith(".wts")
        )
        if not shards:
            raise FileNotFoundError(f"no .wts shards in {ckpt_dir}")
        shard_name = shards[0]
    path = os.path.join(ckpt_dir, shard_name)
    fields, meta = nativeio.read_container(path)
    meta = dict(meta, step=int(new_step))
    nativeio.write_container_sync(path, fields, meta)
    return path


# --------------------------------------------------------------- in flight


def nan_at_step(step: int, array_index: int = 1, once: bool = True):
    """Chunk hook: poison one element of state array `array_index` (default
    1 = u_cur for every path's state convention) with NaN at the first
    chunk boundary >= `step`.  With `once` (the transient-fault model) the
    second attempt after an auto-retry reload runs clean."""
    fired = [False]

    def hook(state, cur_step):
        if cur_step < step or (once and fired[0]):
            return state
        fired[0] = True
        import jax.numpy as jnp

        state = list(state)
        a = state[array_index]
        flat_nan = jnp.ravel(a).at[0].set(float("nan")).reshape(a.shape)
        state[array_index] = flat_nan.astype(a.dtype)
        return tuple(state)

    return hook


def preempt_at_step(step: int, sig: int = signal.SIGTERM, once: bool = True):
    """Chunk hook: deliver `sig` to THIS process at the first chunk
    boundary >= `step` - a deterministic stand-in for the scheduler's
    preemption notice.  The supervisor's handler must then finish the
    bookkeeping, save, and exit resumable (exit code 3)."""
    fired = [False]

    def hook(state, cur_step):
        if cur_step >= step and not (once and fired[0]):
            fired[0] = True
            os.kill(os.getpid(), sig)
        return state

    return hook


ENV_FAULT = "WAVETPU_FAULT"


def hook_from_env(env: Optional[dict] = None):
    """The CLI port of the harness: WAVETPU_FAULT="nan:STEP" or
    "preempt:STEP" returns the matching chunk hook (None when unset).
    Lets subprocess/CLI tests drill the watchdog-halt (exit 4) and
    kill-and-resume (exit 3) paths without timing races.  `serve-*`
    specs (the serve-path plan) and `router-*`/`store-*` specs (the
    router-tier plan, `router_plan_from_env`) are ignored here - a
    router chaos env leaking into a `wavetpu run` subprocess must not
    crash the run."""
    env = os.environ if env is None else env
    spec = env.get(ENV_FAULT)
    if not spec:
        return None
    run_specs = [
        part.strip() for part in spec.split(";")
        if part.strip() and not part.strip().startswith(
            ("serve-",) + _ROUTER_PREFIXES
        )
    ]
    if not run_specs:
        return None
    if len(run_specs) > 1:
        # One run-side fault per drill, as before - silently running
        # only the first would make the second assertion vacuous.
        raise ValueError(
            f"{ENV_FAULT}: at most one run-side spec, got {run_specs}"
        )
    kind, _, at = run_specs[0].partition(":")
    step = int(at)
    if kind == "nan":
        return nan_at_step(step)
    if kind == "preempt":
        return preempt_at_step(step)
    raise ValueError(
        f"{ENV_FAULT}={run_specs[0]!r}: want 'nan:STEP' or "
        f"'preempt:STEP'"
    )


# ------------------------------------------------------------ serve path


SERVE_KINDS = ("compile-fail", "execute-nan", "slow-batch",
               "worker-crash", "conn-drop", "progcache-truncate",
               "progcache-fingerprint", "chunk-crash",
               "handoff-corrupt", "resultcache-corrupt",
               "resultcache-stale-fingerprint", "shadow-fail")

# Router-tier chaos kinds (full spec names - they keep their prefix,
# unlike serve specs, because `router-` and `store-` faults fire in
# DIFFERENT modules: the router data path, fleet/store.py loads, and
# fleet/ha.py lease renewals respectively).
ROUTER_KINDS = ("router-crash", "store-corrupt", "store-stale-lease")
_ROUTER_PREFIXES = ("router-", "store-")

# Program-identity fields a selector may match on (ctx keys the serve
# seams pass to `fire`).
_SELECTOR_FIELDS = ("n", "timesteps", "scheme", "path", "k", "dtype")


class ServeInjection:
    """One armed serve-path injection: a kind, an optional program-
    identity selector, and firing budgets (`after` eligible events are
    skipped first; `count` bounds total fires, None = unlimited)."""

    def __init__(self, kind: str, match: Optional[Dict[str, str]] = None,
                 count: Optional[int] = None, after: int = 0,
                 seconds: float = 0.0):
        if kind not in SERVE_KINDS and kind not in ROUTER_KINDS:
            raise ValueError(
                f"unknown serve fault kind {kind!r}; want one of "
                f"{SERVE_KINDS + ROUTER_KINDS}"
            )
        self.kind = kind
        self.match = dict(match or {})
        if kind == "conn-drop" and self.match:
            # conn-drop fires before the body is parsed - there is no
            # program identity to match, so a selector would silently
            # never fire (the inverse of the counted-firings goal).
            raise ValueError(
                "serve-conn-drop takes no selector (it fires before "
                f"the request is parsed); got {sorted(self.match)}"
            )
        for f in self.match:
            if f not in _SELECTOR_FIELDS:
                raise ValueError(
                    f"serve-{kind}: unknown selector field {f!r}; want "
                    f"one of {_SELECTOR_FIELDS}"
                )
        self.count = count
        self.after = after
        self.seconds = seconds
        self.fired = 0

    def matches(self, ctx: Dict) -> bool:
        return all(
            str(ctx.get(f)) == str(v) for f, v in self.match.items()
        )

    def snapshot(self) -> dict:
        return {
            "kind": self.kind,
            "match": dict(self.match),
            "fired": self.fired,
            "remaining": self.count,
            "after": self.after,
            "seconds": self.seconds,
        }


class ServeFaultPlan:
    """The serve stack's injection registry: engine, scheduler, and HTTP
    layer call `fire(kind, **program_identity)` at their seams; the plan
    decides (thread-safely, budget-counted) whether THIS event breaks.

    One plan per server (build_server shares one object across all
    seams) so `count=` budgets mean what they say.  `bind_registry`
    attaches the `wavetpu_serve_fault_injections_total{kind=}` counter;
    an unbound plan still fires (unit tests), it just counts privately.
    """

    def __init__(self, injections: List[ServeInjection] = ()):
        self._inj = list(injections)
        self._lock = threading.Lock()
        self._counter = None

    @property
    def active(self) -> bool:
        return bool(self._inj)

    def bind_registry(self, registry) -> None:
        self._counter = registry.counter(
            "wavetpu_serve_fault_injections_total",
            "chaos-harness injections fired on the serve path",
            ("kind",),
        )

    def fire(self, kind: str, **ctx) -> Optional[ServeInjection]:
        """The matching armed injection if this event fires (budgets
        decremented, firing counted), else None."""
        if not self._inj:
            return None
        with self._lock:
            for inj in self._inj:
                if inj.kind != kind or not inj.matches(ctx):
                    continue
                if inj.after > 0:
                    inj.after -= 1
                    continue
                if inj.count is not None and inj.count <= 0:
                    continue
                if inj.count is not None:
                    inj.count -= 1
                inj.fired += 1
                if self._counter is not None:
                    self._counter.inc(kind=kind)
                return inj
        return None

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [inj.snapshot() for inj in self._inj]


def parse_serve_spec(spec: str) -> Optional[ServeFaultPlan]:
    """Parse the `serve-*` halves of a WAVETPU_FAULT value into a plan
    (None when the value carries no serve specs).  Grammar per spec:
    `serve-KIND[:key=value,...]` with params `count`/`after`/`seconds`
    and selector fields n/timesteps/scheme/path/k/dtype; specs are
    ';'-separated and may mix with run-side `nan:`/`preempt:` specs."""
    injections: List[ServeInjection] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part or not part.startswith("serve-"):
            continue
        kind, _, params = part[len("serve-"):].partition(":")
        match: Dict[str, str] = {}
        count: Optional[int] = None
        after = 0
        seconds = 0.0
        if params:
            for kv in params.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, sep, v = kv.partition("=")
                if not sep:
                    raise ValueError(
                        f"{ENV_FAULT}: serve-{kind} wants key=value "
                        f"params, got {kv!r}"
                    )
                if k == "count":
                    count = int(v)
                elif k == "after":
                    after = int(v)
                elif k == "seconds":
                    seconds = float(v)
                else:
                    match[k] = v
        injections.append(
            ServeInjection(kind, match, count=count, after=after,
                           seconds=seconds)
        )
    return ServeFaultPlan(injections) if injections else None


def serve_plan_from_env(env: Optional[dict] = None
                        ) -> Optional[ServeFaultPlan]:
    """The serve stack's WAVETPU_FAULT port (None when unset or when the
    value carries only run-side specs)."""
    env = os.environ if env is None else env
    spec = env.get(ENV_FAULT)
    if not spec:
        return None
    return parse_serve_spec(spec)


# ------------------------------------------------------------ router tier


def parse_router_spec(spec: str) -> Optional[ServeFaultPlan]:
    """Parse the router-tier halves of a WAVETPU_FAULT value (None when
    the value carries none).  Grammar mirrors the serve specs -
    `KIND[:key=value,...]` with `count`/`after` budgets, ';'-separated,
    freely mixed with serve-side and run-side specs:

     * `router-crash[:after=K,count=N]` - the router process delivers
       SIGKILL to ITSELF just before proxying a matching /solve
       (`after=K` skips the first K), the real-dead-active half of the
       failover drill: no flush, no lease release, nothing graceful;
     * `store-corrupt[:count=N]` - the control-plane WAL tail is
       truncated just before a store load, driving the per-line
       checksum rejection branch (a counted recoverable miss);
     * `store-stale-lease[:count=N]` - one lease renewal observes a
       stale/foreign lease and fails, forcing the active to demote and
       re-elect (the paused-then-resumed-process drill).

    Every firing is counted; the router exposes the plan's state as
    `wavetpu_router_fault_injections_total{kind=}`."""
    injections: List[ServeInjection] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part or not part.startswith(_ROUTER_PREFIXES):
            continue
        kind, _, params = part.partition(":")
        count: Optional[int] = None
        after = 0
        if params:
            for kv in params.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, sep, v = kv.partition("=")
                if not sep:
                    raise ValueError(
                        f"{ENV_FAULT}: {kind} wants key=value params, "
                        f"got {kv!r}"
                    )
                if k == "count":
                    count = int(v)
                elif k == "after":
                    after = int(v)
                else:
                    raise ValueError(
                        f"{ENV_FAULT}: {kind} takes only count=/after= "
                        f"params, got {kv!r}"
                    )
        injections.append(ServeInjection(kind, count=count, after=after))
    return ServeFaultPlan(injections) if injections else None


def router_plan_from_env(env: Optional[dict] = None
                         ) -> Optional[ServeFaultPlan]:
    """The router tier's WAVETPU_FAULT port (None when unset or when
    the value carries only run/serve-side specs).  One plan per router
    process, shared across the data path, the store, and the lease so
    `count=` budgets mean what they say."""
    env = os.environ if env is None else env
    spec = env.get(ENV_FAULT)
    if not spec:
        return None
    return parse_router_spec(spec)
