"""Solve supervisor: chunked march with checkpoints, watchdog, signals.

The reference (and every solver entry point below this module) treats a
solve as one uninterruptible program: it either finishes or loses
everything since the last manual `--stop-step` save.  Production TPU
workloads are preemptible by design - long integrations must be
restartable jobs (the TPU flow-simulation stack of arXiv:2108.11076 runs
multi-hour solves exactly this way).  This module wraps EVERY solver path
(standard/compensated, 1-step/k-fused, single/sharded, variable-c) in
that discipline:

 * **Chunked march.**  The solve runs as chunks of `ckpt_every` layers
   (snapped down to the k-fusion block size so chunk boundaries sit on
   the uninterrupted march's block grid - which is what keeps supervised
   layers bitwise-identical to an unsupervised solve's).  Chunk 1 is the
   ordinary `solve_*(stop_step=...)` program; every later chunk re-enters
   through the solver's `make_*chunk_runner` - a fixed-length program
   taking the start layer as a RUNTIME scalar, compiled ONCE per config
   and reused for every chunk (no per-chunk retracing; at most one extra
   compile for a shorter final chunk).

 * **Periodic checkpointing.**  Each chunk boundary saves to a FRESH
   entry `step-XXXXXXXX[.npz]` under the rotation root, then atomically
   updates the `latest` pointer file and garbage-collects all but the
   newest `keep` entries (plus stale `latest.tmp-*` debris).  Fresh
   directories + pointer rename are exactly the orchestration-layer
   atomicity `save_sharded_checkpoint`'s multi-host caveat asks for: a
   preemption mid-save can tear only the entry the pointer does not yet
   reference.

 * **Numerical-health watchdog.**  After each chunk (and any injected
   fault - see run/faults.py) the fused guard of run/health.py reduces
   the state to one scalar per array; a NaN/Inf or amplitude blowup halts
   the run with the LAST-GOOD step and checkpoint instead of marching
   garbage to the final layer and reporting it as an error norm.

 * **Preemption.**  SIGTERM/SIGINT set a flag; the supervisor finishes
   the current chunk, saves, and returns `status="preempted"` (CLI exit
   code 3 - requeue me).  `--resume <rotation root>` re-enters from the
   `latest` pointer, and the cycle composes across repeated preemptions.

 * **Bounded auto-retry.**  `retries=N` reloads the last-good checkpoint
   after a watchdog trip and re-runs the chunk - the transient-fault
   model (a bit flip, an injected NaN).  A deterministic blowup trips
   again and exhausts the budget, landing in the watchdog halt (CLI exit
   code 4 - page me).

Exit-code contract (wavetpu.cli): 0 complete, 2 usage/load error,
3 preempted-but-checkpointed (resumable), 4 watchdog halt (last-good
checkpoint preserved).  See docs/robustness.md.

The SERVE path reuses this module's chunk machinery for preemptible
long solves: serve/preempt.py's ChunkRunner drives the same
`make_*chunk_runner` fixed-length chunk programs (compiled once per
config, ProgramKey `@chunk{L}`) inside the scheduler, with the same
bitwise-on-the-block-grid guarantee - there the checkpoint is a
content-addressed state token under --solve-state-dir and "exit 3 /
requeue me" becomes "504/503 + resume_token / resubmit me"
(docs/robustness.md "Preemptible solves").

This module stays import-light: jax is imported inside functions so the
CLI can resolve rotation pointers before the backend exists.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import signal
import threading
import time
from typing import Callable, Optional, Tuple

EXIT_COMPLETE = 0
EXIT_PREEMPTED = 3
EXIT_WATCHDOG = 4

_STEP_PREFIX = "step-"
_LATEST = "latest"


# ------------------------------------------------------------- rotation


def _entry_step(name: str) -> Optional[int]:
    """The step number of a rotation entry name, else None."""
    if not name.startswith(_STEP_PREFIX):
        return None
    stem = name[len(_STEP_PREFIX):]
    if stem.endswith(".npz"):
        stem = stem[:-4]
    return int(stem) if stem.isdigit() else None


def resolve_latest(root: str) -> Optional[str]:
    """The newest checkpoint under a rotation root (absolute-ish path),
    or None.  Prefers the atomically updated `latest` pointer; falls back
    to the highest-numbered `step-*` entry (pointer lost to a crash
    before any update).  os-only: safe before jax exists."""
    if not os.path.isdir(root):
        return None
    ptr = os.path.join(root, _LATEST)
    if os.path.exists(ptr):
        with open(ptr) as f:
            name = f.read().strip()
        cand = os.path.join(root, name)
        if name and os.path.exists(cand):
            return cand
    best = None
    for e in os.listdir(root):
        s = _entry_step(e)
        if s is not None and (best is None or s > best[0]):
            best = (s, e)
    return os.path.join(root, best[1]) if best else None


def looks_like_rotation_root(path: str) -> bool:
    """True for a checkpoint ROTATION directory (what --resume may name),
    as opposed to a per-shard checkpoint directory itself (which carries
    meta.npz at its top level)."""
    if not os.path.isdir(path):
        return False
    if os.path.exists(os.path.join(path, "meta.npz")):
        return False
    if os.path.exists(os.path.join(path, _LATEST)):
        return True
    return any(_entry_step(e) is not None for e in os.listdir(path))


class CheckpointRotation:
    """Rotating fresh-entry checkpoint writer with `latest` pointer and
    keep-last-N garbage collection (see module docstring)."""

    def __init__(self, root: str, keep: int = 2, is_main: bool = True):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.root = root
        self.keep = keep
        self.is_main = is_main
        os.makedirs(root, exist_ok=True)

    def entry_path(self, step: int, directory: bool) -> str:
        name = f"{_STEP_PREFIX}{step:08d}" + ("" if directory else ".npz")
        return os.path.join(self.root, name)

    def save(self, save_fn: Callable[[str], Optional[str]], step: int,
             directory: bool) -> str:
        """Run `save_fn(entry_path)` into a fresh entry, then (on the main
        process) flip the `latest` pointer and GC old entries."""
        path = self.entry_path(step, directory)
        actual = save_fn(path) or path
        if self.is_main:
            self._write_latest(os.path.basename(actual))
            self._gc()
        return actual

    def latest_path(self) -> Optional[str]:
        return resolve_latest(self.root)

    def _write_latest(self, name: str) -> None:
        tmp = os.path.join(self.root, f"{_LATEST}.tmp-{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(name + "\n")
        os.replace(tmp, os.path.join(self.root, _LATEST))

    def _gc(self) -> None:
        entries = sorted(
            (s, e)
            for e in os.listdir(self.root)
            if (s := _entry_step(e)) is not None
        )
        for _, e in entries[:-self.keep]:
            p = os.path.join(self.root, e)
            if os.path.isdir(p):
                shutil.rmtree(p, ignore_errors=True)
            else:
                try:
                    os.remove(p)
                except OSError:
                    pass
        # Stale pointer temp files from a writer killed mid-update.
        for e in os.listdir(self.root):
            if e.startswith(f"{_LATEST}.tmp-"):
                try:
                    os.remove(os.path.join(self.root, e))
                except OSError:
                    pass


# ----------------------------------------------------------------- specs


@dataclasses.dataclass(frozen=True)
class PathSpec:
    """Which solver path to supervise - the resolved form of the CLI's
    backend/scheme/kernel/fusion flags (see cli.py's dispatch)."""

    backend: str = "single"            # "single" | "sharded"
    scheme: str = "standard"           # "standard" | "compensated"
    fuse_steps: int = 1
    kernel: str = "roll"               # resolved: "roll" | "pallas"
    dtype: object = None               # jnp dtype; None -> float32
    v_dtype: object = None             # bf16 increment stream (comp k-fused)
    carry: bool = True                 # Kahan carry on (comp k-fused)
    mesh_shape: Optional[Tuple[int, int, int]] = None
    c2tau2_field: object = None        # host (N,N,N) tau^2 c^2 array
    compute_errors: bool = True
    overlap: bool = False
    interpret: Optional[bool] = None   # None -> auto (not on TPU)
    block_x: Optional[int] = None


@dataclasses.dataclass
class SupervisorOptions:
    ckpt_every: int
    ckpt_dir: str
    retries: int = 0
    watchdog: bool = True
    max_amp: Optional[float] = None    # None -> health.DEFAULT_AMP_BOUND
    keep: int = 2
    handle_signals: bool = True
    chunk_hook: Optional[Callable] = None  # fault port (run/faults.py)


@dataclasses.dataclass
class SupervisedResult:
    result: object                     # leapfrog.SolveResult
    status: str                        # "complete"|"preempted"|"watchdog"
    exit_code: int
    final_step: int                    # layer result.u_cur holds
    checkpoint_path: Optional[str]     # resumable path (rotation entry)
    checkpoints_written: int
    retries_used: int
    overhead_seconds: float            # health checks + saves + GC
    amax_last: Optional[float]         # last watchdog reading


# ---------------------------------------------------------------- signals


class _SignalGuard:
    """Install SIGTERM/SIGINT flag handlers for the duration of a
    supervised march (main thread only; restores the previous handlers on
    exit).  The first signal sets `triggered` and restores that signal's
    original handler, so a second delivery regains its default force-kill
    meaning."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, enabled: bool = True):
        self.enabled = (
            enabled
            and threading.current_thread() is threading.main_thread()
        )
        self.triggered: Optional[int] = None
        self._prev = {}

    def __enter__(self):
        if self.enabled:
            for s in self.SIGNALS:
                self._prev[s] = signal.signal(s, self._handle)
        return self

    def _handle(self, signum, frame):
        self.triggered = signum
        import sys

        print(
            f"wavetpu: received signal {signum}; finishing the current "
            f"chunk, checkpointing, and exiting resumable",
            file=sys.stderr,
        )
        signal.signal(signum, self._prev[signum])

    def __exit__(self, *exc):
        if self.enabled:
            for s, h in self._prev.items():
                if signal.getsignal(s) == self._handle:
                    signal.signal(s, h)
        return False


# ------------------------------------------------------------------ path


class _Path:
    """Adapter from a PathSpec to the underlying solver family: first
    chunk (the ordinary solve program), cached fixed-length chunk
    runners, state <-> checkpoint conversion."""

    def __init__(self, problem, spec: PathSpec):
        import jax
        import jax.numpy as jnp

        from wavetpu.kernels import stencil_ref

        self.problem = problem
        self.spec = spec
        self.dtype = jnp.float32 if spec.dtype is None else spec.dtype
        self.f = stencil_ref.compute_dtype(self.dtype)
        self.interpret = (
            jax.default_backend() != "tpu"
            if spec.interpret is None else spec.interpret
        )
        self.compensated = spec.scheme == "compensated"
        self.k = spec.fuse_steps
        self.carry_on = spec.carry if (self.compensated and self.k > 1) \
            else self.compensated
        self.has_field = spec.c2tau2_field is not None
        self._jit = {}        # chunk length -> jitted runner
        self._compiled = {}   # chunk length -> AOT-compiled runner
        self._field_dev = None
        self._resolve_kind()

    def _single_field(self):
        """The variable-c field as ONE committed device array shared by
        the first-chunk builder and every chunk runner (their internal
        `jnp.asarray` on a committed array is a no-copy, so the N^3 slab
        lives in HBM once, not once per compiled program)."""
        if not self.has_field:
            return None
        if self._field_dev is None:
            import jax.numpy as jnp

            from wavetpu.solver import leapfrog

            self._field_dev = leapfrog.ParamStep.materialize(
                jnp.asarray(self.spec.c2tau2_field, dtype=self.f)
            )
        return self._field_dev

    # -- dispatch ------------------------------------------------------

    def _resolve_kind(self):
        import jax

        spec, problem = self.spec, self.problem
        n = problem.N
        if spec.backend == "single":
            if self.k <= 1:
                self.kind = "comp1" if self.compensated else "single1"
            elif self.compensated:
                self.kind = "kfused_comp"
            elif n % self.k == 0:
                self.kind = "kfused"
            else:
                self.kind = "uneven"
        else:
            if self.k <= 1:
                self.kind = "sharded1"
            elif self.compensated:
                self.kind = "sharded_kfused_comp"
            else:
                from wavetpu.solver import sharded_kfused as sk

                devices = jax.devices()
                n_x, _ = sk._resolve_grid(spec.mesh_shape, None, devices)
                self.kind = (
                    "sharded_kfused" if sk._is_even(problem, self.k, n_x)
                    else "uneven"
                )
        # Mesh/topology objects for the sharded-program kinds ("uneven"
        # covers the single-device pad-and-mask path too: it runs the
        # padded sharded runner on a (1,1,1) grid, exactly as cli.py).
        if self.kind == "sharded1":
            from wavetpu.solver import sharded

            self.topo, self.mesh = sharded._resolve_mesh(
                problem, spec.mesh_shape, None
            )
        elif self.kind in ("sharded_kfused", "sharded_kfused_comp",
                           "uneven"):
            from wavetpu.core.grid import build_mesh
            from wavetpu.solver import sharded_kfused as sk

            devices = jax.devices()
            if self.kind == "uneven" and spec.backend == "single":
                self.grid = (1, 1)
            else:
                self.grid = sk._resolve_grid(spec.mesh_shape, None,
                                             devices)
            n_x, n_y = self.grid
            self.mesh = build_mesh((n_x, n_y, 1), devices[: n_x * n_y])

    @property
    def saves_directory(self) -> bool:
        """Sharded backends checkpoint per-shard directories; the single
        backend (including its uneven pad-and-mask route) one .npz."""
        return self.spec.backend == "sharded"

    # -- first chunk (the ordinary solve program) ----------------------

    def first(self, stop: int):
        spec = self.spec
        if self.kind == "single1":
            from wavetpu.solver import leapfrog

            res = leapfrog.solve(
                self.problem, dtype=self.dtype,
                step_fn=self._step_fn(),
                compute_errors=spec.compute_errors, stop_step=stop,
            )
        elif self.kind == "comp1":
            from wavetpu.solver import leapfrog

            res = leapfrog.solve_compensated(
                self.problem, dtype=self.dtype,
                comp_step_fn=self._comp_step_fn(),
                compute_errors=spec.compute_errors, stop_step=stop,
            )
        elif self.kind == "kfused":
            from wavetpu.solver import kfused

            res = kfused.solve_kfused(
                self.problem, dtype=self.dtype, k=self.k,
                compute_errors=spec.compute_errors, stop_step=stop,
                block_x=spec.block_x, interpret=self.interpret,
                c2tau2_field=self._single_field(),
            )
        elif self.kind == "kfused_comp":
            from wavetpu.solver import kfused_comp

            res = kfused_comp.solve_kfused_comp(
                self.problem, dtype=self.dtype, k=self.k,
                compute_errors=spec.compute_errors, stop_step=stop,
                block_x=spec.block_x, interpret=self.interpret,
                v_dtype=spec.v_dtype, carry=spec.carry,
                c2tau2_field=self._single_field(),
            )
        elif self.kind == "sharded1":
            from wavetpu.solver import sharded

            res = sharded.solve_sharded(
                self.problem, mesh_shape=spec.mesh_shape,
                dtype=self.dtype, compute_errors=spec.compute_errors,
                kernel=spec.kernel, overlap=spec.overlap,
                interpret=self.interpret,
                c2tau2_field=spec.c2tau2_field, stop_step=stop,
                scheme=spec.scheme,
            )
        elif self.kind in ("sharded_kfused", "uneven"):
            from wavetpu.solver import sharded_kfused

            res = sharded_kfused.solve_sharded_kfused(
                self.problem,
                n_shards=1 if spec.backend == "single" else None,
                dtype=self.dtype, k=self.k,
                compute_errors=spec.compute_errors, stop_step=stop,
                block_x=spec.block_x, interpret=self.interpret,
                mesh_shape=(
                    None if spec.backend == "single" else spec.mesh_shape
                ),
                c2tau2_field=spec.c2tau2_field,
            )
        else:  # sharded_kfused_comp
            from wavetpu.solver import kfused_comp

            res = kfused_comp.solve_kfused_comp_sharded(
                self.problem, dtype=self.dtype, k=self.k,
                compute_errors=spec.compute_errors, stop_step=stop,
                block_x=spec.block_x, interpret=self.interpret,
                v_dtype=spec.v_dtype, carry=spec.carry,
                mesh_shape=spec.mesh_shape,
                c2tau2_field=spec.c2tau2_field,
            )
        state = self._state_of(res)
        return (state, res.abs_errors, res.rel_errors,
                res.init_seconds, res.solve_seconds)

    def _state_of(self, res):
        if self.compensated:
            return (res.u_cur, res.comp_v, res.comp_carry)
        return (res.u_prev, res.u_cur)

    def _step_fn(self):
        import jax.numpy as jnp

        spec = self.spec
        if spec.kernel == "pallas":
            from wavetpu.kernels import stencil_pallas

            return stencil_pallas.make_step_fn(
                interpret=self.interpret,
                c2tau2_field=self._single_field(),
            )
        if self.has_field:
            from wavetpu.kernels import stencil_ref

            return stencil_ref.make_variable_c_step(self._single_field())
        return None

    def _comp_step_fn(self):
        if self.spec.kernel == "pallas":
            from wavetpu.kernels import stencil_pallas

            return stencil_pallas.make_compensated_step_fn(
                interpret=self.interpret
            )
        return None

    # -- chunk runners -------------------------------------------------

    def _field_args(self):
        """The per-call runtime field argument tuple, placed once."""
        if hasattr(self, "_field_cache"):
            return self._field_cache
        args = ()
        if self.has_field:
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            fld = jnp.asarray(self.spec.c2tau2_field, dtype=self.f)
            if self.kind in ("single1", "kfused", "kfused_comp"):
                from wavetpu.solver import leapfrog

                args = (leapfrog.ParamStep.materialize(fld),)
            elif self.kind == "sharded1":
                from wavetpu.core.grid import AXIS_NAMES
                from wavetpu.solver import sharded

                args = (jax.device_put(
                    jnp.asarray(
                        sharded.pad_field(self.spec.c2tau2_field,
                                          self.topo),
                        dtype=self.f,
                    ),
                    NamedSharding(self.mesh, P(*AXIS_NAMES)),
                ),)
            elif self.kind == "uneven":
                dg = self._uneven_layout()[0]
                args = (jax.device_put(
                    jnp.pad(fld, ((0, dg - self.problem.N), (0, 0),
                                  (0, 0))),
                    NamedSharding(self.mesh, P("x")),
                ),)
            else:
                args = (jax.device_put(
                    fld, NamedSharding(self.mesh, P("x", "y"))
                ),)
        self._field_cache = args
        return args

    def _uneven_layout(self):
        from wavetpu.solver import sharded_kfused as sk

        import jax.numpy as jnp

        bx, d, _ = sk.uneven_layout(
            self.problem, self.k, self.grid[0],
            jnp.dtype(self.dtype).itemsize,
        )
        dg = self.grid[0] * d
        return dg, dg - self.problem.N

    def _build_runner(self, length: int, state):
        import jax.numpy as jnp

        spec = self.spec
        if self.kind == "single1":
            from wavetpu.solver import leapfrog

            runner, step_params = leapfrog.make_chunk_runner(
                self.problem, dtype=self.dtype, length=length,
                step_fn=self._step_fn(),
                compute_errors=spec.compute_errors,
            )
            return runner, (step_params,)
        if self.kind == "comp1":
            from wavetpu.solver import leapfrog

            runner = leapfrog.make_comp_chunk_runner(
                self.problem, dtype=self.dtype, length=length,
                comp_step_fn=self._comp_step_fn(),
                compute_errors=spec.compute_errors,
            )
            return runner, ()
        if self.kind == "kfused":
            from wavetpu.solver import kfused

            runner, run_params = kfused.make_chunk_runner(
                self.problem, dtype=self.dtype, length=length, k=self.k,
                compute_errors=spec.compute_errors, block_x=spec.block_x,
                interpret=self.interpret,
                c2tau2_field=self._single_field(),
            )
            return runner, tuple(run_params)
        if self.kind == "kfused_comp":
            from wavetpu.solver import kfused_comp

            runner, run_params = kfused_comp.make_chunk_runner(
                self.problem, dtype=self.dtype, length=length, k=self.k,
                compute_errors=spec.compute_errors, block_x=spec.block_x,
                interpret=self.interpret,
                v_dtype=jnp.dtype(state[1].dtype), carry=self.carry_on,
                c2tau2_field=self._single_field(),
            )
            return runner, tuple(run_params)
        if self.kind == "sharded1":
            from wavetpu.solver import sharded

            runner = sharded.make_sharded_chunk_runner(
                self.problem, self.topo, self.mesh, length,
                dtype=self.dtype, compute_errors=spec.compute_errors,
                kernel=spec.kernel, overlap=spec.overlap,
                interpret=self.interpret, has_field=self.has_field,
                scheme=spec.scheme,
            )
            return runner, self._field_args()
        if self.kind in ("sharded_kfused", "uneven"):
            from wavetpu.solver import sharded_kfused

            runner, _ = sharded_kfused.make_chunk_runner(
                self.problem, self.mesh, self.grid, dtype=self.dtype,
                length=length, k=self.k,
                compute_errors=spec.compute_errors, block_x=spec.block_x,
                interpret=self.interpret, has_field=self.has_field,
            )
            return runner, self._field_args()
        from wavetpu.solver import kfused_comp

        runner = kfused_comp.make_sharded_chunk_runner(
            self.problem, self.mesh, self.grid, dtype=self.dtype,
            length=length, k=self.k,
            compute_errors=spec.compute_errors, block_x=spec.block_x,
            interpret=self.interpret,
            v_dtype=jnp.dtype(state[1].dtype), carry=self.carry_on,
            carry_dtype=(
                jnp.result_type(state[2]) if self.carry_on else None
            ),
            has_field=self.has_field,
        )
        return runner, self._field_args()

    def chunk(self, state, start: int, length: int):
        """March layers start+1..start+length through the cached chunk
        program; returns (state', abs_chunk, rel_chunk, solve_s,
        compile_s)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        if length not in self._jit:
            self._jit[length] = self._build_runner(length, state)
        runner, extra = self._jit[length]
        uneven = self.kind == "uneven"
        if uneven:
            state = self._to_padded(state)
        args = tuple(state[: 2 if not self.compensated else 3])
        args = args + (jnp.int32(start),) + extra
        compile_s = 0.0
        if length not in self._compiled:
            t0 = time.perf_counter()
            self._compiled[length] = runner.lower(*args).compile()
            compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = self._compiled[length](*args)
        jax.block_until_ready(out)
        if self.compensated and self.kind == "sharded1":
            u_cur, abs_c, rel_c, v, kc = out[1], out[2], out[3], out[4], \
                out[5]
            state = (u_cur, v, kc)
        elif self.compensated:
            state = (out[0], out[1], out[2])
            abs_c, rel_c = out[3], out[4]
        else:
            state = (out[0], out[1])
            abs_c, rel_c = out[2], out[3]
        # Host readback of the small per-layer error vectors doubles as
        # the execution proof (leapfrog._timed_compile_run rationale).
        abs_np = np.asarray(abs_c, dtype=np.float64)
        rel_np = np.asarray(rel_c, dtype=np.float64)
        solve_s = time.perf_counter() - t0
        if uneven:
            state = self._from_padded(state)
        return state, abs_np, rel_np, solve_s, compile_s

    def _to_padded(self, state):
        """Topology-layout -> padded (MX*D, N, N) layout for the uneven
        pad-and-mask chunk program (the same re-placement
        resume_sharded_kfused performs per call)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        dg, _ = self._uneven_layout()
        padw = ((0, dg - self.problem.N), (0, 0), (0, 0))
        sharding = NamedSharding(self.mesh, P("x"))
        return tuple(
            jax.device_put(
                jnp.pad(jnp.asarray(a, self.dtype)[: self.problem.N],
                        padw),
                sharding,
            )
            for a in state
        )

    def _from_padded(self, state):
        from wavetpu.solver.sharded_kfused import _to_topology_layout

        return tuple(
            _to_topology_layout(a, self.problem, self.mesh, self.grid[0])
            for a in state
        )

    # -- state <-> checkpoints -----------------------------------------

    def health_arrays(self, state):
        return tuple(a for a in state if a is not None)

    def _shim_result(self, state, step: int):
        from wavetpu.solver.leapfrog import SolveResult

        import numpy as np

        if self.compensated:
            u, v, c = state
            u_prev = (
                u.astype(self.f) - v.astype(self.f)
            ).astype(u.dtype)
            u_cur, comp_v, comp_carry = u, v, c
        else:
            u_prev, u_cur = state
            comp_v = comp_carry = None
        z = np.zeros((0,))
        return SolveResult(
            problem=self.problem, u_prev=u_prev, u_cur=u_cur,
            abs_errors=z, rel_errors=z, final_step=step,
            comp_v=comp_v, comp_carry=comp_carry,
        )

    def save(self, rot: CheckpointRotation, state, step: int) -> str:
        from wavetpu.io import checkpoint

        res = self._shim_result(state, step)
        if self.saves_directory:
            return rot.save(
                lambda p: checkpoint.save_sharded_checkpoint(p, res),
                step, directory=True,
            )
        return rot.save(
            lambda p: checkpoint.save_checkpoint(p, res), step,
            directory=False,
        )

    def load(self, path: str):
        """Reload a rotation entry -> (prepared state, step)."""
        from wavetpu.io import checkpoint

        if os.path.isdir(path):
            _, u_prev, u_cur, step, _, scheme, aux = (
                checkpoint.load_sharded_checkpoint(path)
            )
            if self.compensated:
                v, c = aux
                state = (u_cur, v, c if self.carry_on else None)
            else:
                state = (u_prev, u_cur)
        else:
            _, u_prev, u_cur, step = checkpoint.load_checkpoint(path)
            if self.compensated:
                v, c = checkpoint.load_checkpoint_aux(path)
                state = (u_cur, v, c if self.carry_on else None)
            else:
                state = (u_prev, u_cur)
        return self.prepare(state), step

    def prepare(self, state):
        """Device placement + dtype normalization for an injected state
        (a loaded checkpoint) - mirrors the resume_* entry points."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        def place(a, dt=None):
            a = jnp.asarray(a) if dt is None else jnp.asarray(a, dt)
            if self.kind == "sharded1":
                from wavetpu.core.grid import AXIS_NAMES

                return jax.device_put(
                    a, NamedSharding(self.mesh, P(*AXIS_NAMES))
                )
            if self.kind in ("sharded_kfused", "sharded_kfused_comp"):
                return jax.device_put(
                    a, NamedSharding(self.mesh, P("x", "y"))
                )
            if self.kind == "uneven":
                from wavetpu.core.grid import AXIS_NAMES

                return jax.device_put(
                    a, NamedSharding(self.mesh, P(*AXIS_NAMES))
                )
            return a

        if self.compensated:
            from wavetpu.solver.kfused_comp import _normalize_carry

            u, v, c = state
            if c is not None:
                if self.k > 1:
                    # Preserve a valid stored carry dtype (bf16 carries
                    # resume bitwise) - resume_kfused_comp's rule.
                    c = place(_normalize_carry(jnp.asarray(c),
                                               self.dtype))
                else:
                    # The 1-step compensated scans carry the state dtype
                    # (resume_compensated's unconditional cast).
                    c = place(c, self.dtype)
            v = place(v) if self.k > 1 else place(v, self.dtype)
            return (place(u, self.dtype), v, c)
        u_prev, u_cur = state
        return (place(u_prev, self.dtype), place(u_cur, self.dtype))

    def to_result(self, state, abs_full, rel_full, final_step: int,
                  init_s: float, solve_s: float, marched: int):
        from wavetpu.solver.leapfrog import SolveResult

        import jax.numpy as jnp

        if state is None:
            # Watchdog trip before any checkpoint existed: there is no
            # good state to report; a zero field marks "nothing survived"
            # without smuggling garbage into downstream consumers.
            z = jnp.zeros((self.problem.N,) * 3, self.dtype)
            state = (z, z, z) if self.compensated else (z, z)
        shim = self._shim_result(state, final_step)
        return SolveResult(
            problem=self.problem,
            u_prev=shim.u_prev,
            u_cur=shim.u_cur,
            abs_errors=abs_full,
            rel_errors=rel_full,
            init_seconds=init_s,
            solve_seconds=solve_s,
            steps_computed=max(marched, 0) or None,
            final_step=final_step,
            comp_v=shim.comp_v,
            comp_carry=shim.comp_carry,
        )


# ------------------------------------------------------------ supervise


def chunk_length(ckpt_every: int, fuse_steps: int) -> int:
    """The supervised chunk length: `ckpt_every` snapped DOWN to a
    multiple of the k-fusion block (min one block), so every chunk
    boundary lands on the uninterrupted march's block grid and the
    supervised trajectory stays bitwise-identical."""
    if ckpt_every < 1:
        raise ValueError(f"ckpt_every must be >= 1, got {ckpt_every}")
    k = max(1, fuse_steps)
    return max(k, (ckpt_every // k) * k)


def supervise(problem, spec: PathSpec, opts: SupervisorOptions,
              state=None, start_step: Optional[int] = None
              ) -> SupervisedResult:
    """Run (or resume) a solve under supervision; see module docstring.

    `state`/`start_step` inject a loaded checkpoint (the CLI's --resume):
    the supervisor re-enters through the cached chunk programs and keeps
    checkpointing on its own boundary grid.  Without them the march
    starts from scratch via the ordinary solve program.
    """
    import jax
    import numpy as np

    from wavetpu.obs import metrics as obs_metrics
    from wavetpu.obs import perf as obs_perf
    from wavetpu.obs import tracing
    from wavetpu.run import faults, health

    # Supervisor telemetry (docs/observability.md): counters in the
    # process registry plus structured spans when --telemetry-dir is on.
    # Chunk spans carry {start, end, length}; checkpoint spans carry the
    # step they persist - so a trace's chunk boundaries are auditable
    # against the rotation entries on disk.
    c_chunks = obs_metrics.supervisor_counter(
        "chunks_total", "chunk programs executed")
    c_ckpts = obs_metrics.supervisor_counter(
        "checkpoints_total", "rotation entries written")
    c_retries = obs_metrics.supervisor_counter(
        "retries_total", "watchdog auto-retries taken")
    c_trips = obs_metrics.supervisor_counter(
        "watchdog_trips_total", "numerical-health check failures")
    g_step = obs_metrics.supervisor_step_gauge()

    path = _Path(problem, spec)
    is_main = jax.process_index() == 0
    rot = CheckpointRotation(opts.ckpt_dir, keep=opts.keep,
                             is_main=is_main)
    T = problem.timesteps
    L = chunk_length(opts.ckpt_every, path.k)
    hook = opts.chunk_hook or faults.hook_from_env()
    abs_full = np.zeros((T + 1,), dtype=np.float64)
    rel_full = np.zeros((T + 1,), dtype=np.float64)
    init_s = solve_s = overhead_s = 0.0
    ckpts = 0
    retries_used = 0
    marched = 0
    amax = None
    status = "complete"
    cur: Optional[int] = None

    if state is not None:
        if start_step is None:
            raise ValueError("state injection requires start_step")
        state = path.prepare(state)
        cur = start_step
        if rot.latest_path() is None:
            # Seed a fresh rotation with the injected state: the retry
            # and watchdog-halt fallbacks reload `latest`, and without
            # this seed a resumed run whose first chunk trips would
            # restart from layer 0 (or halt reporting step 0) even
            # though the caller's checkpoint was perfectly good.
            t0 = time.perf_counter()
            path.save(rot, state, cur)
            ckpts += 1
            c_ckpts.inc()
            overhead_s += time.perf_counter() - t0

    march_span = tracing.begin_span(
        "supervisor.march", n=problem.N, timesteps=T, chunk_length=L,
        solver_kind=path.kind, start_step=0 if cur is None else cur,
    )
    chunk_span = None
    try:
        with _SignalGuard(opts.handle_signals) as sig:
            while True:
                chunk_ran = True
                if state is None:
                    b = min(T, 1 + L)
                    chunk_span = tracing.begin_span(
                        "supervisor.chunk", start=0, end=b, length=b,
                        first=True,
                    )
                    state, a, r, i_s, s_s = path.first(b)
                    tracing.end_span(
                        chunk_span, solve_seconds=round(s_s, 6),
                        compile_seconds=round(i_s, 6),
                    )
                    chunk_span = None
                    # HBM pressure at chunk granularity: the watermark
                    # gauge is how an OOM-adjacent supervised march is
                    # seen coming (no-op on memory_stats-less backends).
                    obs_perf.record_memory(context="supervisor")
                    abs_full[: b + 1] = a
                    rel_full[: b + 1] = r
                    init_s += i_s
                    solve_s += s_s
                    marched += b
                    cur = b
                elif cur < T:
                    length = min(L, T - cur)
                    chunk_span = tracing.begin_span(
                        "supervisor.chunk", start=cur, end=cur + length,
                        length=length, first=False,
                    )
                    state, a, r, s_s, c_s = path.chunk(state, cur, length)
                    tracing.end_span(
                        chunk_span, solve_seconds=round(s_s, 6),
                        compile_seconds=round(c_s, 6),
                    )
                    chunk_span = None
                    obs_perf.record_memory(context="supervisor")
                    abs_full[cur + 1: cur + length + 1] = a
                    rel_full[cur + 1: cur + length + 1] = r
                    init_s += c_s
                    solve_s += s_s
                    marched += length
                    cur += length
                else:
                    # Injected state already at (or past) the target layer:
                    # no chunk program ran this iteration, so the counter
                    # must not claim one (the chunks-equal-spans audit).
                    chunk_ran = False
                if chunk_ran:
                    c_chunks.inc()
                g_step.set(cur)
                # ---- chunk-boundary bookkeeping at layer `cur` ----
                if hook is not None:
                    state = hook(state, cur)
                t0 = time.perf_counter()
                ok = True
                if opts.watchdog:
                    with tracing.span("supervisor.health", step=cur) as sp:
                        amax = health.state_amax(path.health_arrays(state))
                        ok = health.healthy(amax, opts.max_amp)
                        sp["amax"] = amax
                        sp["ok"] = ok
                if not ok:
                    c_trips.inc()
                    latest = rot.latest_path()
                    if retries_used < opts.retries:
                        # Transient-fault model: reload the last-good
                        # checkpoint (or restart from scratch if none yet)
                        # and re-run the tripped chunk.
                        retries_used += 1
                        c_retries.inc()
                        tracing.event(
                            "supervisor.retry", step=cur, amax=amax,
                            retry=retries_used,
                            reload=latest or "from-scratch",
                        )
                        if latest is None:
                            state, cur = None, None
                        else:
                            state, cur = path.load(latest)
                        overhead_s += time.perf_counter() - t0
                        continue
                    status = "watchdog"
                    tracing.event(
                        "supervisor.watchdog_halt", step=cur, amax=amax
                    )
                    if latest is not None:
                        state, cur = path.load(latest)
                    else:
                        state, cur = None, 0
                    abs_full[cur + 1:] = 0.0
                    rel_full[cur + 1:] = 0.0
                    overhead_s += time.perf_counter() - t0
                    break
                with tracing.span(
                    "supervisor.checkpoint", step=cur
                ) as sp:
                    sp["path"] = path.save(rot, state, cur)
                ckpts += 1
                c_ckpts.inc()
                overhead_s += time.perf_counter() - t0
                if cur >= T:
                    break
                if sig.triggered is not None:
                    status = "preempted"
                    tracing.event("supervisor.preempted", step=cur,
                                  signal=sig.triggered)
                    abs_full[cur + 1:] = 0.0
                    rel_full[cur + 1:] = 0.0
                    break
    except BaseException as e:
        # A crash mid-march (XLA OOM, device error) must still emit
        # the open chunk/march spans - they are the telemetry meant
        # to explain the crash - and must not leave their ids on the
        # thread-local parent stack for later spans to adopt.
        tracing.end_span(chunk_span, error=repr(e))
        tracing.end_span(march_span, status="error", error=repr(e))
        raise
    tracing.end_span(
        march_span, status=status, final_step=cur or 0,
        checkpoints=ckpts, retries=retries_used,
    )
    result = path.to_result(
        state, abs_full, rel_full, cur or 0, init_s, solve_s, marched
    )
    exit_code = {
        "complete": EXIT_COMPLETE,
        "preempted": EXIT_PREEMPTED,
        "watchdog": EXIT_WATCHDOG,
    }[status]
    return SupervisedResult(
        result=result,
        status=status,
        exit_code=exit_code,
        final_step=cur or 0,
        checkpoint_path=rot.latest_path(),
        checkpoints_written=ckpts,
        retries_used=retries_used,
        overhead_seconds=overhead_s,
        amax_last=amax,
    )
