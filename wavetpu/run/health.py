"""Numerical-health watchdog: one fused non-finite/amplitude reduction.

A NaN or Inf born inside a `lax.scan` marches silently to the final layer
unless `--debug-nans` hard-traps the whole program; an amplitude blowup
(e.g. a Courant-unstable config) is worse - every value stays finite for
many layers while the "solution" grows exponentially, and the run ends
with a garbage error norm that LOOKS like a result.  The supervisor
(run/supervisor.py) instead checks each chunk boundary with the guard
below and halts - or retries - with the last-good step and checkpoint.

The guard is a single fused pass per state array:

    amax* = max(where(isfinite(|u|), |u|, +inf))

so NaN/Inf anywhere collapses to +inf and ONE scalar crosses to the host
per array per chunk.  `healthy(amax, bound)` is then a plain float
comparison (NaN-safe: `NaN <= bound` is False).  The analytic solution is
a product of sines (|u| <= 1) and any physical variable-c field keeps the
amplitude O(1), so the default bound of 1e3 only ever trips on genuine
blowups while staying scheme-agnostic.

On sharded state the same jitted guard lowers to a per-shard reduction
plus a scalar all-reduce - no gather.  jax.jit caches one compiled guard
per (shape, dtype, sharding), i.e. one program per solver config.
"""

from __future__ import annotations

from typing import Iterable, Optional

DEFAULT_AMP_BOUND = 1e3

_guard = None


def _guard_fn():
    """The jitted guarded-amax program (built lazily; jax stays out of
    module import so flag parsing never pays for the backend)."""
    global _guard
    if _guard is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def g(u):
            x = jnp.abs(u).astype(jnp.float32)
            return jnp.max(jnp.where(jnp.isfinite(x), x, jnp.inf))

        _guard = g
    return _guard


def guarded_amax(array) -> float:
    """max |array| with every non-finite value counted as +inf (host
    float).  One fused device pass, one scalar transfer."""
    import numpy as np

    return float(np.asarray(_guard_fn()(array)))


_lane_guard = None


def _lane_guard_fn():
    """Jitted per-lane guarded-amax over a (B, ...) batch (lazy, as
    `_guard_fn`)."""
    global _lane_guard
    if _lane_guard is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def g(u):
            x = jnp.abs(u).astype(jnp.float32)
            x = jnp.where(jnp.isfinite(x), x, jnp.inf)
            return jnp.max(x.reshape((x.shape[0], -1)), axis=1)

        _lane_guard = g
    return _lane_guard


def guarded_amax_per_lane(array):
    """Per-lane guarded amax over a leading batch axis: ONE fused device
    pass, B scalars to host (numpy (B,) float array).  The ensemble
    engine's per-batch watchdog (wavetpu/serve/engine.py) - same
    semantics as `guarded_amax` applied lane by lane, without B separate
    reductions."""
    import numpy as np

    return np.asarray(_lane_guard_fn()(array), dtype=np.float64)


def state_amax(arrays: Iterable) -> float:
    """The guarded amax over a state tuple (None entries skipped - e.g.
    the carry-less increment form's missing Kahan carry)."""
    vals = [guarded_amax(a) for a in arrays if a is not None]
    return max(vals) if vals else 0.0


def healthy(amax: float, bound: Optional[float] = None) -> bool:
    """True iff the state passed its chunk check.  NaN/Inf fail (the
    guard maps them to +inf; a literal NaN compares False anyway)."""
    bound = DEFAULT_AMP_BOUND if bound is None else bound
    return amax <= bound
