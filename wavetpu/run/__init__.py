"""Supervised-run subsystem: periodic checkpointing, numerical-health
watchdog, preemption handling, bounded auto-retry, and the fault-injection
harness that proves the recovery paths fire.

 * run/supervisor.py - the solve supervisor (chunked march over cached
   chunk programs, rotating checkpoints, signals, retries, exit codes)
 * run/health.py     - the cheap fused non-finite/amplitude guard
 * run/faults.py     - fault injectors (bit-flip, truncation, stale-step
   shard, NaN-at-step, preempt-at-step) for tests and drills

Modules here stay import-light (no jax at module import) so the CLI can
parse flags and resolve checkpoint pointers before the backend spins up.
"""
