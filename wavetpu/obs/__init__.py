"""Unified telemetry: metrics registry, span tracing, heartbeat files.

 * `obs.registry`  - process-wide counters/gauges/histograms, JSON
   snapshot + Prometheus text exposition (one consistency lock).
 * `obs.tracing`   - JSONL span/event emission with a context-manager
   API that also opens matching `jax.profiler.TraceAnnotation`s.
 * `obs.metrics`   - the domain instruments (per-solve throughput,
   checkpoint I/O, supervisor counters).
 * `obs.perf`      - performance X-ray: the shared analytic cost model
   + roofline gauges, device-memory watermarks, `wavetpu profile`.
 * `obs.ledger`    - persistent compile-cost ledger and
   `wavetpu ledger-report` (what-if cache, warmup manifest).
 * `obs.telemetry` - `--telemetry-dir` glue: trace file + periodic
   registry snapshots (heartbeat.jsonl / metrics.prom) + the ledger.
 * `obs.report`    - `wavetpu trace-report`: per-kind span stats and
   per-request critical-path views over a trace file.

Metric catalog and span kinds: docs/observability.md.
"""

from wavetpu.obs.registry import MetricsRegistry, get_registry  # noqa: F401
