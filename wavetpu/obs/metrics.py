"""Domain instruments over the process-wide registry.

Thin helpers the solver / checkpoint / supervisor layers call at their
natural host boundaries (end of a solve, end of a checkpoint write) so
each call site stays one line.  Everything lands in
`registry.get_registry()` - the process-wide registry the telemetry
heartbeat snapshots and `wavetpu trace-report` complements.

Metric catalog (docs/observability.md is the user-facing copy):

  wavetpu_solves_total{path}            completed solve entry points
  wavetpu_solve_layers_total{path}      leapfrog layers marched
  wavetpu_solve_cells_total{path}       cell updates ((N+1)^3 x layers)
  wavetpu_solve_seconds_total{path}     solve wall seconds (excl compile)
  wavetpu_last_solve_gcells_per_s{path} gauge: most recent throughput
  wavetpu_checkpoint_ops_total{op,kind}      save/load x single/sharded
  wavetpu_checkpoint_bytes_total{op,kind}    file bytes moved
  wavetpu_checkpoint_seconds_total{op,kind}  wall seconds
  wavetpu_supervisor_chunks_total       chunk programs executed
  wavetpu_supervisor_checkpoints_total  rotation entries written
  wavetpu_supervisor_retries_total      watchdog auto-retries taken
  wavetpu_supervisor_watchdog_trips_total   health-check failures
  wavetpu_supervisor_step               gauge: last completed layer
"""

from __future__ import annotations

from wavetpu.obs.registry import get_registry


def record_solve(result, path: str) -> None:
    """Per-solve throughput counters, called at solver entry points.
    `result` is a leapfrog.SolveResult; `path` names the solver family
    (roll / pallas / kfused / kfused_comp / sharded / sharded_kfused)."""
    reg = get_registry()
    problem = result.problem
    steps = (
        result.steps_computed
        if result.steps_computed else problem.timesteps
    )
    cells = float(problem.cells_per_step) * steps
    reg.counter(
        "wavetpu_solves_total", "completed solve entry points", ("path",)
    ).inc(path=path)
    reg.counter(
        "wavetpu_solve_layers_total", "leapfrog layers marched", ("path",)
    ).inc(steps, path=path)
    reg.counter(
        "wavetpu_solve_cells_total",
        "cell updates marched ((N+1)^3 per layer)", ("path",)
    ).inc(cells, path=path)
    reg.counter(
        "wavetpu_solve_seconds_total",
        "solve wall seconds (excludes compile)", ("path",)
    ).inc(float(result.solve_seconds or 0.0), path=path)
    reg.gauge(
        "wavetpu_last_solve_gcells_per_s",
        "throughput of the most recent solve", ("path",)
    ).set(float(result.gcells_per_second or 0.0), path=path)


def record_checkpoint_io(op: str, kind: str, nbytes: float,
                         seconds: float) -> None:
    """Checkpoint I/O accounting: `op` save|load, `kind` single|sharded."""
    reg = get_registry()
    labels = dict(op=op, kind=kind)
    reg.counter(
        "wavetpu_checkpoint_ops_total", "checkpoint operations",
        ("op", "kind")
    ).inc(**labels)
    reg.counter(
        "wavetpu_checkpoint_bytes_total", "checkpoint file bytes moved",
        ("op", "kind")
    ).inc(float(nbytes), **labels)
    reg.counter(
        "wavetpu_checkpoint_seconds_total", "checkpoint I/O wall seconds",
        ("op", "kind")
    ).inc(float(seconds), **labels)


def supervisor_counter(name: str, help: str):
    return get_registry().counter(f"wavetpu_supervisor_{name}", help)


def supervisor_step_gauge():
    return get_registry().gauge(
        "wavetpu_supervisor_step", "last completed layer of the "
        "supervised march"
    )
