"""Domain instruments over the process-wide registry.

Thin helpers the solver / checkpoint / supervisor layers call at their
natural host boundaries (end of a solve, end of a checkpoint write) so
each call site stays one line.  Everything lands in
`registry.get_registry()` - the process-wide registry the telemetry
heartbeat snapshots and `wavetpu trace-report` complements.

Metric catalog (docs/observability.md is the user-facing copy):

  wavetpu_solves_total{path}            completed solve entry points
  wavetpu_solve_layers_total{path}      leapfrog layers marched
  wavetpu_solve_cells_total{path}       cell updates ((N+1)^3 x layers)
  wavetpu_solve_seconds_total{path}     solve wall seconds (excl compile)
  wavetpu_last_solve_gcells_per_s{path} gauge: most recent throughput
  wavetpu_checkpoint_ops_total{op,kind}      save/load x single/sharded
  wavetpu_checkpoint_bytes_total{op,kind}    file bytes moved
  wavetpu_checkpoint_seconds_total{op,kind}  wall seconds
  wavetpu_supervisor_chunks_total       chunk programs executed
  wavetpu_supervisor_checkpoints_total  rotation entries written
  wavetpu_supervisor_retries_total      watchdog auto-retries taken
  wavetpu_supervisor_watchdog_trips_total   health-check failures
  wavetpu_supervisor_step               gauge: last completed layer

The serving QoS layer (serve/scheduler.py owns those instruments)
lands its per-class/per-tenant counters - scheduled/shed/deferred per
priority class, tenant quota and spoof rejections, the brownout rung
gauge - in this same registry, so they ride the identical snapshot,
/metrics render, and fleet aggregation paths as the catalog above.

Roofline + device-memory instruments (obs/perf.py owns the catalog):
`record_solve` also stamps the shared analytic cost model's verdict
(modeled GB/s, roofline fraction) for the config that ran and samples
device memory - both host-side arithmetic at solve granularity.

Accuracy instruments (obs/accuracy.py owns the catalog): a solve that
computed oracle errors additionally stamps
`wavetpu_solve_max_abs_err{path,scheme,dtype}` plus the per-plan
log-bucketed `wavetpu_solve_abs_err` histogram and appends one
accuracy-ledger line under --telemetry-dir.
"""

from __future__ import annotations

from typing import Optional

from wavetpu.obs.registry import get_registry


def record_solve(result, path: str, *, scheme: str = "standard",
                 k: int = 1, v_itemsize: Optional[int] = None,
                 carry: bool = True, with_field: bool = False,
                 block_x: Optional[int] = None,
                 depth: Optional[int] = None,
                 ghosts: bool = False) -> Optional[dict]:
    """Per-solve throughput counters, called at solver entry points.
    `result` is a leapfrog.SolveResult; `path` names the solver family
    (leapfrog / compensated / kfused / kfused_comp[_sharded] / sharded /
    sharded_kfused).  The keyword args describe the config for the
    roofline model (obs/perf.py) - sharded paths pass the shard
    `depth`/`ghosts` their kernel's own block chooser used.  Returns
    the roofline attribution dict (None when the config has no model);
    the gauges it stamps are the canonical read path (cli.py reads
    them back for the cli.solve span)."""
    reg = get_registry()
    problem = result.problem
    steps = (
        result.steps_computed
        if result.steps_computed else problem.timesteps
    )
    cells = float(problem.cells_per_step) * steps
    reg.counter(
        "wavetpu_solves_total", "completed solve entry points", ("path",)
    ).inc(path=path)
    reg.counter(
        "wavetpu_solve_layers_total", "leapfrog layers marched", ("path",)
    ).inc(steps, path=path)
    reg.counter(
        "wavetpu_solve_cells_total",
        "cell updates marched ((N+1)^3 per layer)", ("path",)
    ).inc(cells, path=path)
    reg.counter(
        "wavetpu_solve_seconds_total",
        "solve wall seconds (excludes compile)", ("path",)
    ).inc(float(result.solve_seconds or 0.0), path=path)
    reg.gauge(
        "wavetpu_last_solve_gcells_per_s",
        "throughput of the most recent solve", ("path",)
    ).set(float(result.gcells_per_second or 0.0), path=path)
    # Accuracy observatory (obs/accuracy.py): a solve that computed
    # errors against the analytic oracle stamps its measured
    # max_abs_err (gauge + log-bucketed histogram) and appends one
    # accuracy-ledger line under --telemetry-dir.  Guarded separately
    # from the roofline block so neither X-ray can starve the other.
    try:
        from wavetpu.obs import accuracy

        accuracy.observe_solve(result, path, scheme=scheme, k=k,
                               with_field=with_field, registry=reg)
    except Exception:
        pass
    # Roofline attribution + device-memory sample (obs/perf.py): both a
    # few host-side ops per solve; memory sampling short-circuits after
    # one probe on backends without memory_stats().  Guarded: the X-ray
    # must never fail the solve it measures.
    try:
        from wavetpu.obs import perf

        attribution = perf.record_roofline(reg, path, perf.solve_perf(
            float(result.gcells_per_second or 0.0), path, scheme=scheme,
            k=k, n=problem.N, itemsize=result.u_cur.dtype.itemsize,
            v_itemsize=v_itemsize, carry=carry, with_field=with_field,
            block_x=block_x, depth=depth, ghosts=ghosts,
        ))
        perf.record_memory(reg, context="solve")
        return attribution
    except Exception:
        return None


def record_checkpoint_io(op: str, kind: str, nbytes: float,
                         seconds: float) -> None:
    """Checkpoint I/O accounting: `op` save|load, `kind` single|sharded."""
    reg = get_registry()
    labels = dict(op=op, kind=kind)
    reg.counter(
        "wavetpu_checkpoint_ops_total", "checkpoint operations",
        ("op", "kind")
    ).inc(**labels)
    reg.counter(
        "wavetpu_checkpoint_bytes_total", "checkpoint file bytes moved",
        ("op", "kind")
    ).inc(float(nbytes), **labels)
    reg.counter(
        "wavetpu_checkpoint_seconds_total", "checkpoint I/O wall seconds",
        ("op", "kind")
    ).inc(float(seconds), **labels)


def supervisor_counter(name: str, help: str):
    return get_registry().counter(f"wavetpu_supervisor_{name}", help)


def supervisor_step_gauge():
    return get_registry().gauge(
        "wavetpu_supervisor_step", "last completed layer of the "
        "supervised march"
    )
