"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The framework's observability was three disconnected fragments (a bespoke
JSON snapshot at `GET /metrics`, one-shot phase probes, a CLI-only
profiler flag).  This module is the standard serving-stack answer: one
thread-safe registry of named metrics with label support, rendered two
ways from the same state -

 * `snapshot()` - a JSON-friendly dict (the serve layer's existing
   `/metrics` JSON fields write through this registry and stay
   byte-compatible);
 * `render_prometheus()` - Prometheus text exposition (version 0.0.4),
   content-negotiated on `GET /metrics` via `Accept: text/plain` and
   dumped to `metrics.prom` by the telemetry heartbeat.

Concurrency discipline: ONE registry-wide lock guards every read and
write, so a snapshot (or a Prometheus scrape) is a CONSISTENT cut - no
scrape can see counter A after an update that counter B has not received
yet.  That is deliberate and cheap: metric updates are host-side integer
adds on chunk/batch boundaries, never in the device hot loop.

Instruments:

 * `Counter` - monotonically increasing float (`.inc(v)`).
 * `Gauge`   - settable float (`.set(v)` / `.inc` / `.dec`).
 * `Histogram` - fixed cumulative buckets + sum + count
   (`.observe(v)`); renders the standard `_bucket{le=...}`, `_sum`,
   `_count` sample triplet.  `observe(v, exemplar={...})` additionally
   pins an OpenMetrics exemplar (e.g. a request id) to the bucket the
   observation landed in, so a scraped p99 bucket is JOINABLE to the
   exact trace record that filled it (`wavetpu trace-report --request`).
   Exemplars only render under `render_prometheus(openmetrics=True)` -
   the classic 0.0.4 text view stays byte-stable for parsers that do
   not speak the `# {label="v"} value ts` suffix.

Labels: declare `labelnames` at registration, address a child with
keyword labels on every call (`c.inc(1, path="kfused")`).  Re-registering
the same name is idempotent when the type/labelnames match and a
ValueError otherwise - two subsystems cannot silently fight over a name.

This module imports neither jax nor numpy: it must be safe to import
before the backend exists (same discipline as run/supervisor.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0,
)


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def escape_help(v: str) -> str:
    """# HELP line escaping: backslash and newline only (no quotes)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: float) -> str:
    """Sample-value formatting: integers render bare (1, not 1.0)."""
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class _Metric:
    """Base: one named metric family; per-label-tuple children."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labelnames: Sequence[str] = ()):
        self._registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} wants labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def _labelstr(self, key: Tuple[str, ...],
                  extra: Optional[Tuple[str, str]] = None) -> str:
        pairs = list(zip(self.labelnames, key))
        if extra is not None:
            pairs.append(extra)
        if not pairs:
            return ""
        body = ",".join(
            f'{n}="{escape_label_value(v)}"' for n, v in pairs
        )
        return "{" + body + "}"


class Counter(_Metric):
    kind = "counter"

    def __init__(self, registry, name, help, labelnames=()):
        super().__init__(registry, name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = self._key(labels)
        with self._registry.lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._registry.lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum over every label child (the JSON snapshot's single-number
        view of a labeled counter)."""
        with self._registry.lock:
            return sum(self._values.values())

    def _samples(self) -> List[Tuple[str, float, Optional[str]]]:
        return [
            (self.name + self._labelstr(key), v, None)
            for key, v in sorted(self._values.items())
        ]

    def _snapshot_value(self):
        if not self.labelnames:
            return self._values.get((), 0.0)
        return {
            ",".join(key): v for key, v in sorted(self._values.items())
        }


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, registry, name, help, labelnames=()):
        super().__init__(registry, name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._registry.lock:
            self._values[key] = float(value)

    def inc(self, value: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._registry.lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def dec(self, value: float = 1.0, **labels) -> None:
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._registry.lock:
            return self._values.get(key, 0.0)

    _samples = Counter._samples
    _snapshot_value = Counter._snapshot_value


class Histogram(_Metric):
    """Fixed cumulative buckets (upper bounds) + sum + count."""

    kind = "histogram"

    def __init__(self, registry, name, help, labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.buckets = bs
        # key -> (per-bucket counts, +Inf count, sum)
        self._values: Dict[Tuple[str, ...], list] = {}
        # key -> {bucket index (len(buckets) = +Inf) -> (labels, v, ts)}:
        # the LATEST exemplar per bucket, OpenMetrics-rendered.
        self._exemplars: Dict[Tuple[str, ...], dict] = {}

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None,
                **labels) -> None:
        key = self._key(labels)
        v = float(value)
        with self._registry.lock:
            slot = self._values.get(key)
            if slot is None:
                slot = [[0] * len(self.buckets), 0, 0.0]
                self._values[key] = slot
            landed = len(self.buckets)  # +Inf unless a bound catches it
            for i, b in enumerate(self.buckets):
                if v <= b:
                    slot[0][i] += 1
                    landed = min(landed, i)
            slot[1] += 1
            slot[2] += v
            if exemplar:
                self._exemplars.setdefault(key, {})[landed] = (
                    dict(exemplar), v, time.time()
                )

    def count(self, **labels) -> int:
        key = self._key(labels)
        with self._registry.lock:
            slot = self._values.get(key)
            return 0 if slot is None else slot[1]

    def _exemplar_str(self, key: Tuple[str, ...], idx: int) -> Optional[str]:
        ex = self._exemplars.get(key, {}).get(idx)
        if ex is None:
            return None
        labels, v, ts = ex
        body = ",".join(
            f'{n}="{escape_label_value(x)}"' for n, x in sorted(labels.items())
        )
        return f"# {{{body}}} {format_value(v)} {round(ts, 3)}"

    def _samples(self) -> List[Tuple[str, float, Optional[str]]]:
        out = []
        for key, (counts, total, vsum) in sorted(self._values.items()):
            for i, (b, c) in enumerate(zip(self.buckets, counts)):
                out.append((
                    self.name + "_bucket"
                    + self._labelstr(key, ("le", format_value(b))),
                    c,
                    self._exemplar_str(key, i),
                ))
            out.append((
                self.name + "_bucket" + self._labelstr(key, ("le", "+Inf")),
                total,
                self._exemplar_str(key, len(self.buckets)),
            ))
            out.append((self.name + "_sum" + self._labelstr(key), vsum, None))
            out.append((self.name + "_count" + self._labelstr(key), total,
                        None))
        return out

    def _snapshot_value(self):
        def one(slot):
            counts, total, vsum = slot
            return {"count": total, "sum": vsum}

        if not self.labelnames:
            slot = self._values.get(())
            return one(slot) if slot is not None else {"count": 0, "sum": 0.0}
        return {
            ",".join(key): one(slot)
            for key, slot in sorted(self._values.items())
        }


class MetricsRegistry:
    """A named collection of metrics with one consistency lock.

    `lock` is public on purpose: a caller holding state that must stay
    consistent WITH the registry (the serve layer's latency reservoir)
    may guard it under the same lock, so one snapshot sees one cut of
    everything."""

    def __init__(self):
        self.lock = threading.RLock()
        self._metrics: "Dict[str, _Metric]" = {}
        self.created = time.time()

    def _register(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self.lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name} already registered as "
                        f"{existing.kind}{existing.labelnames}"
                    )
                if "buckets" in kw and tuple(
                    sorted(float(b) for b in kw["buckets"])
                ) != existing.buckets:
                    # A silently-ignored bucket declaration would bin the
                    # second caller's observations into bounds it never
                    # asked for - loud error, same as a type mismatch.
                    raise ValueError(
                        f"histogram {name} already registered with "
                        f"buckets {existing.buckets}"
                    )
                return existing
            m = cls(self, name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    def names(self) -> List[str]:
        with self.lock:
            return sorted(self._metrics)

    def snapshot(self) -> dict:
        """One consistent JSON-friendly cut of every metric."""
        with self.lock:
            return {
                name: m._snapshot_value()
                for name, m in sorted(self._metrics.items())
            }

    def render_prometheus(self, openmetrics: bool = False) -> str:
        """Text exposition - one consistent cut.

        `openmetrics=False` (the default) is the classic 0.0.4 format
        every textfile collector parses; `openmetrics=True` renders the
        same families with histogram EXEMPLARS (`# {request_id="..."} v
        ts` bucket suffixes) and the `# EOF` terminator - the subset of
        OpenMetrics the serve layer content-negotiates for
        `Accept: application/openmetrics-text` scrapes."""
        with self.lock:
            lines = []
            for name, m in sorted(self._metrics.items()):
                family = name
                if (openmetrics and m.kind == "counter"
                        and name.endswith("_total")):
                    # OpenMetrics names a counter FAMILY without the
                    # _total suffix; the samples keep it.  The 0.0.4
                    # view keeps the historical full-name TYPE line.
                    family = name[: -len("_total")]
                lines.append(f"# HELP {family} {escape_help(m.help)}")
                lines.append(f"# TYPE {family} {m.kind}")
                for sample, value, exemplar in m._samples():
                    line = f"{sample} {format_value(value)}"
                    if openmetrics and exemplar is not None:
                        line += f" {exemplar}"
                    lines.append(line)
            if openmetrics:
                lines.append("# EOF")
            return "\n".join(lines) + "\n"


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (solver / checkpoint /
    supervisor counters).  The serve layer builds its OWN registry per
    server so concurrent test servers do not share counters."""
    return _REGISTRY
