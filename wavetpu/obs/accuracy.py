"""Accuracy observatory: per-plan measured-error ledger + plan-report.

The fleet measures speed at every seam (roofline gauges, compile
ledger, Server-Timing, distributed traces) but the bench data spans a
10x speed-accuracy trade (bf16-increment k=4 at 61.6 Gcell/s /
max_abs_err 0.66 vs compensated f32 at 12.4 Gcell/s / 5.7e-6) that no
production signal records.  This module is the accuracy half: every
solve that computes errors against the analytic oracle appends one line
to an APPEND-ONLY JSONL file under `--telemetry-dir`:

    {"type": "accuracy", "ts": 1754500000.0, "pid": 4242,
     "plan": {"scheme": "standard", "path": "kfused", "k": 4,
              "dtype": "bf16", "with_field": false},
     "n": 512, "n_bucket": 512, "timesteps": 1000,
     "max_abs_err": 0.66, "wall_s": 2.19, "cells": 1.35e11,
     "source": "oracle"}

`plan` is the (scheme, path, k, dtype, with_field) tuple - the exact
program-identity slice that decides numerical behavior, shared with
`wavetpu.progkey`.  `n_bucket` is N rounded up to a power of two so
requests at N=100 and N=120 aggregate into one frontier row.  `source`
distinguishes how the error was measured: "oracle" (analytic standing
wave - solo CLI solves and serve lanes with compute_errors on) vs
"shadow" (`wavetpu serve --shadow-sample-rate P`, serve/shadow.py:
max_abs_err is then the measured L-infinity DIVERGENCE of the served
plan's answer vs its compensated-f32 reference twin - accuracy
telemetry even where no analytic solution exists).

The file follows `obs/ledger.py`'s discipline exactly: append-only,
best-effort writes (a full disk never crashes the solve it observes),
EXEMPT from telemetry rotation, foreign/malformed lines skipped with a
stderr note instead of crashing the report, and pure stdlib - never
imports jax - so `wavetpu plan-report` runs off-accelerator against a
scraped telemetry dir.

`wavetpu plan-report DIR [--json] [--emit-plan-table OUT.json]` joins
this ledger with the compile ledger and `obs/perf.py`'s roofline model
into the measured speed-accuracy frontier per (plan, N-bucket):
measured Gcell/s, measured wall s/request, measured error percentiles,
compile spend, roofline fraction, and Pareto-dominance flags.
`--emit-plan-table` writes `plan_table.json` - the input ROADMAP
direction 4's error-budget planner consumes, and (carrying measured
wall s/request per plan) the drop-in replacement for the analytic
cells pricing in `fleet/quota.py`.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

ACCURACY_FILENAME = "accuracy_ledger.jsonl"
LEDGER_FILENAME = ACCURACY_FILENAME  # telemetry.py symmetry with ledger.py

PLAN_TABLE_FLAG = "wavetpu_plan_table"

PLAN_FIELDS = ("scheme", "path", "k", "dtype", "with_field")

# Log-decade buckets for the per-plan error histogram: the measured
# trade spans 5.7e-6 (compensated f32) to 0.66 (bf16 onion), so decades
# from 1e-8 up cover every plan the bench has produced with room on
# both ends.
ERR_BUCKETS = (1e-8, 1e-7, 1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0)


def n_bucket(n: int) -> int:
    """N rounded UP to a power of two (N=100 and N=120 share bucket
    128): frontier rows aggregate comparable problem sizes without one
    row per distinct grid."""
    n = int(n)
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def normalize_plan(plan: dict) -> dict:
    """Validate + canonically order a plan dict (the scheme/path/k/
    dtype/with_field slice of a ProgramKey).  Unknown fields are
    rejected loudly - same discipline as progkey.normalize_key."""
    extra = set(plan) - set(PLAN_FIELDS)
    if extra:
        raise ValueError(f"unknown plan field(s): {sorted(extra)}")
    missing = set(PLAN_FIELDS) - set(plan)
    if missing:
        raise ValueError(f"missing plan field(s): {sorted(missing)}")
    return {
        "scheme": str(plan["scheme"]),
        "path": str(plan["path"]),
        "k": int(plan["k"]),
        "dtype": str(plan["dtype"]),
        "with_field": bool(plan["with_field"]),
    }


def canonical_plan(plan: dict) -> str:
    return json.dumps(normalize_plan(plan), sort_keys=True)


def plan_label(plan: dict) -> str:
    return (
        f"{plan['scheme']}:{plan['path']} k={plan['k']} {plan['dtype']}"
        + (" field" if plan.get("with_field") else "")
    )


def make_plan(scheme: str, path: str, k: int, dtype: str,
              with_field: bool = False) -> dict:
    """A plan dict from the loose (scheme, path, k, dtype) call-site
    shape; `k` forced to 1 off the onion paths, like ProgramKey."""
    return normalize_plan({
        "scheme": scheme, "path": path,
        "k": k if "kfused" in path else 1,
        "dtype": dtype, "with_field": bool(with_field),
    })


_DTYPE_NAMES = {
    "float32": "f32", "float64": "f64", "bfloat16": "bf16",
    "f32": "f32", "f64": "f64", "bf16": "bf16",
}


def dtype_name(dtype) -> str:
    """Ledger dtype label from a numpy/jax dtype or a name string
    (unknown dtypes pass through as their string form - a foreign
    dtype must not crash the recording seam)."""
    return _DTYPE_NAMES.get(str(dtype), str(dtype))


class AccuracyLedger:
    """Append-only JSONL writer for one accuracy ledger file.

    Best-effort like the compile ledger: a full disk must never crash
    the solve the ledger observes.  The file accumulates across
    processes (append mode, no rotation)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def record(self, plan: dict, n: int, timesteps: int,
               max_abs_err: float, wall_s: float, cells: float,
               source: str = "oracle", ts: Optional[float] = None,
               pid: Optional[int] = None) -> dict:
        rec = {
            "type": "accuracy",
            "ts": round(time.time() if ts is None else ts, 3),
            "pid": os.getpid() if pid is None else int(pid),
            "plan": normalize_plan(plan),
            "n": int(n),
            "n_bucket": n_bucket(n),
            "timesteps": int(timesteps),
            "max_abs_err": float(max_abs_err),
            "wall_s": round(float(wall_s), 6),
            "cells": float(cells),
            "source": str(source),
        }
        # Serving-auth attribution (tenant), bound per-thread by the
        # scheduler worker - same seam as compile-ledger lines.
        from wavetpu.obs import ledger as compile_ledger

        rec.update(compile_ledger.request_context())
        with self._lock:
            try:
                if not self._f.closed:
                    self._f.write(json.dumps(rec) + "\n")
                    self._f.flush()
            except (OSError, ValueError):
                pass
        return rec

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


# ------------------------------------------------- process singleton

_ledger: Optional[AccuracyLedger] = None
_config_lock = threading.Lock()


def configure(path: str) -> AccuracyLedger:
    """Bind the process accuracy ledger (telemetry.start does this
    under `--telemetry-dir`); replaces a previous one."""
    global _ledger
    with _config_lock:
        if _ledger is not None:
            _ledger.close()
        _ledger = AccuracyLedger(path)
        return _ledger


def disable() -> None:
    global _ledger
    with _config_lock:
        if _ledger is not None:
            _ledger.close()
        _ledger = None


def get_ledger() -> Optional[AccuracyLedger]:
    return _ledger


def enabled() -> bool:
    return _ledger is not None


def record_accuracy(plan: dict, n: int, timesteps: int,
                    max_abs_err: float, wall_s: float, cells: float,
                    source: str = "oracle") -> None:
    """Record one measured error into the process ledger; a None-check
    no-op (zero file I/O) when no telemetry dir configured one."""
    led = _ledger
    if led is not None:
        led.record(plan, n, timesteps, max_abs_err, wall_s, cells,
                   source=source)


def record_error_metrics(registry, plan: dict, max_abs_err: float,
                         shadow: bool = False) -> None:
    """Stamp one measured error into `registry` (gauge + log-bucketed
    histogram, labeled by the plan's path/scheme/dtype).  Shadow
    divergences get their own gauge so the oracle signal and the
    production-divergence signal never overwrite each other."""
    labels = dict(path=plan["path"], scheme=plan["scheme"],
                  dtype=plan["dtype"])
    if shadow:
        registry.gauge(
            "wavetpu_shadow_divergence",
            "L-inf divergence of the served plan vs its reference "
            "twin, most recent shadow solve",
            ("path", "scheme", "dtype"),
        ).set(float(max_abs_err), **labels)
    else:
        registry.gauge(
            "wavetpu_solve_max_abs_err",
            "max abs error vs the analytic oracle, most recent solve",
            ("path", "scheme", "dtype"),
        ).set(float(max_abs_err), **labels)
    registry.histogram(
        "wavetpu_solve_abs_err",
        "per-plan measured-error distribution (log-decade buckets)",
        ("path", "scheme", "dtype"), buckets=ERR_BUCKETS,
    ).observe(float(max_abs_err), **labels)


def observe_solve(result, path: str, *, scheme: str, k: int,
                  with_field: bool, registry) -> None:
    """The single recording seam for the instrumented solver entry
    points (obs/metrics.record_solve threads every solver family
    through here).  `result` is a leapfrog.SolveResult whose
    `abs_errors` is None when the oracle was skipped - then NOTHING is
    recorded: the accuracy observatory only ever reports measured
    errors.  Caller guards exceptions (the X-ray must never fail the
    solve)."""
    errs = getattr(result, "abs_errors", None)
    if errs is None:
        return
    max_err = float(max(float(e) for e in errs))
    # The solver family's errors-off sentinel is an ALL-ZERO error
    # array (bench.py's errors_computed contract): a measured max of
    # exactly 0.0 is that sentinel, never a real oracle verdict -
    # ledgering it would claim perfect accuracy for an unchecked solve.
    if max_err <= 0.0:
        return
    plan = make_plan(scheme, path, k, dtype_name(result.u_cur.dtype),
                     with_field)
    record_error_metrics(registry, plan, max_err)
    problem = result.problem
    steps = result.steps_computed or problem.timesteps
    record_accuracy(
        plan, problem.N, problem.timesteps, max_err,
        float(result.solve_seconds or 0.0),
        float(problem.cells_per_step) * steps,
    )


def observe_serve_batch(result, verdicts, *, scheme: str, k: int,
                        dtype: str, registry) -> None:
    """Per-lane accuracy recording off the serve engine's watchdog
    reduction: each HEALTHY lane that computed oracle errors records
    one ledger line + metric stamp for the plan that served it (the
    batch's actual `result.path`, so a lane-loop fallback is labeled
    as what ran).  Tripped lanes are excluded - their error fields are
    poison, and their 422 already tells the story.  Caller guards
    exceptions (the X-ray must never fail the batch)."""
    plan = None
    for r, verdict in zip(result.results, verdicts):
        if verdict is not None:
            continue
        errs = getattr(r, "abs_errors", None)
        if errs is None:
            continue
        max_err = float(max(float(e) for e in errs))
        if max_err <= 0.0:
            continue  # all-zero = the errors-off sentinel, not a verdict
        if plan is None:
            plan = make_plan(scheme, result.path, k, dtype_name(dtype))
        record_error_metrics(registry, plan, max_err)
        problem = r.problem
        steps = getattr(r, "steps_computed", None) or problem.timesteps
        record_accuracy(
            plan, problem.N, problem.timesteps, max_err,
            float(result.solve_seconds or 0.0),
            float(problem.cells_per_step) * steps,
        )


# ------------------------------------------------- report / plan table


def resolve_accuracy_path(path: str) -> str:
    """Accept a telemetry DIR (the common case) or the ledger file."""
    if os.path.isdir(path):
        return os.path.join(path, ACCURACY_FILENAME)
    return path


def load_accuracy_ledger(path: str) -> List[dict]:
    """Parse the accuracy ledger; malformed/foreign lines counted, not
    fatal (the file may be mid-append, and an append-only cross-version
    file may hold records a newer/older wavetpu wrote - skipped, never
    a crash)."""
    records, bad = [], 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if not (
                isinstance(rec, dict) and rec.get("type") == "accuracy"
                and isinstance(rec.get("plan"), dict)
                and isinstance(rec.get("max_abs_err"), (int, float))
                and isinstance(rec.get("n"), int)
            ):
                bad += 1
                continue
            try:
                rec["plan"] = normalize_plan(rec["plan"])
            except (ValueError, TypeError):
                bad += 1
                continue
            rec.setdefault("n_bucket", n_bucket(rec["n"]))
            rec.setdefault("wall_s", 0.0)
            rec.setdefault("cells", 0.0)
            rec.setdefault("source", "oracle")
            records.append(rec)
    if bad:
        print(f"note: skipped {bad} malformed accuracy ledger line(s)",
              file=sys.stderr)
    return records


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _compile_spend(compile_records: Sequence[dict]) -> Dict[tuple, dict]:
    """Compile seconds per (plan, n_bucket), from obs/ledger.py
    records.  `source: disk` lines are cache loads, not compiles -
    excluded, like ledger.aggregate."""
    out: Dict[tuple, dict] = {}
    for rec in compile_records:
        if rec.get("source") == "disk":
            continue
        key = rec.get("key") or {}
        try:
            plan = make_plan(key["scheme"], key["path"], key.get("k", 1),
                             key["dtype"], key.get("with_field", False))
            bucket = n_bucket(key["N"])
        except (KeyError, ValueError, TypeError):
            continue
        row = out.setdefault((canonical_plan(plan), bucket),
                             {"compiles": 0, "compile_s": 0.0})
        row["compiles"] += 1
        row["compile_s"] += float(rec.get("compile_s", 0.0))
    return out


def _roofline(plan: dict, n: int, gcells_per_s: float) -> Optional[dict]:
    """The analytic roofline verdict for a measured throughput - best
    effort: plan-report must run off-accelerator even if obs/perf (or
    its model for this config) is unavailable."""
    try:
        from wavetpu.obs import perf

        return perf.solve_perf(
            gcells_per_s, plan["path"], scheme=plan["scheme"],
            k=plan["k"], n=n,
            itemsize=perf.DTYPE_ITEMSIZE.get(plan["dtype"], 4),
            with_field=plan["with_field"],
        )
    except Exception:
        return None


def build_plan_table(accuracy_records: Sequence[dict],
                     compile_records: Sequence[dict] = ()) -> dict:
    """The measured speed-accuracy frontier per (plan, N-bucket).

    Each row aggregates that plan's ledger lines in the bucket:
    measured Gcell/s (median of per-record cells/wall), measured wall
    s/request (median - the quota cost-model feedback ROADMAP's
    carry-over asks for), error percentiles p50/p95/max over every
    measured line (oracle and shadow alike - both are measured errors
    of the SERVED plan), the compile-ledger spend for matching keys,
    and the roofline model's verdict on the measured throughput.

    Pareto flags: within an N-bucket, a plan is `pareto_dominated`
    when some other plan is at least as fast (median Gcell/s) AND at
    least as accurate (p50 error), strictly better on one axis - the
    rows direction 4's planner can discard outright."""
    per: Dict[tuple, dict] = {}
    for rec in accuracy_records:
        key = (canonical_plan(rec["plan"]), int(rec["n_bucket"]))
        row = per.setdefault(key, {
            "plan": rec["plan"], "n_bucket": int(rec["n_bucket"]),
            "_errs": [], "_walls": [], "_gcells": [],
            "requests": 0, "oracle_requests": 0, "shadow_requests": 0,
            "_n_max": 0,
        })
        row["requests"] += 1
        if rec.get("source") == "shadow":
            row["shadow_requests"] += 1
        else:
            row["oracle_requests"] += 1
        row["_errs"].append(float(rec["max_abs_err"]))
        row["_n_max"] = max(row["_n_max"], int(rec["n"]))
        wall = float(rec.get("wall_s") or 0.0)
        cells = float(rec.get("cells") or 0.0)
        if wall > 0.0:
            row["_walls"].append(wall)
            if cells > 0.0:
                row["_gcells"].append(cells / wall / 1e9)
    spend = _compile_spend(compile_records)
    rows = []
    for (canon, bucket), row in sorted(per.items()):
        errs = sorted(row.pop("_errs"))
        walls = sorted(row.pop("_walls"))
        gcells = sorted(row.pop("_gcells"))
        n_max = row.pop("_n_max")
        row["err_p50"] = _percentile(errs, 0.50)
        row["err_p95"] = _percentile(errs, 0.95)
        row["err_max"] = errs[-1] if errs else 0.0
        row["wall_s_per_request"] = round(_percentile(walls, 0.50), 6)
        row["gcells_per_s"] = round(_percentile(gcells, 0.50), 6)
        comp = spend.get((canon, bucket))
        row["compiles"] = 0 if comp is None else comp["compiles"]
        row["compile_s"] = (
            0.0 if comp is None else round(comp["compile_s"], 6)
        )
        rf = _roofline(row["plan"], n_max, row["gcells_per_s"])
        row["roofline_fraction"] = (
            None if rf is None else rf["roofline_fraction"]
        )
        row["model_gbps"] = None if rf is None else rf["model_gbps"]
        rows.append(row)
    # Pareto-dominance within each bucket, on (median Gcell/s, p50 err).
    for row in rows:
        row["pareto_dominated"] = any(
            other is not row
            and other["n_bucket"] == row["n_bucket"]
            and other["gcells_per_s"] >= row["gcells_per_s"]
            and other["err_p50"] <= row["err_p50"]
            and (other["gcells_per_s"] > row["gcells_per_s"]
                 or other["err_p50"] < row["err_p50"])
            for other in rows
        )
    return {
        PLAN_TABLE_FLAG: True,
        "version": 1,
        "generated_unix": round(time.time(), 3),
        "entries": len(accuracy_records),
        "rows": rows,
    }


def format_plan_report(table: dict) -> str:
    rows = table["rows"]
    lines = [
        f"accuracy ledger: {table['entries']} measured solve(s), "
        f"{len(rows)} (plan, N-bucket) frontier row(s)",
        "",
        f"{'plan':<38} {'N<=':>5} {'req':>4} {'gcell/s':>9} "
        f"{'wall_s':>8} {'err_p50':>9} {'err_p95':>9} {'dominated':>9}",
    ]
    lines.append("-" * len(lines[-1]))
    for row in rows:
        lines.append(
            f"{plan_label(row['plan']):<38} {row['n_bucket']:>5} "
            f"{row['requests']:>4} {row['gcells_per_s']:>9.4f} "
            f"{row['wall_s_per_request']:>8.3f} "
            f"{row['err_p50']:>9.2e} {row['err_p95']:>9.2e} "
            f"{'yes' if row['pareto_dominated'] else 'no':>9}"
        )
    shadows = sum(r["shadow_requests"] for r in rows)
    if shadows:
        lines += [
            "",
            f"shadow-solve divergence lines: {shadows} (measured vs "
            f"the compensated-f32 reference twin, serve/shadow.py)",
        ]
    lines += [
        "",
        "wall_s is the MEASURED per-request cost per plan - the "
        "drop-in replacement for the analytic cells pricing in "
        "fleet/quota.py (ROADMAP quota cost-model carry-over); "
        "non-dominated rows are the measured speed-accuracy frontier "
        "direction 4's planner consumes.",
    ]
    return "\n".join(lines)


_USAGE = (
    "usage: wavetpu plan-report TELEMETRY_DIR|ACCURACY_LEDGER.jsonl "
    "[--json] [--emit-plan-table OUT.json]"
)


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    path = None
    as_json = False
    table_out = None
    it = iter(argv)
    try:
        for a in it:
            if a == "--json":
                as_json = True
            elif a == "--emit-plan-table":
                table_out = next(it)
            elif a.startswith("--emit-plan-table="):
                table_out = a.split("=", 1)[1]
            elif a.startswith("--"):
                raise ValueError(f"unknown flag {a}")
            elif path is None:
                path = a
            else:
                raise ValueError(f"unexpected positional {a!r}")
        if path is None:
            raise ValueError("missing telemetry dir / ledger path")
    except (ValueError, StopIteration) as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2
    try:
        records = load_accuracy_ledger(resolve_accuracy_path(path))
    except OSError as e:
        print(f"error: cannot read accuracy ledger: {e}",
              file=sys.stderr)
        return 2
    # The compile-ledger join is best effort: a telemetry dir scraped
    # before any compile was recorded still reports its frontier.
    compile_records: List[dict] = []
    if os.path.isdir(path):
        from wavetpu.obs import ledger as compile_ledger

        cpath = os.path.join(path, compile_ledger.LEDGER_FILENAME)
        if os.path.exists(cpath):
            try:
                compile_records = compile_ledger.load_ledger(cpath)
            except OSError:
                pass
    table = build_plan_table(records, compile_records)
    if as_json:
        print(json.dumps(table, indent=1, sort_keys=True))
    else:
        print(format_plan_report(table))
    if table_out is not None:
        with open(table_out, "w", encoding="utf-8") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        print(f"plan table ({len(table['rows'])} row(s)): {table_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
