"""Performance X-ray: roofline attribution + device-memory watermarks.

BENCH rounds pinned the solver family as memory-bandwidth-bound (the
k-step onion exists exactly to cut HBM traffic per layer), yet nothing
in the obs stack said how close a given solve actually ran to that
roofline, and nothing watched HBM pressure at all.  This module closes
both gaps:

ROOFLINE ATTRIBUTION.  `model_bytes_per_cell` is the ONE shared
analytic cost model for every solver path - cells x steps x scheme x
path x k x dtype -> bytes moved per cell-update - factored out of the
per-row traffic models bench.py used to hard-code and reconciled with
`choose_kstep_block` / `choose_kstep_comp_block`'s VMEM accounting (the
onion models read the SAME block depth the chooser blesses, so the
modeled traffic follows the block the kernel actually runs).  From it,
`solve_perf` turns a measured Gcell/s into:

    model_gbps        = bytes_per_cell x achieved Gcell/s  (achieved HBM
                        bandwidth under the model)
    roofline_fraction = model_gbps / peak_gbps             (how close to
                        the memory roofline this solve ran)
    arithmetic_intensity = flops_per_cell / bytes_per_cell

`metrics.record_solve` stamps these on every instrumented solve
(gauges + per-path GB/s histograms), and the serve engine attaches the
same attrs to its `serve.execute` spans.  `peak_gbps` defaults to the
measured pallas copy bandwidth on this repo's v5e (~250 GB/s, see
kernels/stencil_pallas.py's k-step section comment) and is overridable
via WAVETPU_PEAK_GBPS for other parts.

DEVICE-MEMORY OBSERVABILITY.  `memory_snapshot()` reads
`device.memory_stats()` (None on backends without it - e.g. the CPU
backend this repo's CI runs on); `record_memory()` samples it into
gauges around solo solves, per supervisor chunk, and per serve batch,
maintains a process-lifetime high-watermark gauge, counts watermark
raises, and fires a `memory.warn` trace event + counter when bytes in
use cross a configurable threshold (WAVETPU_MEM_WARN_BYTES).  The
"unsupported" verdict is probed once and cached, so on backends without
memory_stats every later call is a dict lookup - the no-op discipline
of PR 5.

`wavetpu profile` (profile_main) brackets one solve - or a whole serve
window - with `jax.profiler.start_trace`/`stop_trace`, so the PR 5 span
annotations (tracing.py opens a matching `jax.profiler.TraceAnnotation`
per span) land INSIDE the device trace, then prints a post-capture
summary.

Metric catalog additions (docs/observability.md is the user copy):

  wavetpu_solve_roofline_fraction{path}   gauge: last solve's fraction
  wavetpu_solve_model_gbps{path}          gauge: last solve's modeled GB/s
  wavetpu_solve_gbps{path}                histogram: modeled-GB/s dist
  wavetpu_device_bytes_in_use{context}    gauge: last sample
  wavetpu_device_peak_bytes{context}      gauge: allocator peak at sample
  wavetpu_device_memory_watermark_bytes   gauge: process-lifetime max
  wavetpu_device_memory_watermark_raises_total  counter: times it rose
  wavetpu_device_memory_warn_total        counter: threshold crossings

jax is NEVER imported at module level (same discipline as tracing.py):
the callers that need the model all run inside jax-using layers, and
`sys.modules` is consulted for the backend-dependent defaults.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, Optional, Sequence

from wavetpu.obs import tracing
from wavetpu.obs.registry import MetricsRegistry, get_registry

# Approximate op counts per cell-update, read off the kernel bodies
# (kernels/stencil_pallas.py): the standard step is a 7-point Laplacian
# (3 axes x [2 adds + 1 axpy-style combine] ~ 12) plus the leapfrog
# combine 2u + C*lap - u_prev (~3); the compensated velocity form adds
# the increment accumulate and the Kahan two-sum (~6 more).  These feed
# arithmetic intensity only - the family is bandwidth-bound, so bytes
# are the number that matters and flops just document WHY.
FLOPS_PER_CELL = {"standard": 15.0, "compensated": 21.0}

# Measured pallas copy bandwidth on this repo's v5e (the 1-step wall
# analysis in stencil_pallas.py's k-step section comment); CPU/other
# backends get a nominal figure - their fractions exercise the plumbing,
# not the analysis.
DEFAULT_PEAK_GBPS = {"tpu": 250.0}
FALLBACK_PEAK_GBPS = 25.0

# Serve-layer dtype names -> state itemsize (the engine's roofline
# call resolves its ProgramKey dtype string through this).
DTYPE_ITEMSIZE = {"f32": 4, "f64": 8, "bf16": 2}


def peak_gbps() -> float:
    """The roofline ceiling: WAVETPU_PEAK_GBPS env override, else the
    backend default (measured copy bandwidth on TPU, nominal elsewhere)."""
    env = os.environ.get("WAVETPU_PEAK_GBPS")
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            pass
    backend = None
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            backend = jax.default_backend()
        except Exception:
            backend = None
    return DEFAULT_PEAK_GBPS.get(backend, FALLBACK_PEAK_GBPS)


def _is_comp_onion(path: str, scheme: str) -> bool:
    return path in ("kfused_comp", "kfused_comp_sharded") or (
        path == "kfused" and scheme == "compensated"
    )


def model_bytes_per_cell(
    path: str,
    *,
    scheme: str = "standard",
    k: int = 1,
    n: Optional[int] = None,
    itemsize: int = 4,
    v_itemsize: Optional[int] = None,
    carry: bool = True,
    with_field: bool = False,
    block_x: Optional[int] = None,
    depth: Optional[int] = None,
    ghosts: bool = False,
) -> Optional[float]:
    """HBM bytes moved per cell-update under the path's traffic model.

    The ONE source of truth for the per-row models bench.py documents
    (its hard-coded numbers are now this function's outputs):

     * 1-step paths (`leapfrog`/`roll`/`pallas`/`sharded`, standard
       scheme): 3 state streams (u_prev + u in, u_next out) x itemsize,
       plus one f32 field stream under variable c.
     * 1-step compensated (`compensated`, or `sharded` with
       scheme="compensated"): u/v/carry each in + out = 6 streams.
     * standard k-step onion (`kfused`/`sharded_kfused`): per k-block of
       bx planes the pipeline fetches (bx + 2k) prev + (bx + 2k) cur
       onions and writes 2 bx-plane slabs -> (4bx + 4k) state planes
       per (k x bx) cell-layers; the field onion adds (bx + 2k) f32
       planes.  bx is `block_x` or what `choose_kstep_block` blesses -
       the SAME accounting that sizes the kernel's VMEM pipeline, so
       model and kernel can never drift.  The sharded variants choose
       their block against the SHARD depth with ghost buffers in the
       pipeline (`depth=`/`ghosts=True` - the same arguments the
       solvers pass the chooser); the bytes formula is unchanged (ghost
       planes replace the wraparound halo reads one-for-one), only the
       blessed bx moves.
     * compensated velocity-form onion (`kfused_comp[_sharded]`): u and
       v onions ride in+out at their own itemsizes ((2bx + 2k) planes
       each); the carry rides slab-only (2bx planes) at an effective
       2 B/plane (the calibrated figure from the measured BENCH rows -
       Mosaic keeps part of the carry stream resident); carry-less
       (bf16-increment) mode drops it.  bx from
       `choose_kstep_comp_block`.

    Returns None when the onion does not fit VMEM at this (n, k, dtype)
    per the chooser - the caller then has no roofline model to report,
    which is the honest answer.
    """
    onion = path in ("kfused", "sharded_kfused") and scheme != "compensated"
    comp_onion = _is_comp_onion(path, scheme)
    if not onion and not comp_onion:
        if scheme == "compensated" or path == "compensated":
            return 6.0 * itemsize
        return 3.0 * itemsize + (4.0 if with_field else 0.0)
    if n is None:
        return None
    # Lazy: stencil_pallas imports jax; every caller of an onion model
    # already runs inside a jax-using layer.
    from wavetpu.kernels.stencil_pallas import (
        choose_kstep_block,
        choose_kstep_comp_block,
    )

    if onion:
        bx = block_x or choose_kstep_block(
            n, k, itemsize, depth=depth, ghosts=ghosts,
            field=with_field,
        )
        if bx is None:
            return None
        per_block = float((4 * bx + 4 * k) * itemsize)
        if with_field:
            per_block += (bx + 2 * k) * 4.0
        return per_block / (k * bx)
    v_item = itemsize if v_itemsize is None else v_itemsize
    bx = block_x or choose_kstep_comp_block(
        n, k, itemsize, v_item, itemsize if carry else None,
        depth=depth, ghosts=ghosts, field=with_field,
    )
    if bx is None:
        return None
    per_block = float(
        (2 * bx + 2 * k) * itemsize + (2 * bx + 2 * k) * v_item
    )
    if carry:
        per_block += 2 * bx * 2.0  # calibrated effective carry traffic
    if with_field:
        per_block += (bx + 2 * k) * 4.0
    return per_block / (k * bx)


def flops_per_cell(scheme: str = "standard") -> float:
    return FLOPS_PER_CELL.get(scheme, FLOPS_PER_CELL["standard"])


def solve_perf(
    gcells_per_s: float,
    path: str,
    *,
    scheme: str = "standard",
    k: int = 1,
    n: Optional[int] = None,
    itemsize: int = 4,
    v_itemsize: Optional[int] = None,
    carry: bool = True,
    with_field: bool = False,
    block_x: Optional[int] = None,
    depth: Optional[int] = None,
    ghosts: bool = False,
) -> Optional[Dict[str, float]]:
    """One solve's roofline attribution, or None when no model exists
    for the config (onion over VMEM, zero throughput)."""
    if not gcells_per_s or gcells_per_s <= 0:
        return None
    bpc = model_bytes_per_cell(
        path, scheme=scheme, k=k, n=n, itemsize=itemsize,
        v_itemsize=v_itemsize, carry=carry, with_field=with_field,
        block_x=block_x, depth=depth, ghosts=ghosts,
    )
    if bpc is None:
        return None
    peak = peak_gbps()
    model_gbps = gcells_per_s * bpc
    fpc = flops_per_cell(scheme)
    return {
        "model_bytes_per_cell": round(bpc, 4),
        "model_gbps": round(model_gbps, 3),
        "peak_gbps": peak,
        "roofline_fraction": round(model_gbps / peak, 4),
        "flops_per_cell": fpc,
        "arithmetic_intensity": round(fpc / bpc, 4),
    }


_GBPS_BUCKETS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 150.0, 200.0,
                 250.0, 350.0, 500.0, 1000.0)


def record_roofline(registry: Optional[MetricsRegistry], path: str,
                    perf: Optional[Dict[str, float]]
                    ) -> Optional[Dict[str, float]]:
    """Stamp one solve's roofline attribution into `registry` (the
    process registry by default).  Returns `perf` unchanged so call
    sites can also attach the attrs to an open span."""
    if perf is None:
        return None
    reg = registry if registry is not None else get_registry()
    reg.gauge(
        "wavetpu_solve_roofline_fraction",
        "modeled-GB/s share of the memory roofline, most recent solve",
        ("path",),
    ).set(perf["roofline_fraction"], path=path)
    reg.gauge(
        "wavetpu_solve_model_gbps",
        "achieved HBM GB/s under the path's traffic model, most recent "
        "solve", ("path",),
    ).set(perf["model_gbps"], path=path)
    reg.histogram(
        "wavetpu_solve_gbps",
        "per-solve modeled-GB/s distribution", ("path",),
        buckets=_GBPS_BUCKETS,
    ).observe(perf["model_gbps"], path=path)
    return perf


# ------------------------------------------------- device memory


_mem_lock = threading.Lock()
# None = not yet probed; False = backend has no memory_stats (every
# later call short-circuits); True = supported.
_mem_supported: Optional[bool] = None
# Test hook: a callable returning a memory_stats-shaped dict (or None)
# instead of reading the real device.
_stats_provider: Optional[Callable[[], Optional[dict]]] = None
_warn_bytes_override: Optional[int] = None


def set_memory_stats_provider(
    fn: Optional[Callable[[], Optional[dict]]]
) -> None:
    """Test hook: replace the device read (None restores it and resets
    the cached supported/unsupported verdict)."""
    global _stats_provider, _mem_supported
    with _mem_lock:
        _stats_provider = fn
        _mem_supported = None


def configure_memory_warn(warn_bytes: Optional[int]) -> None:
    """Set (or clear) the warn threshold programmatically; the
    WAVETPU_MEM_WARN_BYTES env var is the CLI-facing knob."""
    global _warn_bytes_override
    _warn_bytes_override = warn_bytes


def memory_warn_bytes() -> Optional[int]:
    if _warn_bytes_override is not None:
        return _warn_bytes_override
    env = os.environ.get("WAVETPU_MEM_WARN_BYTES")
    if env:
        try:
            v = int(float(env))
            if v > 0:
                return v
        except ValueError:
            pass
    return None


def memory_snapshot() -> Optional[Dict[str, int]]:
    """{bytes_in_use, peak_bytes} from device 0's allocator, or None on
    backends without `memory_stats()` (the CPU backend returns None).
    The unsupported verdict is cached - later calls cost a dict lookup."""
    global _mem_supported
    if _mem_supported is False:
        return None
    stats = None
    provider = _stats_provider
    if provider is not None:
        try:
            stats = provider()
        except Exception:
            return None  # transient: no verdict, re-probe next call
    else:
        jax = sys.modules.get("jax")
        if jax is None:
            return None  # backend not up yet: not a verdict, re-probe
        try:
            stats = jax.devices()[0].memory_stats()
        except Exception:
            # A transient read failure (e.g. a race during backend
            # bring-up) is NOT an "unsupported" verdict - do not latch,
            # just skip this sample and re-probe next time.
            return None
    if not stats:
        # memory_stats() answered cleanly with nothing: the backend
        # genuinely has no stats (the CPU backend) - cache that.
        with _mem_lock:
            _mem_supported = False
        return None
    with _mem_lock:
        _mem_supported = True
    in_use = int(stats.get("bytes_in_use", 0))
    return {
        "bytes_in_use": in_use,
        "peak_bytes": int(stats.get("peak_bytes_in_use", in_use)),
    }


def record_memory(registry: Optional[MetricsRegistry] = None,
                  context: str = "solve") -> Optional[Dict[str, int]]:
    """Sample device memory into gauges (labeled by where the sample was
    taken: solve / supervisor / serve), raise the process high-watermark
    gauge when exceeded (counting each raise), and fire the configurable
    warn-threshold event.  No-op (None) on backends without
    memory_stats."""
    snap = memory_snapshot()
    if snap is None:
        return None
    reg = registry if registry is not None else get_registry()
    reg.gauge(
        "wavetpu_device_bytes_in_use",
        "device-allocator bytes in use at the last sample", ("context",),
    ).set(snap["bytes_in_use"], context=context)
    reg.gauge(
        "wavetpu_device_peak_bytes",
        "device-allocator peak bytes at the last sample", ("context",),
    ).set(snap["peak_bytes"], context=context)
    wm = reg.gauge(
        "wavetpu_device_memory_watermark_bytes",
        "highest device bytes-in-use observed this process",
    )
    with reg.lock:
        if snap["bytes_in_use"] > wm.value():
            wm.set(snap["bytes_in_use"])
            reg.counter(
                "wavetpu_device_memory_watermark_raises_total",
                "times the high watermark rose",
            ).inc()
    warn = memory_warn_bytes()
    if warn is not None and snap["bytes_in_use"] > warn:
        reg.counter(
            "wavetpu_device_memory_warn_total",
            "samples above the WAVETPU_MEM_WARN_BYTES threshold",
        ).inc()
        tracing.event(
            "memory.warn", context=context,
            bytes_in_use=snap["bytes_in_use"], warn_bytes=warn,
        )
    return snap


# ------------------------------------------------- `wavetpu profile`


_PROFILE_USAGE = (
    "usage: wavetpu profile --out DIR [--] ARGS...\n"
    "  ARGS is a full wavetpu command line: solver positionals + flags\n"
    "  for one solve, or `serve ...` to profile a whole serve window\n"
    "  (the capture ends when the server shuts down).  The run gets a\n"
    "  --telemetry-dir under DIR unless ARGS already carries one, so\n"
    "  the span annotations land inside the device trace."
)


def _dir_file_summary(root: str) -> Sequence[str]:
    lines = []
    for dirpath, _dirs, files in os.walk(root):
        for f in sorted(files):
            p = os.path.join(dirpath, f)
            try:
                size = os.path.getsize(p)
            except OSError:
                continue
            lines.append(f"  {os.path.relpath(p, root)}  {size} B")
    return lines


def profile_main(argv: Sequence[str]) -> int:
    """`wavetpu profile`: bracket one solve (or serve window) with
    `jax.profiler` so application spans land in a device trace, then
    print a post-capture summary (span stats + captured files).  Do not
    combine with the inner `--profile` flag - this subcommand IS the
    bracket."""
    argv = list(argv)
    out = None
    inner = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--out" and i + 1 < len(argv):
            out = argv[i + 1]
            i += 2
        elif a.startswith("--out="):
            out = a.split("=", 1)[1]
            i += 1
        elif a == "--":
            inner = argv[i + 1:]
            i = len(argv)
        else:
            inner = argv[i:]
            i = len(argv)
    if not out or not inner:
        print(_PROFILE_USAGE, file=sys.stderr)
        return 2
    if "--profile" in inner or any(
        a.startswith("--profile=") for a in inner
    ):
        print("error: do not pass --profile under `wavetpu profile` "
              "(the subcommand owns the bracket)", file=sys.stderr)
        return 2
    telemetry_dir = None
    for j, a in enumerate(inner):
        if a == "--telemetry-dir" and j + 1 < len(inner):
            telemetry_dir = inner[j + 1]
        elif a.startswith("--telemetry-dir="):
            telemetry_dir = a.split("=", 1)[1]
    if telemetry_dir is None:
        telemetry_dir = os.path.join(out, "telemetry")
        inner = inner + ["--telemetry-dir", telemetry_dir]
    os.makedirs(out, exist_ok=True)

    import jax

    from wavetpu import cli as wavetpu_cli

    print(f"profiling `wavetpu {' '.join(inner)}` -> {out}")
    t0 = time.perf_counter()
    jax.profiler.start_trace(out)
    try:
        rc = wavetpu_cli.main(inner)
    finally:
        jax.profiler.stop_trace()
    wall = time.perf_counter() - t0

    print(f"\nprofile capture: {wall:.3f}s wall, exit {rc}")
    trace_path = os.path.join(telemetry_dir, "trace.jsonl")
    if os.path.exists(trace_path):
        from wavetpu.obs import report as obs_report

        records = obs_report.load_trace(trace_path)
        print("span summary (these kinds are annotated inside the "
              "device trace):")
        print(obs_report.format_summary(obs_report.summarize(records)))
    files = _dir_file_summary(out)
    print(f"captured files under {out}:")
    for line in files[:40]:
        print(line)
    if len(files) > 40:
        print(f"  ... {len(files) - 40} more")
    print("open in xprof/TensorBoard: "
          f"tensorboard --logdir {out}")
    return rc
