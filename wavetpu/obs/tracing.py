"""Structured span tracing: JSONL application spans + XLA trace bridging.

`--profile DIR` captures op-level XLA device traces but says nothing
about the APPLICATION structure around them - which request a compile
belonged to, how long a chunk waited on a checkpoint write.  This module
emits that structure as newline-delimited JSON records an operator can
tail and `wavetpu trace-report` can summarize:

    {"type": "span", "kind": "supervisor.chunk", "span_id": "1f03-4",
     "parent_id": "1f03-1", "thread": "MainThread",
     "t_start": 1722772800.123, "dur_s": 0.512, "attrs": {...}}

 * `span(kind, **attrs)` - context manager: allocates a span id, links
   the enclosing span on the SAME THREAD as parent, measures wall time,
   and writes one record on exit.  The yielded dict is the record's
   `attrs`: mutate it to attach results discovered mid-span (occupancy,
   cache verdicts).  While a span is open it also holds a matching
   `jax.profiler.TraceAnnotation(kind)` - IF jax is already imported -
   so application spans line up with device traces captured via
   `--profile` in the same run.  (jax is never imported here: tracing
   must not drag the backend in; `sys.modules` is consulted instead.)
 * `begin_span()` / `end_span()` - the same span without the `with`
   block, for call sites where a context manager would force a 300-line
   reindent (cli.py's solve dispatch).
 * `event(kind, **attrs)` - a zero-duration record.

The module-level tracer is a process-wide singleton configured by
`configure(path)` (the CLI's `--telemetry-dir` does this).  When NOT
configured every call is a cheap no-op - `span()` yields a throwaway
dict without allocating ids or touching any lock - so instrumented code
paths cost nothing in untraced runs (bench.py pins the traced overhead
itself at <= 2%).

Cross-thread linkage: parenthood is thread-local (a scheduler-worker
span is not a child of whatever the HTTP thread had open).  Cross-thread
stories - one serve request enqueued on thread A and executed on thread
B - are stitched by shared ATTRIBUTES instead (`request_id` /
`request_ids`), which `wavetpu trace-report --request` joins on.

Cross-PROCESS linkage (the fleet story) rides W3C trace context:
`parse_traceparent` / `format_traceparent` speak the `traceparent`
header (`00-{32-hex trace id}-{16-hex parent id}-{flags}`), and
`begin()` accepts `remote=(trace_id, parent_id)` to adopt an inbound
context as the span's parent.  Internal span ids stay `{pid:x}-{n}`;
a FORWARDING span (router attempt, serve request) additionally mints a
16-hex W3C id, records it as its `w3c_id` attr, and sends it downstream
as the traceparent parent - the trace joiner (obs/report.py) resolves
`w3c_id -> span_id` at merge time, so one request's spans across the
client, the router, and N replicas share one `trace_id` and one tree.
Preemption resume chains that cross requests use record-level `links`
(`[{"trace_id": ..., "span_id": ...}]`) instead of parenthood.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import sys
import threading
import time
from typing import List, Optional, Tuple


# ------------------------------------------- W3C trace context (fleet)

_ZERO_TRACE = "0" * 32
_ZERO_SPAN = "0" * 16


def mint_trace_id() -> str:
    """A fresh 32-hex W3C trace id (crypto-random, never all-zero)."""
    while True:
        tid = os.urandom(16).hex()
        if tid != _ZERO_TRACE:
            return tid


def mint_span_id() -> str:
    """A fresh 16-hex W3C span id for the wire (the `traceparent`
    parent-id field).  Internal span ids stay `{pid:x}-{n}`; this is
    only what a FORWARDING span advertises downstream."""
    while True:
        sid = os.urandom(8).hex()
        if sid != _ZERO_SPAN:
            return sid


def format_traceparent(trace_id: str, parent_id: str,
                       flags: str = "01") -> str:
    """`00-{trace_id}-{parent_id}-{flags}` (W3C Trace Context v00)."""
    return f"00-{trace_id}-{parent_id}-{flags}"


def parse_traceparent(header: Optional[str]
                      ) -> Optional[Tuple[str, str]]:
    """`traceparent` header -> (trace_id, parent_id), or None for
    anything malformed (wrong field count/width, non-hex, all-zero ids,
    the reserved version ff).  Garbage from an arbitrary proxy must
    degrade to 'untraced', never to a crash or a poisoned trace id."""
    if not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, parent_id, flags = parts
    if (len(version), len(trace_id), len(parent_id), len(flags)) != \
            (2, 32, 16, 2):
        return None
    try:
        int(version, 16), int(trace_id, 16)
        int(parent_id, 16), int(flags, 16)
    except ValueError:
        return None
    if version == "ff" or trace_id == _ZERO_TRACE \
            or parent_id == _ZERO_SPAN:
        return None
    return trace_id, parent_id


def rotate_file(path: str, keep: int) -> None:
    """Size-rotation shift: path -> path.1 -> ... -> path.{keep-1}, the
    oldest segment dropped.  Every move is an atomic `os.replace`, so a
    concurrent reader (trace-report on a live dir) sees whole segments,
    never a half-renamed set.  `keep` counts TOTAL retained segments
    including the live file; keep=1 means rotation just truncates."""
    keep = max(1, int(keep))
    if keep == 1:
        try:
            os.replace(path, path + ".dropped")
            os.remove(path + ".dropped")
        except OSError:
            pass
        return
    for i in range(keep - 1, 0, -1):
        src = path if i == 1 else f"{path}.{i - 1}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i}")


_tracer_instances = itertools.count()


class Tracer:
    """JSONL span writer bound to one output file (append mode).

    `max_bytes` caps the live segment: a write that would exceed it
    first rotates (`rotate_file`, keep-last-`keep` segments), so a
    long-lived server's trace.jsonl cannot append forever.  Rotation
    happens under the write lock; `wavetpu trace-report` reads the
    whole rotated segment set (obs/report.py)."""

    def __init__(self, path: str, max_bytes: Optional[int] = None,
                 keep: int = 4):
        self.path = path
        self.max_bytes = max_bytes
        self.keep = max(1, int(keep))
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._wlock = threading.Lock()
        self._ids = itertools.count(1)
        # Span ids are `{prefix}-{n}`.  The prefix must be unique PER
        # TRACER, not just per process: a router and an in-process
        # replica (tests, bench) each own a Tracer, and two id
        # namespaces both rooted at the bare pid would collide on
        # `{pid:x}-1` - corrupting the joiner's by-id maps.  The first
        # tracer in a process keeps the plain pid (the production
        # one-tracer-per-process shape); later instances get a distinct
        # `{pid}t{k}` namespace.
        n = next(_tracer_instances)
        self._prefix = (
            f"{os.getpid():x}" if n == 0 else f"{os.getpid():x}t{n}"
        )
        self._local = threading.local()

    # -- ids / stack ---------------------------------------------------

    def new_id(self) -> str:
        return f"{self._prefix}-{next(self._ids)}"

    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_span_id(self) -> Optional[str]:
        st = self._stack()
        return st[-1][0] if st else None

    def current_trace_id(self) -> Optional[str]:
        """The W3C trace id of the innermost open span on THIS thread
        (None when untraced / no span open) - child spans inherit it."""
        st = self._stack()
        return st[-1][1] if st else None

    # -- emission ------------------------------------------------------

    def _write(self, record: dict) -> None:
        # Best-effort: telemetry must never crash the run it observes.
        # OSError = disk full / EIO; ValueError = file closed by a
        # concurrent disable() while another thread still held a span.
        line = json.dumps(record, default=str)
        try:
            with self._wlock:
                if (
                    self.max_bytes is not None
                    and self._f.tell() > 0
                    and self._f.tell() + len(line) + 1 > self.max_bytes
                ):
                    self._f.close()
                    rotate_file(self.path, self.keep)
                    self._f = open(self.path, "a", encoding="utf-8")
                self._f.write(line + "\n")
                self._f.flush()
        except (OSError, ValueError):
            pass

    def begin(self, kind: str, attrs: dict, /,
              remote: Optional[Tuple[str, Optional[str]]] = None,
              links: Optional[List[dict]] = None,
              trace_id: Optional[str] = None) -> dict:
        """Open a span; returns the handle `end()` wants.  Also opens a
        matching jax.profiler.TraceAnnotation when jax is already loaded
        so application spans land in `--profile` device traces.

        `remote=(trace_id, parent_id)` adopts an INBOUND W3C context
        (another process's traceparent) as the parent instead of this
        thread's stack: parent_id may be a 16-hex wire id (the joiner
        resolves it against the sender's `w3c_id` attr) or None for a
        trace root.  `trace_id` alone stamps the record's trace id
        without touching parenthood (a scheduler-thread chunk span that
        belongs to a request's trace but is not its tree child).
        `links` attaches record-level cross-trace links (the preemption
        resume chain)."""
        annotation = None
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                annotation = jax.profiler.TraceAnnotation(kind)
                annotation.__enter__()
            except Exception:
                annotation = None
        if remote is not None:
            parent_id: Optional[str] = remote[1]
            trace_id = remote[0]
        else:
            parent_id = self.current_span_id()
            if trace_id is None:
                trace_id = self.current_trace_id()
        handle = {
            "kind": kind,
            "span_id": self.new_id(),
            "parent_id": parent_id,
            "trace_id": trace_id,
            "links": list(links) if links else None,
            "t_start": time.time(),
            "_t0": time.perf_counter(),
            "_annotation": annotation,
            "attrs": attrs,
        }
        self._stack().append((handle["span_id"], trace_id))
        return handle

    def end(self, handle: dict, **extra_attrs) -> None:
        t0 = handle.pop("_t0", None)
        if t0 is None:
            # Already ended: a crash-path end_span can race the normal
            # end on the same handle (supervisor's except handler).
            # Ending twice must not raise (it would mask the original
            # exception) or emit a duplicate record.
            return
        st = self._stack()
        if st and st[-1][0] == handle["span_id"]:
            st.pop()
        else:  # unbalanced begin/end: recover
            for i, (sid, _tid) in enumerate(st):
                if sid == handle["span_id"]:
                    del st[i]
                    break
        annotation = handle.pop("_annotation", None)
        if annotation is not None:
            try:
                annotation.__exit__(None, None, None)
            except Exception:
                pass
        handle["attrs"] = dict(handle["attrs"], **extra_attrs)
        dur = time.perf_counter() - t0
        record = {
            "type": "span",
            "kind": handle["kind"],
            "span_id": handle["span_id"],
            "parent_id": handle["parent_id"],
            "thread": threading.current_thread().name,
            "t_start": round(handle["t_start"], 6),
            "dur_s": round(dur, 6),
            "attrs": handle["attrs"],
        }
        if handle.get("trace_id") is not None:
            record["trace_id"] = handle["trace_id"]
        if handle.get("links"):
            record["links"] = handle["links"]
        self._write(record)

    @contextlib.contextmanager
    def span(self, kind: str, /,
             remote: Optional[Tuple[str, Optional[str]]] = None,
             links: Optional[List[dict]] = None,
             trace_id: Optional[str] = None, **attrs):
        handle = self.begin(kind, attrs, remote=remote, links=links,
                            trace_id=trace_id)
        try:
            yield handle["attrs"]
        finally:
            self.end(handle)

    def event(self, kind: str, /, **attrs) -> None:
        record = {
            "type": "event",
            "kind": kind,
            "span_id": self.new_id(),
            "parent_id": self.current_span_id(),
            "thread": threading.current_thread().name,
            "t_start": round(time.time(), 6),
            "attrs": attrs,
        }
        tid = self.current_trace_id()
        if tid is not None:
            record["trace_id"] = tid
        self._write(record)

    def close(self) -> None:
        with self._wlock:
            if not self._f.closed:
                self._f.close()


# ------------------------------------------------- module-level tracer

_tracer: Optional[Tracer] = None
_config_lock = threading.Lock()


def configure(path: str, max_bytes: Optional[int] = None,
              keep: int = 4) -> Tracer:
    """Start (or replace) the process tracer, writing JSONL to `path`.
    `max_bytes`/`keep` turn on size-based segment rotation (the
    telemetry layer passes its defaults; direct callers - tests - get
    an unrotated file unless they ask)."""
    global _tracer
    with _config_lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = Tracer(path, max_bytes=max_bytes, keep=keep)
        return _tracer


def disable() -> None:
    global _tracer
    with _config_lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = None


def get_tracer() -> Optional[Tracer]:
    return _tracer


def enabled() -> bool:
    return _tracer is not None


@contextlib.contextmanager
def span(kind: str, /, remote: Optional[Tuple[str, Optional[str]]] = None,
         links: Optional[List[dict]] = None,
         trace_id: Optional[str] = None, **attrs):
    """Module-level span: no-op (fresh throwaway attrs dict) when no
    tracer is configured, so instrumented paths cost nothing untraced."""
    t = _tracer
    if t is None:
        yield attrs
        return
    with t.span(kind, remote=remote, links=links, trace_id=trace_id,
                **attrs) as a:
        yield a


def begin_span(kind: str, /,
               remote: Optional[Tuple[str, Optional[str]]] = None,
               links: Optional[List[dict]] = None,
               trace_id: Optional[str] = None, **attrs
               ) -> Optional[dict]:
    t = _tracer
    return None if t is None else t.begin(
        kind, attrs, remote=remote, links=links, trace_id=trace_id
    )


def end_span(handle: Optional[dict], **extra_attrs) -> None:
    t = _tracer
    if t is not None and handle is not None:
        t.end(handle, **extra_attrs)


def event(kind: str, /, **attrs) -> None:
    t = _tracer
    if t is not None:
        t.event(kind, **attrs)


def new_id() -> Optional[str]:
    """A fresh id in the tracer's namespace (request correlation), or
    None untraced."""
    t = _tracer
    return None if t is None else t.new_id()
