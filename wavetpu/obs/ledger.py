"""Persistent compile-cost ledger + `wavetpu ledger-report`.

BENCH_r04/r05 put compilation at 30-62 s against 2-7 s solves: for a
service, compile spend IS the dominant cold-start and autoscaling cost,
and it is invisible across process restarts - every replica pays it
again and nothing adds it up.  This module records every compile into
an APPEND-ONLY JSONL file under `--telemetry-dir`:

    {"type": "compile", "ts": 1754300000.0, "pid": 4242, "cold": true,
     "compile_s": 31.25,
     "key": {"N": 512, "Lx": 1.0, ..., "scheme": "compensated",
             "path": "kfused", "k": 4, "dtype": "f32",
             "with_field": false, "compute_errors": true,
             "batch": 4, "mesh": null}}

`key` is a `serve.engine.ProgramKey` as a JSON object (solo CLI solves
record a batch=1 key in the same shape).  `cold` marks the first
compile of a key IN THIS PROCESS; a later entry with cold=false is an
in-process recompile (LRU eviction churn).  The file is deliberately
EXEMPT from the telemetry size rotation (one line per compile - a
ledger that rotated away its history could not answer the cross-restart
questions it exists for) and is opened in append mode, so entries
accumulate across process lifetimes.

`wavetpu ledger-report DIR` then answers the questions a restart
erases:

 * compile spend per ProgramKey (count / cold count / seconds),
 * keys recompiled across restarts (cold in >= 2 distinct pids - the
   exact keys a persistent cross-process AOT cache would have served),
 * a WHAT-IF simulation of that cache: replay the ledger through an
   infinite persistent cache - every cold compile of an already-seen
   key is a hit, and the seconds saved are those compiles' MEASURED
   seconds (validated: saved_s + residual first-compile seconds ==
   total recorded compile seconds, exactly),
 * `--emit-warmup-manifest OUT.json`: the distinct key set in the exact
   shape the planned `wavetpu warmup --manifest` (ROADMAP direction 2)
   will consume - each key round-trips through `ProgramKey` parsing
   (`program_key_from_dict`).

Everything here is pure stdlib (never imports jax): the report tool
runs off-accelerator against a scraped telemetry dir, like
trace-report.  When no ledger is configured, `record_compile` is a
None-check no-op and NO file is ever created - the PR 5 discipline.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

LEDGER_FILENAME = "compile_ledger.jsonl"

MANIFEST_FLAG = "wavetpu_warmup_manifest"

# The key canonicalization (KEY_FIELDS order, normalize/canonical,
# ProgramKey <-> JSON-dict round trip) moved to `wavetpu.progkey` when
# the fleet router joined the consumers; re-exported here so existing
# callers (and ledger files on disk) see no change.
from wavetpu.progkey import (  # noqa: E402,F401
    KEY_FIELDS,
    canonical_key,
    key_from_program_key,
    normalize_key,
    program_key_from_dict,
)


# ------------------------------------------------- request context
#
# Serving-auth round: the router terminates API keys and forwards the
# mapped tenant label; the scheduler worker binds it here (THREAD-local,
# not a contextvar - the compile happens on the worker thread, not the
# HTTP handler thread that knew the tenant) so every ledger line a
# solve records carries `tenant` without threading it through the whole
# engine call chain.

_request_ctx = threading.local()


def set_request_context(tenant: Optional[str] = None) -> None:
    """Bind per-request attribution for ledger lines recorded on THIS
    thread until `clear_request_context`.  None values are dropped."""
    ctx = {}
    if tenant:
        ctx["tenant"] = str(tenant)
    _request_ctx.fields = ctx


def clear_request_context() -> None:
    _request_ctx.fields = {}


def request_context() -> dict:
    return dict(getattr(_request_ctx, "fields", None) or {})


def solo_key(problem, scheme: str, path: str, k: int, dtype: str,
             with_field: bool, compute_errors: bool,
             mesh=None) -> dict:
    """A batch=1 key for a solo CLI solve, same shape as the serve
    engine's (`k` is forced to 1 off the kfused path, like
    ProgramKey.for_batch)."""
    return normalize_key({
        "N": problem.N, "Lx": problem.Lx, "Ly": problem.Ly,
        "Lz": problem.Lz, "T": problem.T,
        "timesteps": problem.timesteps, "scheme": scheme, "path": path,
        "k": k if path == "kfused" else 1, "dtype": dtype,
        "with_field": bool(with_field),
        "compute_errors": bool(compute_errors), "batch": 1,
        "mesh": None if mesh is None else list(mesh),
    })


class CompileLedger:
    """Append-only JSONL writer for one ledger file.

    Best-effort like the Tracer: a full disk must never crash the run
    the ledger observes.  `_seen` tracks keys compiled by THIS process
    (the cold/warm verdict); the file itself accumulates across
    processes."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self._seen: set = set()

    def record(self, key: dict, compile_s: float,
               cold: Optional[bool] = None, ts: Optional[float] = None,
               pid: Optional[int] = None, source: Optional[str] = None,
               fresh_compile_s: Optional[float] = None) -> dict:
        """`source` (persistent-cache round): "fresh" = a real XLA
        compile, "disk" = the persistent program cache served it -
        `compile_s` is then the DESERIALIZE wall and `fresh_compile_s`
        the compile the entry replaced (the measured-savings credit).
        None omits the field - the pre-cache line format, which
        `aggregate` treats as fresh."""
        canon = canonical_key(key)
        with self._lock:
            if cold is None:
                cold = canon not in self._seen
            self._seen.add(canon)
            rec = {
                "type": "compile",
                "ts": round(time.time() if ts is None else ts, 3),
                "pid": os.getpid() if pid is None else int(pid),
                "cold": bool(cold),
                "compile_s": round(float(compile_s), 6),
                "key": normalize_key(key),
            }
            if source is not None:
                rec["source"] = str(source)
            if fresh_compile_s is not None:
                rec["fresh_compile_s"] = round(float(fresh_compile_s), 6)
            # Serving-auth attribution: whatever request context the
            # recording thread bound (today: tenant).  Absent outside
            # the serve path, so CLI ledgers are byte-identical.
            rec.update(request_context())
            try:
                if not self._f.closed:
                    self._f.write(json.dumps(rec) + "\n")
                    self._f.flush()
            except (OSError, ValueError):
                pass
        return rec

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


# ------------------------------------------------- process singleton

_ledger: Optional[CompileLedger] = None
_config_lock = threading.Lock()


def configure(path: str) -> CompileLedger:
    """Bind the process ledger (telemetry.start does this under
    `--telemetry-dir`); replaces a previous one."""
    global _ledger
    with _config_lock:
        if _ledger is not None:
            _ledger.close()
        _ledger = CompileLedger(path)
        return _ledger


def disable() -> None:
    global _ledger
    with _config_lock:
        if _ledger is not None:
            _ledger.close()
        _ledger = None


def get_ledger() -> Optional[CompileLedger]:
    return _ledger


def enabled() -> bool:
    return _ledger is not None


def record_compile(key: dict, compile_s: float, **kw) -> None:
    """Record one compile into the process ledger; a None-check no-op
    (zero file I/O) when no telemetry dir configured one."""
    led = _ledger
    if led is not None:
        led.record(key, compile_s, **kw)


# ------------------------------------------------- report / what-if


def resolve_ledger_path(path: str) -> str:
    """Accept a telemetry DIR (the common case) or the ledger file."""
    if os.path.isdir(path):
        return os.path.join(path, LEDGER_FILENAME)
    return path


def load_ledger(path: str) -> List[dict]:
    """Parse the ledger; malformed lines counted, not fatal (the file
    may be mid-append, and an append-only cross-version file may hold
    records a newer/older wavetpu wrote - a key with fields this
    version does not know, a missing compile_s - which must be skipped,
    never crash the report)."""
    records, bad = [], 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if not (
                isinstance(rec, dict) and rec.get("type") == "compile"
                and isinstance(rec.get("key"), dict)
                and isinstance(rec.get("compile_s"), (int, float))
            ):
                bad += 1
                continue
            try:
                rec["key"] = normalize_key(rec["key"])
            except (ValueError, TypeError):
                bad += 1
                continue
            records.append(rec)
    if bad:
        print(f"note: skipped {bad} malformed ledger line(s)",
              file=sys.stderr)
    return records


def aggregate(records: Sequence[dict]) -> dict:
    """Per-key compile spend, cross-restart recompile detection, and
    the persistent-cache what-if (see module docstring for the saving
    rule).  `what_if.saved_s + what_if.residual_s` equals the total
    recorded compile seconds EXACTLY - the self-validation the tests
    pin.

    Since the persistent-cache round, `source: disk` records (the
    cache actually serving a key; compile_s is the deserialize wall)
    are partitioned OUT of the compile accounting - they are not
    compiles - and reported as `measured_persistent_cache`: measured
    savings next to the simulation.  Old-format lines with no `source`
    are fresh compiles, so pre-cache ledgers aggregate bit-identically
    to before."""
    records = sorted(
        records, key=lambda r: (r.get("ts", 0.0), r.get("pid", 0))
    )
    disk_records = [
        r for r in records if r.get("source") == "disk"
    ]
    records = [r for r in records if r.get("source") != "disk"]
    per: Dict[str, dict] = {}
    pids = set()
    for rec in records:
        canon = canonical_key(rec["key"])
        pids.add(rec.get("pid"))
        row = per.setdefault(canon, {
            "key": normalize_key(rec["key"]),
            "compiles": 0, "cold_compiles": 0,
            "total_s": 0.0, "cold_s": 0.0,
            "pids": [], "first_cold_s": None, "saved_s": 0.0,
        })
        row["compiles"] += 1
        row["total_s"] += rec["compile_s"]
        if rec.get("pid") not in row["pids"]:
            row["pids"].append(rec.get("pid"))
        if rec.get("cold"):
            row["cold_compiles"] += 1
            row["cold_s"] += rec["compile_s"]
            if row["first_cold_s"] is None:
                # The one compile even a persistent cache must pay.
                row["first_cold_s"] = rec["compile_s"]
            else:
                # A cold compile of a key some process already built:
                # a persistent cross-process cache serves it instead,
                # saving exactly the measured seconds.
                row["saved_s"] += rec["compile_s"]
    cross_restart = [
        row for row in per.values() if len(row["pids"]) > 1
    ]
    total_s = sum(r["compile_s"] for r in records)
    saved_s = sum(row["saved_s"] for row in per.values())
    # Residual: first cold compiles (unavoidable) plus in-process warm
    # recompiles (eviction churn a persistent cache would ALSO absorb,
    # but conservatively not credited - they were warm in-process and
    # their cost is jax-cache dependent).
    residual_s = total_s - saved_s
    keys = sorted(per.values(), key=lambda r: -r["total_s"])
    for row in keys:
        row["total_s"] = round(row["total_s"], 6)
        row["cold_s"] = round(row["cold_s"], 6)
        row["saved_s"] = round(row["saved_s"], 6)
    # Measured reconciliation of the what-if: every `source: disk`
    # record is one compile the REAL persistent cache served -
    # compile_s is its deserialize wall, fresh_compile_s the compile it
    # replaced.  Where both exist the measured saving is their
    # difference (floored at 0); hits whose entry predates the
    # fresh_compile_s field are counted unattributed.
    measured_saved = 0.0
    unattributed = 0
    for rec in disk_records:
        fresh = rec.get("fresh_compile_s")
        if isinstance(fresh, (int, float)):
            measured_saved += max(0.0, fresh - rec["compile_s"])
        else:
            unattributed += 1
    return {
        "entries": len(records),
        "distinct_keys": len(per),
        "processes": len(pids),
        "total_compile_s": round(total_s, 6),
        "keys": keys,
        "recompiled_across_restarts": len(cross_restart),
        "what_if_persistent_cache": {
            "saved_s": round(saved_s, 6),
            "residual_s": round(residual_s, 6),
            "served_compiles": sum(
                row["cold_compiles"] - 1
                for row in per.values() if row["cold_compiles"] > 1
            ),
        },
        "measured_persistent_cache": {
            "disk_hits": len(disk_records),
            "load_s": round(
                sum(r["compile_s"] for r in disk_records), 6
            ),
            "measured_saved_s": round(measured_saved, 6),
            "unattributed_hits": unattributed,
        },
    }


def warmup_manifest(records: Sequence[dict]) -> dict:
    """The distinct key set, in the exact shape ROADMAP direction 2's
    `wavetpu warmup --manifest` will consume; every entry round-trips
    through `program_key_from_dict`."""
    seen: Dict[str, dict] = {}
    for rec in records:
        seen.setdefault(canonical_key(rec["key"]),
                        normalize_key(rec["key"]))
    return {
        MANIFEST_FLAG: True,
        "version": 1,
        "generated_unix": round(time.time(), 3),
        "keys": [seen[c] for c in sorted(seen)],
    }


def _key_label(key: dict) -> str:
    mesh = key.get("mesh")
    return (
        f"N={key['N']}/{key['timesteps']} {key['scheme']}:{key['path']}"
        f" k={key['k']} {key['dtype']}"
        + (" field" if key.get("with_field") else "")
        + f" b={key['batch']}"
        + (f" mesh={tuple(mesh)}" if mesh else "")
    )


def format_report(agg: dict) -> str:
    lines = [
        f"compile ledger: {agg['entries']} compiles, "
        f"{agg['distinct_keys']} distinct keys, "
        f"{agg['processes']} process(es), "
        f"{agg['total_compile_s']:.3f}s total compile spend",
        "",
        f"{'program key':<58} {'n':>3} {'cold':>4} {'total_s':>9} "
        f"{'procs':>5}",
    ]
    lines.append("-" * len(lines[-1]))
    for row in agg["keys"]:
        lines.append(
            f"{_key_label(row['key']):<58} {row['compiles']:>3} "
            f"{row['cold_compiles']:>4} {row['total_s']:>9.3f} "
            f"{len(row['pids']):>5}"
        )
    wi = agg["what_if_persistent_cache"]
    lines += [
        "",
        f"recompiled across restarts: "
        f"{agg['recompiled_across_restarts']} key(s)",
        f"what-if persistent AOT cache (ROADMAP direction 2): "
        f"{wi['saved_s']:.3f}s saved over {wi['served_compiles']} "
        f"served compile(s); {wi['residual_s']:.3f}s residual "
        f"(first-compile + in-process churn)",
    ]
    mp = agg.get("measured_persistent_cache") or {}
    if mp.get("disk_hits"):
        # The what-if became a measured fact: print them side by side.
        line = (
            f"measured persistent cache: {mp['disk_hits']} disk "
            f"hit(s) served in {mp['load_s']:.3f}s deserialize, "
            f"{mp['measured_saved_s']:.3f}s compile spend saved "
            f"(measured)"
        )
        if mp.get("unattributed_hits"):
            line += (
                f"; {mp['unattributed_hits']} hit(s) without a "
                f"recorded fresh-compile cost"
            )
        lines.append(line)
    return "\n".join(lines)


_USAGE = (
    "usage: wavetpu ledger-report TELEMETRY_DIR|LEDGER.jsonl "
    "[--json] [--emit-warmup-manifest OUT.json]"
)


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    path = None
    as_json = False
    manifest_out = None
    it = iter(argv)
    try:
        for a in it:
            if a == "--json":
                as_json = True
            elif a == "--emit-warmup-manifest":
                manifest_out = next(it)
            elif a.startswith("--emit-warmup-manifest="):
                manifest_out = a.split("=", 1)[1]
            elif a.startswith("--"):
                raise ValueError(f"unknown flag {a}")
            elif path is None:
                path = a
            else:
                raise ValueError(f"unexpected positional {a!r}")
        if path is None:
            raise ValueError("missing telemetry dir / ledger path")
    except (ValueError, StopIteration) as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2
    ledger_path = resolve_ledger_path(path)
    try:
        records = load_ledger(ledger_path)
    except OSError as e:
        print(f"error: cannot read ledger: {e}", file=sys.stderr)
        return 2
    agg = aggregate(records)
    if as_json:
        print(json.dumps(agg, indent=1, sort_keys=True))
    else:
        print(format_report(agg))
        # Companion pointer (quota cost-model carry-over): when the same
        # telemetry dir also holds an accuracy ledger, `wavetpu
        # plan-report DIR` joins the two into plan_table.json, whose
        # MEASURED wall s/request per plan is the drop-in replacement
        # for the analytic cells pricing fleet/quota.py charges today.
        if os.path.isdir(path):
            from wavetpu.obs import accuracy as _accuracy

            acc = os.path.join(path, _accuracy.ACCURACY_FILENAME)
            if os.path.exists(acc):
                print(
                    f"\naccuracy ledger present ({acc}): run `wavetpu "
                    f"plan-report {path}` for the measured "
                    f"speed-accuracy plan table; its wall s/request "
                    f"replaces the analytic cells pricing in "
                    f"fleet/quota.py"
                )
    if manifest_out is not None:
        manifest = warmup_manifest(records)
        with open(manifest_out, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        print(f"warmup manifest ({len(manifest['keys'])} key(s)): "
              f"{manifest_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
