"""`--telemetry-dir`: tracing + heartbeat snapshots for one run.

One call wires the whole observability surface to a directory a
babysitting operator can tail:

    DIR/trace.jsonl      structured spans/events (obs/tracing.py)
    DIR/heartbeat.jsonl  one registry snapshot per interval, appended -
                         `tail -f` shows counters move while a
                         multi-hour march is mid-chunk
    DIR/metrics.prom     the LATEST Prometheus text exposition,
                         atomically replaced each beat - node-exporter
                         textfile-collector compatible, so even a batch
                         CLI run is scrapable from disk

`start()` returns a `Telemetry` handle; `stop()` writes one final beat
(so short runs always leave a snapshot), joins the heartbeat thread,
and closes the tracer.  The heartbeat thread is a daemon: a crashed run
never hangs on telemetry.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Optional

from wavetpu.obs import accuracy as accuracy_ledger
from wavetpu.obs import ledger as compile_ledger
from wavetpu.obs import tracing
from wavetpu.obs.registry import MetricsRegistry, get_registry

TRACE_FILENAME = "trace.jsonl"
HEARTBEAT_FILENAME = "heartbeat.jsonl"
PROM_FILENAME = "metrics.prom"

# Size cap per telemetry file before rotation (keep-last-ROTATE_KEEP
# segments, atomic os.replace shifts): a long-lived `wavetpu serve`
# under sustained traffic must not append trace.jsonl/heartbeat.jsonl
# forever.  64 MiB x 4 segments bounds the dir at ~512 MiB worst case
# while keeping hours of serve spans at production request rates.
DEFAULT_MAX_BYTES = 64 * 1024 * 1024
ROTATE_KEEP = 4


class Telemetry:
    def __init__(self, directory: str,
                 registry: Optional[MetricsRegistry] = None,
                 interval: float = 10.0,
                 max_bytes: Optional[int] = DEFAULT_MAX_BYTES,
                 keep: int = ROTATE_KEEP):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self.directory = directory
        self.registry = registry if registry is not None else get_registry()
        self.interval = interval
        self.max_bytes = max_bytes
        self.keep = max(1, int(keep))
        os.makedirs(directory, exist_ok=True)
        self.trace_path = os.path.join(directory, TRACE_FILENAME)
        self.heartbeat_path = os.path.join(directory, HEARTBEAT_FILENAME)
        self.prom_path = os.path.join(directory, PROM_FILENAME)
        self.ledger_path = os.path.join(
            directory, compile_ledger.LEDGER_FILENAME
        )
        self.accuracy_path = os.path.join(
            directory, accuracy_ledger.ACCURACY_FILENAME
        )
        tracing.configure(self.trace_path, max_bytes=max_bytes, keep=keep)
        # Compile-cost + accuracy ledgers: append-only and deliberately
        # EXEMPT from the size rotation below - one line per compile /
        # measured solve, and rotating away history would defeat the
        # cross-restart accounting `wavetpu ledger-report` and
        # `wavetpu plan-report` exist for (obs/ledger.py,
        # obs/accuracy.py).
        compile_ledger.configure(self.ledger_path)
        accuracy_ledger.configure(self.accuracy_path)
        self._stop = threading.Event()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, name="wavetpu-heartbeat", daemon=True
        )
        self._thread.start()
        # Safety net for error exits that never reach an explicit
        # stop() (a CLI usage error after telemetry started, an
        # uncaught exception): the final beat still lands.  stop()
        # unregisters it again, so repeated start/stop cycles (tests,
        # bench) do not pin dead Telemetry objects for process life.
        atexit.register(self.stop)

    def beat(self) -> None:
        """Write one heartbeat line + refresh the Prometheus dump.
        The heartbeat file rotates like the trace (size cap, keep-last-K
        atomic segment shift) - a week-long server cannot grow it
        unbounded."""
        snap = {
            "ts": round(time.time(), 3),
            "metrics": self.registry.snapshot(),
        }
        if self.max_bytes is not None:
            try:
                if os.path.getsize(self.heartbeat_path) > self.max_bytes:
                    tracing.rotate_file(self.heartbeat_path, self.keep)
            except OSError:
                pass  # not created yet
        with open(self.heartbeat_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(snap) + "\n")
        tmp = f"{self.prom_path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.registry.render_prometheus())
        os.replace(tmp, self.prom_path)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.beat()
            except OSError:
                # A torn-down telemetry dir must not kill the run the
                # telemetry exists to observe.
                pass

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        atexit.unregister(self.stop)
        self._stop.set()
        self._thread.join(timeout=5.0)
        try:
            self.beat()  # final snapshot: short runs still leave one
        except OSError:
            pass
        # Only tear the tracer down if it is still THIS telemetry's (a
        # later configure() - another Telemetry, a test - owns it now).
        t = tracing.get_tracer()
        if t is not None and t.path == self.trace_path:
            tracing.disable()
        led = compile_ledger.get_ledger()
        if led is not None and led.path == self.ledger_path:
            compile_ledger.disable()
        acc = accuracy_ledger.get_ledger()
        if acc is not None and acc.path == self.accuracy_path:
            accuracy_ledger.disable()


def start(directory: str, registry: Optional[MetricsRegistry] = None,
          interval: float = 10.0,
          max_bytes: Optional[int] = DEFAULT_MAX_BYTES,
          keep: int = ROTATE_KEEP) -> Telemetry:
    return Telemetry(directory, registry=registry, interval=interval,
                     max_bytes=max_bytes, keep=keep)
