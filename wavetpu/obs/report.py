"""`wavetpu trace-report`: summarize and JOIN JSONL span traces.

Reads the trace files `--telemetry-dir` produces (obs/tracing.py
records) and answers the operator questions a raw JSONL tail cannot:

 * WHERE did time go, by span kind - count / total / p50 / p95 per kind,
   sorted by total time, plus event counts;
 * WHERE did ONE request's latency go - `--request ID` prints the
   request's span tree (queue wait vs batch execute vs compile), joining
   the HTTP-thread request span to the scheduler-thread batch span on
   the shared `request_id`/`request_ids` attributes.

It is also the FLEET trace joiner: pass several sources (positional
trace files and/or repeated `--dir DIR`, each DIR meaning
`DIR/trace.jsonl` plus its rotated segments) and the merged record set
is stitched across processes.  Forwarding spans (router.attempt,
serve.request) mint a 16-hex W3C wire id, record it as their `w3c_id`
attr, and send it downstream as the traceparent parent; the joiner
resolves each wire parent_id back to the minting span, so one request's
spans across the client, the router, and N replicas render as ONE tree
- including a long solve preempted on replica A and resumed on B, whose
successor chunk spans share the trace id (and carry `links` back to the
originating request when the resume arrived under a fresh trace).

Pure stdlib + host-side; never imports jax (a babysitting operator runs
this against a live run's telemetry dir without touching the backend).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from wavetpu.obs.telemetry import TRACE_FILENAME

_USAGE = (
    "usage: wavetpu trace-report [TRACE.jsonl ...] [--dir DIR ...] "
    "[--kind KIND] [--request REQUEST_ID]\n"
    "  each --dir DIR reads DIR/trace.jsonl (+ rotated segments); "
    "multiple sources are merged and cross-process joined"
)

_HEX = frozenset("0123456789abcdef")


def trace_segments(path: str) -> List[str]:
    """The rotated segment set for a trace path, OLDEST FIRST: the size
    rotation (obs/tracing.py `rotate_file`) shifts trace.jsonl ->
    trace.jsonl.1 -> .2 ..., so higher suffixes are older and the live
    file is newest.  A never-rotated trace is just [path]."""
    old = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        old.append(f"{path}.{i}")
        i += 1
    return list(reversed(old)) + [path]


def load_trace(path: str, include_rotated: bool = True) -> List[dict]:
    """Parse a JSONL trace; malformed lines are counted, not fatal (the
    file may be mid-write when an operator runs the report).  Rotated
    segments (`path.1`, `path.2`, ...) are read too, oldest first, so a
    long-lived server's report covers the whole retained window."""
    records, bad = [], 0
    segments = trace_segments(path) if include_rotated else [path]
    for seg in segments:
        try:
            f = open(seg, encoding="utf-8")
        except OSError:
            if seg == path:
                raise  # the live file must exist; segments may race GC
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    bad += 1
                    continue
                if isinstance(rec, dict) and "kind" in rec:
                    records.append(rec)
    if bad:
        print(f"note: skipped {bad} malformed line(s)", file=sys.stderr)
    return records


def load_traces(paths: Sequence[str],
                include_rotated: bool = True) -> List[dict]:
    """Merge several trace files (each with its rotated segment set)
    into one record list, sorted by wall-clock start so interleaved
    multi-process output reads chronologically."""
    records: List[dict] = []
    for path in paths:
        records.extend(load_trace(path, include_rotated=include_rotated))
    records.sort(key=lambda r: r.get("t_start", 0.0))
    return records


def _is_wire_id(value) -> bool:
    """A 16-hex W3C wire id (what a traceparent carries).  Internal span
    ids are `{pid:x}-{n}` and always contain a dash, so the two
    namespaces cannot collide."""
    return (
        isinstance(value, str)
        and len(value) == 16
        and all(c in _HEX for c in value)
    )


def join_processes(records: Sequence[dict]) -> List[dict]:
    """Stitch a merged multi-process record set into connected trees.

    A forwarding span mints a wire id, records it as its `w3c_id` attr,
    and sends it downstream as the traceparent parent - so the
    receiving span's `parent_id` is a 16-hex wire id, not an internal
    `{pid:x}-{n}` id.  Rewrite every wire parent_id to the minting
    span's internal id when that span is in the set; wire parents whose
    minting span is NOT here (the upstream hop's dir was not passed)
    become roots (parent_id None) so the tree renders cleanly instead
    of dangling.  Idempotent: rewritten parents are internal ids."""
    wire_to_span: Dict[str, str] = {}
    for r in records:
        w3c = (r.get("attrs") or {}).get("w3c_id")
        if _is_wire_id(w3c):
            wire_to_span[w3c] = r["span_id"]
    out = []
    for r in records:
        parent = r.get("parent_id")
        if _is_wire_id(parent):
            r = dict(r)
            r["parent_id"] = wire_to_span.get(parent)
        out.append(r)
    return out


def percentile_nearest_rank(sorted_vals: Sequence[float],
                            p: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence - the ONE
    percentile definition shared by trace-report and the serve layer's
    /metrics latency fields (scheduler.ServeMetrics), so the two views
    can never disagree on identical data."""
    idx = min(len(sorted_vals) - 1, int(round(p * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summarize(records: Sequence[dict]) -> dict:
    """Per-kind span stats + event counts, machine-readable."""
    spans: Dict[str, List[float]] = {}
    events: Dict[str, int] = {}
    for r in records:
        if r.get("type") == "span":
            spans.setdefault(r["kind"], []).append(float(r.get("dur_s", 0.0)))
        else:
            events[r["kind"]] = events.get(r["kind"], 0) + 1
    kinds = {}
    for kind, durs in spans.items():
        durs.sort()
        kinds[kind] = {
            "count": len(durs),
            "total_s": round(sum(durs), 6),
            "p50_ms": round(percentile_nearest_rank(durs, 0.50) * 1e3, 3),
            "p95_ms": round(percentile_nearest_rank(durs, 0.95) * 1e3, 3),
            "max_ms": round(durs[-1] * 1e3, 3),
        }
    return {"spans": kinds, "events": events,
            "n_records": len(records)}


def format_summary(summary: dict) -> str:
    lines = []
    header = (
        f"{'span kind':<34} {'count':>6} {'total_s':>9} "
        f"{'p50_ms':>9} {'p95_ms':>9} {'max_ms':>9}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    by_total = sorted(
        summary["spans"].items(), key=lambda kv: -kv[1]["total_s"]
    )
    for kind, st in by_total:
        lines.append(
            f"{kind:<34} {st['count']:>6} {st['total_s']:>9.3f} "
            f"{st['p50_ms']:>9.2f} {st['p95_ms']:>9.2f} "
            f"{st['max_ms']:>9.2f}"
        )
    if summary["events"]:
        lines.append("")
        lines.append(f"{'event kind':<34} {'count':>6}")
        for kind, n in sorted(summary["events"].items()):
            lines.append(f"{kind:<34} {n:>6}")
    lines.append("")
    lines.append(f"{summary['n_records']} records")
    return "\n".join(lines)


def _touches_request(rec: dict, request_id: str) -> bool:
    attrs = rec.get("attrs") or {}
    if attrs.get("request_id") == request_id:
        return True
    ids = attrs.get("request_ids")
    return isinstance(ids, (list, tuple)) and request_id in ids


def request_view(records: Sequence[dict], request_id: str) -> List[dict]:
    """Every span/event that belongs to one request's story: records
    tagged with the request id (HTTP request span, the batch that
    carried it) plus their tree descendants (execute / compile /
    watchdog sub-spans) - AND, across processes, everything sharing the
    request's trace id(s), following `links` both ways so a preempted
    solve resumed under a fresh client trace still joins (successor
    chunk spans link back to the originating request; the closure runs
    to fixpoint in either direction).  Start-time order."""
    records = join_processes(records)
    roots = [r for r in records if _touches_request(r, request_id)]
    keep = {r["span_id"] for r in roots}
    # Trace-id closure: the request's trace ids, expanded through
    # cross-trace links until stable, then every record on any of them.
    tids = {r["trace_id"] for r in roots if r.get("trace_id")}
    if tids:
        changed = True
        while changed:
            changed = False
            for r in records:
                linked = {
                    ln.get("trace_id") for ln in (r.get("links") or ())
                    if ln.get("trace_id")
                }
                if not linked:
                    continue
                mine = r.get("trace_id")
                if mine in tids and not linked <= tids:
                    tids |= linked
                    changed = True
                elif mine and mine not in tids and linked & tids:
                    tids.add(mine)
                    changed = True
        for r in records:
            if r.get("trace_id") in tids:
                keep.add(r["span_id"])
    # Pull in descendants of any kept span (child spans carry no
    # request tag of their own): one parent->children index + BFS, so a
    # long-lived server's hundred-thousand-record trace stays O(n).
    children: Dict[str, List[str]] = {}
    for r in records:
        parent = r.get("parent_id")
        if parent is not None:
            children.setdefault(parent, []).append(r["span_id"])
    frontier = list(keep)
    while frontier:
        sid = frontier.pop()
        for child in children.get(sid, ()):
            if child not in keep:
                keep.add(child)
                frontier.append(child)
    out = [r for r in records if r["span_id"] in keep]
    out.sort(key=lambda r: r.get("t_start", 0.0))
    return out


def _pid_of(span_id) -> Optional[str]:
    """The `{pid:x}` prefix of an internal span id (None for wire ids
    or missing)."""
    if isinstance(span_id, str) and "-" in span_id:
        return span_id.split("-", 1)[0]
    return None


def format_request_view(records: Sequence[dict], request_id: str) -> str:
    if not records:
        return f"no records for request {request_id}"
    t0 = records[0].get("t_start", 0.0)
    by_id = {r["span_id"]: r for r in records}
    n_procs = len({_pid_of(r["span_id"]) for r in records} - {None})
    depth = {None: -1}
    lines = [
        f"critical path of request {request_id}"
        + (f" (joined across {n_procs} processes)" if n_procs > 1 else "")
        + ":"
    ]
    for r in records:
        parent = r.get("parent_id")
        d = depth.get(parent, 0) + 1
        depth[r["span_id"]] = d
        rel = (r.get("t_start", t0) - t0) * 1e3
        dur = r.get("dur_s")
        dur_txt = (
            f"{dur * 1e3:9.2f}ms" if dur is not None else "    event"
        )
        attrs = r.get("attrs") or {}
        attr_txt = " ".join(
            f"{k}={v}" for k, v in sorted(attrs.items())
            if k not in ("request_ids",) and not isinstance(v, (list, dict))
        )
        # A parent in ANOTHER process means this span starts a network
        # hop: the start-to-start gap is wire + downstream queue time
        # (wall clocks, so cross-host skew shows up here too).
        hop_txt = ""
        p = by_id.get(parent)
        if p is not None and _pid_of(parent) != _pid_of(r["span_id"]):
            gap = (r.get("t_start", t0) - p.get("t_start", t0)) * 1e3
            hop_txt = f"  <-hop {gap:+.2f}ms"
        link_txt = ""
        if r.get("links"):
            link_txt = "  ~>resumed-from " + ",".join(
                str(ln.get("span_id") or ln.get("trace_id") or "?")
                for ln in r["links"]
            )
        lines.append(
            f"  +{rel:9.2f}ms {dur_txt}  {'  ' * d}{r['kind']}"
            + (f"  [{attr_txt}]" if attr_txt else "")
            + hop_txt + link_txt
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    paths: List[str] = []
    kind = None
    request = None
    it = iter(argv)
    try:
        for a in it:
            if a == "--kind":
                kind = next(it)
            elif a == "--request":
                request = next(it)
            elif a == "--dir":
                paths.append(os.path.join(next(it), TRACE_FILENAME))
            elif a.startswith("--"):
                raise ValueError(f"unknown flag {a}")
            else:
                paths.append(a)
        if not paths:
            raise ValueError(
                "no trace source (pass TRACE.jsonl paths and/or "
                "--dir DIR)"
            )
    except (ValueError, StopIteration) as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2
    try:
        records = load_traces(paths)
    except OSError as e:
        print(f"error: cannot read trace: {e}", file=sys.stderr)
        return 2
    if kind is not None:
        records = [r for r in records if r["kind"] == kind]
    if request is not None:
        print(format_request_view(request_view(records, request), request))
        return 0
    print(format_summary(summarize(records)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
