"""`wavetpu trace-report`: summarize a JSONL span trace.

Reads the trace file `--telemetry-dir` produces (obs/tracing.py records)
and answers the two operator questions a raw JSONL tail cannot:

 * WHERE did time go, by span kind - count / total / p50 / p95 per kind,
   sorted by total time, plus event counts;
 * WHERE did ONE request's latency go - `--request ID` prints the
   request's span tree (queue wait vs batch execute vs compile), joining
   the HTTP-thread request span to the scheduler-thread batch span on
   the shared `request_id`/`request_ids` attributes.

Pure stdlib + host-side; never imports jax (a babysitting operator runs
this against a live run's telemetry dir without touching the backend).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Sequence

_USAGE = (
    "usage: wavetpu trace-report TRACE.jsonl [--kind KIND] "
    "[--request REQUEST_ID]"
)


def trace_segments(path: str) -> List[str]:
    """The rotated segment set for a trace path, OLDEST FIRST: the size
    rotation (obs/tracing.py `rotate_file`) shifts trace.jsonl ->
    trace.jsonl.1 -> .2 ..., so higher suffixes are older and the live
    file is newest.  A never-rotated trace is just [path]."""
    old = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        old.append(f"{path}.{i}")
        i += 1
    return list(reversed(old)) + [path]


def load_trace(path: str, include_rotated: bool = True) -> List[dict]:
    """Parse a JSONL trace; malformed lines are counted, not fatal (the
    file may be mid-write when an operator runs the report).  Rotated
    segments (`path.1`, `path.2`, ...) are read too, oldest first, so a
    long-lived server's report covers the whole retained window."""
    records, bad = [], 0
    segments = trace_segments(path) if include_rotated else [path]
    for seg in segments:
        try:
            f = open(seg, encoding="utf-8")
        except OSError:
            if seg == path:
                raise  # the live file must exist; segments may race GC
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    bad += 1
                    continue
                if isinstance(rec, dict) and "kind" in rec:
                    records.append(rec)
    if bad:
        print(f"note: skipped {bad} malformed line(s)", file=sys.stderr)
    return records


def percentile_nearest_rank(sorted_vals: Sequence[float],
                            p: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence - the ONE
    percentile definition shared by trace-report and the serve layer's
    /metrics latency fields (scheduler.ServeMetrics), so the two views
    can never disagree on identical data."""
    idx = min(len(sorted_vals) - 1, int(round(p * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summarize(records: Sequence[dict]) -> dict:
    """Per-kind span stats + event counts, machine-readable."""
    spans: Dict[str, List[float]] = {}
    events: Dict[str, int] = {}
    for r in records:
        if r.get("type") == "span":
            spans.setdefault(r["kind"], []).append(float(r.get("dur_s", 0.0)))
        else:
            events[r["kind"]] = events.get(r["kind"], 0) + 1
    kinds = {}
    for kind, durs in spans.items():
        durs.sort()
        kinds[kind] = {
            "count": len(durs),
            "total_s": round(sum(durs), 6),
            "p50_ms": round(percentile_nearest_rank(durs, 0.50) * 1e3, 3),
            "p95_ms": round(percentile_nearest_rank(durs, 0.95) * 1e3, 3),
            "max_ms": round(durs[-1] * 1e3, 3),
        }
    return {"spans": kinds, "events": events,
            "n_records": len(records)}


def format_summary(summary: dict) -> str:
    lines = []
    header = (
        f"{'span kind':<34} {'count':>6} {'total_s':>9} "
        f"{'p50_ms':>9} {'p95_ms':>9} {'max_ms':>9}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    by_total = sorted(
        summary["spans"].items(), key=lambda kv: -kv[1]["total_s"]
    )
    for kind, st in by_total:
        lines.append(
            f"{kind:<34} {st['count']:>6} {st['total_s']:>9.3f} "
            f"{st['p50_ms']:>9.2f} {st['p95_ms']:>9.2f} "
            f"{st['max_ms']:>9.2f}"
        )
    if summary["events"]:
        lines.append("")
        lines.append(f"{'event kind':<34} {'count':>6}")
        for kind, n in sorted(summary["events"].items()):
            lines.append(f"{kind:<34} {n:>6}")
    lines.append("")
    lines.append(f"{summary['n_records']} records")
    return "\n".join(lines)


def _touches_request(rec: dict, request_id: str) -> bool:
    attrs = rec.get("attrs") or {}
    if attrs.get("request_id") == request_id:
        return True
    ids = attrs.get("request_ids")
    return isinstance(ids, (list, tuple)) and request_id in ids


def request_view(records: Sequence[dict], request_id: str) -> List[dict]:
    """Every span/event that belongs to one request's critical path:
    records tagged with the request id (HTTP request span, the batch
    that carried it) plus their tree descendants (execute / compile /
    watchdog sub-spans), in start-time order."""
    roots = [r for r in records if _touches_request(r, request_id)]
    keep = {r["span_id"] for r in roots}
    # Pull in descendants of any kept span (child spans carry no
    # request tag of their own): one parent->children index + BFS, so a
    # long-lived server's hundred-thousand-record trace stays O(n).
    children: Dict[str, List[str]] = {}
    for r in records:
        parent = r.get("parent_id")
        if parent is not None:
            children.setdefault(parent, []).append(r["span_id"])
    frontier = list(keep)
    while frontier:
        sid = frontier.pop()
        for child in children.get(sid, ()):
            if child not in keep:
                keep.add(child)
                frontier.append(child)
    out = [r for r in records if r["span_id"] in keep]
    out.sort(key=lambda r: r.get("t_start", 0.0))
    return out


def format_request_view(records: Sequence[dict], request_id: str) -> str:
    if not records:
        return f"no records for request {request_id}"
    t0 = records[0].get("t_start", 0.0)
    depth = {None: -1}
    lines = [f"critical path of request {request_id}:"]
    for r in records:
        d = depth.get(r.get("parent_id"), 0) + 1
        depth[r["span_id"]] = d
        rel = (r.get("t_start", t0) - t0) * 1e3
        dur = r.get("dur_s")
        dur_txt = (
            f"{dur * 1e3:9.2f}ms" if dur is not None else "    event"
        )
        attrs = r.get("attrs") or {}
        attr_txt = " ".join(
            f"{k}={v}" for k, v in sorted(attrs.items())
            if k not in ("request_ids",) and not isinstance(v, (list, dict))
        )
        lines.append(
            f"  +{rel:9.2f}ms {dur_txt}  {'  ' * d}{r['kind']}"
            + (f"  [{attr_txt}]" if attr_txt else "")
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    path = None
    kind = None
    request = None
    it = iter(argv)
    try:
        for a in it:
            if a == "--kind":
                kind = next(it)
            elif a == "--request":
                request = next(it)
            elif a.startswith("--"):
                raise ValueError(f"unknown flag {a}")
            elif path is None:
                path = a
            else:
                raise ValueError(f"unexpected positional {a!r}")
        if path is None:
            raise ValueError("missing TRACE.jsonl path")
    except (ValueError, StopIteration) as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2
    try:
        records = load_trace(path)
    except OSError as e:
        print(f"error: cannot read trace: {e}", file=sys.stderr)
        return 2
    if kind is not None:
        records = [r for r in records if r["kind"] == kind]
    if request is not None:
        print(format_request_view(request_view(records, request), request))
        return 0
    print(format_summary(summarize(records)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
