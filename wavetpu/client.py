"""`WavetpuClient` - the retrying HTTP client for `wavetpu serve`.

The server side of the resilience contract (serve/api.py) promises
typed, retriable failures: 429 + Retry-After under backpressure, 503 +
Retry-After for a draining replica / a circuit-broken program / a
crashed-and-restarted scheduler worker, 504 for an expired deadline.
This client is the matching half:

 * **Jittered exponential backoff** on retriable outcomes (transport
   errors, 429, 500, 503), HONORING a `Retry-After` header when the
   server sends one - the server knows its cooldown better than any
   client-side curve.
 * **Per-request deadlines**: `deadline_s` is one budget across ALL
   attempts; each attempt forwards the remaining budget as
   `deadline_ms` so the server sheds work this client has already given
   up on, and retrying stops the moment the budget is gone.
 * **Request-id reuse**: every attempt of one logical request carries
   the SAME `X-Request-Id`, so `wavetpu trace-report --request ID`
   against the server's telemetry shows the whole retry chain as one
   story, not N unrelated requests.

`solve()` returns a `SolveOutcome` (it does not raise on HTTP errors -
the status/error fields are the result; a load generator must count
failures, not crash on them).  Pure stdlib, never imports jax - safe
for load-generation hosts with no accelerator stack (same discipline as
loadgen/runner.py, which adopts this client behind `--retries`).

    from wavetpu.client import WavetpuClient

    client = WavetpuClient("http://localhost:8077", retries=3,
                           deadline_s=30.0)
    out = client.solve({"N": 64, "timesteps": 100})
    if out.ok:
        print(out.payload["report"]["gcells_per_second"])
    else:
        print(out.status, out.error, f"after {out.attempts} attempts")
"""

from __future__ import annotations

import dataclasses
import json
import random
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

# Outcomes worth a retry: transport failure (status 0), backpressure
# (429), engine failure (500 - the batch died, a retry lands in a fresh
# batch), and retriable unavailability (503: draining, quarantined
# program, restarted worker).  400/404/413/422 are THIS request's fault
# and retrying cannot fix them; 504 means the deadline is already gone.
RETRIABLE_STATUSES = frozenset((0, 429, 500, 503))


@dataclasses.dataclass
class SolveOutcome:
    """One logical request's final result plus its retry history."""

    status: int                    # final HTTP status; 0 = transport
    payload: Optional[dict]        # parsed JSON body (None unparsable)
    headers: Dict[str, str]        # final attempt's response headers
    attempts: int                  # total attempts made (>= 1)
    retries: List[dict]            # per-retry {status, delay_s, error}
    latency_s: float               # wall across ALL attempts + backoff
    request_id: str                # the id EVERY attempt carried
    error: Optional[str] = None    # final error string (None on 200)

    @property
    def ok(self) -> bool:
        return self.status == 200

    @property
    def server_timing(self) -> Optional[str]:
        return self.headers.get("Server-Timing")


def parse_retry_after(headers: Dict[str, str]) -> Optional[float]:
    """Seconds from a `Retry-After` header (delta-seconds form only -
    the server emits integers; HTTP-date is a proxy exotic we skip).
    None when absent or unparseable."""
    raw = headers.get("Retry-After")
    if raw is None:
        return None
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return None


class WavetpuClient:
    """Thread-safe-enough stdlib client (urllib per call, a lock-free
    counter for minted ids is the only shared state - worst case two
    threads mint the same id, which only merges two trace views).

    `retries` is the RETRY budget (total attempts = retries + 1);
    `deadline_s` the default per-request budget (None = unbounded);
    `backoff_base_s`/`backoff_max_s` shape the jittered exponential
    curve `min(max, base * 2^attempt) * uniform(0.5, 1.0)`.  `rng` and
    `sleep` are injectable for deterministic tests."""

    def __init__(
        self,
        base_url: str,
        retries: int = 2,
        timeout: float = 120.0,
        deadline_s: Optional[float] = None,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s}"
            )
        self.base_url = base_url.rstrip("/")
        self.retries = retries
        self.timeout = timeout
        self.deadline_s = deadline_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        self._n = 0
        self._tag = f"{int(time.time() * 1e3) & 0xFFFFFFFF:x}"

    def _mint(self) -> str:
        self._n += 1
        return f"cl-{self._tag}-{self._n}"

    # ---- transport ----

    def _attempt(self, body: dict, rid: str, timeout: float):
        """One POST /solve: (status, payload, headers, error)."""
        req = urllib.request.Request(
            self.base_url + "/solve", data=json.dumps(body).encode(),
            headers={
                "Content-Type": "application/json",
                "X-Request-Id": rid,
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                raw = r.read()
                status, headers = r.status, dict(r.headers)
        except urllib.error.HTTPError as e:
            raw = e.read()
            status, headers = e.code, dict(e.headers)
        except (OSError, urllib.error.URLError) as e:
            return 0, None, {}, str(e)
        try:
            payload = json.loads(raw or b"{}")
        except (ValueError, TypeError):
            payload = None
        error = None
        if status != 200:
            error = (payload or {}).get("error") or f"HTTP {status}"
        return status, payload, headers, error

    def healthz(self, timeout: float = 10.0) -> dict:
        with urllib.request.urlopen(
            self.base_url + "/healthz", timeout=timeout
        ) as r:
            return json.loads(r.read())

    # ---- the retry loop ----

    def solve(
        self,
        body: dict,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        retries: Optional[int] = None,
        timeout: Optional[float] = None,
    ) -> SolveOutcome:
        """POST /solve with retry/backoff/deadline per the class doc.
        The per-call kwargs override the client defaults; `request_id`
        (else a minted `cl-*` id) rides EVERY attempt."""
        retries = self.retries if retries is None else retries
        deadline_s = (
            self.deadline_s if deadline_s is None else deadline_s
        )
        timeout = self.timeout if timeout is None else timeout
        rid = request_id or self._mint()
        t0 = time.monotonic()
        deadline = None if deadline_s is None else t0 + deadline_s
        retried: List[dict] = []
        attempt = 0
        status, payload, headers, error = 0, None, {}, "not attempted"
        while True:
            remaining = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                error = (
                    f"client deadline {deadline_s:g}s exhausted after "
                    f"{attempt} attempt(s); last: {error}"
                )
                break
            send_body = body
            if remaining is not None and "deadline_ms" not in body:
                # Forward the REMAINING budget so the server sheds work
                # this client will no longer read.
                send_body = dict(
                    body, deadline_ms=round(remaining * 1e3, 3)
                )
            att_timeout = (
                timeout if remaining is None
                else min(timeout, remaining + 0.25)
            )
            attempt += 1
            status, payload, headers, error = self._attempt(
                send_body, rid, att_timeout
            )
            if (
                status == 200
                or status not in RETRIABLE_STATUSES
                or attempt > retries
            ):
                break
            delay = parse_retry_after(headers)
            if delay is None:
                delay = min(
                    self.backoff_max_s,
                    self.backoff_base_s * (2 ** (attempt - 1)),
                ) * (0.5 + 0.5 * self._rng.random())
            if deadline is not None:
                budget = deadline - time.monotonic()
                if delay >= budget:
                    error = (
                        f"client deadline {deadline_s:g}s would expire "
                        f"during backoff ({delay:.3f}s) after {attempt} "
                        f"attempt(s); last: {error}"
                    )
                    break
            retried.append({
                "status": status,
                "delay_s": round(delay, 4),
                "error": error,
            })
            self._sleep(delay)
        return SolveOutcome(
            status=status, payload=payload, headers=headers,
            attempts=attempt, retries=retried,
            latency_s=time.monotonic() - t0, request_id=rid,
            error=error if status != 200 else None,
        )
