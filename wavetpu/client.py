"""`WavetpuClient` - the retrying HTTP client for `wavetpu serve`.

The server side of the resilience contract (serve/api.py) promises
typed, retriable failures: 429 + Retry-After under backpressure, 503 +
Retry-After for a draining replica / a circuit-broken program / a
crashed-and-restarted scheduler worker, 504 for an expired deadline.
This client is the matching half:

 * **Jittered exponential backoff** on retriable outcomes (transport
   errors, 429, 500, 503), HONORING a `Retry-After` header when the
   server sends one - the server knows its cooldown better than any
   client-side curve.
 * **Per-request deadlines**: `deadline_s` is one budget across ALL
   attempts; each attempt forwards the remaining budget as
   `deadline_ms` so the server sheds work this client has already given
   up on, and retrying stops the moment the budget is gone.
 * **Request-id reuse**: every attempt of one logical request carries
   the SAME `X-Request-Id`, so `wavetpu trace-report --request ID`
   against the server's telemetry shows the whole retry chain as one
   story, not N unrelated requests.
 * **Distributed trace context**: every attempt also carries the SAME
   W3C `traceparent` (one trace id minted per logical request), so the
   router's and every replica's spans for all attempts hang under ONE
   fleet-wide trace (docs/observability.md "Distributed tracing").  The
   server echoes the trace context back; `SolveOutcome.traceparent` is
   the join handle `wavetpu trace-report` resolves.
 * **Transparent resume**: a 503/504 carrying `resume_token` (a
   preempted chunked long solve - docs/robustness.md) has the token
   re-presented on every later attempt, so the retry continues the
   march from the last completed chunk instead of restarting; a
   504-with-token is even retried (while budget remains) because each
   attempt makes forward progress.
 * **Multi-endpoint failover**: `base_url` may be a LIST of router
   URLs (an HA pair/fleet - docs/fleet.md "Control plane & router
   HA").  A transport failure or a standby-503 (`"standby": true`,
   the not-the-lease-holder answer) ROTATES the client to the next
   endpoint for the retry - counted as `endpoint_failovers` - instead
   of backing off against a dead or deferring router.  The retry
   budget, deadline, request-id, resume-token, and traceparent
   semantics are unchanged: a failover retry is just a retry that
   lands somewhere more useful.

`solve()` returns a `SolveOutcome` (it does not raise on HTTP errors -
the status/error fields are the result; a load generator must count
failures, not crash on them).  Pure stdlib, never imports jax - safe
for load-generation hosts with no accelerator stack (same discipline as
loadgen/runner.py, which adopts this client behind `--retries`).

    from wavetpu.client import WavetpuClient

    client = WavetpuClient("http://localhost:8077", retries=3,
                           deadline_s=30.0)
    out = client.solve({"N": 64, "timesteps": 100})
    if out.ok:
        print(out.payload["report"]["gcells_per_second"])
    else:
        print(out.status, out.error, f"after {out.attempts} attempts")
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import random
import threading
import time
import urllib.parse
from typing import Callable, Dict, List, Optional, Sequence, Tuple, \
    Union

from wavetpu.obs.tracing import format_traceparent, mint_span_id, \
    mint_trace_id

# Outcomes worth a retry: transport failure (status 0), backpressure
# (429), engine failure (500 - the batch died, a retry lands in a fresh
# batch), and retriable unavailability (503: draining, quarantined
# program, restarted worker).  400/404/413/422 are THIS request's fault
# and retrying cannot fix them; 504 means the deadline is already gone.
RETRIABLE_STATUSES = frozenset((0, 429, 500, 503))


@dataclasses.dataclass
class SolveOutcome:
    """One logical request's final result plus its retry history."""

    status: int                    # final HTTP status; 0 = transport
    payload: Optional[dict]        # parsed JSON body (None unparsable)
    headers: Dict[str, str]        # final attempt's response headers
    attempts: int                  # total attempts made (>= 1)
    retries: List[dict]            # per-retry {status, delay_s, error}
    latency_s: float               # wall across ALL attempts + backoff
    request_id: str                # the id EVERY attempt carried
    error: Optional[str] = None    # final error string (None on 200)
    traceparent: str = ""          # W3C context EVERY attempt carried

    @property
    def ok(self) -> bool:
        return self.status == 200

    @property
    def server_timing(self) -> Optional[str]:
        return self.headers.get("Server-Timing")

    @property
    def trace_id(self) -> Optional[str]:
        """The 32-hex fleet trace id this request rode (None if the
        client somehow sent no context)."""
        parts = self.traceparent.split("-")
        return parts[1] if len(parts) == 4 else None


def parse_retry_after(headers: Dict[str, str]) -> Optional[float]:
    """Seconds from a `Retry-After` header (delta-seconds form only -
    the server emits integers; HTTP-date is a proxy exotic we skip).
    None when absent or unparseable."""
    raw = headers.get("Retry-After")
    if raw is None:
        return None
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return None


class WavetpuClient:
    """Thread-safe stdlib client with KEEP-ALIVE: one persistent
    `http.client.HTTPConnection` per calling thread (threading.local),
    reused across requests - the serve handler speaks HTTP/1.1, so the
    per-request TCP handshake the old urllib transport paid (and the
    fleet router tier would have amplified 2x) is gone.  Any transport
    error closes and resets that thread's connection, so the NEXT
    attempt reconnects fresh - a stale kept-alive socket (server
    drained, restarted, or chaos-dropped between requests) costs one
    retriable status-0 attempt, never a wedged client.  A response
    carrying `Connection: close` (drain 503, 413) retires the socket
    in an orderly way (not counted as a reset).

    `retries` is the RETRY budget (total attempts = retries + 1);
    `deadline_s` the default per-request budget (None = unbounded);
    `backoff_base_s`/`backoff_max_s` shape the jittered exponential
    curve `min(max, base * 2^attempt) * uniform(0.5, 1.0)`.  `rng` and
    `sleep` are injectable for deterministic tests.

    Connection accounting (for tests and the loadgen report):
    `connections_opened` / `requests_on_reused_connection` /
    `connection_resets` under one stats lock."""

    def __init__(
        self,
        base_url: Union[str, Sequence[str]],
        retries: int = 2,
        timeout: float = 120.0,
        deadline_s: Optional[float] = None,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        headers: Optional[Dict[str, str]] = None,
    ):
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s}"
            )
        # One endpoint is the historical single-server client; several
        # are an HA router set the client fails over across.  All
        # threads share ONE current-endpoint cursor: once one thread
        # discovers an endpoint is dead/standby, nobody else should
        # have to rediscover it.
        urls = [base_url] if isinstance(base_url, str) else list(base_url)
        if not urls:
            raise ValueError("base_url needs at least one endpoint")
        self.endpoints: List[str] = []
        self._parsed: List[Tuple[str, int, str]] = []
        for u in urls:
            u = str(u).rstrip("/")
            parts = urllib.parse.urlsplit(u)
            if parts.scheme != "http" or not parts.hostname:
                raise ValueError(
                    f"base_url must be http://host[:port], got {u!r}"
                )
            self.endpoints.append(u)
            self._parsed.append(
                (parts.hostname, parts.port or 80,
                 parts.path.rstrip("/"))
            )
        self._cur = 0
        self.endpoint_failovers = 0
        self.retries = retries
        self.timeout = timeout
        self.deadline_s = deadline_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        # Extra request headers on EVERY /solve attempt - how a caller
        # authenticates (X-Api-Key / Authorization) and declares its
        # priority class (X-Priority) against a QoS-enabled router.
        self.headers: Dict[str, str] = dict(headers or {})
        self._n = 0
        self._tag = f"{int(time.time() * 1e3) & 0xFFFFFFFF:x}"
        self._local = threading.local()
        self._stats_lock = threading.Lock()
        self.connections_opened = 0
        self.requests_on_reused_connection = 0
        self.connection_resets = 0

    def _mint(self) -> str:
        self._n += 1
        return f"cl-{self._tag}-{self._n}"

    @property
    def base_url(self) -> str:
        """The endpoint requests currently target (the only endpoint
        for a single-URL client) - kept as an attribute-shaped property
        so existing callers and reports read the live value."""
        return self.endpoints[self._cur]

    def _rotate(self, from_idx: int) -> None:
        """Advance the shared endpoint cursor past `from_idx` - the
        endpoint that just failed.  A no-op if another thread already
        moved it (their failover counts once, ours doesn't double) or
        if there is nowhere else to go."""
        if len(self.endpoints) < 2:
            return
        with self._stats_lock:
            if self._cur != from_idx:
                return
            self._cur = (from_idx + 1) % len(self.endpoints)
            self.endpoint_failovers += 1

    # ---- transport (keep-alive) ----

    def _conn(self, idx: int, timeout: float
              ) -> Tuple[http.client.HTTPConnection, bool]:
        """This thread's persistent connection TO ENDPOINT `idx`
        (created on first use), with the socket timeout refreshed for
        this request.  Returns (conn, reused) - reused=True when the
        socket is already up."""
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = {}
            self._local.conns = conns
        conn = conns.get(idx)
        if conn is None:
            host, port, _prefix = self._parsed[idx]
            conn = http.client.HTTPConnection(host, port,
                                              timeout=timeout)
            conns[idx] = conn
            with self._stats_lock:
                self.connections_opened += 1
        reused = conn.sock is not None
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        return conn, reused

    def _reset_conn(self, idx: int, orderly: bool = False) -> None:
        """Close and forget this thread's connection to endpoint `idx`
        (next request there reconnects).  `orderly` = the server
        announced `Connection: close`; anything else counts as a
        reset."""
        conns = getattr(self._local, "conns", None)
        conn = conns.get(idx) if conns else None
        if conn is None:
            return
        try:
            conn.close()
        except Exception:
            pass
        conns.pop(idx, None)
        if not orderly:
            with self._stats_lock:
                self.connection_resets += 1

    def close(self) -> None:
        """Retire the CALLING thread's persistent connections (other
        threads' sockets close when their conns are garbage-collected)."""
        conns = getattr(self._local, "conns", None)
        for idx in list(conns) if conns else ():
            self._reset_conn(idx, orderly=True)

    def _request(self, method: str, path: str, data: Optional[bytes],
                 headers: Dict[str, str], timeout: float,
                 idx: Optional[int] = None
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        """One HTTP exchange on the thread's kept-alive connection to
        endpoint `idx` (default: the current endpoint).  Raises
        OSError/http.client errors on transport failure (after
        resetting that connection so the next attempt reconnects)."""
        if idx is None:
            idx = self._cur
        conn, reused = self._conn(idx, timeout)
        prefix = self._parsed[idx][2]
        try:
            conn.request(method, prefix + path, body=data,
                         headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
        except Exception:
            self._reset_conn(idx)
            raise
        if reused:
            with self._stats_lock:
                self.requests_on_reused_connection += 1
        if resp.will_close:
            self._reset_conn(idx, orderly=True)
        return resp.status, raw, dict(resp.headers)

    def _attempt(self, body: dict, rid: str, timeout: float,
                 traceparent: str = "",
                 extra_headers: Optional[Dict[str, str]] = None,
                 idx: Optional[int] = None):
        """One POST /solve: (status, payload, headers, error)."""
        headers = dict(self.headers)
        if extra_headers:
            headers.update(extra_headers)
        headers["Content-Type"] = "application/json"
        headers["X-Request-Id"] = rid
        if traceparent:
            headers["traceparent"] = traceparent
        try:
            status, raw, headers = self._request(
                "POST", "/solve", json.dumps(body).encode(), headers,
                timeout, idx=idx,
            )
        except (OSError, http.client.HTTPException) as e:
            return 0, None, {}, f"{type(e).__name__}: {e}" if str(e) \
                else type(e).__name__
        try:
            payload = json.loads(raw or b"{}")
        except (ValueError, TypeError):
            payload = None
        error = None
        if status != 200:
            error = (payload or {}).get("error") or f"HTTP {status}"
        return status, payload, headers, error

    def healthz(self, timeout: float = 10.0) -> dict:
        status, raw, _headers = self._request("GET", "/healthz", None,
                                              {}, timeout)
        return json.loads(raw)

    # ---- the retry loop ----

    def solve(
        self,
        body: dict,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        retries: Optional[int] = None,
        timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> SolveOutcome:
        """POST /solve with retry/backoff/deadline per the class doc.
        The per-call kwargs override the client defaults; `request_id`
        (else a minted `cl-*` id) rides EVERY attempt.  `headers`
        merge OVER the client-level extra headers per attempt (e.g. a
        per-request X-Priority on a shared authenticated client)."""
        retries = self.retries if retries is None else retries
        deadline_s = (
            self.deadline_s if deadline_s is None else deadline_s
        )
        timeout = self.timeout if timeout is None else timeout
        # `headers` is reused below for RESPONSE headers; keep the
        # caller's request extras under their own name.
        per_call_headers = headers
        rid = request_id or self._mint()
        # One trace id for the whole logical request: every attempt
        # (and thus every router hop and replica it lands on) carries
        # the SAME traceparent, so retries are one fleet trace.
        traceparent = format_traceparent(mint_trace_id(), mint_span_id())
        t0 = time.monotonic()
        deadline = None if deadline_s is None else t0 + deadline_s
        retried: List[dict] = []
        attempt = 0
        status, payload, headers, error = 0, None, {}, "not attempted"
        while True:
            remaining = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                error = (
                    f"client deadline {deadline_s:g}s exhausted after "
                    f"{attempt} attempt(s); last: {error}"
                )
                break
            send_body = body
            if remaining is not None and "deadline_ms" not in body:
                # Forward the REMAINING budget so the server sheds work
                # this client will no longer read.
                send_body = dict(
                    body, deadline_ms=round(remaining * 1e3, 3)
                )
            att_timeout = (
                timeout if remaining is None
                else min(timeout, remaining + 0.25)
            )
            attempt += 1
            endpoint_idx = self._cur
            status, payload, headers, error = self._attempt(
                send_body, rid, att_timeout, traceparent,
                extra_headers=per_call_headers, idx=endpoint_idx,
            )
            # Transparent resume (preemptible long solves): a 503 from
            # a draining replica - or a 504 whose budget died mid-march
            # - may carry `resume_token`, the server-side checkpoint of
            # the chunks already marched.  Re-present it on every later
            # attempt so the retry CONTINUES the solve instead of
            # restarting at layer 0 (on a fleet, possibly on a
            # different replica sharing --solve-state-dir).
            token = (
                payload.get("resume_token")
                if isinstance(payload, dict) else None
            )
            if isinstance(token, str) and token:
                body = dict(body, resume_token=token)
            retriable = status in RETRIABLE_STATUSES or (
                # 504 is normally final (the budget is gone), but with
                # a token each retry makes PROGRESS - worth it while
                # client budget remains.
                status == 504 and bool(token)
                and (deadline is None
                     or deadline - time.monotonic() > 0)
            )
            if status == 200 or not retriable or attempt > retries:
                break
            # Multi-endpoint failover: a dead socket (status 0) or a
            # standby router's not-the-lease-holder 503 means THIS
            # endpoint is the problem, not this request - rotate the
            # shared cursor so the retry (and every other thread) lands
            # on the next router.  A rotated retry ignores Retry-After:
            # that header described the endpoint being left.
            standby = (
                status == 503 and isinstance(payload, dict)
                and payload.get("standby") is True
            )
            rotated = False
            if (status == 0 or standby) and len(self.endpoints) > 1:
                self._rotate(endpoint_idx)
                rotated = True
            delay = None if rotated else parse_retry_after(headers)
            if delay is None:
                delay = min(
                    self.backoff_max_s,
                    self.backoff_base_s * (2 ** (attempt - 1)),
                ) * (0.5 + 0.5 * self._rng.random())
            if deadline is not None:
                budget = deadline - time.monotonic()
                if delay >= budget:
                    error = (
                        f"client deadline {deadline_s:g}s would expire "
                        f"during backoff ({delay:.3f}s) after {attempt} "
                        f"attempt(s); last: {error}"
                    )
                    break
            retried.append({
                "status": status,
                "delay_s": round(delay, 4),
                "error": error,
            })
            self._sleep(delay)
        return SolveOutcome(
            status=status, payload=payload, headers=headers,
            attempts=attempt, retries=retried,
            latency_s=time.monotonic() - t0, request_id=rid,
            error=error if status != 200 else None,
            traceparent=traceparent,
        )
