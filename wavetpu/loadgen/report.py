"""loadgen_report.json + the perf-regression gate.

`build_report` turns one replay (client-side outcomes + the /metrics
cuts bracketing it) into a machine-readable report:

 * overall and PER-SCENARIO-TIER latency percentiles (p50/p95/p99,
   nearest-rank - the same definition /metrics and trace-report use),
 * outcome accounting: ok / 429-reject / error rates,
 * mean Server-Timing attribution (queue vs compile vs execute vs
   padding) overall and per tier - where the latency went, fleet-wide,
 * server-side deltas for exactly the replayed window: batch occupancy,
   padding-lane waste, cold-vs-warm compile counts, queue rejections,
   aggregate Gcell/s,
 * the slowest request ids - each joinable to its server-side critical
   path via `wavetpu trace-report --request ID`.

`gate(report, baseline, slo)` is the regression gate `wavetpu loadgen
--baseline OLD.json` runs: absolute SLOs (p99 budget, error budget) and
relative ones against the baseline report (p99 regression %, throughput
floor %).  It returns a violation list; the CLI exits 1 when it is
non-empty.  Defaults are deliberately loose enough for shared-chip
noise (~+-15% solo-run variance measured across BENCH rounds) and tight
enough that a 10x max-wait misconfiguration cannot pass.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

from wavetpu.obs.report import percentile_nearest_rank

# Gate defaults: see module docstring for the calibration argument.
DEFAULT_SLO = {
    "p99_budget_ms": None,        # absolute p99 cap (None = off)
    "error_budget": 0.0,          # allowed non-ok non-429 fraction
    "reject_budget": None,        # allowed 429 fraction (None = off)
    "p99_regression_pct": 50.0,   # p99 may grow this % over baseline
    "throughput_floor_pct": 50.0,  # req/s may drop this % under baseline
    "max_cold_compiles": None,    # fresh-compile cap (0 = "a warm
                                  # replica must compile nothing")
    "min_cache_hit_rate": None,   # result-cache floor across all tiers
                                  # (replica hits + coalesced riders +
                                  # router edge hits, over requests)
    # Per-tenant absolute gates on the report's `tenants` breakdown:
    # {"TENANT": {"error_budget": F, "reject_budget": F,
    #             "p95_budget_ms": X}} - the isolation drill's "victim
    # sees zero errors while the aggressor eats 429s" check in ONE
    # mixed replay (--tenant-slo victim:error_budget=0).
    "tenant_slos": None,
    # Per-tier MEASURED-ACCURACY gates: {"TIER": MAX_ABS_ERR} against
    # the tiers' `max_abs_err` (worst response-sidecar oracle error in
    # the window) - the error-budget loop's CI form (--error-slo
    # compensated=1e-4 fails a replay where the flagship scheme's
    # measured error regressed past its budget).
    "error_slos": None,
}

_TIMING_KEYS = ("queue", "compile", "execute", "padding")


def _pcts(latencies_ms: Sequence[float]) -> Dict[str, Optional[float]]:
    if not latencies_ms:
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None,
                "mean_ms": None, "max_ms": None}
    s = sorted(latencies_ms)
    return {
        "p50_ms": round(percentile_nearest_rank(s, 0.50), 3),
        "p95_ms": round(percentile_nearest_rank(s, 0.95), 3),
        "p99_ms": round(percentile_nearest_rank(s, 0.99), 3),
        "mean_ms": round(sum(s) / len(s), 3),
        "max_ms": round(s[-1], 3),
    }


def _delta(after: Dict[str, float], before: Dict[str, float],
           name: str) -> float:
    return after.get(name, 0.0) - before.get(name, 0.0)


def build_report(result, trace_path: Optional[str] = None,
                 target: Optional[str] = None,
                 meta: Optional[dict] = None,
                 error_budgets: Optional[Dict[str, float]] = None) -> dict:
    """One replay -> the loadgen_report.json dict (see module doc).
    `result` is a runner.ReplayResult.  `error_budgets` maps scenario
    tier -> advisory accuracy budget (the trace records' error_budget
    field); budgets are echoed next to each tier's measured
    max_abs_err so the report reads as measured-vs-budget."""
    outs = result.outcomes
    n = len(outs)
    ok = sum(1 for o in outs if o.status == 200)
    rejected = sum(1 for o in outs if o.status == 429)
    errors = n - ok - rejected
    lat_ms = [o.latency_s * 1e3 for o in outs]

    tiers: Dict[str, dict] = {}
    for tier in sorted({o.scenario for o in outs}):
        sub = [o for o in outs if o.scenario == tier]
        t_lat = [o.latency_s * 1e3 for o in sub]
        t_ok = sum(1 for o in sub if o.status == 200)
        row = {
            "requests": len(sub),
            "ok": t_ok,
            "error_rate": round(1.0 - t_ok / len(sub), 4),
            # Per-tier retry accounting (the aggregate-only fields below
            # hid WHICH tier the retrying client was absorbing failures
            # for - e.g. one circuit-broken tier retrying while the rest
            # sail through).
            "attempts_total": sum(o.attempts for o in sub),
            "retried_requests": sum(1 for o in sub if o.attempts > 1),
        }
        row.update(_pcts(t_lat))
        # Measured accuracy from the response sidecar (the error-budget
        # loop): the tier's worst oracle error over the window, next to
        # its advisory budget from the trace.  Both omitted when the
        # server computed no errors for the tier (c2-field lanes,
        # --no-errors) so pre-accuracy baselines keep their shape.
        errs = [
            o.max_abs_error for o in sub
            if getattr(o, "max_abs_error", None) is not None
        ]
        if errs:
            row["max_abs_err"] = max(errs)
            row["measured_requests"] = len(errs)
        budget = (error_budgets or {}).get(tier)
        if budget is not None:
            row["error_budget"] = budget
        st = [o.server_timing for o in sub if o.server_timing]
        if st:
            row["server_timing_mean_ms"] = {
                k: round(
                    sum(s.get(k, 0.0) for s in st) / len(st) * 1e3, 3
                )
                for k in _TIMING_KEYS
            }
        tiers[tier] = row

    st_all = [o.server_timing for o in outs if o.server_timing]
    timing_mean = {
        k: round(
            sum(s.get(k, 0.0) for s in st_all) / len(st_all) * 1e3, 3
        )
        for k in _TIMING_KEYS
    } if st_all else None

    before, after = result.metrics_before, result.metrics_after
    occ_sum = _delta(after, before, "wavetpu_serve_batch_occupancy_sum")
    occ_n = _delta(after, before, "wavetpu_serve_batch_occupancy_count")
    cells = _delta(after, before, "wavetpu_serve_cells_total")
    solve_s = _delta(after, before, "wavetpu_serve_solve_seconds_total")
    server = {
        "batches": int(occ_n),
        "occupancy_mean": round(occ_sum / occ_n, 3) if occ_n else None,
        "padding_lanes": int(_delta(
            after, before, "wavetpu_serve_padding_lanes_total"
        )),
        "queue_rejected": int(_delta(
            after, before, "wavetpu_serve_rejected_total"
        )),
        "limit_rejected": int(sum(
            _delta(after, before, name)
            for name in after
            if name.startswith("wavetpu_serve_limit_rejected_total")
        )),
        "fallback_batches": int(_delta(
            after, before, "wavetpu_serve_fallback_batches_total"
        )),
        # Cold-vs-warm program traffic during the replay window: misses
        # are FRESH compiles the replay paid, hits the warmed steady
        # state, disk_hits persistent-cache adoptions (a restarted
        # replica with a warm --program-cache-dir shows disk_hits > 0
        # and cold_compiles == 0 - the "compiled nothing" CI assert).
        "cold_compiles": int(_delta(
            after, before,
            'wavetpu_program_cache_events_total{event="miss"}',
        )),
        "warm_hits": int(_delta(
            after, before,
            'wavetpu_program_cache_events_total{event="hit"}',
        )),
        "disk_hits": int(_delta(
            after, before,
            'wavetpu_program_cache_events_total{event="disk_hit"}',
        )),
        "evictions": int(_delta(
            after, before,
            'wavetpu_program_cache_events_total{event="eviction"}',
        )),
        "aggregate_gcells_per_s": (
            round(cells / solve_s / 1e9, 4) if solve_s else None
        ),
    }
    # Result-cache traffic during the window, per tier: replica hits
    # (stored solve replayed, no march), coalesced riders (fanned out
    # from an identical in-flight solve), and router edge hits (zero
    # replica I/O).  Omitted entirely when no cache tier moved, so
    # pre-cache reports and baselines keep their exact shape.
    cache_hits = int(_delta(
        after, before,
        'wavetpu_serve_resultcache_events_total{event="hit"}',
    ))
    coalesced = int(_delta(
        after, before, "wavetpu_serve_coalesced_total",
    ))
    edge_hits = int(_delta(
        after, before, "wavetpu_router_edgecache_hits_total",
    ))
    cache_stores = int(_delta(
        after, before,
        'wavetpu_serve_resultcache_events_total{event="store"}',
    ))
    if cache_hits or coalesced or edge_hits or cache_stores:
        server["cache"] = {
            "replica_hits": cache_hits,
            "coalesced": coalesced,
            "edge_hits": edge_hits,
            "stores": cache_stores,
            "misses": int(_delta(
                after, before,
                'wavetpu_serve_resultcache_events_total{event="miss"}',
            )),
        }

    # Per-target breakdown (repeated --target, i.e. a fleet driven
    # without a router in front): which replica served what, and which
    # one the failures came from - a fleet drill must attribute, not
    # average.  Omitted for the single-target report (no new field to
    # confuse old baselines).
    per_target: Optional[Dict[str, dict]] = None
    target_urls = sorted({o.target for o in outs if o.target})
    if len(getattr(result, "targets", []) or []) > 1 or \
            len(target_urls) > 1:
        per_target = {}
        for t in sorted(set(getattr(result, "targets", []) or [])
                        | set(target_urls)):
            sub = [o for o in outs if o.target == t]
            t_ok = sum(1 for o in sub if o.status == 200)
            t_rej = sum(1 for o in sub if o.status == 429)
            row = {
                "requests": len(sub),
                "ok": t_ok,
                "rejected_429": t_rej,
                "errors": len(sub) - t_ok - t_rej,
                "retried_requests": sum(
                    1 for o in sub if o.attempts > 1
                ),
            }
            row.update(_pcts([o.latency_s * 1e3 for o in sub]))
            per_target[t] = row

    # Per-tenant / per-class breakdown (QoS traces: records carrying
    # `tenant` / `priority`).  Omitted entirely for single-tenant
    # traces so pre-QoS reports and baselines keep their exact shape.
    def _qos_rows(key) -> Optional[Dict[str, dict]]:
        labels = sorted({key(o) for o in outs if key(o)})
        if not labels:
            return None
        rows: Dict[str, dict] = {}
        for label in labels:
            sub = [o for o in outs if key(o) == label]
            s_ok = sum(1 for o in sub if o.status == 200)
            s_rej = sum(1 for o in sub if o.status == 429)
            row = {
                "requests": len(sub),
                "ok": s_ok,
                "rejected_429": s_rej,
                "errors": len(sub) - s_ok - s_rej,
                "reject_rate": round(s_rej / len(sub), 4),
                "error_rate": round(
                    (len(sub) - s_ok - s_rej) / len(sub), 4
                ),
                "retried_requests": sum(
                    1 for o in sub if o.attempts > 1
                ),
            }
            row.update(_pcts([o.latency_s * 1e3 for o in sub]))
            rows[label] = row
        return rows

    tenants = _qos_rows(lambda o: getattr(o, "tenant", ""))
    classes = _qos_rows(lambda o: getattr(o, "priority", ""))

    slowest = sorted(outs, key=lambda o: -o.latency_s)[:5]
    report = {
        "loadgen_report": True,
        "generated_unix": round(time.time(), 3),
        "target": target,
        "trace": trace_path,
        "mode": result.mode,
        "concurrency": result.concurrency,
        "speed": result.speed,
        "warmup_requests": len(result.warmup_outcomes),
        "wall_seconds": round(result.wall_seconds, 3),
        "requests": n,
        "ok": ok,
        "rejected_429": rejected,
        "errors": errors,
        "reject_rate": round(rejected / n, 4) if n else None,
        "error_rate": round(errors / n, 4) if n else None,
        # Retry accounting (the retrying client's absorption record):
        # attempts_total == requests when --retries is off or nothing
        # failed; retried_requests counts logical requests that needed
        # more than one attempt to reach their final status.
        "attempts_total": sum(o.attempts for o in outs),
        "retried_requests": sum(1 for o in outs if o.attempts > 1),
        "requests_per_s": (
            round(n / result.wall_seconds, 3)
            if result.wall_seconds else None
        ),
        # Fraction of replayed bodies that were exact repeats of an
        # earlier body in the same trace - the result-cache tiers'
        # opportunity ceiling (a warm replay's hit rate approaches it).
        "duplicate_rate": round(
            getattr(result, "duplicate_rate", 0.0), 4
        ),
        "cache_hit_rate": (
            round((cache_hits + coalesced + edge_hits) / n, 4)
            if n else None
        ),
        "latency_ms": _pcts(lat_ms),
        "server_timing_mean_ms": timing_mean,
        "tiers": tiers,
        "server": server,
        # The join handles: feed any of these to
        # `wavetpu trace-report --request ID` against the server's
        # telemetry dir(s) to see that exact request's critical path;
        # `traceparent` carries the fleet trace id the request rode
        # across the router and every replica it touched.
        "slowest_requests": [
            {
                "request_id": o.request_id,
                "scenario": o.scenario,
                "status": o.status,
                "latency_ms": round(o.latency_s * 1e3, 3),
                "traceparent": getattr(o, "traceparent", ""),
            }
            for o in slowest
        ],
    }
    if per_target is not None:
        report["per_target"] = per_target
        report["targets"] = list(getattr(result, "targets", []) or [])
    if getattr(result, "failover", False):
        # HA replay: how many times the shared client rotated off a
        # dead or standby endpoint (0 on an uneventful run).
        report["failover"] = True
        report["endpoint_failovers"] = int(
            getattr(result, "endpoint_failovers", 0)
        )
    if tenants is not None:
        report["tenants"] = tenants
    if classes is not None:
        report["classes"] = classes
    if meta:
        report["meta"] = meta
    return report


def load_report(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        report = json.load(f)
    if not isinstance(report, dict) or not report.get("loadgen_report"):
        raise ValueError(f"{path} is not a loadgen report")
    return report


def gate(report: dict, baseline: Optional[dict] = None,
         slo: Optional[dict] = None) -> List[dict]:
    """Evaluate the SLOs; returns the violation list (empty = pass).
    Absolute gates (p99 budget, error/reject budgets) always apply;
    relative gates (p99 regression, throughput floor) need `baseline`."""
    cfg = dict(DEFAULT_SLO)
    if slo:
        unknown = set(slo) - set(DEFAULT_SLO)
        if unknown:
            raise ValueError(f"unknown SLO keys {sorted(unknown)}")
        cfg.update({k: v for k, v in slo.items() if v is not None})
    out: List[dict] = []

    def fail(name, observed, budget, detail):
        out.append({"slo": name, "observed": observed,
                    "budget": budget, "detail": detail})

    p99 = (report.get("latency_ms") or {}).get("p99_ms")
    if cfg["p99_budget_ms"] is not None:
        if p99 is None or p99 > cfg["p99_budget_ms"]:
            fail("p99_budget_ms", p99, cfg["p99_budget_ms"],
                 f"p99 {p99} ms exceeds budget "
                 f"{cfg['p99_budget_ms']} ms")
    err = report.get("error_rate")
    if cfg["error_budget"] is not None and err is not None \
            and err > cfg["error_budget"]:
        fail("error_budget", err, cfg["error_budget"],
             f"error rate {err} exceeds budget {cfg['error_budget']}")
    rej = report.get("reject_rate")
    if cfg["reject_budget"] is not None and rej is not None \
            and rej > cfg["reject_budget"]:
        fail("reject_budget", rej, cfg["reject_budget"],
             f"429 reject rate {rej} exceeds budget "
             f"{cfg['reject_budget']}")
    # Persistent-cache gate: a replay against a replica whose program
    # cache SHOULD be warm (second replica start) asserts zero fresh
    # compiles here - the CI-checkable form of "restart paid nothing".
    cold = (report.get("server") or {}).get("cold_compiles")
    if cfg["max_cold_compiles"] is not None and cold is not None \
            and cold > cfg["max_cold_compiles"]:
        fail("max_cold_compiles", cold, cfg["max_cold_compiles"],
             f"{cold} fresh compile(s) during replay exceeds budget "
             f"{cfg['max_cold_compiles']} (program cache not warm)")
    # Result-cache gate: a WARM hotkey replay (same trace replayed
    # twice through the same replica/router) asserts a hit-rate floor
    # here - the CI-checkable form of "repeats were answered from
    # memory, not re-marched".
    hit_rate = report.get("cache_hit_rate")
    if cfg["min_cache_hit_rate"] is not None and (
            hit_rate is None or hit_rate < cfg["min_cache_hit_rate"]):
        fail("min_cache_hit_rate", hit_rate, cfg["min_cache_hit_rate"],
             f"cache hit rate {hit_rate} below floor "
             f"{cfg['min_cache_hit_rate']} (result cache not warm)")
    # Per-tenant gates against the QoS breakdown: the isolation drill's
    # one-replay form (victim zero-error while the aggressor is
    # legitimately shedding 429s).
    if cfg["tenant_slos"]:
        rows = report.get("tenants") or {}
        for tenant, tslo in sorted(cfg["tenant_slos"].items()):
            row = rows.get(tenant)
            if row is None:
                fail(f"tenant:{tenant}", None, tslo,
                     f"tenant {tenant!r} has an SLO but no requests "
                     f"in the report")
                continue
            unknown = set(tslo) - {
                "error_budget", "reject_budget", "p95_budget_ms"
            }
            if unknown:
                raise ValueError(
                    f"unknown tenant SLO keys {sorted(unknown)} "
                    f"for {tenant!r}"
                )
            if tslo.get("error_budget") is not None \
                    and row["error_rate"] > tslo["error_budget"]:
                fail(f"tenant:{tenant}:error_budget",
                     row["error_rate"], tslo["error_budget"],
                     f"tenant {tenant!r} error rate "
                     f"{row['error_rate']} exceeds budget "
                     f"{tslo['error_budget']}")
            if tslo.get("reject_budget") is not None \
                    and row["reject_rate"] > tslo["reject_budget"]:
                fail(f"tenant:{tenant}:reject_budget",
                     row["reject_rate"], tslo["reject_budget"],
                     f"tenant {tenant!r} 429 rate "
                     f"{row['reject_rate']} exceeds budget "
                     f"{tslo['reject_budget']}")
            if tslo.get("p95_budget_ms") is not None and (
                row["p95_ms"] is None
                or row["p95_ms"] > tslo["p95_budget_ms"]
            ):
                fail(f"tenant:{tenant}:p95_budget_ms",
                     row["p95_ms"], tslo["p95_budget_ms"],
                     f"tenant {tenant!r} p95 {row['p95_ms']} ms "
                     f"exceeds budget {tslo['p95_budget_ms']} ms")

    # Measured-accuracy gates: the error-budget loop's teeth.  A tier
    # with an SLO must exist AND have measured errors AND be inside its
    # budget - "no data" passes nothing (a --no-errors server or a
    # renamed tier must not silently green the accuracy gate).
    if cfg["error_slos"]:
        rows = report.get("tiers") or {}
        for tier, budget in sorted(cfg["error_slos"].items()):
            row = rows.get(tier)
            if row is None:
                fail(f"err:{tier}", None, budget,
                     f"tier {tier!r} has an error SLO but no requests "
                     f"in the report")
                continue
            measured = row.get("max_abs_err")
            if measured is None:
                fail(f"err:{tier}", None, budget,
                     f"tier {tier!r} has an error SLO but the replay "
                     f"measured no errors (server --no-errors, or a "
                     f"c2-field tier with no oracle)")
            elif measured > budget:
                fail(f"err:{tier}", measured, budget,
                     f"tier {tier!r} measured max_abs_err "
                     f"{measured:.3e} exceeds budget {budget:.3e}")

    if baseline is not None:
        base_p99 = (baseline.get("latency_ms") or {}).get("p99_ms")
        if cfg["p99_regression_pct"] is not None and base_p99 and p99:
            limit = base_p99 * (1.0 + cfg["p99_regression_pct"] / 100.0)
            if p99 > limit:
                fail("p99_regression_pct",
                     round(100.0 * (p99 / base_p99 - 1.0), 1),
                     cfg["p99_regression_pct"],
                     f"p99 {p99} ms vs baseline {base_p99} ms "
                     f"(+{100.0 * (p99 / base_p99 - 1.0):.1f}% > "
                     f"+{cfg['p99_regression_pct']}% allowed)")
        base_rps = baseline.get("requests_per_s")
        rps = report.get("requests_per_s")
        if cfg["throughput_floor_pct"] is not None and base_rps and rps:
            floor = base_rps * (1.0 - cfg["throughput_floor_pct"] / 100.0)
            if rps < floor:
                fail("throughput_floor_pct",
                     round(100.0 * (1.0 - rps / base_rps), 1),
                     cfg["throughput_floor_pct"],
                     f"throughput {rps} req/s vs baseline {base_rps} "
                     f"req/s (-{100.0 * (1.0 - rps / base_rps):.1f}% > "
                     f"-{cfg['throughput_floor_pct']}% allowed)")
    return out


def format_gate(violations: Sequence[dict], report: dict,
                baseline: Optional[dict] = None) -> str:
    """The human-readable gate diff (also a useful CI artifact)."""
    lines = ["loadgen regression gate"]

    def row(label, new, old, unit=""):
        if old is not None and new is not None and old:
            pct = 100.0 * (new / old - 1.0)
            lines.append(
                f"  {label:<18} {new:>10} vs {old:>10} {unit} "
                f"({pct:+.1f}%)"
            )
        else:
            lines.append(f"  {label:<18} {new!r:>10} (no baseline)")

    lat = report.get("latency_ms") or {}
    blat = (baseline or {}).get("latency_ms") or {}
    row("p50_ms", lat.get("p50_ms"), blat.get("p50_ms"), "ms")
    row("p99_ms", lat.get("p99_ms"), blat.get("p99_ms"), "ms")
    row("requests_per_s", report.get("requests_per_s"),
        (baseline or {}).get("requests_per_s"), "req/s")
    lines.append(
        f"  {'error_rate':<18} {report.get('error_rate')!r:>10}"
        f"   reject_rate {report.get('reject_rate')!r}"
    )
    srv = report.get("server") or {}
    if "cold_compiles" in srv:
        # Compile traffic during the window: the line CI greps to prove
        # a restarted replica served entirely from the persistent cache.
        lines.append(
            f"  {'compiles':<18} {srv.get('cold_compiles')} fresh, "
            f"{srv.get('disk_hits', 0)} disk hit(s), "
            f"{srv.get('warm_hits')} warm hit(s)"
        )
    cache = srv.get("cache")
    if cache:
        # Cache traffic per tier: the line CI greps to prove a warm
        # replay was answered from memory (and WHERE - replica vs edge).
        lines.append(
            f"  {'cache':<18} rate "
            f"{report.get('cache_hit_rate')!r} "
            f"(replica {cache.get('replica_hits')}, coalesced "
            f"{cache.get('coalesced')}, edge {cache.get('edge_hits')}; "
            f"dup rate {report.get('duplicate_rate')!r})"
        )
    measured_tiers = {
        tier: row for tier, row in (report.get("tiers") or {}).items()
        if row.get("max_abs_err") is not None
    }
    if measured_tiers:
        # Measured accuracy vs advisory budget, per tier: the line CI
        # greps to prove the error-budget loop closed on real numbers.
        for tier, trow in sorted(measured_tiers.items()):
            budget = trow.get("error_budget")
            lines.append(
                f"  {'err:' + tier:<18} max_abs_err "
                f"{trow['max_abs_err']:.3e} over "
                f"{trow.get('measured_requests')} measured"
                + (f" (budget {budget:.3e})" if budget is not None
                   else " (no budget)")
            )
    for section, singular in (("tenants", "tenant"), ("classes", "class")):
        # QoS breakdown: one line per tenant/class so the isolation
        # drill's victim-vs-aggressor split is visible in the gate text.
        for label, trow in sorted((report.get(section) or {}).items()):
            lines.append(
                f"  {singular + ':' + label:<18} "
                f"{trow['requests']} req, p95 {trow.get('p95_ms')!r} ms, "
                f"429 {trow['rejected_429']}, err {trow['errors']}"
            )
    att = report.get("attempts_total")
    req = report.get("requests")
    if att and req and att > req:
        # Retry absorption, broken out per tier: the gate diff must say
        # WHERE the retrying client worked, not just that it did.
        lines.append(
            f"  {'retries':<18} {report.get('retried_requests')} "
            f"request(s) retried ({att} attempts / {req} requests)"
        )
        for tier, row in sorted((report.get("tiers") or {}).items()):
            if row.get("retried_requests"):
                lines.append(
                    f"    {tier}: {row['retried_requests']} retried, "
                    f"{row['attempts_total']} attempts / "
                    f"{row['requests']} requests"
                )
    if violations:
        lines.append("violations:")
        for v in violations:
            lines.append(f"  FAIL [{v['slo']}] {v['detail']}")
        lines.append("-> FAIL")
    else:
        lines.append("-> PASS")
    return "\n".join(lines)
