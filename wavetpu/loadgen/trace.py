"""Scenario traces: the loadgen workload format, generators, recorder.

Format (JSONL, one request per line, `t`-ordered):

    {"t": 0.153, "scenario": "small-standard",
     "body": {"N": 8, "timesteps": 20, "phase": 1.0},
     "error_budget": 1e-3}

 * `t`        - seconds since trace start: the OPEN-LOOP replay offset
                (closed-loop replay ignores it and drives by
                concurrency).
 * `scenario` - the tier label per-tier SLO reporting groups by; when
                absent it is derived from the body (`scenario_label`).
 * `body`     - the verbatim POST /solve JSON (serve/api.py request
                fields: N, timesteps, steps, scheme, kernel,
                fuse_steps, dtype, phase, c2_field, mesh, ...).
 * `error_budget` - ADVISORY accuracy SLO for the tier, recorded so
                traces stay forward-compatible with the accuracy-aware
                autotuner direction (ROADMAP #5: requests declare an
                error budget instead of a scheme).  Not sent to the
                server today.
 * `tenant` / `api_key` / `priority` - OPTIONAL multi-tenant QoS
                fields: the runner sends them as X-Wavetpu-Tenant /
                X-Api-Key / X-Priority request headers (docs/fleet.md
                "API keys", docs/serving.md "Priority classes"), and
                the report breaks latency/429 rates down per tenant
                and per class.  The `tenants` mix generates a seeded
                aggressor-vs-victim two-tenant trace with them set.

Generators are seeded and deterministic: the same (mix, duration, qps,
seed) always emits the same trace, so a CI regression gate compares
like against like.  `TraceRecorder` is the server-side half: `wavetpu
serve --record-trace FILE` appends every ACCEPTED /solve body with its
arrival offset, producing a trace that replays real traffic.
"""

from __future__ import annotations

import json
import math
import os
import random
import threading
import time
from typing import Dict, List, Optional, Sequence

MIXES = ("uniform", "poisson", "diurnal", "hotkey", "tenants")

# The multi-tenant QoS record fields (optional per record; the runner
# maps them onto request headers).
QOS_FIELDS = ("tenant", "api_key", "priority")


def scenario_label(body: dict) -> str:
    """A stable tier label derived from the program-identity-ish body
    fields - what the recorder and the report use when a record carries
    no explicit scenario name."""
    parts = [f"N{body.get('N', '?')}/{body.get('timesteps', 20)}"]
    parts.append(str(body.get("scheme", "standard")))
    if body.get("fuse_steps", 1) and int(body.get("fuse_steps", 1)) > 1:
        parts.append(f"k{body['fuse_steps']}")
    if body.get("kernel"):
        parts.append(str(body["kernel"]))
    if body.get("dtype", "f32") != "f32":
        parts.append(str(body["dtype"]))
    if body.get("c2_field"):
        parts.append(str(body["c2_field"]))
    if body.get("steps") is not None:
        parts.append(f"stop{body['steps']}")
    if body.get("mesh"):
        parts.append("mesh" + "x".join(str(m) for m in body["mesh"]))
    return "-".join(parts)


def default_scenarios(n: int = 8, timesteps: int = 20,
                      pallas: bool = False) -> List[dict]:
    """The standard mixed-traffic tier set: N, steps, scheme, phase and
    c2-field presets all vary (every knob the batcher shape-buckets on),
    with per-tier advisory error budgets.  `pallas=True` adds a k-fused
    onion tier (skip it on CPU hosts where interpret-mode pallas would
    dominate the replay wall time).  Bodies deliberately omit `kernel`
    so the server's --kernel default resolves per backend."""
    t = int(timesteps)
    tiers = [
        {"name": "small-standard", "weight": 4, "error_budget": 1e-3,
         "body": {"N": n, "timesteps": t}},
        # Shifted phase: distinct per-lane work that still batches with
        # the reference-phase tier (same program identity).
        {"name": "small-phase", "weight": 3, "error_budget": 1e-3,
         "body": {"N": n, "timesteps": t, "phase": 1.0}},
        # Early stop: exercises per-lane stop masking and (when the
        # server runs --length-bucket-steps) the length buckets.
        {"name": "small-stop", "weight": 2, "error_budget": 1e-3,
         "body": {"N": n, "timesteps": t, "steps": max(2, t // 2)}},
        # The flagship accuracy scheme through the vmapped core.
        {"name": "compensated", "weight": 2, "error_budget": 1e-5,
         "body": {"N": n, "timesteps": t, "scheme": "compensated"}},
        # Variable-c preset: no analytic oracle, field-keyed programs.
        {"name": "lens-field", "weight": 1, "error_budget": None,
         "body": {"N": n, "timesteps": t, "c2_field": "gaussian-lens"}},
        # A longer march: a distinct program identity (timesteps is in
        # the bucket key), so the mix always spans >= 2 programs.
        {"name": "long", "weight": 1, "error_budget": 1e-3,
         "body": {"N": n, "timesteps": 2 * t}},
    ]
    if pallas:
        tiers.append(
            {"name": "kfused", "weight": 2, "error_budget": 1e-3,
             "body": {"N": n, "timesteps": t, "kernel": "pallas",
                      "fuse_steps": 2}},
        )
    return tiers


def _record(t: float, tier: dict, body: Optional[dict] = None) -> dict:
    rec = {
        "t": round(t, 6),
        "scenario": tier["name"],
        "body": dict(body if body is not None else tier["body"]),
    }
    if tier.get("error_budget") is not None:
        rec["error_budget"] = tier["error_budget"]
    for f in QOS_FIELDS:
        if tier.get(f):
            rec[f] = tier[f]
    return rec


def _weighted(rng: random.Random, scenarios: Sequence[dict]) -> dict:
    return rng.choices(
        list(scenarios),
        weights=[s.get("weight", 1) for s in scenarios],
    )[0]


def gen_uniform(duration: float, qps: float, scenarios: Sequence[dict],
                seed: int = 0) -> List[dict]:
    """Evenly spaced arrivals, scenarios drawn by weight: the baseline
    steady-state mix."""
    rng = random.Random(seed)
    n = max(1, int(duration * qps))
    gap = duration / n
    return [
        _record(i * gap, _weighted(rng, scenarios)) for i in range(n)
    ]


def gen_poisson(duration: float, qps: float, scenarios: Sequence[dict],
                seed: int = 0) -> List[dict]:
    """Open-loop Poisson arrivals (exponential inter-arrival times):
    the bursty mix - back-to-back clusters that fill batches and gaps
    that let the max-wait window idle out."""
    rng = random.Random(seed)
    out, t = [], 0.0
    while True:
        t += rng.expovariate(qps)
        if t >= duration:
            break
        out.append(_record(t, _weighted(rng, scenarios)))
    if not out:  # a tiny duration*qps must still emit one request
        out.append(_record(0.0, _weighted(rng, scenarios)))
    return out


def gen_diurnal(duration: float, qps: float, scenarios: Sequence[dict],
                seed: int = 0) -> List[dict]:
    """A ramp-up/ramp-down day compressed into `duration`: Poisson
    thinning of a peak-rate `qps` process against a raised-cosine rate
    curve (0 at the edges, `qps` mid-trace).  Exercises both the
    under-occupied ramp and the saturated peak in one trace."""
    rng = random.Random(seed)
    out, t = [], 0.0
    while True:
        t += rng.expovariate(qps)
        if t >= duration:
            break
        rate_frac = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / duration))
        if rng.random() < rate_frac:
            out.append(_record(t, _weighted(rng, scenarios)))
    if not out:
        out.append(_record(duration / 2.0, _weighted(rng, scenarios)))
    return out


def gen_hotkey(duration: float, qps: float, scenarios: Sequence[dict],
               seed: int = 0, distinct: int = 12,
               hot_frac: float = 0.7) -> List[dict]:
    """Cache-adversarial: `hot_frac` of requests hit one hot program
    key, the rest cycle through `distinct` cold keys (the hot body with
    shifted `timesteps`, each a distinct ProgramKey).  With `distinct`
    above the server's --max-programs this thrashes the LRU - the mix
    that makes cold-vs-warm compile counts and eviction rates in the
    report mean something."""
    rng = random.Random(seed)
    hot = scenarios[0]
    out, t, i = [], 0.0, 0
    while True:
        t += rng.expovariate(qps)
        if t >= duration:
            break
        if rng.random() < hot_frac:
            out.append(_record(t, hot))
        else:
            body = dict(hot["body"])
            body["timesteps"] = int(body.get("timesteps", 20)) + 1 + (
                i % max(1, distinct)
            )
            cold = {"name": f"cold-{i % max(1, distinct)}",
                    "error_budget": hot.get("error_budget")}
            out.append(_record(t, cold, body))
            i += 1
    if not out:
        out.append(_record(0.0, hot))
    return out


def gen_tenants(duration: float, qps: float, scenarios: Sequence[dict],
                seed: int = 0, victim_frac: float = 0.4,
                victim_tenant: str = "victim",
                aggressor_tenant: str = "aggressor",
                victim_key: Optional[str] = None,
                aggressor_key: Optional[str] = None,
                aggressor_mult: int = 4) -> List[dict]:
    """The aggressor-vs-victim isolation drill: two interleaved Poisson
    streams.  The VICTIM replays the weighted scenario mix at
    `victim_frac` of `qps`, every request `interactive`; the AGGRESSOR
    fires long marches (the first scenario's body with `timesteps`
    multiplied by `aggressor_mult` - a heavier, distinct program
    identity) at the remaining rate, every request `best_effort`.  Each
    record carries its tenant label (and api_key when given), so a
    replay through a quota-enforcing router shows the aggressor eating
    429s while the victim's interactive p95 holds - the bench `qos`
    row's and the CI QoS smoke's workload.  Deterministic in
    (duration, qps, seed, scenarios)."""
    rng = random.Random(seed)
    v_qps = max(qps * victim_frac, 1e-9)
    a_qps = max(qps - v_qps, 1e-9)
    out: List[dict] = []
    t = 0.0
    while True:
        t += rng.expovariate(v_qps)
        if t >= duration:
            break
        tier = dict(_weighted(rng, scenarios))
        tier["name"] = f"victim-{tier['name']}"
        tier["tenant"] = victim_tenant
        tier["priority"] = "interactive"
        if victim_key:
            tier["api_key"] = victim_key
        out.append(_record(t, tier))
    hot = scenarios[0]
    body = dict(hot["body"])
    body["timesteps"] = int(
        body.get("timesteps", 20)
    ) * max(1, aggressor_mult)
    agg_tier = {
        "name": "aggressor-long",
        "error_budget": None,
        "tenant": aggressor_tenant,
        "priority": "best_effort",
    }
    if aggressor_key:
        agg_tier["api_key"] = aggressor_key
    t = 0.0
    while True:
        t += rng.expovariate(a_qps)
        if t >= duration:
            break
        out.append(_record(t, agg_tier, body))
    if not out:
        out.append(_record(0.0, agg_tier, body))
    out.sort(key=lambda r: r["t"])
    return out


_GENERATORS = {
    "uniform": gen_uniform,
    "poisson": gen_poisson,
    "diurnal": gen_diurnal,
    "hotkey": gen_hotkey,
    "tenants": gen_tenants,
}


def generate(mix: str, duration: float, qps: float,
             scenarios: Optional[Sequence[dict]] = None, seed: int = 0,
             **kw) -> List[dict]:
    """Generate a synthetic scenario trace.  Deterministic in
    (mix, duration, qps, seed, scenarios)."""
    if mix not in _GENERATORS:
        raise ValueError(f"mix must be one of {MIXES}, got {mix!r}")
    if duration <= 0 or qps <= 0:
        raise ValueError(
            f"duration and qps must be > 0, got {duration}/{qps}"
        )
    if scenarios is None:
        scenarios = default_scenarios()
    return _GENERATORS[mix](duration, qps, scenarios, seed=seed, **kw)


def save_scenario_trace(path: str, records: Sequence[dict]) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def load_scenario_trace(path: str) -> List[dict]:
    """Parse + validate a scenario trace; returns records sorted by t.
    Raises ValueError on a structurally broken record (a bad trace must
    fail the replay loudly, not fire garbage at a production server)."""
    out = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not JSON: {e}")
            if not isinstance(rec, dict) or not isinstance(
                rec.get("body"), dict
            ):
                raise ValueError(
                    f"{path}:{lineno}: record needs an object 'body'"
                )
            t = rec.get("t", 0.0)
            if not isinstance(t, (int, float)) or t < 0:
                raise ValueError(
                    f"{path}:{lineno}: 't' must be a number >= 0, "
                    f"got {t!r}"
                )
            for f in QOS_FIELDS:
                v = rec.get(f)
                if v is not None and (
                    not isinstance(v, str) or not v
                ):
                    raise ValueError(
                        f"{path}:{lineno}: {f!r} must be a non-empty "
                        f"string, got {v!r}"
                    )
            rec.setdefault("scenario", scenario_label(rec["body"]))
            out.append(rec)
    if not out:
        raise ValueError(f"{path}: empty trace")
    out.sort(key=lambda r: r["t"])
    return out


class TraceRecorder:
    """Server-side traffic capture: one accepted /solve body per line,
    timestamped relative to the FIRST recorded request, so the file is
    directly a replayable scenario trace.  Thread-safe (handler threads
    record concurrently); writes are best-effort - recording must never
    fail the request it observes (same discipline as obs/tracing.py)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self._t0: Optional[float] = None

    def record(self, body: dict, request_id: Optional[str] = None,
               scenario: Optional[str] = None) -> None:
        now = time.monotonic()
        rec: Dict = {"body": body}
        try:
            with self._lock:
                if self._t0 is None:
                    self._t0 = now
                rec["t"] = round(now - self._t0, 6)
                rec["scenario"] = scenario or scenario_label(body)
                if request_id:
                    rec["id"] = request_id
                self._f.write(json.dumps(rec) + "\n")
                self._f.flush()
        except (OSError, ValueError, TypeError):
            pass

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()
