"""`wavetpu loadgen` - generate, replay, gate.

    wavetpu loadgen generate --out TRACE.jsonl [--mix poisson]
        [--duration S] [--qps Q] [--seed N] [--n N] [--timesteps T]
        [--pallas] [--distinct D] [--victim-frac F] [--victim-key K]
        [--aggressor-key K] [--aggressor-mult M]
    wavetpu loadgen replay TRACE.jsonl --target URL [--target URL2 ...]
        [--mode open|closed]
        [--concurrency C] [--speed X] [--warmup W] [--timeout S]
        [--retries N] [--duration SECONDS] [--failover]
        [--out REPORT.json] [--no-preflight]
        [--baseline OLD.json] [SLO flags]
    wavetpu loadgen gate REPORT.json --baseline OLD.json [SLO flags]

Repeating `--target` fans the replay out round-robin across N replica
URLs (a router-less fleet drill); the report carries a `per_target`
request/error breakdown so failures attribute to a replica, and
server-side metric deltas are summed across all targets.

`--retries N` sends every request through the retrying WavetpuClient
(jittered backoff honoring Retry-After, request-id reuse across
attempts - the chaos-drill client); `--duration S` is SOAK mode: loop
the trace until the wall-clock budget elapses, reported as replay-
window deltas like any run.

`--failover` (requires `--retries` >= 1) flips multi-target from
fan-out to HA: every `--target` joins ONE multi-endpoint client that
rotates off a dead or standby router on retry (the router-failover
drill).  Preflight passes if ANY target is ready, and a target whose
/metrics cannot be scraped (the killed active) is dropped from the
bracketing cuts; the report carries `endpoint_failovers`.

SLO flags (gate + replay-with-baseline; the ABSOLUTE ones also gate a
baseline-less replay when passed explicitly - the chaos smoke's
"zero client-visible errors" check):
    --p99-budget-ms X          absolute p99 cap
    --error-budget F           allowed non-ok non-429 fraction (default 0)
    --reject-budget F          allowed 429 fraction
    --p99-regression-pct P     p99 may grow P% over the baseline (50)
    --throughput-floor-pct P   req/s may drop P% under the baseline (50)
    --max-cold-compiles N      fresh-compile cap for the replay window
                               (0 = a warm program cache must serve
                               every program - the restart drill)
    --min-cache-hit-rate F     result-cache hit-rate floor (replica
                               hits + coalesced + edge hits, over
                               requests) - the warm hotkey-replay
                               drill's "repeats came from memory" check
    --tenant-slo T:KEY=V       per-tenant absolute gate (repeatable);
                               KEY is error-budget, reject-budget, or
                               p95-budget-ms.  The isolation drill pins
                               `--tenant-slo victim:error-budget=0`
                               while the aggressor sheds 429s.
    --error-slo TIER=BUDGET    per-tier MEASURED-ACCURACY gate
                               (repeatable): the tier's worst
                               response-sidecar max_abs_error over the
                               window must exist and stay <= BUDGET -
                               the error-budget loop closed on real
                               numbers (--error-slo compensated=1e-4).
                               Tiers' advisory budgets from the trace
                               are echoed in the report either way.

`--mix tenants` generates the aggressor-vs-victim QoS trace: a victim
tenant replaying the scenario mix at interactive priority interleaved
with an aggressor flooding oversized best_effort solves
(`--victim-frac` splits the qps; `--victim-key`/`--aggressor-key`
stamp api_keys; `--aggressor-mult` scales the aggressor's timesteps).

Exit codes: 0 pass / generated / replayed; 1 SLO violation (the
regression gate failed); 2 usage, unreadable input, or preflight
failure.  `replay` without `--baseline` or SLO flags just writes the
report; `replay --baseline OLD.json` additionally diffs against it and
exits 1 on violation - the one-command perf-regression gate CI runs.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, Optional, Sequence

from wavetpu.core.flags import split_flags as _split_flags
from wavetpu.loadgen import report as lg_report
from wavetpu.loadgen import runner, trace

_USAGE = __doc__.split("Exit codes:")[0].strip()

_SLO_FLAGS = {
    "p99-budget-ms": ("p99_budget_ms", float),
    "error-budget": ("error_budget", float),
    "reject-budget": ("reject_budget", float),
    "p99-regression-pct": ("p99_regression_pct", float),
    "throughput-floor-pct": ("throughput_floor_pct", float),
    "max-cold-compiles": ("max_cold_compiles", int),
    "min-cache-hit-rate": ("min_cache_hit_rate", float),
}

_TENANT_SLO_KEYS = {
    "error-budget": ("error_budget", float),
    "reject-budget": ("reject_budget", float),
    "p95-budget-ms": ("p95_budget_ms", float),
}


def _parse_error_slos(values: Sequence[str]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for raw in values:
        tier, eq, val = raw.partition("=")
        if not (eq and tier):
            raise ValueError(
                f"--error-slo wants TIER=BUDGET, got {raw!r}"
            )
        try:
            out[tier] = float(val)
        except ValueError:
            raise ValueError(
                f"--error-slo budget must be a number, got {raw!r}"
            )
    return out


def _parse_tenant_slos(values: Sequence[str]) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for raw in values:
        head, eq, val = raw.partition("=")
        tenant, colon, key = head.partition(":")
        if not (eq and colon and tenant) or key not in _TENANT_SLO_KEYS:
            raise ValueError(
                f"--tenant-slo wants TENANT:KEY=VALUE with KEY one of "
                f"{sorted(_TENANT_SLO_KEYS)}, got {raw!r}"
            )
        name, conv = _TENANT_SLO_KEYS[key]
        out.setdefault(tenant, {})[name] = conv(val)
    return out


def _slo_from_flags(flags: dict) -> Dict[str, object]:
    slo: Dict[str, object] = {}
    for flag, (key, conv) in _SLO_FLAGS.items():
        if flag in flags:
            slo[key] = conv(flags[flag])
    if flags.get("tenant-slo"):
        slo["tenant_slos"] = _parse_tenant_slos(flags["tenant-slo"])
    if flags.get("error-slo"):
        slo["error_slos"] = _parse_error_slos(flags["error-slo"])
    return slo


def _usage_error(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    print(_USAGE, file=sys.stderr)
    return 2


def _generate(argv: Sequence[str]) -> int:
    try:
        pos, flags = _split_flags(
            argv,
            known=("out", "mix", "duration", "qps", "seed", "n",
                   "timesteps", "pallas", "distinct", "victim-frac",
                   "victim-key", "aggressor-key", "aggressor-mult"),
            valueless=("pallas",),
        )
        if pos:
            raise ValueError(f"unexpected positional {pos[0]!r}")
        if "out" not in flags:
            raise ValueError("generate needs --out TRACE.jsonl")
        mix = flags.get("mix", "poisson")
        duration = float(flags.get("duration", "30"))
        qps = float(flags.get("qps", "4"))
        seed = int(flags.get("seed", "0"))
        scenarios = trace.default_scenarios(
            n=int(flags.get("n", "8")),
            timesteps=int(flags.get("timesteps", "20")),
            pallas="pallas" in flags,
        )
        kw = {}
        if mix == "hotkey" and "distinct" in flags:
            kw["distinct"] = int(flags["distinct"])
        if mix == "tenants":
            if "victim-frac" in flags:
                kw["victim_frac"] = float(flags["victim-frac"])
            if "victim-key" in flags:
                kw["victim_key"] = flags["victim-key"]
            if "aggressor-key" in flags:
                kw["aggressor_key"] = flags["aggressor-key"]
            if "aggressor-mult" in flags:
                kw["aggressor_mult"] = int(flags["aggressor-mult"])
        records = trace.generate(
            mix, duration, qps, scenarios=scenarios, seed=seed, **kw
        )
    except ValueError as e:
        return _usage_error(str(e))
    trace.save_scenario_trace(flags["out"], records)
    tiers = sorted({r["scenario"] for r in records})
    print(
        f"wrote {len(records)} requests / {len(tiers)} tiers "
        f"({mix}, {duration:g}s @ {qps:g} qps, seed {seed}) "
        f"-> {flags['out']}"
    )
    return 0


def _run_gate(report: dict, baseline_path: str, slo: dict) -> int:
    try:
        baseline = lg_report.load_report(baseline_path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        return _usage_error(f"cannot read baseline: {e}")
    violations = lg_report.gate(report, baseline=baseline, slo=slo)
    print(lg_report.format_gate(violations, report, baseline))
    return 1 if violations else 0


def _replay(argv: Sequence[str]) -> int:
    try:
        pos, flags = _split_flags(
            argv,
            known=("target", "mode", "concurrency", "speed", "warmup",
                   "timeout", "out", "baseline", "no-preflight",
                   "retries", "duration", "tenant-slo", "error-slo",
                   "failover")
            + tuple(_SLO_FLAGS),
            valueless=("no-preflight", "failover"),
            repeatable=("target", "tenant-slo", "error-slo"),
        )
        if len(pos) != 1:
            raise ValueError("replay wants exactly one TRACE.jsonl")
        if "target" not in flags:
            raise ValueError("replay needs --target URL")
        targets = list(flags["target"])
        mode = flags.get("mode", "open")
        concurrency = int(flags.get("concurrency", "4"))
        speed = float(flags.get("speed", "1"))
        warmup = int(flags.get("warmup", "0"))
        timeout = float(flags.get("timeout", "120"))
        retries = int(flags.get("retries", "0"))
        duration = (
            float(flags["duration"]) if "duration" in flags else None
        )
        slo = _slo_from_flags(flags)
        records = trace.load_scenario_trace(pos[0])
    except ValueError as e:
        return _usage_error(str(e))
    except OSError as e:
        return _usage_error(f"cannot read trace: {e}")
    try:
        result = runner.replay(
            targets, records, mode=mode,
            concurrency=concurrency, speed=speed, warmup=warmup,
            timeout=timeout, skip_preflight="no-preflight" in flags,
            retries=retries, duration=duration,
            failover="failover" in flags,
        )
    except runner.PreflightError as e:
        print(f"error: preflight failed: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        return _usage_error(str(e))
    # Advisory per-tier accuracy budgets from the trace itself (every
    # record of a tier carries the same error_budget) - echoed next to
    # the measured max_abs_err in the report's tier rows.
    budgets: Dict[str, float] = {}
    for rec in records:
        if rec.get("error_budget") is not None:
            budgets.setdefault(rec["scenario"], rec["error_budget"])
    report = lg_report.build_report(
        result, trace_path=pos[0],
        target=targets[0] if len(targets) == 1 else targets,
        error_budgets=budgets or None,
    )
    lat = report["latency_ms"]
    occ = report["server"]["occupancy_mean"]
    print(
        f"replayed {report['requests']} requests in "
        f"{report['wall_seconds']}s ({report['mode']} loop): "
        f"ok {report['ok']}, 429 {report['rejected_429']}, errors "
        f"{report['errors']}; p50 {lat['p50_ms']}ms p99 {lat['p99_ms']}ms; "
        f"occupancy {occ}; cold compiles "
        f"{report['server']['cold_compiles']}; disk hits "
        f"{report['server']['disk_hits']}"
    )
    cache = (report.get("server") or {}).get("cache")
    if cache:
        print(
            f"cache: hit rate {report['cache_hit_rate']} "
            f"(replica {cache['replica_hits']}, coalesced "
            f"{cache['coalesced']}, edge {cache['edge_hits']}); "
            f"duplicate rate {report['duplicate_rate']}"
        )
    if retries:
        print(
            f"retries: {report['retried_requests']} of "
            f"{report['requests']} requests needed retries "
            f"({report['attempts_total']} attempts total)"
        )
    if report.get("failover"):
        print(
            f"failover: {report['endpoint_failovers']} endpoint "
            f"rotation(s) across {len(targets)} router(s)"
        )
    for t, row in sorted((report.get("per_target") or {}).items()):
        print(
            f"  {t}: {row['requests']} requests, ok {row['ok']}, "
            f"429 {row['rejected_429']}, errors {row['errors']}, "
            f"p95 {row['p95_ms']}ms"
        )
    for tenant, row in sorted((report.get("tenants") or {}).items()):
        print(
            f"  tenant {tenant}: {row['requests']} requests, "
            f"ok {row['ok']}, 429 {row['rejected_429']}, "
            f"errors {row['errors']}, p95 {row['p95_ms']}ms"
        )
    for tier, row in sorted((report.get("tiers") or {}).items()):
        # The error-budget loop's human-readable form: measured oracle
        # error per tier vs the trace's advisory budget.
        if row.get("max_abs_err") is None:
            continue
        budget = row.get("error_budget")
        print(
            f"  err {tier}: max_abs_err {row['max_abs_err']:.3e} "
            f"over {row['measured_requests']} measured"
            + (f" (budget {budget:.3e})" if budget is not None else "")
        )
    if "out" in flags:
        with open(flags["out"], "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"report written: {flags['out']}")
    if "baseline" in flags:
        return _run_gate(report, flags["baseline"], slo)
    absolute = {
        k: v for k, v in slo.items()
        if k in ("p99_budget_ms", "error_budget", "reject_budget",
                 "max_cold_compiles", "min_cache_hit_rate",
                 "tenant_slos", "error_slos")
    }
    if absolute:
        # An explicitly-passed ABSOLUTE SLO gates even without a
        # baseline (the chaos smoke's zero-client-visible-errors
        # check).  A relative-only flag set does NOT - relative gates
        # need a baseline, and triggering the strict default
        # error_budget off an unrelated flag would fail runs nobody
        # asked to gate.
        violations = lg_report.gate(report, baseline=None, slo=absolute)
        print(lg_report.format_gate(violations, report, None))
        return 1 if violations else 0
    return 0


def _gate(argv: Sequence[str]) -> int:
    try:
        pos, flags = _split_flags(
            argv, known=("baseline", "tenant-slo", "error-slo")
            + tuple(_SLO_FLAGS),
            repeatable=("tenant-slo", "error-slo"),
        )
        if len(pos) != 1:
            raise ValueError("gate wants exactly one REPORT.json")
        if "baseline" not in flags:
            raise ValueError("gate needs --baseline OLD.json")
        slo = _slo_from_flags(flags)
    except ValueError as e:
        return _usage_error(str(e))
    try:
        report = lg_report.load_report(pos[0])
    except (OSError, ValueError, json.JSONDecodeError) as e:
        return _usage_error(f"cannot read report: {e}")
    return _run_gate(report, flags["baseline"], slo)


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        return _usage_error("missing subcommand (generate|replay|gate)")
    cmd, rest = argv[0], argv[1:]
    if cmd == "generate":
        return _generate(rest)
    if cmd == "replay":
        return _replay(rest)
    if cmd == "gate":
        return _gate(rest)
    return _usage_error(f"unknown subcommand {cmd!r}")


if __name__ == "__main__":
    sys.exit(main())
