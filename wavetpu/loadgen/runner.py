"""Replay a scenario trace against a live `wavetpu serve`.

Two drive modes (the standard loadgen pair):

 * OPEN loop - fire each request at its trace timestamp (optionally
   time-scaled by `speed`), regardless of whether earlier requests have
   returned: measures the server under the OFFERED load, including
   queue growth and 429 shedding.  This is the mode arrival-process
   realism (poisson / diurnal traces) exists for.
 * CLOSED loop - `concurrency` workers each hold at most one request in
   flight and send the next the moment the previous returns, ignoring
   timestamps: measures sustainable throughput and per-request latency
   at a fixed multiprogramming level.

Both modes run an optional WARMUP phase first (one request per distinct
scenario tier, excluded from the measurement) so a report's p99 is the
steady state, not the first-contact compile - unless the trace is
explicitly cache-adversarial (hotkey mix), where warmup is the thing
being measured and should be 0.

Every request carries a minted `X-Request-Id` header; the server echoes
it, tags its trace spans with it, and pins it as the exemplar on the
latency histogram bucket - so any outlier in the client-side report is
joinable to its server-side critical path via
`wavetpu trace-report --request ID`.  The response's `Server-Timing`
header is parsed into per-request queue/compile/execute/padding
seconds.  `/metrics` (Prometheus text view) is scraped before and after
the measured phase; the report layer turns the deltas into occupancy,
padding-waste, reject-rate and cold-vs-warm compile numbers for exactly
the replayed window.

Pure stdlib; never imports jax.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Union

from wavetpu.obs.tracing import format_traceparent, mint_span_id, \
    mint_trace_id


class PreflightError(RuntimeError):
    """The target server failed the health preflight - replaying a
    trace at a down/draining server would produce a garbage report."""


def _get(url: str, timeout: float, accept: Optional[str] = None):
    req = urllib.request.Request(
        url, headers={"Accept": accept} if accept else {}
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read().decode()


def preflight(base_url: str, timeout: float = 10.0) -> dict:
    """Assert the target is alive, READY, and accepting BEFORE replay:
    /healthz must answer 200 with status ok, `ready` not false (false =
    still warming or draining - a load balancer would not route there,
    so neither does the loadgen), and draining false.  Returns the
    health payload (uptime, last_batch_age_seconds - null means the
    server has never executed a batch, i.e. replay starts cold)."""
    url = base_url.rstrip("/") + "/healthz"
    try:
        status, text = _get(url, timeout)
        health = json.loads(text)
    except (OSError, ValueError, urllib.error.URLError) as e:
        raise PreflightError(f"cannot reach {url}: {e}")
    if status != 200 or health.get("status") != "ok":
        raise PreflightError(f"{url} unhealthy: {health}")
    if health.get("ready") is False:
        raise PreflightError(
            f"{url} not ready "
            f"(warming={health.get('warming')}, "
            f"draining={health.get('draining')})"
        )
    if health.get("draining"):
        raise PreflightError(f"{url} is draining (shutting down)")
    return health


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Minimal Prometheus 0.0.4 text parser: {sample_name_with_labels:
    value}.  Enough for metric deltas; exemplar suffixes and # EOF (the
    OpenMetrics render) are tolerated but the loadgen scrapes the plain
    text view anyway."""
    samples: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        if " # " in line:  # OpenMetrics exemplar suffix
            line = line.split(" # ", 1)[0]
        name, _, value = line.rpartition(" ")
        if not name:
            continue
        try:
            samples[name] = float(value.replace("+Inf", "inf"))
        except ValueError:
            continue
    return samples


def scrape_metrics(base_url: str, timeout: float = 30.0
                   ) -> Dict[str, float]:
    """One consistent /metrics cut in the Prometheus text view (it
    carries cells/solve-seconds/occupancy-sum counters the JSON
    snapshot summarizes away)."""
    _, text = _get(
        base_url.rstrip("/") + "/metrics", timeout, accept="text/plain"
    )
    return parse_prometheus_text(text)


def parse_server_timing(header: Optional[str]) -> Dict[str, float]:
    """`queue;dur=1.2, execute;dur=45` -> {"queue": 0.0012, ...}
    (seconds).  Unparseable entries are skipped - the report must not
    die on a proxy that rewrites headers."""
    out: Dict[str, float] = {}
    if not header:
        return out
    for part in header.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, params = part.partition(";")
        for p in params.split(";"):
            k, _, v = p.strip().partition("=")
            if k == "dur":
                try:
                    out[name.strip()] = float(v) / 1e3
                except ValueError:
                    pass
    return out


@dataclasses.dataclass
class RequestOutcome:
    """One replayed request, client-side view + parsed Server-Timing.
    `attempts` > 1 means the retrying client (`--retries`) absorbed
    retriable failures before this final status."""

    index: int
    scenario: str
    request_id: str
    status: int            # HTTP status; 0 = transport error/timeout
    latency_s: float
    t_sent: float          # offset from replay start
    server_timing: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )
    error: Optional[str] = None
    attempts: int = 1
    target: str = ""       # which --target URL served this request
    traceparent: str = ""  # W3C context the request carried (fleet
                           # trace join handle for trace-report)
    tenant: str = ""       # the record's tenant label (QoS traces)
    priority: str = ""     # the record's declared priority class
    # Measured oracle error from the response sidecar
    # (report.max_abs_error) - None when the server did not compute
    # errors (c2-field lane, --no-errors server).  Feeds the report's
    # per-tier error-budget table and the --error-slo gate.
    max_abs_error: Optional[float] = None


@dataclasses.dataclass
class ReplayResult:
    outcomes: List[RequestOutcome]
    warmup_outcomes: List[RequestOutcome]
    metrics_before: Dict[str, float]   # summed across targets
    metrics_after: Dict[str, float]    # summed across targets
    wall_seconds: float
    mode: str
    concurrency: int
    speed: float
    targets: List[str] = dataclasses.field(default_factory=list)
    failover: bool = False             # --failover: one HA client
    endpoint_failovers: int = 0        # times the client rotated
    # Share of replayed requests whose canonical body is a repeat of an
    # earlier one - the result-cache tier's opportunity ceiling (a
    # warm hit rate can never exceed it).
    duplicate_rate: float = 0.0


def duplicate_rate_of(records: Sequence[dict]) -> float:
    """1 - unique canonical bodies / total over `records` (0.0 when
    empty).  Canonicalized with sort_keys so key order never makes two
    identical requests look distinct."""
    bodies = [
        json.dumps(r.get("body") or {}, sort_keys=True) for r in records
    ]
    if not bodies:
        return 0.0
    return 1.0 - len(set(bodies)) / len(bodies)


def sum_metrics(cuts: Sequence[Dict[str, float]]) -> Dict[str, float]:
    """Sample-wise sum of several /metrics cuts - the fleet view of N
    replicas' counters (deltas of a sum = sum of deltas, so the report
    layer's delta math is unchanged)."""
    out: Dict[str, float] = {}
    for cut in cuts:
        for name, value in cut.items():
            out[name] = out.get(name, 0.0) + value
    return out


def _qos_headers(rec: dict) -> Dict[str, str]:
    """Map a record's multi-tenant QoS fields onto request headers:
    api_key -> X-Api-Key (the authenticated-router form), tenant ->
    X-Wavetpu-Tenant (open-router labeling; a keyed router strips it
    and stamps its own), priority -> X-Priority."""
    h: Dict[str, str] = {}
    if rec.get("api_key"):
        h["X-Api-Key"] = str(rec["api_key"])
    if rec.get("tenant"):
        h["X-Wavetpu-Tenant"] = str(rec["tenant"])
    if rec.get("priority"):
        h["X-Priority"] = str(rec["priority"])
    return h


def _sidecar_error(payload) -> Optional[float]:
    """report.max_abs_error from a parsed /solve body (None when the
    server did not compute errors, or the body is not the sidecar
    shape - a proxy error page must not kill the replay)."""
    if not isinstance(payload, dict):
        return None
    report = payload.get("report")
    if not isinstance(report, dict):
        return None
    v = report.get("max_abs_error")
    return float(v) if isinstance(v, (int, float)) else None


def _post_one(base_url: str, index: int, rec: dict, rid: str,
              t_sent: float, timeout: float,
              client=None) -> RequestOutcome:
    qos = _qos_headers(rec)
    if client is not None:
        # The retrying path (`--retries`): wavetpu.client.WavetpuClient
        # absorbs transport errors / 429 / 500 / 503 with jittered
        # backoff honoring Retry-After; the SAME request id rides every
        # attempt, so the report's join handles still resolve.
        out = client.solve(rec["body"], request_id=rid,
                           headers=qos or None)
        return RequestOutcome(
            index=index, scenario=rec.get("scenario", "?"),
            request_id=rid, status=out.status,
            latency_s=out.latency_s, t_sent=t_sent,
            server_timing=parse_server_timing(
                out.headers.get("Server-Timing")
            ),
            error=out.error, attempts=out.attempts,
            target=base_url.rstrip("/"),
            traceparent=out.traceparent,
            tenant=rec.get("tenant", "") or "",
            priority=rec.get("priority", "") or "",
            max_abs_error=_sidecar_error(out.payload),
        )
    body = json.dumps(rec["body"]).encode()
    traceparent = format_traceparent(mint_trace_id(), mint_span_id())
    req = urllib.request.Request(
        base_url.rstrip("/") + "/solve", data=body,
        headers={
            "Content-Type": "application/json",
            "X-Request-Id": rid,
            "traceparent": traceparent,
            **qos,
        },
    )
    t0 = time.perf_counter()
    status, timing, err, measured_err = 0, {}, None, None
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            raw = r.read()
            status = r.status
            timing = parse_server_timing(r.headers.get("Server-Timing"))
            try:
                measured_err = _sidecar_error(json.loads(raw))
            except (ValueError, TypeError):
                measured_err = None
    except urllib.error.HTTPError as e:
        status = e.code
        timing = parse_server_timing(e.headers.get("Server-Timing"))
        try:
            err = json.loads(e.read()).get("error")
        except Exception:
            err = str(e)
    except (OSError, urllib.error.URLError) as e:
        err = str(e)
    return RequestOutcome(
        index=index, scenario=rec.get("scenario", "?"), request_id=rid,
        status=status, latency_s=time.perf_counter() - t0,
        t_sent=t_sent, server_timing=timing, error=err,
        target=base_url.rstrip("/"), traceparent=traceparent,
        tenant=rec.get("tenant", "") or "",
        priority=rec.get("priority", "") or "",
        max_abs_error=measured_err,
    )


def _mint_rid(run_tag: str, index: int) -> str:
    return f"lg-{run_tag}-{index}"


def extend_for_duration(records: Sequence[dict], duration: float,
                        speed: float = 1.0) -> List[dict]:
    """The open-loop soak schedule: loop the trace (each lap offset by
    the trace span plus one mean gap, so laps never collide on the same
    timestamp) until the wall-clock budget `duration` is filled at
    replay `speed`.  Always returns at least one record."""
    records = list(records)
    span = records[-1]["t"]
    gap = (span / len(records)) if span > 0 else 0.01
    lap_len = span + max(gap, 1e-3)
    out: List[dict] = []
    lap = 0
    while (lap * lap_len) / speed < duration:
        for rec in records:
            t = rec["t"] + lap * lap_len
            if t / speed >= duration:
                break
            out.append(dict(rec, t=t))
        lap += 1
    if not out:
        out.append(dict(records[0], t=0.0))
    return out


def replay(
    base_url: Union[str, Sequence[str]],
    records: Sequence[dict],
    mode: str = "open",
    concurrency: int = 4,
    speed: float = 1.0,
    warmup: int = 0,
    timeout: float = 120.0,
    run_tag: Optional[str] = None,
    skip_preflight: bool = False,
    retries: int = 0,
    duration: Optional[float] = None,
    failover: bool = False,
) -> ReplayResult:
    """Drive `records` at `base_url`; returns outcomes + the /metrics
    cuts bracketing the measured phase.  `warmup` > 0 first serves up
    to that many requests - one per distinct scenario, sequential,
    excluded from the measurement - so steady-state numbers are not
    first-compile numbers.  `speed` > 1 time-compresses an open-loop
    trace (a 300 s recorded trace replayed at speed=10 offers 10x the
    QPS in 30 s).  `retries` > 0 sends every request through the
    retrying `wavetpu.client.WavetpuClient` (jittered backoff honoring
    Retry-After, request-id reuse - outcomes record `attempts`).
    `duration` turns the replay into a SOAK: the trace loops until the
    wall-clock budget elapses (open loop re-offsets each lap's
    timestamps; closed loop cycles the records), still reported as
    replay-window deltas like any run.

    `base_url` may be a LIST of targets (repeated `--target`): requests
    round-robin across them - the no-router way to drive a fleet of
    replicas directly.  Every target is preflighted; warmup serves each
    tier at EVERY target (one replica warm is not the fleet warm); the
    bracketing /metrics cuts are summed sample-wise across targets so
    the report's delta math sees the fleet as one server.  Outcomes
    carry `target` for the per-replica breakdown.

    `failover=True` flips the multi-target semantics from fan-out to
    HA: ALL targets become ONE multi-endpoint `WavetpuClient` (requires
    `retries` >= 1 - rotation happens on retry), so requests follow the
    client's endpoint cursor to whichever router is active and rotate
    away from a dead/standby one.  Preflight passes if ANY target is
    ready (a standby answers ready=false by design), warmup warms each
    tier once through the shared client, and a target whose /metrics
    cannot be scraped (e.g. the killed active) is dropped from the
    bracketing cuts instead of aborting the report."""
    if mode not in ("open", "closed"):
        raise ValueError(f"mode must be open|closed, got {mode!r}")
    if concurrency < 1:
        raise ValueError(f"concurrency must be >= 1, got {concurrency}")
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    if retries < 0:
        raise ValueError(f"retries must be >= 0, got {retries}")
    if duration is not None and duration <= 0:
        raise ValueError(f"duration must be > 0, got {duration}")
    if failover and retries < 1:
        raise ValueError(
            "failover mode needs retries >= 1 (the client rotates "
            "endpoints on retry; with no retry budget a dead router "
            "is a client-visible error)"
        )
    if isinstance(base_url, str):
        targets = [base_url.rstrip("/")]
    else:
        targets = [u.rstrip("/") for u in base_url]
    if not targets:
        raise ValueError("need at least one target")
    records = list(records)
    if not records:
        raise ValueError("empty trace")
    if not skip_preflight:
        if failover:
            # An HA set is healthy when ANYONE is ready - the standby
            # answers ready=false (not the lease holder) by design.
            errs: List[str] = []
            for t in targets:
                try:
                    preflight(t)
                    break
                except PreflightError as e:
                    errs.append(str(e))
            else:
                raise PreflightError(
                    "no ready endpoint in the HA set: "
                    + "; ".join(errs)
                )
        else:
            for t in targets:
                preflight(t)
    if run_tag is None:
        # Unique enough across replays against one server; hex keeps it
        # inside the server's sanitized request-id alphabet.
        run_tag = f"{int(time.time() * 1e3) & 0xFFFFFFFF:x}"
    clients: Dict[str, object] = {}
    shared = None
    if retries > 0:
        from wavetpu.client import WavetpuClient

        if failover:
            # ONE client over the whole HA set: its endpoint cursor is
            # the failover state, shared by every replay thread.
            shared = WavetpuClient(targets, retries=retries,
                                   timeout=timeout)
            clients = {t: shared for t in targets}
        else:
            clients = {
                t: WavetpuClient(t, retries=retries, timeout=timeout)
                for t in targets
            }

    def _target(i: int) -> str:
        if shared is not None:
            # Label outcomes with the endpoint the HA client currently
            # points at (best-effort: a mid-request rotation lands on
            # the next one).
            return shared.base_url
        return targets[i % len(targets)]

    def _scrape_all() -> Dict[str, float]:
        cuts = []
        for t in targets:
            try:
                cuts.append(scrape_metrics(t))
            except (OSError, ValueError, urllib.error.URLError):
                # In an HA drill the killed active cannot be scraped;
                # its counters live on in the survivors' store-restored
                # state.  Outside failover mode a dead target is a
                # configuration error worth dying on.
                if not failover:
                    raise
        return sum_metrics(cuts)

    warmup_outcomes: List[RequestOutcome] = []
    if warmup > 0:
        seen = set()
        wi = 0
        for rec in records:
            tier = rec.get("scenario", "?")
            if tier in seen or len(seen) >= warmup:
                continue
            seen.add(tier)
            # Failover mode warms through the shared client (whichever
            # router is active proxies to the fleet); fan-out mode
            # warms every target - one replica warm is not the fleet
            # warm.
            for t in ([_target(0)] if failover else targets):
                warmup_outcomes.append(_post_one(
                    t, wi, rec, _mint_rid(run_tag + "w", wi), 0.0,
                    timeout, clients.get(t),
                ))
                wi += 1

    if duration is not None and mode == "open":
        records = extend_for_duration(records, duration, speed)

    metrics_before = _scrape_all()
    t_start = time.perf_counter()

    if duration is not None and mode == "closed":
        # Soak: `concurrency` workers cycle the trace until the budget
        # elapses; outcomes accumulate (the request count is a result,
        # not an input).
        soak: List[RequestOutcome] = []
        nxt = {"i": 0}
        lock = threading.Lock()
        stop_at = t_start + duration

        def soak_worker():
            while time.perf_counter() < stop_at:
                with lock:
                    i = nxt["i"]
                    nxt["i"] = i + 1
                t = _target(i)
                out = _post_one(
                    t, i, records[i % len(records)],
                    _mint_rid(run_tag, i),
                    time.perf_counter() - t_start, timeout,
                    clients.get(t),
                )
                with lock:
                    soak.append(out)

        threads = [
            threading.Thread(target=soak_worker, daemon=True)
            for _ in range(concurrency)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(duration + timeout + 30.0)
        with lock:
            done = sorted(soak, key=lambda o: o.index)
        return ReplayResult(
            outcomes=done, warmup_outcomes=warmup_outcomes,
            metrics_before=metrics_before,
            metrics_after=_scrape_all(),
            wall_seconds=time.perf_counter() - t_start, mode=mode,
            concurrency=concurrency, speed=speed, targets=targets,
            failover=failover,
            endpoint_failovers=(
                shared.endpoint_failovers if shared is not None else 0
            ),
            duplicate_rate=duplicate_rate_of(records),
        )

    outcomes: List[Optional[RequestOutcome]] = [None] * len(records)

    def fire(i: int, rec: dict) -> None:
        t = _target(i)
        outcomes[i] = _post_one(
            t, i, rec, _mint_rid(run_tag, i),
            time.perf_counter() - t_start, timeout, clients.get(t),
        )

    if mode == "open":
        threads = []
        for i, rec in enumerate(records):
            delay = rec.get("t", 0.0) / speed - (
                time.perf_counter() - t_start
            )
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=fire, args=(i, rec), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout + 30.0)
    else:
        nxt = {"i": 0}
        lock = threading.Lock()

        def worker():
            while True:
                with lock:
                    i = nxt["i"]
                    if i >= len(records):
                        return
                    nxt["i"] = i + 1
                fire(i, records[i])

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(min(concurrency, len(records)))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout * len(records) + 30.0)

    wall = time.perf_counter() - t_start
    metrics_after = _scrape_all()
    done = [
        o if o is not None else RequestOutcome(
            index=i, scenario=records[i].get("scenario", "?"),
            request_id=_mint_rid(run_tag, i), status=0,
            latency_s=timeout, t_sent=0.0, error="never completed",
            target=_target(i),
            tenant=records[i].get("tenant", "") or "",
            priority=records[i].get("priority", "") or "",
        )
        for i, o in enumerate(outcomes)
    ]
    return ReplayResult(
        outcomes=done, warmup_outcomes=warmup_outcomes,
        metrics_before=metrics_before, metrics_after=metrics_after,
        wall_seconds=wall, mode=mode, concurrency=concurrency,
        speed=speed, targets=targets, failover=failover,
        endpoint_failovers=(
            shared.endpoint_failovers if shared is not None else 0
        ),
        duplicate_rate=duplicate_rate_of(records),
    )
