"""Workload observability: scenario traces, replay harness, SLO gate.

`wavetpu loadgen` closes the last observability gap: PR 5 made ONE
request's latency attributable (queue vs compile vs execute vs padding
spans); this package makes the service observable under realistic MIXED
traffic - the sustained-workload methodology the scale-out papers in
PAPERS.md report by (arXiv:2506.09242 multi-GPU PALABOS,
arXiv:2108.11076 TPU-pod), and the measurement harness every ROADMAP
direction (pod-scale serving, cold-start elimination, comm overlap,
autotuned tiers) must be judged against: tail latency under load, not
solo-solve Gcell/s.

    trace.py   JSONL scenario-trace format, synthetic generators
               (uniform / poisson / diurnal / hotkey), and the recorder
               `wavetpu serve --record-trace` uses to capture real
               /solve traffic into replayable traces
    runner.py  open-/closed-loop replay against a live server: preflight
               health check, warmup phase, per-request Server-Timing
               capture, /metrics scrapes bracketing the run
    report.py  loadgen_report.json builder + the regression gate
               (`--baseline OLD.json` diffs, exit != 0 on SLO violation)
    cli.py     `wavetpu loadgen generate | replay | gate`

Pure stdlib HTTP client + host-side math; never imports jax - the load
generator must be runnable from a machine that has no accelerator.
"""
