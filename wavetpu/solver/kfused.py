"""Temporally fused k-step solver (single device).

Drives `stencil_pallas.fused_kstep`: the time loop scans over BLOCKS of k
leapfrog layers, each block one pallas call that keeps the intermediate
layers in VMEM and writes only the block's last two layers to HBM - the
1-step path's ~3 HBM field-streams per step become (4 + 4k/bx)/k.  Measured
on a single v5e at the flagship N=512/1000-step config with per-layer
errors on: 20.3 Gcell/s (1-step kernel) -> 43.8 Gcell/s (k=4).

Per-layer L-inf abs/rel errors remain reported for EVERY layer - the
kernel emits per-x-plane maxes for the in-VMEM intermediate layers (the
separable-oracle factorization, stencil_pallas.py section comment), and
this module applies the tiny per-plane rescales and the x!=0 interior
mask outside (reference error contract: mpi_new.cpp:335-345,
openmp_sol.cpp:169-190).

Each substep is op-for-op the 1-step pallas kernel's update, so k-fused
layers are bitwise identical to 1-step pallas layers: a solve may stop at
any layer (`stop_step`), checkpoint, and resume with either path
(tests/test_kfused.py pins this).

The reference has no counterpart to fuse-k (every variant launches one
kernel per layer with a global sync between); SURVEY.md section 7's perf
plan called the HBM stream count the budget to beat, and this is the
mechanism that beats it.

Variable wave speed composes with the onion: `c2tau2_field` threads the
tau^2 c^2(x,y,z) slab through every k-block as its own onion (slab +
k-plane halos, stencil_pallas._kstep_kernel has_field) and through the
1-step bootstrap/remainder kernels, keeping the bitwise-mixing contract
with the 1-step variable-c path (tests/test_kfused_varc.py).  The field
onion's VMEM cost caps the block choice (choose_kstep_block field=True:
k=2/bx=4 at N=512 under the calibrated budget; k=4/bx=4 models ~5% over
the physical ceiling and stays reachable via an explicit block_x for
on-chip attempts - bench.py's kfused_varc row records the outcome).
There is no analytic oracle for variable c, so a field requires
compute_errors=False.  The compensated (Kahan) scheme takes the field
through solver/kfused_comp.py's velocity-form onion.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.obs import metrics as obs_metrics
from wavetpu.solver import leapfrog
from wavetpu.verify import oracle


def _oracle_parts(problem: Problem, f_dtype, phase: float = oracle.TWO_PI):
    """Precomputed separable-oracle pieces for the in-kernel error path.

    syz / rsyz are the (N, N) planes sy*sz and 1/|sy*sz| (exact-zero cells
    -> 0: there u = f = 0 and the reference's NaN-skip reports 0,
    oracle.layer_errors).  inv_absx is the per-x-plane rescale 1/|sx| with
    the x=0 interior exclusion and exact zeros folded in.  `phase` is the
    analytic solution's time phase (per-lane in the ensemble engine).
    """
    sx, sy, sz = oracle.spatial_factors(problem, f_dtype)
    ct = oracle.time_factor_table(problem, f_dtype, phase)
    syz = sy[:, None] * sz[None, :]
    rsyz = jnp.where(
        syz == 0, jnp.asarray(0, f_dtype),
        1.0 / jnp.where(syz == 0, jnp.asarray(1, f_dtype), syz),
    )
    rsyz = jnp.abs(rsyz)
    absx = jnp.abs(sx)
    xmask = jnp.asarray(np.arange(problem.N) != 0)
    inv_absx = jnp.where(
        xmask & (absx != 0),
        1.0 / jnp.where(absx == 0, jnp.asarray(1, f_dtype), absx),
        jnp.asarray(0, f_dtype),
    )
    return sx, ct, syz, rsyz, xmask, inv_absx


def _layer_rows_local(u, sxct_row, syz_c, rsyz_c, f):
    """(1, nl) per-x-plane abs/rel error maxes of one stored layer's local
    block vs its oracle slice - the jnp bootstrap-layer counterpart of the
    kernels' in-onion rows, shared by every sharded k-fused solver (a
    change to this contract must not diverge between them)."""
    diff = jnp.abs(u.astype(f) - sxct_row[:, None, None] * syz_c[None])
    d = jnp.max(diff, axis=(1, 2))[None]
    r = jnp.max(diff * rsyz_c[None], axis=(1, 2))[None]
    return d, r


def _block_errors(dmax, rmax, ctk, xmask, inv_absx):
    """(k,) abs / rel layer errors from the kernel's (k, N) plane maxes."""
    abs_e = jnp.max(jnp.where(xmask[None, :], dmax, 0.0), axis=1)
    rel_e = jnp.max(
        jnp.where(xmask[None, :], rmax * inv_absx[None, :], 0.0), axis=1
    )
    ictk = jnp.abs(ctk)
    rel_e = jnp.where(
        ictk != 0, rel_e / jnp.where(ictk == 0, 1.0, ictk), 0.0
    )
    return abs_e, rel_e


def _validate(problem: Problem, k: int, c2tau2_field=None,
              compute_errors: bool = True,
              phase: float = oracle.TWO_PI):
    if k < 2:
        raise ValueError(f"k must be >= 2 (got {k}); use leapfrog.solve "
                         "with the pallas step for k=1")
    if problem.N % k:
        raise ValueError(f"k={k} must divide N={problem.N}")
    if c2tau2_field is not None and compute_errors:
        raise ValueError(
            "variable-c runs have no analytic oracle; pass "
            "compute_errors=False with c2tau2_field"
        )
    if c2tau2_field is not None and phase != oracle.TWO_PI:
        raise ValueError(
            "a shifted phase bootstraps layer 1 from the analytic "
            "solution, which only exists for constant speed; use the "
            "reference phase with c2tau2_field"
        )


def _make_march(problem, dtype, k, compute_errors, block_x, interpret,
                nsteps, c2tau2_field=None, chunk_len=None,
                phase: float = oracle.TWO_PI):
    """Shared march: k-fused blocks + a 1-step remainder tail.

    `make_kfused_solver`, `resume_kfused`, and `make_chunk_runner` MUST
    use this single implementation - the bitwise-equal-resume guarantee
    rests on every path emitting the identical per-layer op sequence (the
    same reasoning as leapfrog._scan_layers being shared).

    Returns `march(u_prev, u_cur, start)` -> (u_prev, u_cur, abs, rel)
    covering layers start+1..nsteps (`start` must be a Python int).  With
    `chunk_len` set, the march instead covers exactly chunk_len layers
    from a RUNTIME `start` (nblocks/remainder derive from chunk_len, so
    one compiled program serves every equal-length chunk of a supervised
    march); on block-aligned starts the op sequence equals the
    uninterrupted march's prefix.

    With `c2tau2_field` every k-block runs the variable-c onion and the
    bootstrap/remainder run the 1-step variable-c pallas kernel - the
    same ParamStep plumbing as leapfrog.make_solver, so the field is a
    runtime argument, never an HLO literal.
    """
    f = stencil_ref.compute_dtype(dtype)
    sx, ct, syz, rsyz, xmask, inv_absx = _oracle_parts(problem, f, phase)
    errors = leapfrog._error_fn(problem, dtype, phase)
    # The field enters the jitted program as a RUNTIME argument (the
    # `*field_params` splat below: () constant-c, (field,) variable-c) -
    # closing over it would embed an N^3 HLO literal (leapfrog.ParamStep).
    step1 = stencil_pallas.make_step_fn(
        interpret=interpret, c2tau2_field=(
            None if c2tau2_field is None
            else jnp.asarray(c2tau2_field, dtype=f)
        )
    )
    step1_fn, params0 = leapfrog._as_param_step(step1)
    has_field = c2tau2_field is not None

    def kblock(carry, nstart, field_params):
        u_prev, u = carry
        ctk = lax.dynamic_slice(ct, (nstart + 1,), (k,))
        sxct = ctk[:, None] * sx[None, :]
        up, uc, dmax, rmax = stencil_pallas.fused_kstep(
            u_prev, u, syz, rsyz, sxct,
            k=k, coeff=problem.a2tau2, inv_h2=problem.inv_h2,
            c2tau2_field=field_params[0] if has_field else None,
            block_x=block_x, interpret=interpret,
            with_errors=compute_errors,
        )
        if compute_errors:
            abs_e, rel_e = _block_errors(dmax, rmax, ctk, xmask, inv_absx)
        else:
            abs_e = rel_e = jnp.zeros((k,), f)
        return (up, uc), (abs_e, rel_e)

    def march(u_prev, u_cur, start, *field_params):
        if chunk_len is None:
            nblocks = (nsteps - start) // k
            rem = (nsteps - start) - nblocks * k
        else:
            nblocks = chunk_len // k
            rem = chunk_len - nblocks * k
        starts = start + k * jnp.arange(nblocks)
        (u_prev, u_cur), (abs_b, rel_b) = lax.scan(
            lambda carry, nstart: kblock(carry, nstart, field_params),
            (u_prev, u_cur), starts,
        )
        abs_parts = [abs_b.reshape(-1)]
        rel_parts = [rel_b.reshape(-1)]
        if rem:
            params = field_params[0] if has_field else params0
            rem_start = (
                nsteps - rem if chunk_len is None
                else start + chunk_len - rem
            )
            (u_prev, u_cur), (ra, rr) = leapfrog._scan_layers_xs(
                problem, step1_fn, params, errors, compute_errors, dtype,
                u_prev, u_cur,
                rem_start + 1 + jnp.arange(rem, dtype=jnp.int32),
            )
            abs_parts.append(ra)
            rel_parts.append(rr)
        return u_prev, u_cur, jnp.concatenate(abs_parts), jnp.concatenate(
            rel_parts)

    return march, step1_fn, errors


def make_kfused_solver(
    problem: Problem,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    block_x: Optional[int] = None,
    interpret: bool = False,
    c2tau2_field=None,
    phase: float = oracle.TWO_PI,
):
    """Build the jitted k-fused solver; returns `(runner, run_params)`
    where `run_params` is the runtime-argument tuple to call the runner
    with - () for constant speed (a zero-arg runner, as before), or the
    materialized device field for a variable-c solve (the field must ride
    as an argument, not a constant; see leapfrog.ParamStep).

    Layers 0/1 bootstrap exactly as `leapfrog.make_solver` with the pallas
    1-step kernel; then (nsteps-1)//k fused blocks; a remainder of
    (nsteps-1) % k layers runs the 1-step kernel (same ops, so the tail is
    seamless).  Requires k >= 2 and N % k == 0; a field requires
    compute_errors=False (no analytic oracle) and the reference phase
    (a shifted phase needs the analytic layer-1 bootstrap, which does
    not exist under variable c).
    """
    _validate(problem, k, c2tau2_field, compute_errors, phase)
    nsteps = problem.timesteps if stop_step is None else stop_step
    if not 1 <= nsteps <= problem.timesteps:
        raise ValueError(
            f"stop_step must be in [1, {problem.timesteps}], got {nsteps}"
        )
    f = stencil_ref.compute_dtype(dtype)
    # Materialize the field ONCE; _make_march's jnp.asarray on this
    # committed device array is a no-copy, so the step closure and the
    # runtime argument share one N^3 buffer (no duplicate HBM/upload).
    field_dev = None
    if c2tau2_field is not None:
        field_dev = leapfrog.ParamStep.materialize(
            jnp.asarray(c2tau2_field, dtype=f)
        )
    march, step1_fn, errors = _make_march(
        problem, dtype, k, compute_errors, block_x, interpret, nsteps,
        field_dev, phase=phase,
    )

    def run(*field_params):
        u0 = leapfrog.initial_layer0(problem, dtype, phase)
        params = field_params[0] if field_params else ()
        if phase != oracle.TWO_PI:
            # Shifted phases have nonzero initial velocity, which the
            # step-derived Taylor bootstrap cannot represent; layer 1 is
            # the exact analytic initialization instead (statically
            # absent at the reference phase - see leapfrog.make_solver).
            u1 = leapfrog.analytic_layer(problem, dtype, phase, 1)
        else:
            u1 = (0.5 * (
                u0.astype(f) + step1_fn(u0, u0, problem, params).astype(f)
            )).astype(dtype)
        a0 = r0 = jnp.zeros((), f)
        if compute_errors:
            a1, r1 = errors(u1, 1)
        else:
            a1 = r1 = jnp.zeros((), f)
        u_prev, u_cur, abs_t, rel_t = march(u0, u1, 1, *field_params)
        abs_all = jnp.concatenate([jnp.stack([a0, a1]), abs_t])
        rel_all = jnp.concatenate([jnp.stack([r0, r1]), rel_t])
        return u_prev, u_cur, abs_all, rel_all

    run_params = () if field_dev is None else (field_dev,)
    return jax.jit(run), run_params


def solve_kfused(
    problem: Problem,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    block_x: Optional[int] = None,
    interpret: bool = False,
    c2tau2_field=None,
    phase: float = oracle.TWO_PI,
) -> leapfrog.SolveResult:
    """Compile + run the k-fused solve (reference timing phases as
    `leapfrog.solve`).  `c2tau2_field` (host (N,N,N) tau^2 c^2 array,
    `stencil_ref.make_c2tau2_field`) selects the variable-c onion; pair
    it with compute_errors=False."""
    runner, run_params = make_kfused_solver(
        problem, dtype, k, compute_errors, stop_step, block_x, interpret,
        c2tau2_field, phase,
    )
    (u_prev, u_cur, abs_all, rel_all), init_s, solve_s = (
        leapfrog._timed_compile_run(
            runner, run_params, sync=lambda out: np.asarray(out[2])
        )
    )
    result = leapfrog.SolveResult(
        problem=problem,
        u_prev=u_prev,
        u_cur=u_cur,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=stop_step,
        final_step=stop_step if stop_step is not None else problem.timesteps,
    )
    obs_metrics.record_solve(
        result, "kfused", k=k, with_field=c2tau2_field is not None,
        block_x=block_x,
    )
    return result


def resume_kfused(
    problem: Problem,
    u_prev,
    u_cur,
    start_step: int,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: bool = False,
    c2tau2_field=None,
) -> leapfrog.SolveResult:
    """Re-enter the k-fused march at layer `start_step`.

    Because every k-fused substep is op-identical to the 1-step pallas
    kernel's step, a checkpoint written by either path resumes bitwise-
    equal under either path (error arrays cover start_step+1..timesteps,
    earlier entries zero, as `leapfrog.resume`).  A variable-c checkpoint
    resumes under the SAME field, re-passed by the caller (checkpoints
    store state, not the coefficient field).
    """
    _validate(problem, k, c2tau2_field, compute_errors)
    nsteps = problem.timesteps
    if not 1 <= start_step <= nsteps:
        raise ValueError(
            f"start_step must be in [1, {nsteps}], got {start_step}"
        )
    f = stencil_ref.compute_dtype(dtype)
    # One materialization shared by the step closure and the runtime
    # argument (see make_kfused_solver).
    field_dev = None
    if c2tau2_field is not None:
        field_dev = leapfrog.ParamStep.materialize(
            jnp.asarray(c2tau2_field, dtype=f)
        )
    march, _, _ = _make_march(
        problem, dtype, k, compute_errors, block_x, interpret, nsteps,
        field_dev,
    )

    def run(u_prev, u_cur, *field_params):
        u_prev, u_cur, abs_t, rel_t = march(
            u_prev, u_cur, start_step, *field_params
        )
        head = jnp.zeros((start_step + 1,), f)
        return (
            u_prev, u_cur,
            jnp.concatenate([head, abs_t]),
            jnp.concatenate([head, rel_t]),
        )

    args = (jnp.asarray(u_prev, dtype), jnp.asarray(u_cur, dtype))
    if field_dev is not None:
        args = args + (field_dev,)
    (u_p, u_c, abs_all, rel_all), init_s, solve_s = (
        leapfrog._timed_compile_run(
            jax.jit(run), args, sync=lambda out: np.asarray(out[2])
        )
    )
    return leapfrog.SolveResult(
        problem=problem,
        u_prev=u_p,
        u_cur=u_c,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=nsteps - start_step,
        final_step=nsteps,
    )


def make_chunk_runner(
    problem: Problem,
    dtype=jnp.float32,
    length: int = 4,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: bool = False,
    c2tau2_field=None,
):
    """Fixed-length k-fused re-entry for supervised solves.

    Returns `(runner, run_params)`; `runner(u_prev, u_cur, start,
    *run_params)` marches layers start+1..start+length with a RUNTIME
    `start` - one compiled program per chunk length, reused across every
    chunk (run/supervisor.py's no-retrace contract).  Chunks whose length
    is a multiple of k on starts aligned to the uninterrupted march's
    block grid reproduce its op sequence exactly; a trailing length % k
    runs the 1-step kernel, as the uninterrupted remainder tail does.
    """
    _validate(problem, k, c2tau2_field, compute_errors)
    if length < 1:
        raise ValueError(f"chunk length must be >= 1, got {length}")
    f = stencil_ref.compute_dtype(dtype)
    field_dev = None
    if c2tau2_field is not None:
        field_dev = leapfrog.ParamStep.materialize(
            jnp.asarray(c2tau2_field, dtype=f)
        )
    march, _, _ = _make_march(
        problem, dtype, k, compute_errors, block_x, interpret, None,
        field_dev, chunk_len=length,
    )

    def run(u_prev, u_cur, start, *field_params):
        return march(u_prev, u_cur, start, *field_params)

    run_params = () if field_dev is None else (field_dev,)
    return jax.jit(run), run_params
