"""Distributed solver: one jitted `shard_map` program over a 3D device mesh.

The analog of the reference's MPI variants (mpi_new.cpp:324-372 fused loop,
mpi_sol.cpp:374-478 topology setup) redesigned for ICI: the whole solve -
layer-0/1 bootstrap, the time loop, halo exchange, boundary masking, and the
cross-device error max-reduction - is a single XLA computation per chip.
There is no host round-trip anywhere: halos ride `ppermute` (comm/halo.py)
and the per-layer L-inf errors are `lax.pmax`-reduced in-program (the
counterpart of the end-of-run MPI_Reduce(MPI_MAX), mpi_new.cpp:360-361).

The hot kernel is injectable, like `leapfrog.make_solver`'s `step_fn`:
`kernel="pallas"` runs the fused Pallas slab kernel on every shard - the
true analog of the reference's flagship binary, where each MPI rank drives
the CUDA kernel (cuda_sol.cpp:381-443 launching calculate_layer,
cuda_sol_kernels.cu:24-47); `kernel="roll"` keeps the pure-XLA
halo-extended stencil as the semantic reference.  `overlap=True` issues the
6 `ppermute`s with no data dependence on the bulk update so XLA's scheduler
can fly them during the stencil, then patches the 6 faces - the
compute/communication overlap the reference leaves on the table (its
exchange is fully serialized with the loop, mpi_new.cpp:327-352).

Sharding model (see core/grid.py): the fundamental (N, N, N) state is
zero-padded per axis to a multiple of the mesh dim and laid out
PartitionSpec("x", "y", "z").  All 1-D problem data (analytic factors, error
masks, boundary masks) is precomputed on host in f64, padded, and sharded
along its own axis, so every shard receives exactly its slice - the moral
equivalent of the reference's per-rank x_0/y_0/z_0 offsets
(mpi_sol.cpp:423-429) without any per-rank branching.  A variable-c field
(tau^2 c^2(x,y,z)) is padded the same way and rides through the program as
a runtime argument sharded P("x","y","z") - never a closed-over constant
(see solver.leapfrog.ParamStep for why).

bf16 state computes in f32 (stencil_ref.compute_dtype), matching the
single-device solver's bf16-storage / f32-accumulation contract.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from wavetpu.comm import halo
from wavetpu.core.grid import AXIS_NAMES, Topology, build_mesh, choose_mesh_shape
from wavetpu.core.problem import Problem
from wavetpu import compat
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.obs import metrics as obs_metrics
from wavetpu.solver.leapfrog import SolveResult
from wavetpu.verify import oracle


def _padded_factors(problem: Problem, topo: Topology, dtype):
    """Host-f64 1-D analytic factors on the padded per-axis grids.

    Pad cells get factor 0, so the padded analytic field vanishes there
    (consistent with the zero-padded state).  The factor formulas live in
    oracle.spatial_factors_np (single source of truth).
    """
    n = problem.N

    def pad(v, p):
        out = np.zeros(p, dtype=np.float64)
        out[:n] = v
        return out

    factors = oracle.spatial_factors_np(problem, n)
    return tuple(
        jnp.asarray(pad(v, p), dtype=dtype)
        for v, p in zip(factors, topo.padded)
    )


def _masks(problem: Problem, topo: Topology, dtype):
    """1-D boundary multipliers and error-interior masks, padded.

    bc (multiplied into every updated layer):
      x: 1 for real cells (global i < N) - the x=0 plane is a live periodic
         cell; 0 for pad cells.
      y/z: 0 at the stored Dirichlet plane (global 0) and pad cells
         (reference zeroes its y/z faces each step, openmp_sol.cpp:104-112).
    err (error reduction, reference interior = global 1..N-1 per axis,
         openmp_sol.cpp:174-176): global index != 0 and < N.

    The Pallas kernel reproduces exactly this bc predicate in-register
    from global offsets (the fused mask in stencil_pallas._sharded_kernel)
    so the two kernels stay interchangeable.
    """
    n = problem.N
    bc, err = [], []
    for axis, p in enumerate(topo.padded):
        g = np.arange(p)
        real = g < n
        if axis == 0:
            bc.append(real.astype(np.float64))
        else:
            bc.append((real & (g != 0)).astype(np.float64))
        err.append(real & (g != 0))
    bcs = tuple(jnp.asarray(b, dtype=dtype) for b in bc)
    errs = tuple(jnp.asarray(e) for e in err)
    return bcs, errs


def pad_field(field: np.ndarray, topo: Topology) -> np.ndarray:
    """Zero-pad an (N, N, N) host field to the topology's padded shape."""
    field = np.asarray(field)
    out = np.zeros(topo.padded, dtype=field.dtype)
    n = field.shape
    out[: n[0], : n[1], : n[2]] = field
    return out


def _shard_offsets(topo: Topology):
    """This shard's global cell offsets, int32 (3,) - must run inside
    shard_map.  The analog of the reference's per-rank x_0/y_0/z_0
    (mpi_sol.cpp:423-429)."""
    return jnp.stack(
        [
            lax.axis_index(name).astype(jnp.int32) * topo.block[axis]
            for axis, name in enumerate(AXIS_NAMES)
        ]
    )


def _self_ghosts(u):
    """The cyclic wrap planes of a block, shaped like collect_ghosts output.

    Feeding these to the sharded kernel makes it exactly periodic within the
    shard - the bulk update of overlap mode, and the correct ghosts for any
    axis whose mesh dim is 1.
    """
    ghosts = []
    for axis in range(3):
        b = u.shape[axis]
        lo = lax.slice_in_dim(u, b - 1, b, axis=axis)
        hi = lax.slice_in_dim(u, 0, 1, axis=axis)
        ghosts.append((lo, hi))
    return tuple(ghosts)


def _face_ext(u, ghosts, axis: int, p: int):
    """Halo-extended 3-plane slab around face plane `p` of `axis`.

    Returns a (3, by+2, bz+2)-shaped (axis-permuted) array whose interior
    `laplacian_ext` is the correct update stencil for the face plane,
    including its edge/corner cells: the out-of-block `axis` neighbour is
    the ghost plane, transverse neighbours come from the block itself, and
    the face plane's transverse *edges* come from the transverse ghosts
    (which `collect_ghosts` provides for every axis - local wrap slices on
    1-dim mesh axes).  Even shard splits only (overlap mode's contract).
    """
    b = u.shape[axis]
    glo, ghi = ghosts[axis]
    parts = []
    if p == 0:
        parts.append(glo)
    if b == 1:
        parts.append(u)
    else:
        lo = max(p - 1, 0)
        parts.append(lax.slice_in_dim(u, lo, min(p + 2, b), axis=axis))
    if p == b - 1:
        parts.append(ghi)
    core = jnp.concatenate(parts, axis)
    pads = [(1, 1)] * 3
    pads[axis] = (0, 0)
    ext = jnp.pad(core, pads)
    # Transverse ghost edges of the central (face) plane.
    for a in range(3):
        if a == axis:
            continue
        tlo, thi = ghosts[a]
        tlo = lax.slice_in_dim(tlo, p, p + 1, axis=axis)
        thi = lax.slice_in_dim(thi, p, p + 1, axis=axis)
        starts_lo = [0] * 3
        starts_hi = [0] * 3
        for d in range(3):
            if d == axis:
                starts_lo[d] = starts_hi[d] = 1  # central plane
            elif d == a:
                starts_lo[d] = 0
                starts_hi[d] = ext.shape[d] - 1
            else:
                starts_lo[d] = starts_hi[d] = 1
        ext = lax.dynamic_update_slice(ext, tlo, starts_lo)
        ext = lax.dynamic_update_slice(ext, thi, starts_hi)
    return ext


def _make_local_step(
    problem: Problem,
    topo: Topology,
    dtype,
    kernel: str,
    overlap: bool,
    interpret: bool,
    exchange: bool = True,
):
    """Build the per-shard step function `step(u_prev, u, bc, field)`.

    Returns the full leapfrog-form update u_next = 2u - u_prev + C*lap(u)
    with boundary/pad masking applied, where C is the scalar a2tau2 or the
    per-cell `field` block.  Runs inside shard_map.  The layer-1 bootstrap
    derives from this same function ((u0 + step(u0, u0))/2), so any kernel
    choice bootstraps consistently.

    `exchange=False` substitutes the local wrap planes for the ppermute'd
    ghosts - the identical program minus ICI traffic.  It exists ONLY for
    the phase-timing probe (solver/timing.py): the numbers it produces are
    wrong at shard boundaries whenever a mesh axis is >1.
    """
    if kernel not in ("roll", "pallas"):
        raise ValueError(f"kernel must be 'roll' or 'pallas', got {kernel!r}")
    f = stencil_ref.compute_dtype(dtype)
    n = problem.N
    inv_h2 = problem.inv_h2
    c_full = problem.a2tau2
    uneven = any(r != b for r, b in zip(topo.r_last, topo.block))
    if overlap and uneven:
        raise ValueError(
            "overlap mode requires N divisible by every mesh dim "
            f"(N={n}, mesh={topo.mesh_shape})"
        )
    multi_axes = [a for a in range(3) if topo.mesh_shape[a] > 1]

    def pallas_update(u_prev, u, ghosts, field):
        return stencil_pallas.sharded_fused_step(
            u_prev, u, ghosts, _shard_offsets(topo), n,
            inv_h2=inv_h2, mesh_shape=topo.mesh_shape, r_last=topo.r_last,
            alpha=2.0, beta=1.0,
            coeff=None if field is not None else c_full,
            c2tau2_block=field, interpret=interpret, compute_dtype=f,
        )

    def ext_update(u_prev, u, ext, bc, field):
        """Halo-extended XLA stencil, stencil_ref.leapfrog_step op order."""
        lap = stencil_ref.laplacian_ext(ext.astype(f), inv_h2)
        coeff = (
            jnp.asarray(c_full, f) if field is None else field.astype(f)
        )
        u_next = 2.0 * u.astype(f) - u_prev.astype(f) + coeff * lap
        return (u_next * bc.astype(f)).astype(dtype)

    def step_serial(u_prev, u, bc, field):
        ghosts = (
            halo.collect_ghosts(u, topo) if exchange else _self_ghosts(u)
        )
        if kernel == "pallas":
            u_in = halo.absorb_hi_ghosts(u, ghosts, topo)
            return pallas_update(u_prev, u_in, ghosts, field)
        ext = halo.place_ghosts(u, ghosts, topo)
        return ext_update(u_prev, u, ext, bc, field)

    def step_overlap(u_prev, u, bc, field):
        # The 6 ppermutes launch first and feed ONLY the face patches, so
        # the scheduler can overlap them with the bulk update below.
        ghosts = (
            halo.collect_ghosts(u, topo) if exchange else _self_ghosts(u)
        )
        if kernel == "pallas":
            bulk = pallas_update(u_prev, u, _self_ghosts(u), field)
        else:
            uc = u.astype(f)
            coeff = (
                jnp.asarray(c_full, f) if field is None else field.astype(f)
            )
            u_next = (
                2.0 * uc
                - u_prev.astype(f)
                + coeff * stencil_ref.laplacian(uc, inv_h2)
            )
            bulk = (u_next * bc.astype(f)).astype(dtype)
        if not multi_axes:
            return bulk
        # Patch the faces whose wrap neighbour crossed a shard boundary.
        # Each face's 3-plane extension is assembled directly from ghost +
        # block slices (never the full (b+2)^3 padded block - that would
        # re-add a block-sized copy per step to the loop the overlap exists
        # to shorten).
        for axis in multi_axes:
            b = topo.block[axis]
            for p in sorted({0, b - 1}):
                ext_f = _face_ext(u, ghosts, axis, p).astype(f)
                lap = stencil_ref.laplacian_ext(ext_f, inv_h2)
                fsl = [slice(None)] * 3
                fsl[axis] = slice(p, p + 1)
                fsl = tuple(fsl)
                coeff = (
                    jnp.asarray(c_full, f)
                    if field is None
                    else field[fsl].astype(f)
                )
                face = (
                    2.0 * u[fsl].astype(f)
                    - u_prev[fsl].astype(f)
                    + coeff * lap
                ) * bc[fsl].astype(f)
                starts = [p if a == axis else 0 for a in range(3)]
                bulk = lax.dynamic_update_slice(
                    bulk, face.astype(dtype), starts
                )
        return bulk

    return step_overlap if overlap else step_serial


def _make_local_comp_step(
    problem: Problem,
    topo: Topology,
    dtype,
    kernel: str,
    interpret: bool,
    exchange: bool = True,
):
    """Per-shard compensated (Kahan) step `(u, v, carry, bc, coeff) ->
    (u', v', carry')` - the sharded counterpart of
    stencil_ref.compensated_step; ghosts/masking as in `_make_local_step`.
    """
    if kernel not in ("roll", "pallas"):
        raise ValueError(f"kernel must be 'roll' or 'pallas', got {kernel!r}")
    f = stencil_ref.compute_dtype(dtype)
    if f != dtype:
        raise ValueError(
            "compensated scheme requires f32/f64 state (bf16 representation "
            "error dominates anything the compensation recovers)"
        )
    n = problem.N
    inv_h2 = problem.inv_h2

    def comp_step(u, v, carry, bc, coeff):
        ghosts = (
            halo.collect_ghosts(u, topo) if exchange else _self_ghosts(u)
        )
        if kernel == "pallas":
            u_in = halo.absorb_hi_ghosts(u, ghosts, topo)
            return stencil_pallas.sharded_compensated_step(
                u_in, v, carry, ghosts, _shard_offsets(topo), n,
                inv_h2=inv_h2, mesh_shape=topo.mesh_shape,
                r_last=topo.r_last, coeff=coeff,
                interpret=interpret, compute_dtype=f,
            )
        ext = halo.place_ghosts(u, ghosts, topo)
        lap = stencil_ref.laplacian_ext(ext.astype(f), inv_h2)
        d = (jnp.asarray(coeff, f) * lap) * bc.astype(f)
        v_next = v + d
        y = v_next - carry
        t = u + y
        carry_next = (t - u) - y
        # bc re-applied to the sum for store parity with the Pallas
        # kernel's masked store (a no-op here: u and d are both masked).
        return t * bc.astype(f), v_next, carry_next

    return comp_step


def _local_solve_fns(
    problem: Problem,
    topo: Topology,
    dtype,
    compute_errors: bool,
    kernel: str,
    overlap: bool,
    interpret: bool,
    scheme: str = "standard",
    phase: float = oracle.TWO_PI,
):
    """The per-shard solve/resume bodies (closed over by shard_map).

    `phase` shifts the analytic initial condition (ensemble lane
    identity): a shifted phase bootstraps layer 1 ANALYTICALLY (the
    exact two-level initialization - leapfrog.make_solver's reasoning),
    standard scheme only."""
    f = stencil_ref.compute_dtype(dtype)
    if scheme not in ("standard", "compensated"):
        raise ValueError(
            f"scheme must be 'standard' or 'compensated', got {scheme!r}"
        )
    compensated = scheme == "compensated"
    if compensated and overlap:
        raise ValueError("overlap mode is not available for the "
                         "compensated scheme yet")
    analytic_bootstrap = phase != oracle.TWO_PI
    if analytic_bootstrap and compensated:
        raise ValueError(
            "the sharded compensated scheme serves the reference phase "
            "only (use the single-device compensated solvers for "
            "shifted-phase lanes)"
        )
    if compensated:
        comp_step = _make_local_comp_step(
            problem, topo, dtype, kernel, interpret
        )
        step = None
    else:
        step = _make_local_step(
            problem, topo, dtype, kernel, overlap, interpret
        )

    def errors_fn(mex, mey, mez, sx, sy, sz, ct):
        def errors(u, layer):
            if not compute_errors:
                z = jnp.zeros((), f)
                return z, z
            field = oracle.analytic_field(sx, sy, sz, ct[layer])
            ae, re = oracle.layer_errors(u.astype(f), field, mex, mey, mez)
            return (
                lax.pmax(ae, AXIS_NAMES),
                lax.pmax(re, AXIS_NAMES),
            )

        return errors

    def bootstrap(sx, sy, sz, bcx, bcy, bcz, ct, field):
        """Layers 0 and 1 (calculate_start, mpi_new.cpp:271-316).

        Returns (bc, carry0) where carry0 is the scan carry at layer 1:
        (u0, u1) for the standard scheme, (u1, v1, carry1) for the
        compensated one (the same step with v = carry = 0 and coeff = C/2
        is exactly the Taylor half-step bootstrap).
        """
        bc = (
            bcx[:, None, None] * bcy[None, :, None] * bcz[None, None, :]
        )
        u0 = (oracle.analytic_field(sx, sy, sz, ct[0]) * bc).astype(dtype)
        if compensated:
            zero = jnp.zeros_like(u0)
            u1, v1, c1 = comp_step(
                u0, zero, zero, bc, 0.5 * problem.a2tau2
            )
            return bc, (u1, v1, c1), u1
        if analytic_bootstrap:
            # Shifted phases have nonzero initial velocity; layer 1 is
            # the exact analytic initialization (leapfrog.make_solver).
            u1 = (
                oracle.analytic_field(sx, sy, sz, ct[1]) * bc
            ).astype(dtype)
            return bc, (u0, u1), u1
        # Layer 1 derived from the step function (u1 = (u0 + step(u0, u0))/2
        # == u0 + C/2 lap(u0)), so the kernel choice and a variable-c field
        # bootstrap consistently - same trick as leapfrog.make_solver.
        s = step(u0, u0, bc, field)
        u1 = (0.5 * (u0.astype(f) + s.astype(f))).astype(dtype)
        return bc, (u0, u1), u1

    def scan_layers(step_args, carry0, xs, errors):
        # `xs` holds the layer indices to march - `arange(start+1, stop+1)`
        # for solve/resume, `start + 1 + arange(L)` with a RUNTIME start for
        # the supervisor's cached chunk program.  One body serves all three,
        # which is what keeps resumed/supervised layers bitwise-identical.
        bc, field = step_args

        if compensated:
            def body(carry, layer):
                u, v, c = carry
                u2, v2, c2 = comp_step(u, v, c, bc, problem.a2tau2)
                ae, re = errors(u2, layer)
                return (u2, v2, c2), (ae, re)
        else:
            def body(carry, layer):
                u_prev, u = carry
                u_next = step(u_prev, u, bc, field)
                ae, re = errors(u_next, layer)
                return (u, u_next), (ae, re)

        return lax.scan(body, carry0, xs)

    def final_state(carry):
        """(u_prev, u_cur) from the scan carry; the compensated carry
        reconstructs u_prev from the increment (leapfrog.py rationale)."""
        if compensated:
            u, v, c = carry
            return u - v, u
        return carry

    return errors_fn, bootstrap, scan_layers, final_state


def _replicated_inputs(problem, topo, dtype, phase: float = oracle.TWO_PI):
    """The small closed-over program inputs (factors, masks, time table)."""
    f = stencil_ref.compute_dtype(dtype)
    sx, sy, sz = _padded_factors(problem, topo, f)
    (bcx, bcy, bcz), (mex, mey, mez) = _masks(problem, topo, f)
    ct = oracle.time_factor_table(problem, f, phase)
    return (sx, sy, sz), (bcx, bcy, bcz), (mex, mey, mez), ct


def make_sharded_solver(
    problem: Problem,
    topo: Topology,
    mesh: jax.sharding.Mesh,
    dtype=jnp.float32,
    compute_errors: bool = True,
    kernel: str = "roll",
    overlap: bool = False,
    interpret: bool = False,
    has_field: bool = False,
    stop_step: Optional[int] = None,
    scheme: str = "standard",
    phase: float = oracle.TWO_PI,
):
    """Build the jitted end-to-end sharded solver.

    Returns the jitted runner: call `runner()` (constant speed) or, when
    `has_field`, `runner(field)` with `field` a padded (topo.padded)
    tau^2 c^2 array (sharded or host; jit shards it P("x","y","z")).
    Output is (u_prev, u_cur, abs_errs, rel_errs) with u_* sharded
    P("x","y","z") and the error vectors replicated.  `phase` shifts the
    analytic initial condition (standard scheme, constant speed only -
    the analytic layer-1 bootstrap has no closed form under variable c).
    """
    nsteps = problem.timesteps if stop_step is None else stop_step
    if not 1 <= nsteps <= problem.timesteps:
        raise ValueError(
            f"stop_step must be in [1, {problem.timesteps}], got {nsteps}"
        )
    f = stencil_ref.compute_dtype(dtype)
    if phase != oracle.TWO_PI and has_field:
        raise ValueError(
            "a shifted phase bootstraps layer 1 from the analytic "
            "solution, which only exists for constant speed; use the "
            "reference phase with c2tau2_field"
        )
    (sx, sy, sz), bcs, mes, ct = _replicated_inputs(
        problem, topo, dtype, phase
    )
    if scheme == "compensated" and has_field:
        raise ValueError(
            "compensated scheme does not support a variable-c field yet"
        )
    errors_fn, bootstrap, scan_layers, final_state = _local_solve_fns(
        problem, topo, dtype, compute_errors, kernel, overlap, interpret,
        scheme, phase,
    )

    compensated = scheme == "compensated"

    def local_solve(sx, sy, sz, bcx, bcy, bcz, mex, mey, mez, ct, *rest):
        field = rest[0] if has_field else None
        errors = errors_fn(mex, mey, mez, sx, sy, sz, ct)
        bc, carry0, u1 = bootstrap(sx, sy, sz, bcx, bcy, bcz, ct, field)
        a0 = r0 = jnp.zeros((), f)  # layer 0 assigned from the oracle
        a1, r1 = errors(u1, 1)
        carry, (abs_t, rel_t) = scan_layers(
            (bc, field), carry0, jnp.arange(2, nsteps + 1), errors
        )
        u_prev, u_cur = final_state(carry)
        abs_all = jnp.concatenate([jnp.stack([a0, a1]), abs_t])
        rel_all = jnp.concatenate([jnp.stack([r0, r1]), rel_t])
        if compensated:
            # v and the Kahan carry ride out for checkpointing.
            _, v, kc = carry
            return u_prev, u_cur, abs_all, rel_all, v, kc
        return u_prev, u_cur, abs_all, rel_all

    in_specs = [
        P("x"), P("y"), P("z"),
        P("x"), P("y"), P("z"),
        P("x"), P("y"), P("z"),
        P(),
    ]
    if has_field:
        in_specs.append(P(*AXIS_NAMES))
    out_specs = [P(*AXIS_NAMES), P(*AXIS_NAMES), P(), P()]
    if compensated:
        out_specs += [P(*AXIS_NAMES), P(*AXIS_NAMES)]
    # check_vma=False: the Pallas interpret path (CPU tests/dryruns) does
    # not yet propagate varying-mesh-axes through in-kernel concatenates;
    # parity with the roll kernel is pinned by tests instead.
    sharded_fn = compat.shard_map(
        local_solve,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs),
        check_vma=False,
    )

    def run(*rt_args):
        return sharded_fn(sx, sy, sz, *bcs, *mes, ct, *rt_args)

    return jax.jit(run)


def make_sharded_resumer(
    problem: Problem,
    topo: Topology,
    mesh: jax.sharding.Mesh,
    start_step: int,
    dtype=jnp.float32,
    compute_errors: bool = True,
    kernel: str = "roll",
    overlap: bool = False,
    interpret: bool = False,
    has_field: bool = False,
    scheme: str = "standard",
):
    """Jitted re-entry into the sharded time loop at layer `start_step`.

    `runner(u_prev, u_cur[, field])` marches to problem.timesteps; the
    per-step op sequence is identical to `make_sharded_solver`'s, so a
    resumed run reproduces the uninterrupted one (tests/test_sharded_ckpt).
    Error entries before start_step+1 are zero, as in `leapfrog.resume`.
    """
    nsteps = problem.timesteps
    if not 1 <= start_step <= nsteps:
        raise ValueError(
            f"start_step must be in [1, {nsteps}], got {start_step}"
        )
    f = stencil_ref.compute_dtype(dtype)
    (sx, sy, sz), bcs, mes, ct = _replicated_inputs(problem, topo, dtype)
    errors_fn, _, scan_layers, final_state = _local_solve_fns(
        problem, topo, dtype, compute_errors, kernel, overlap, interpret,
        scheme,
    )
    compensated = scheme == "compensated"
    n_state = 3 if compensated else 2

    def local_resume(*args):
        state = args[:n_state]
        (sx, sy, sz, bcx, bcy, bcz, mex, mey, mez, ct, *rest) = (
            args[n_state:]
        )
        field = rest[0] if has_field else None
        errors = errors_fn(mex, mey, mez, sx, sy, sz, ct)
        bc = bcx[:, None, None] * bcy[None, :, None] * bcz[None, None, :]
        carry, (abs_t, rel_t) = scan_layers(
            (bc, field), state, jnp.arange(start_step + 1, nsteps + 1),
            errors,
        )
        u_p, u_c = final_state(carry)
        head = jnp.zeros((start_step + 1,), f)
        abs_all = jnp.concatenate([head, abs_t])
        rel_all = jnp.concatenate([head, rel_t])
        if compensated:
            _, v, kc = carry
            return u_p, u_c, abs_all, rel_all, v, kc
        return u_p, u_c, abs_all, rel_all

    state_spec = P(*AXIS_NAMES)
    in_specs = [state_spec] * n_state + [
        P("x"), P("y"), P("z"),
        P("x"), P("y"), P("z"),
        P("x"), P("y"), P("z"),
        P(),
    ]
    if has_field:
        in_specs.append(P(*AXIS_NAMES))
    out_specs = [state_spec, state_spec, P(), P()]
    if compensated:
        out_specs += [state_spec, state_spec]
    sharded_fn = compat.shard_map(
        local_resume,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs),
        check_vma=False,
    )

    def run(*state_and_args):
        state = tuple(
            jnp.asarray(a, dtype) for a in state_and_args[:n_state]
        )
        rt_args = state_and_args[n_state:]
        return sharded_fn(*state, sx, sy, sz, *bcs, *mes, ct, *rt_args)

    return jax.jit(run)


def make_sharded_chunk_runner(
    problem: Problem,
    topo: Topology,
    mesh: jax.sharding.Mesh,
    length: int,
    dtype=jnp.float32,
    compute_errors: bool = True,
    kernel: str = "roll",
    overlap: bool = False,
    interpret: bool = False,
    has_field: bool = False,
    scheme: str = "standard",
):
    """Fixed-length sharded re-entry for supervised solves.

    `runner(u_prev, u_cur, start[, field])` (compensated: `runner(u, v,
    carry, start[, field])`) marches layers start+1..start+length with a
    RUNTIME `start` - one compiled program per chunk length, reused for
    every chunk (run/supervisor.py).  The scan body is the same
    `scan_layers` closure `make_sharded_solver`/`make_sharded_resumer`
    run, so supervised layers stay bitwise-identical to an uninterrupted
    sharded solve's.
    """
    if length < 1:
        raise ValueError(f"chunk length must be >= 1, got {length}")
    f = stencil_ref.compute_dtype(dtype)
    (sx, sy, sz), bcs, mes, ct = _replicated_inputs(problem, topo, dtype)
    errors_fn, _, scan_layers, final_state = _local_solve_fns(
        problem, topo, dtype, compute_errors, kernel, overlap, interpret,
        scheme,
    )
    compensated = scheme == "compensated"
    n_state = 3 if compensated else 2

    def local_chunk(*args):
        state = args[:n_state]
        (start, sx, sy, sz, bcx, bcy, bcz, mex, mey, mez, ct, *rest) = (
            args[n_state:]
        )
        field = rest[0] if has_field else None
        errors = errors_fn(mex, mey, mez, sx, sy, sz, ct)
        bc = bcx[:, None, None] * bcy[None, :, None] * bcz[None, None, :]
        xs = start + 1 + jnp.arange(length, dtype=jnp.int32)
        carry, (abs_t, rel_t) = scan_layers((bc, field), state, xs, errors)
        u_p, u_c = final_state(carry)
        if compensated:
            _, v, kc = carry
            return u_p, u_c, abs_t, rel_t, v, kc
        return u_p, u_c, abs_t, rel_t

    state_spec = P(*AXIS_NAMES)
    in_specs = [state_spec] * n_state + [
        P(),
        P("x"), P("y"), P("z"),
        P("x"), P("y"), P("z"),
        P("x"), P("y"), P("z"),
        P(),
    ]
    if has_field:
        in_specs.append(P(*AXIS_NAMES))
    out_specs = [state_spec, state_spec, P(), P()]
    if compensated:
        out_specs += [state_spec, state_spec]
    sharded_fn = compat.shard_map(
        local_chunk,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs),
        check_vma=False,
    )

    def run(*state_start_args):
        state = tuple(
            jnp.asarray(a, dtype) for a in state_start_args[:n_state]
        )
        start = state_start_args[n_state]
        rt_args = state_start_args[n_state + 1:]
        return sharded_fn(
            *state, start, sx, sy, sz, *bcs, *mes, ct, *rt_args
        )

    return jax.jit(run)


def _default_interpret() -> bool:
    """Pallas needs Mosaic (TPU); anywhere else run the kernel interpreted
    so CPU tests/dryruns exercise the identical program structure."""
    return jax.default_backend() != "tpu"


def _run_timed(runner, rt_args):
    """(outputs, abs_np, rel_np, init_s, solve_s); outputs is the runner's
    tuple (u_prev, u_cur, abs, rel[, v, carry])."""
    t0 = time.perf_counter()
    compiled = runner.lower(*rt_args).compile()
    t1 = time.perf_counter()
    out = compiled(*rt_args)
    jax.block_until_ready(out)
    # The small error-vector readback inside the timed region proves the
    # program actually ran: on remote backends block_until_ready can return
    # before execution (see leapfrog._timed_compile_run).
    abs_np = np.asarray(out[2], dtype=np.float64)
    rel_np = np.asarray(out[3], dtype=np.float64)
    t2 = time.perf_counter()
    return out, abs_np, rel_np, t1 - t0, t2 - t1


def _resolve_mesh(problem, mesh_shape, devices):
    if devices is None:
        devices = jax.devices()
    if mesh_shape is None:
        mesh_shape = choose_mesh_shape(len(devices))
    topo = Topology(N=problem.N, mesh_shape=mesh_shape)
    if len(devices) < topo.n_devices:
        raise ValueError(
            f"mesh {mesh_shape} needs {topo.n_devices} devices, "
            f"only {len(devices)} available"
        )
    mesh = build_mesh(mesh_shape, devices[: topo.n_devices])
    return topo, mesh


def solve_sharded(
    problem: Problem,
    mesh_shape: Optional[Tuple[int, int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    dtype=jnp.float32,
    compute_errors: bool = True,
    kernel: str = "roll",
    overlap: bool = False,
    interpret: Optional[bool] = None,
    c2tau2_field: Optional[np.ndarray] = None,
    stop_step: Optional[int] = None,
    scheme: str = "standard",
    phase: float = oracle.TWO_PI,
) -> SolveResult:
    """Compile + run the distributed solve; returns the same SolveResult as
    the single-device path (errors are cross-device maxima).

    `mesh_shape` defaults to a near-cubic factorization of the available
    device count (MPI_Dims_create analog, mpi_sol.cpp:407).  `kernel`
    selects the per-shard hot kernel ("pallas" = the fused slab kernel,
    "roll" = the XLA reference stencil); `overlap` requests
    compute/communication overlap (even shard splits only).
    `c2tau2_field` is an (N, N, N) host array from
    `stencil_ref.make_c2tau2_field`; pair it with compute_errors=False
    (the analytic oracle holds for constant speed only).  `phase` shifts
    the analytic initial condition (standard scheme, constant speed
    only) - the lane identity of the sharded ensemble engine.
    """
    topo, mesh = _resolve_mesh(problem, mesh_shape, devices)
    if interpret is None:
        interpret = _default_interpret()
    has_field = c2tau2_field is not None
    runner = make_sharded_solver(
        problem, topo, mesh, dtype, compute_errors, kernel, overlap,
        interpret, has_field, stop_step, scheme, phase,
    )
    rt_args = ()
    if has_field:
        f = stencil_ref.compute_dtype(dtype)
        rt_args = (jnp.asarray(pad_field(c2tau2_field, topo), dtype=f),)
    out, abs_np, rel_np, init_s, solve_s = _run_timed(runner, rt_args)
    result = SolveResult(
        problem=problem,
        u_prev=out[0],
        u_cur=out[1],
        abs_errors=abs_np,
        rel_errors=rel_np,
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=stop_step,
        final_step=stop_step if stop_step is not None else problem.timesteps,
        comp_v=out[4] if scheme == "compensated" else None,
        comp_carry=out[5] if scheme == "compensated" else None,
    )
    obs_metrics.record_solve(
        result, "sharded", scheme=scheme,
        with_field=c2tau2_field is not None,
    )
    return result


def resume_sharded(
    problem: Problem,
    u_prev,
    u_cur,
    start_step: int,
    mesh_shape: Optional[Tuple[int, int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    dtype=jnp.float32,
    compute_errors: bool = True,
    kernel: str = "roll",
    overlap: bool = False,
    interpret: Optional[bool] = None,
    c2tau2_field: Optional[np.ndarray] = None,
    scheme: str = "standard",
    comp_v=None,
    comp_carry=None,
) -> SolveResult:
    """Re-enter the sharded time loop at layer `start_step` and run to the
    end.  `u_prev`/`u_cur` are padded (topo.padded) arrays - what
    `solve_sharded(stop_step=...)` returned and io/checkpoint.py stored.
    A compensated resume additionally takes (comp_v, comp_carry) and
    re-enters from (u_cur, v, carry); u_prev is then ignored."""
    topo, mesh = _resolve_mesh(problem, mesh_shape, devices)
    if interpret is None:
        interpret = _default_interpret()
    has_field = c2tau2_field is not None
    compensated = scheme == "compensated"
    if compensated and (comp_v is None or comp_carry is None):
        raise ValueError(
            "compensated resume needs comp_v and comp_carry"
        )
    runner = make_sharded_resumer(
        problem, topo, mesh, start_step, dtype, compute_errors, kernel,
        overlap, interpret, has_field, scheme,
    )
    if compensated:
        rt_args = (u_cur, comp_v, comp_carry)
    else:
        rt_args = (u_prev, u_cur)
    if has_field:
        f = stencil_ref.compute_dtype(dtype)
        rt_args = rt_args + (
            jnp.asarray(pad_field(c2tau2_field, topo), dtype=f),
        )
    out, abs_np, rel_np, init_s, solve_s = _run_timed(runner, rt_args)
    return SolveResult(
        problem=problem,
        u_prev=out[0],
        u_cur=out[1],
        abs_errors=abs_np,
        rel_errors=rel_np,
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=problem.timesteps - start_step,
        final_step=problem.timesteps,
        comp_v=out[4] if compensated else None,
        comp_carry=out[5] if compensated else None,
    )


def gather_fundamental(u: jax.Array, problem: Problem) -> np.ndarray:
    """Fetch the (possibly padded) sharded field to host and strip padding,
    returning the (N, N, N) fundamental domain."""
    n = problem.N
    return np.asarray(u)[:n, :n, :n]
