"""Distributed solver: one jitted `shard_map` program over a 3D device mesh.

The analog of the reference's MPI variants (mpi_new.cpp:324-372 fused loop,
mpi_sol.cpp:374-478 topology setup) redesigned for ICI: the whole solve -
layer-0/1 bootstrap, the time loop, halo exchange, boundary masking, and the
cross-device error max-reduction - is a single XLA computation per chip.
There is no host round-trip anywhere: halos ride `ppermute` (comm/halo.py)
and the per-layer L-inf errors are `lax.pmax`-reduced in-program (the
counterpart of the end-of-run MPI_Reduce(MPI_MAX), mpi_new.cpp:360-361).

Sharding model (see core/grid.py): the fundamental (N, N, N) state is
zero-padded per axis to a multiple of the mesh dim and laid out
PartitionSpec("x", "y", "z").  All 1-D problem data (analytic factors, error
masks, boundary masks) is precomputed on host in f64, padded, and sharded
along its own axis, so every shard receives exactly its slice - the moral
equivalent of the reference's per-rank x_0/y_0/z_0 offsets
(mpi_sol.cpp:423-429) without any per-rank branching.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from wavetpu.comm import halo
from wavetpu.core.grid import AXIS_NAMES, Topology, build_mesh, choose_mesh_shape
from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_ref
from wavetpu.solver.leapfrog import SolveResult
from wavetpu.verify import oracle


def _padded_factors(problem: Problem, topo: Topology, dtype):
    """Host-f64 1-D analytic factors on the padded per-axis grids.

    Pad cells get factor 0, so the padded analytic field vanishes there
    (consistent with the zero-padded state).  The factor formulas live in
    oracle.spatial_factors_np (single source of truth).
    """
    n = problem.N

    def pad(v, p):
        out = np.zeros(p, dtype=np.float64)
        out[:n] = v
        return out

    factors = oracle.spatial_factors_np(problem, n)
    return tuple(
        jnp.asarray(pad(v, p), dtype=dtype)
        for v, p in zip(factors, topo.padded)
    )


def _masks(problem: Problem, topo: Topology, dtype):
    """1-D boundary multipliers and error-interior masks, padded.

    bc (multiplied into every updated layer):
      x: 1 for real cells (global i < N) - the x=0 plane is a live periodic
         cell; 0 for pad cells.
      y/z: 0 at the stored Dirichlet plane (global 0) and pad cells
         (reference zeroes its y/z faces each step, openmp_sol.cpp:104-112).
    err (error reduction, reference interior = global 1..N-1 per axis,
         openmp_sol.cpp:174-176): global index != 0 and < N.
    """
    n = problem.N
    bc, err = [], []
    for axis, p in enumerate(topo.padded):
        g = np.arange(p)
        real = g < n
        if axis == 0:
            bc.append(real.astype(np.float64))
        else:
            bc.append((real & (g != 0)).astype(np.float64))
        err.append(real & (g != 0))
    bcs = tuple(jnp.asarray(b, dtype=dtype) for b in bc)
    errs = tuple(jnp.asarray(e) for e in err)
    return bcs, errs


def make_sharded_solver(
    problem: Problem,
    topo: Topology,
    mesh: jax.sharding.Mesh,
    dtype=jnp.float32,
    compute_errors: bool = True,
):
    """Build the jitted end-to-end sharded solver (no runtime array inputs).

    Returns a zero-arg callable producing (u_prev, u_cur, abs_errs, rel_errs)
    with u_* sharded P("x","y","z") and the error vectors replicated.
    """
    nsteps = problem.timesteps
    c_full = problem.a2tau2
    inv_h2 = problem.inv_h2

    sx, sy, sz = _padded_factors(problem, topo, dtype)
    (bcx, bcy, bcz), (mex, mey, mez) = _masks(problem, topo, dtype)
    ct_table = oracle.time_factor_table(problem, dtype)

    def local_solve(sx, sy, sz, bcx, bcy, bcz, mex, mey, mez, ct):
        bc = bcx[:, None, None] * bcy[None, :, None] * bcz[None, None, :]

        def errors(u, n):
            if not compute_errors:
                z = jnp.zeros((), dtype)
                return z, z
            f = oracle.analytic_field(sx, sy, sz, ct[n])
            ae, re = oracle.layer_errors(u, f, mex, mey, mez)
            return (
                jax.lax.pmax(ae, AXIS_NAMES),
                jax.lax.pmax(re, AXIS_NAMES),
            )

        def step(u_prev, u, coeff):
            ext = halo.halo_extend(u, topo)
            lap = stencil_ref.laplacian_ext(ext, inv_h2)
            return u_prev + coeff * lap

        # Layer 0: analytic init (calculate_start, mpi_new.cpp:271-290).
        u0 = oracle.analytic_field(sx, sy, sz, ct[0]) * bc
        # Layer 0 is assigned from the oracle, so its error is zero by
        # definition (see solver/leapfrog.py for the rationale and the XLA
        # rematerialization-noise trap this avoids).
        a0 = r0 = jnp.zeros((), dtype)
        # Layer 1 Taylor half-step, derived from the full step exactly as
        # the single-device solver does (u1 = (u0 + leapfrog(u0, u0))/2 ==
        # u0 + c/2 lap(u0); mpi_new.cpp:300-316) so the two backends stay
        # bitwise-comparable (tests/test_sharded.py's 1e-9 rtol).
        s = step(2.0 * u0 - u0, u0, jnp.asarray(c_full, dtype))
        u1 = (0.5 * (u0 + s)) * bc
        a1, r1 = errors(u1, 1)

        def body(carry, n):
            u_prev, u = carry
            # Leapfrog: 2u - u_prev + c lap(u) (mpi_new.cpp:335-347).
            u_next = step(2.0 * u - u_prev, u, jnp.asarray(c_full, dtype)) * bc
            ae, re = errors(u_next, n)
            return (u, u_next), (ae, re)

        (u_prev, u_cur), (abs_t, rel_t) = jax.lax.scan(
            body, (u0, u1), jnp.arange(2, nsteps + 1)
        )
        abs_all = jnp.concatenate([jnp.stack([a0, a1]), abs_t])
        rel_all = jnp.concatenate([jnp.stack([r0, r1]), rel_t])
        return u_prev, u_cur, abs_all, rel_all

    sharded = jax.shard_map(
        local_solve,
        mesh=mesh,
        in_specs=(
            P("x"), P("y"), P("z"),
            P("x"), P("y"), P("z"),
            P("x"), P("y"), P("z"),
            P(),
        ),
        out_specs=(P(*AXIS_NAMES), P(*AXIS_NAMES), P(), P()),
    )

    def run():
        return sharded(sx, sy, sz, bcx, bcy, bcz, mex, mey, mez, ct_table)

    return jax.jit(run)


def solve_sharded(
    problem: Problem,
    mesh_shape: Optional[Tuple[int, int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    dtype=jnp.float32,
    compute_errors: bool = True,
) -> SolveResult:
    """Compile + run the distributed solve; returns the same SolveResult as
    the single-device path (errors are cross-device maxima).

    `mesh_shape` defaults to a near-cubic factorization of the available
    device count (MPI_Dims_create analog, mpi_sol.cpp:407).
    """
    if devices is None:
        devices = jax.devices()
    if mesh_shape is None:
        mesh_shape = choose_mesh_shape(len(devices))
    topo = Topology(N=problem.N, mesh_shape=mesh_shape)
    if len(devices) < topo.n_devices:
        raise ValueError(
            f"mesh {mesh_shape} needs {topo.n_devices} devices, "
            f"only {len(devices)} available"
        )
    mesh = build_mesh(mesh_shape, devices[: topo.n_devices])

    t0 = time.perf_counter()
    runner = make_sharded_solver(problem, topo, mesh, dtype, compute_errors)
    compiled = runner.lower().compile()
    t1 = time.perf_counter()
    u_prev, u_cur, abs_all, rel_all = compiled()
    jax.block_until_ready((u_prev, u_cur, abs_all, rel_all))
    # The small error-vector readback inside the timed region proves the
    # program actually ran: on remote backends block_until_ready can return
    # before execution (see leapfrog._timed_compile_run).
    abs_np = np.asarray(abs_all, dtype=np.float64)
    rel_np = np.asarray(rel_all, dtype=np.float64)
    t2 = time.perf_counter()
    return SolveResult(
        problem=problem,
        u_prev=u_prev,
        u_cur=u_cur,
        abs_errors=abs_np,
        rel_errors=rel_np,
        init_seconds=t1 - t0,
        solve_seconds=t2 - t1,
    )


def gather_fundamental(u: jax.Array, problem: Problem) -> np.ndarray:
    """Fetch the (possibly padded) sharded field to host and strip padding,
    returning the (N, N, N) fundamental domain."""
    n = problem.N
    return np.asarray(u)[:n, :n, :n]
