"""Temporally fused k-step solver over an (MX, MY, 1)-sharded device mesh.

Composes the repo's two flagship mechanisms: the k-step VMEM-onion kernel
(solver/kfused.py - the single-chip HBM-traffic win) and the shard_map
decomposition with ppermute halo exchange (solver/sharded.py - the
reference's MPI role, mpi_new.cpp:324-372).  Exchanging k-deep ghosts per
k LAYERS amortizes the per-step latency cost of the reference's per-layer
exchange (mpi_new.cpp:327-352) by k - halo BYTES per layer stay the same,
messages drop k-fold.

Two kernel regimes, dispatched on the mesh:

 * **x-only** ((P, 1, 1)): y/z stay full-domain per shard, so the
   in-kernel y/z rolls and Dirichlet mask are exactly the single-device
   kernel's; one cyclic x-ppermute pair per field per k-block.
 * **x/y** ((MX, MY, 1)): each block is first extended with k cyclic
   ghost ROWS per y side (one y-ppermute pair), then the x ghost planes
   are ppermute'd FROM THE EXTENDED blocks - the diagonal corner data a
   2D onion needs arrives through that sequencing with no extra
   collectives.  The kernel keeps the extended y width constant (rolls
   still deliver neighbours for every onion-valid row; staleness creeps
   only through ghost rows that are never written back) and re-imposes
   the Dirichlet zero on the WRAPPED global y index, so evolved ghost
   copies of the y=0 stored plane stay zero.  Ops per valid element are
   identical to the single-device kernel's - results stay bitwise equal
   across every mesh shape (tests/test_sharded_kfused.py).

z stays unsharded (MZ = 1): z is the 128-lane dimension, and cutting it
would shrink every vector register tile; BASELINE's target meshes up to
256 chips factor as (MX, MY, 1) without it.

Per-layer L-inf errors: each shard's kernel emits (k, N/MX) per-x-plane
maxes over its y range, pmax'd over the y axis and concatenated along x
(out_spec P(None, "x")) into global (layer, N) rows; the tiny per-plane
rescale + interior mask run on the replicated result.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from wavetpu.core.grid import build_mesh
from wavetpu.core.problem import Problem
from wavetpu import compat
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.obs import metrics as obs_metrics
from wavetpu.solver import kfused, leapfrog
from wavetpu.solver.leapfrog import SolveResult


def _is_even(problem: Problem, k: int, n_x: int) -> bool:
    """True when the x decomposition divides evenly (the point-to-point
    flagship path); False routes to the pad-and-mask path."""
    return problem.N % n_x == 0 and (problem.N // n_x) % k == 0


def uneven_layout(problem: Problem, k: int, n_x: int, itemsize: int = 4):
    """(bx, D, r) for the pad-and-mask x-only path.

    D is the uniform padded per-shard depth (a multiple of the slab
    depth bx, itself a multiple of k), chosen as the largest
    VMEM-fitting bx with D = bx * ceil(N / (MX * bx)).  r = N - (MX-1)*D
    is the last shard's real-plane count - the remainder-folding analog
    of the reference (mpi_sol.cpp:417-421).  Raises when no layout keeps
    every leading shard full AND the last shard non-empty (r >= 1): that
    means the mesh is too large for N at this k - use fewer shards.
    """
    n = problem.N
    best = None
    bx = k
    while bx <= 8:
        d = bx * (-(-n // (n_x * bx)))  # bx * ceil(n / (n_x * bx))
        r = n - (n_x - 1) * d
        fits = stencil_pallas.choose_kstep_block(
            n, k, itemsize, depth=d, ghosts=True
        )
        if r >= 1 and fits is not None and fits >= bx:
            best = (bx, d, r)
        bx *= 2
    if best is None:
        raise ValueError(
            f"no pad-and-mask layout for N={n} over {n_x} x-shards at "
            f"k={k}: every candidate leaves the last shard empty or "
            f"exceeds VMEM; use fewer shards or a smaller k"
        )
    return best


def _validate(problem: Problem, k: int, n_x: int, n_y: int = 1,
              c2tau2_field=None, compute_errors: bool = True):
    if c2tau2_field is not None and compute_errors:
        raise ValueError(
            "variable-c runs have no analytic oracle; pass "
            "compute_errors=False with c2tau2_field"
        )
    if k < 2:
        raise ValueError(f"k must be >= 2 (got {k})")
    if n_x < 1 or n_y < 1:
        raise ValueError(
            f"mesh axes must be >= 1 (got MX={n_x}, MY={n_y})"
        )
    if problem.N < k:
        raise ValueError(f"k={k} exceeds N={problem.N}")
    if not _is_even(problem, k, n_x):
        if n_y > 1:
            raise ValueError(
                f"2D-mesh k-fusion needs N % MX == 0 and k | N/MX "
                f"(N={problem.N}, MX={n_x}, k={k}); uneven N is "
                f"supported on (MX, 1, 1) meshes"
            )
        uneven_layout(problem, k, n_x)  # raises if no layout exists
    if problem.N % n_y:
        raise ValueError(
            f"y-sharded k-fusion needs N % y-shards == 0 "
            f"(N={problem.N}, y-shards={n_y})"
        )
    if problem.N // n_y < k:
        raise ValueError(
            f"k={k} exceeds the y shard depth {problem.N // n_y} "
            f"(the k-row ghost strip must fit one neighbour)"
        )


def _assemble_errors(oracle_parts, dmax_rows, rmax_rows):
    """Global per-layer abs/rel errors from (layers, N) plane-max rows.

    Thin adapter over the single source of the error-rescale contract
    (kfused._oracle_parts / _block_errors): the same exact-zero guards and
    x!=0 interior mask, applied to all layers' rows at once (ctk is just
    longer)."""
    _, ct, _, _, xmask, inv_absx = oracle_parts
    return kfused._block_errors(
        dmax_rows, rmax_rows, ct[: dmax_rows.shape[0]], xmask, inv_absx
    )


def _make_runner(
    problem: Problem,
    mesh,
    shard_grid: Tuple[int, int],
    dtype,
    k: int,
    compute_errors: bool,
    nsteps: int,
    start_step: Optional[int],
    block_x: Optional[int],
    interpret: bool,
    has_field: bool = False,
    chunk_len: Optional[int] = None,
):
    """One jitted program: [bootstrap +] k-block scan + 1-step remainder.

    `shard_grid` = (n_x, n_y) mesh extents.  n_y == 1 runs the x-only
    kernel (in-shard y rolls ARE the boundary condition); n_y > 1 extends
    each block with k ghost rows per side via a cyclic y-ppermute pair and
    runs the xy kernel - the x ghosts are then sliced FROM the extended
    blocks, which ships the diagonal corners without extra collectives.

    `start_step=None` builds the from-scratch solver (bootstrap included);
    an int builds the resume program re-entering at that layer; with
    `chunk_len` set (start_step None) the runner is the supervised chunk
    program `run(u_prev, u, start, ...)` marching exactly chunk_len
    layers from a RUNTIME start (run/supervisor.py's cached program).
    All use the same local march so the per-layer op sequence is
    identical (the bitwise-resume invariant, solver/kfused.py).

    With `has_field` the c^2tau^2 field rides as an extra P("x","y")
    runtime argument; being time-invariant, its y extension and x-ghost
    exchange are hoisted OUT of the layer scan (once per solve per
    needed ghost depth: k for the blocks, 1 for bootstrap/remainder).
    """
    n_x, n_y = shard_grid
    f = stencil_ref.compute_dtype(dtype)
    nl = problem.N // n_x
    nl_y = problem.N // n_y
    oracle_parts = kfused._oracle_parts(problem, f)
    sx, ct, syz, rsyz, xmask, inv_absx = oracle_parts
    sxct_all = ct[:, None] * sx[None, :]            # (T+1, N)
    perm_fwd = [(i, (i + 1) % n_x) for i in range(n_x)]
    perm_bwd = [(i, (i - 1) % n_x) for i in range(n_x)]
    perm_fwd_y = [(i, (i + 1) % n_y) for i in range(n_y)]
    perm_bwd_y = [(i, (i - 1) % n_y) for i in range(n_y)]
    coeff = problem.a2tau2
    if chunk_len is None:
        start = 1 if start_step is None else start_step
        nblocks = (nsteps - start) // k
        rem = (nsteps - start) - nblocks * k
    else:
        nblocks = chunk_len // k
        rem = chunk_len - nblocks * k

    def ghosts(a, depth):
        """(lo, hi) ghost planes from the cyclic x-neighbours."""
        lo = lax.ppermute(a[-depth:], "x", perm_fwd)
        hi = lax.ppermute(a[:depth], "x", perm_bwd)
        return lo, hi

    def extend_y(a, depth):
        """Block extended with `depth` cyclic ghost rows per y side."""
        lo = lax.ppermute(a[:, -depth:], "y", perm_fwd_y)
        hi = lax.ppermute(a[:, :depth], "y", perm_bwd_y)
        return jnp.concatenate([lo, a, hi], axis=1)

    def field_pack(fld, kk):
        """(block_or_ext, x-ghost pair) of the time-invariant field at
        ghost depth kk - built once per solve, outside the scan."""
        if fld is None:
            return None
        if n_y == 1:
            return fld, ghosts(fld, kk)
        fe = extend_y(fld, kk)
        return fe, ghosts(fe, kk)

    def kcall(syz_c, rsyz_c, u_prev, u, sxct_k, kk, with_errors, bxo,
              fp=None):
        c2b = fp[0] if fp is not None else None
        c2g = fp[1] if fp is not None else None
        if n_y == 1:
            return stencil_pallas.fused_kstep_sharded(
                u_prev, u, ghosts(u_prev, kk), ghosts(u, kk), syz_c,
                rsyz_c, sxct_k, k=kk, coeff=coeff, inv_h2=problem.inv_h2,
                c2tau2_block=c2b, c2_ghosts=c2g,
                block_x=bxo, interpret=interpret, with_errors=with_errors,
            )
        pe = extend_y(u_prev, kk)
        ce = extend_y(u, kk)
        y0 = lax.axis_index("y") * nl_y
        up, uc, dm, rm = stencil_pallas.fused_kstep_sharded_xy(
            pe, ce, ghosts(pe, kk), ghosts(ce, kk), syz_c, rsyz_c,
            sxct_k, y0, problem.N, k=kk, nl_y=nl_y, coeff=coeff,
            inv_h2=problem.inv_h2, c2tau2_ext=c2b, c2_ghosts=c2g,
            block_x=bxo, interpret=interpret,
            with_errors=with_errors,
        )
        if with_errors:
            dm = lax.pmax(dm, "y")
            rm = lax.pmax(rm, "y")
        return up, uc, dm, rm

    def layer_rows(syz_c, rsyz_c, u, sxct_row):
        """Bootstrap-layer rows (kfused._layer_rows_local), pmax'd across
        the y mesh axis on 2D meshes."""
        d, r = kfused._layer_rows_local(u, sxct_row, syz_c, rsyz_c, f)
        if n_y > 1:
            d = lax.pmax(d, "y")
            r = lax.pmax(r, "y")
        return d, r

    def local_march(syz_c, rsyz_c, u_prev, u, sxct_loc, first, fld=None):
        """Layers first+1..nsteps; returns carry + (rows_d, rows_r) for
        exactly nsteps - first layers."""
        rows_d, rows_r = [], []
        fp_k = field_pack(fld, k)
        fp_1 = field_pack(fld, 1) if rem else None

        def body(carry, nstart):
            u_prev, u = carry
            sxct_k = lax.dynamic_slice(sxct_loc, (nstart + 1, 0), (k, nl))
            up, uc, dm, rm = kcall(
                syz_c, rsyz_c, u_prev, u, sxct_k, k, compute_errors,
                block_x, fp_k,
            )
            if not compute_errors:
                dm = rm = jnp.zeros((k, nl), f)
            return (up, uc), (dm, rm)

        starts = first + k * jnp.arange(nblocks)
        (u_prev, u), (dmb, rmb) = lax.scan(body, (u_prev, u), starts)
        rows_d.append(dmb.reshape(-1, nl))
        rows_r.append(rmb.reshape(-1, nl))
        for t in range(rem):
            # == nsteps - rem + 1 + t on the full march; off `first` the
            # identical arithmetic also serves a traced chunk start.
            layer = jnp.asarray(first + nblocks * k + 1 + t, jnp.int32)
            sxct_1 = lax.dynamic_slice(
                sxct_loc, (layer, jnp.int32(0)), (1, nl)
            )
            u_prev, u, dm, rm = kcall(
                syz_c, rsyz_c, u_prev, u, sxct_1, 1, compute_errors, None,
                fp_1,
            )
            if not compute_errors:
                dm = rm = jnp.zeros((1, nl), f)
            rows_d.append(dm)
            rows_r.append(rm)
        return u_prev, u, jnp.concatenate(rows_d), jnp.concatenate(rows_r)

    state_spec = P("x", "y")
    rows_spec = P(None, "x")
    plane_spec = P("y", None)

    field_specs = (state_spec,) if has_field else ()

    if chunk_len is not None:
        assert start_step is None

        def local_chunk(u_prev, u, start, sxct_loc, syz_c, rsyz_c,
                        *fargs):
            return local_march(
                syz_c, rsyz_c, u_prev, u, sxct_loc, start,
                fargs[0] if has_field else None,
            )

        local_fn = compat.shard_map(
            local_chunk, mesh=mesh,
            in_specs=(state_spec, state_spec, P(), rows_spec, plane_spec,
                      plane_spec) + field_specs,
            out_specs=(state_spec, state_spec, rows_spec, rows_spec),
            check_vma=False,
        )

        def run_chunk(u_prev, u, start, *fargs):
            u_prev, u, dmax, rmax = local_fn(
                u_prev, u, start, sxct_all, syz, rsyz, *fargs
            )
            if compute_errors:
                ctk = lax.dynamic_slice(ct, (start + 1,), (chunk_len,))
                abs_e, rel_e = kfused._block_errors(
                    dmax, rmax, ctk, xmask, inv_absx
                )
            else:
                abs_e = rel_e = jnp.zeros((chunk_len,), f)
            return u_prev, u, abs_e, rel_e

        return jax.jit(run_chunk), ()

    if start_step is None:

        def local(u0, sxct_loc, syz_c, rsyz_c, *fargs):
            fld = fargs[0] if has_field else None
            # kcall returns (layer n+k-1, layer n+k, ...): the stepped
            # field u0 + C*lap(u0) is the SECOND output.  With a field
            # the same identity holds per point (s0 = u0 + c^2tau^2*lap),
            # so the bootstrap needs no half-field.
            _, s0, _, _ = kcall(
                syz_c, rsyz_c, u0, u0, jnp.zeros((1, nl), f), 1, False,
                None, field_pack(fld, 1),
            )
            u1 = (0.5 * (u0.astype(f) + s0.astype(f))).astype(dtype)
            if compute_errors:
                d1, r1 = layer_rows(syz_c, rsyz_c, u1, sxct_loc[1])
            else:
                d1 = r1 = jnp.zeros((1, nl), f)
            u_prev, u, rows_d, rows_r = local_march(
                syz_c, rsyz_c, u0, u1, sxct_loc, 1, fld
            )
            zero = jnp.zeros((1, nl), f)
            return (
                u_prev, u,
                jnp.concatenate([zero, d1, rows_d]),
                jnp.concatenate([zero, r1, rows_r]),
            )

        local_fn = compat.shard_map(
            local, mesh=mesh,
            in_specs=(state_spec, rows_spec, plane_spec, plane_spec)
            + field_specs,
            out_specs=(state_spec, state_spec, rows_spec, rows_spec),
            # vma inference cannot see through the pallas kernel's mixed
            # ghost/wraparound concat (same workaround as solver/timing.py)
            check_vma=False,
        )

        def run(*fargs):
            u0 = lax.with_sharding_constraint(
                leapfrog.initial_layer0(problem, dtype),
                NamedSharding(mesh, state_spec),
            )
            u_prev, u, dmax, rmax = local_fn(
                u0, sxct_all, syz, rsyz, *fargs
            )
            if compute_errors:
                abs_e, rel_e = _assemble_errors(oracle_parts, dmax, rmax)
            else:
                abs_e = rel_e = jnp.zeros((nsteps + 1,), f)
            return u_prev, u, abs_e, rel_e

        return jax.jit(run), ()

    def local_resume(u_prev, u, sxct_loc, syz_c, rsyz_c, *fargs):
        u_prev, u, rows_d, rows_r = local_march(
            syz_c, rsyz_c, u_prev, u, sxct_loc, start_step,
            fargs[0] if has_field else None,
        )
        head = jnp.zeros((start_step + 1, nl), f)
        return (
            u_prev, u,
            jnp.concatenate([head, rows_d]),
            jnp.concatenate([head, rows_r]),
        )

    local_fn = compat.shard_map(
        local_resume, mesh=mesh,
        in_specs=(state_spec, state_spec, rows_spec, plane_spec,
                  plane_spec) + field_specs,
        out_specs=(state_spec, state_spec, rows_spec, rows_spec),
        check_vma=False,
    )

    def run(u_prev, u, *fargs):
        u_prev, u, dmax, rmax = local_fn(u_prev, u, sxct_all, syz, rsyz,
                                         *fargs)
        if compute_errors:
            abs_e, rel_e = _assemble_errors(oracle_parts, dmax, rmax)
        else:
            abs_e = rel_e = jnp.zeros((nsteps + 1,), f)
        return u_prev, u, abs_e, rel_e

    return jax.jit(run), None


def _make_padded_runner(
    problem: Problem,
    mesh,
    n_x: int,
    dtype,
    k: int,
    compute_errors: bool,
    nsteps: int,
    start_step: Optional[int],
    block_x: Optional[int],
    interpret: bool,
    has_field: bool = False,
    chunk_len: Optional[int] = None,
):
    """Pad-and-mask x-only runner for uneven decompositions.

    Covers N % MX != 0 and/or k not dividing N/MX (the reference folds
    the remainder into the last rank, mpi_sol.cpp:417-421).  Every shard
    holds a uniform padded depth D; ghosts are true cyclic REAL planes,
    assembled from up to two source shards when the last shard owns
    fewer than k real planes (one extra two-hop ppermute pair, built
    only when r < k), and each block is locally extended to
    [lo(k) | D | junk(k)] with the hi ghost spliced at the real boundary
    (see stencil_pallas.fused_kstep_padded).  The runner's raw outputs
    are (MX*D, N, N) globals; solve/resume re-place them on the 1-step
    sharded path's Topology layout so checkpointing, gather_fundamental
    and every downstream consumer see the SAME convention as all other
    sharded results.

    Cost: the per-block ext assembly (concat + hi-ghost splice) is one
    extra memory pass over both fields per k layers (~+4/k field-streams
    per step).  Measured on v5e at N=510/1000 k=4: 26.9 Gcell/s vs 44.9
    for the even point-to-point path and 20.3 for the 1-step kernel -
    the fallback is still a clear win over not fusing.

    With `has_field` the c^2tau^2 field arrives zero-padded to the
    (MX*D, N, N) layout as an extra P("x") runtime argument; its
    extended form (lo ghosts | D | hi spliced, zero junk) is assembled
    ONCE per solve per ghost depth with exactly the state's machinery.
    """
    f = stencil_ref.compute_dtype(dtype)
    n = problem.N
    bx, d, r = uneven_layout(
        problem, k, n_x, jnp.dtype(dtype).itemsize
    )
    if block_x is not None:
        bx = block_x
        d = bx * (-(-n // (n_x * bx)))
        r = n - (n_x - 1) * d
        if r < 1 or d % bx or bx % k:
            raise ValueError(
                f"block_x={bx} gives no valid pad-and-mask layout for "
                f"N={n} over {n_x} shards at k={k}"
            )
    dg = n_x * d
    pad = dg - n
    sx, ct, syz, rsyz, xmask, inv_absx = kfused._oracle_parts(problem, f)
    zpad = jnp.zeros((pad,), f)
    sx_p = jnp.concatenate([sx, zpad])
    xmask_p = jnp.concatenate([xmask, jnp.zeros((pad,), bool)])
    inv_absx_p = jnp.concatenate([inv_absx, zpad])
    padded_parts = (sx_p, ct, syz, rsyz, xmask_p, inv_absx_p)
    sxct_all = ct[:, None] * sx_p[None, :]          # (T+1, MX*D)
    perm_fwd = [(i, (i + 1) % n_x) for i in range(n_x)]
    perm_bwd = [(i, (i - 1) % n_x) for i in range(n_x)]
    perm_fwd2 = [(i, (i + 2) % n_x) for i in range(n_x)]
    perm_bwd2 = [(i, (i - 2) % n_x) for i in range(n_x)]
    coeff = problem.a2tau2
    if chunk_len is None:
        start = 1 if start_step is None else start_step
        nblocks = (nsteps - start) // k
        rem = (nsteps - start) - nblocks * k
    else:
        nblocks = chunk_len // k
        rem = chunk_len - nblocks * k
    multi = n_x > 1

    def nm_scalar():
        if not multi:
            return jnp.int32(r)
        return jnp.where(
            lax.axis_index("x") == n_x - 1, r, d
        ).astype(jnp.int32)

    def ghosts_of(both, kk):
        """True cyclic real-plane ghosts of the leading-stacked fields
        (shape (F, D, N, N)).

        lo = the kk real planes globally preceding this shard's start,
        hi = the kk real planes following its real end.  When the last
        shard owns r < kk real planes, the seam windows span two source
        shards; the static r makes the piece sizes static, so two extra
        two-hop ppermutes + concats assemble them.
        """
        if not multi:
            lo = lax.dynamic_slice_in_dim(both, r - kk, kk, 1)
            hi = lax.slice_in_dim(both, 0, kk, axis=1)
            return lo, hi
        ai = lax.axis_index("x")
        tail_start = jnp.where(ai == n_x - 1, max(r - kk, 0), d - kk)
        tail = lax.dynamic_slice_in_dim(both, tail_start, kk, 1)
        head = lax.slice_in_dim(both, 0, kk, axis=1)
        lo = lax.ppermute(tail, "x", perm_fwd)
        hi = lax.ppermute(head, "x", perm_bwd)
        if r < kk:
            lo2 = lax.ppermute(tail, "x", perm_fwd2)
            hi2 = lax.ppermute(head, "x", perm_bwd2)
            # Shard 0's lo window = [N-kk, N): the last shard's r real
            # planes preceded by the second-to-last shard's tail.
            lo0 = jnp.concatenate([lo2[:, r:], lo[:, :r]], axis=1)
            lo = jnp.where(ai == 0, lo0, lo)
            # Shard MX-2's hi window = the last shard's r real planes
            # followed by shard 0's head (the cyclic wrap).
            him = jnp.concatenate([hi[:, :r], hi2[:, :kk - r]], axis=1)
            hi = jnp.where(ai == n_x - 2, him, hi)
        return lo, hi

    def ghosts(up, uc, kk):
        return ghosts_of(jnp.stack([up, uc]), kk)

    def field_ext(fld, nm, kk):
        """The field's (D + 2kk, N, N) extended array - same lo-ghost /
        hi-splice / zero-junk layout as the state ext, assembled once per
        solve (the field is time-invariant)."""
        if fld is None:
            return None
        lo, hi = ghosts_of(fld[None], kk)
        return build_ext(fld, lo[0], hi[0], nm, kk)

    def build_ext(field, lo_f, hi_f, nm, kk):
        ny, nz = field.shape[1], field.shape[2]
        ext = jnp.concatenate(
            [lo_f, field, jnp.zeros((kk, ny, nz), field.dtype)], 0
        )
        z = jnp.int32(0)
        return lax.dynamic_update_slice(
            ext, hi_f, (jnp.int32(kk) + nm, z, z)
        )

    def kcall(syz_c, rsyz_c, up, uc, sxct_k, kk, with_err, ec2=None):
        nm = nm_scalar()
        lo, hi = ghosts(up, uc, kk)
        ep = build_ext(up, lo[0], hi[0], nm, kk)
        ec = build_ext(uc, lo[1], hi[1], nm, kk)
        return stencil_pallas.fused_kstep_padded(
            ep, ec, nm, syz_c, rsyz_c, sxct_k, k=kk, coeff=coeff,
            inv_h2=problem.inv_h2, ext_c2=ec2, block_x=bx,
            interpret=interpret, with_errors=with_err,
        )

    def layer_rows(syz_c, rsyz_c, u, sxct_row):
        return kfused._layer_rows_local(u, sxct_row, syz_c, rsyz_c, f)

    def local_march(syz_c, rsyz_c, u_prev, u, sxct_loc, first, fld=None):
        rows_d, rows_r = [], []
        nm = nm_scalar()
        ec2_k = field_ext(fld, nm, k)
        ec2_1 = field_ext(fld, nm, 1) if (fld is not None and rem) \
            else None

        def body(carry, nstart):
            u_prev, u = carry
            sxct_k = lax.dynamic_slice(sxct_loc, (nstart + 1, 0), (k, d))
            up, uc, dm, rm = kcall(
                syz_c, rsyz_c, u_prev, u, sxct_k, k, compute_errors,
                ec2_k,
            )
            if not compute_errors:
                dm = rm = jnp.zeros((k, d), f)
            return (up, uc), (dm, rm)

        starts = first + k * jnp.arange(nblocks)
        (u_prev, u), (dmb, rmb) = lax.scan(body, (u_prev, u), starts)
        rows_d.append(dmb.reshape(-1, d))
        rows_r.append(rmb.reshape(-1, d))
        for t in range(rem):
            # == nsteps - rem + 1 + t on the full march (traced-start
            # chunk form, as _make_runner).
            layer = jnp.asarray(first + nblocks * k + 1 + t, jnp.int32)
            sxct_1 = lax.dynamic_slice(
                sxct_loc, (layer, jnp.int32(0)), (1, d)
            )
            u_prev, u, dm, rm = kcall(
                syz_c, rsyz_c, u_prev, u, sxct_1, 1, compute_errors,
                ec2_1,
            )
            if not compute_errors:
                dm = rm = jnp.zeros((1, d), f)
            rows_d.append(dm)
            rows_r.append(rm)
        return u_prev, u, jnp.concatenate(rows_d), jnp.concatenate(rows_r)

    state_spec = P("x")
    rows_spec = P(None, "x")
    plane_spec = P(None, None)

    def assemble(dmax, rmax):
        if compute_errors:
            return _assemble_errors(padded_parts, dmax, rmax)
        z = jnp.zeros((nsteps + 1,), f)
        return z, z

    field_specs = (state_spec,) if has_field else ()

    if chunk_len is not None:
        assert start_step is None

        def local_chunk(u_prev, u, start, sxct_loc, syz_c, rsyz_c,
                        *fargs):
            return local_march(
                syz_c, rsyz_c, u_prev, u, sxct_loc, start,
                fargs[0] if has_field else None,
            )

        local_fn = compat.shard_map(
            local_chunk, mesh=mesh,
            in_specs=(state_spec, state_spec, P(), rows_spec, plane_spec,
                      plane_spec) + field_specs,
            out_specs=(state_spec, state_spec, rows_spec, rows_spec),
            check_vma=False,
        )

        def run_chunk(u_prev, u, start, *fargs):
            u_prev, u, dmax, rmax = local_fn(
                u_prev, u, start, sxct_all, syz, rsyz, *fargs
            )
            if compute_errors:
                ctk = lax.dynamic_slice(ct, (start + 1,), (chunk_len,))
                abs_e, rel_e = kfused._block_errors(
                    dmax, rmax, ctk, xmask_p, inv_absx_p
                )
            else:
                abs_e = rel_e = jnp.zeros((chunk_len,), f)
            return u_prev, u, abs_e, rel_e

        return jax.jit(run_chunk), (dg, pad)

    if start_step is None:

        def local(u0, sxct_loc, syz_c, rsyz_c, *fargs):
            fld = fargs[0] if has_field else None
            _, s0, _, _ = kcall(
                syz_c, rsyz_c, u0, u0, jnp.zeros((1, d), f), 1, False,
                field_ext(fld, nm_scalar(), 1),
            )
            u1 = (0.5 * (u0.astype(f) + s0.astype(f))).astype(dtype)
            if compute_errors:
                d1, r1 = layer_rows(syz_c, rsyz_c, u1, sxct_loc[1])
            else:
                d1 = r1 = jnp.zeros((1, d), f)
            u_prev, u, rows_d, rows_r = local_march(
                syz_c, rsyz_c, u0, u1, sxct_loc, 1, fld
            )
            zero = jnp.zeros((1, d), f)
            return (
                u_prev, u,
                jnp.concatenate([zero, d1, rows_d]),
                jnp.concatenate([zero, r1, rows_r]),
            )

        local_fn = compat.shard_map(
            local, mesh=mesh,
            in_specs=(state_spec, rows_spec, plane_spec, plane_spec)
            + field_specs,
            out_specs=(state_spec, state_spec, rows_spec, rows_spec),
            check_vma=False,
        )

        def run(*fargs):
            u0 = jnp.pad(
                leapfrog.initial_layer0(problem, dtype),
                ((0, pad), (0, 0), (0, 0)),
            )
            u0 = lax.with_sharding_constraint(
                u0, NamedSharding(mesh, state_spec)
            )
            u_prev, u, dmax, rmax = local_fn(
                u0, sxct_all, syz, rsyz, *fargs
            )
            abs_e, rel_e = assemble(dmax, rmax)
            return u_prev, u, abs_e, rel_e

        return jax.jit(run), (dg, pad)

    def local_resume(u_prev, u, sxct_loc, syz_c, rsyz_c, *fargs):
        u_prev, u, rows_d, rows_r = local_march(
            syz_c, rsyz_c, u_prev, u, sxct_loc, start_step,
            fargs[0] if has_field else None,
        )
        head = jnp.zeros((start_step + 1, d), f)
        return (
            u_prev, u,
            jnp.concatenate([head, rows_d]),
            jnp.concatenate([head, rows_r]),
        )

    local_fn = compat.shard_map(
        local_resume, mesh=mesh,
        in_specs=(state_spec, state_spec, rows_spec, plane_spec,
                  plane_spec) + field_specs,
        out_specs=(state_spec, state_spec, rows_spec, rows_spec),
        check_vma=False,
    )

    def run(u_prev, u, *fargs):
        u_prev, u, dmax, rmax = local_fn(u_prev, u, sxct_all, syz, rsyz,
                                         *fargs)
        abs_e, rel_e = assemble(dmax, rmax)
        return u_prev, u, abs_e, rel_e

    return jax.jit(run), (dg, pad)


def _to_topology_layout(u, problem: Problem, mesh, n_x: int):
    """Re-place a padded-runner global (MX*D, N, N) field on the standard
    Topology layout (MX*ceil(N/MX) planes, P(x,y,z)-sharded).

    The padded runner's D is kernel-driven (a multiple of bx) and differs
    from Topology's ceil block, so its outputs cannot be checkpointed
    per-shard as-is (slicing to N outside jit collapses the sharding and
    every device would claim shard starts (0,0,0)).  One device_put onto
    the canonical layout makes uneven k-fused results indistinguishable
    from every other sharded result: save_sharded_checkpoint,
    gather_fundamental and resume all consume them unchanged.
    """
    from wavetpu.core.grid import AXIS_NAMES, Topology

    topo = Topology(N=problem.N, mesh_shape=(n_x, 1, 1))
    padx = topo.padded[0] - problem.N
    a = jnp.pad(u[: problem.N], ((0, padx), (0, 0), (0, 0)))
    return jax.device_put(a, NamedSharding(mesh, P(*AXIS_NAMES)))


def _resolve_grid(mesh_shape, n_shards, devices):
    """(n_x, n_y) from an explicit (MX, MY, 1) mesh_shape, the x-only
    n_shards shorthand, or all visible devices."""
    if mesh_shape is not None:
        if len(mesh_shape) != 3 or mesh_shape[2] != 1:
            raise ValueError(
                f"k-fusion supports (MX, MY, 1) meshes, got {mesh_shape}"
            )
        return mesh_shape[0], mesh_shape[1]
    if n_shards is None:
        n_shards = len(devices)
    return n_shards, 1


def solve_sharded_kfused(
    problem: Problem,
    n_shards: Optional[int] = None,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[Tuple[int, int, int]] = None,
    c2tau2_field=None,
) -> SolveResult:
    """k-fused solve over an (MX, MY, 1) mesh; reference timing phases as
    `leapfrog.solve`.  `n_shards` is the x-only shorthand (MX, 1, 1);
    `mesh_shape` selects a 2D decomposition (defaults to all devices on
    the x axis).  `c2tau2_field` threads the variable-c slab through the
    sharded onion (sharded on the same mesh, k-deep ghost planes
    exchanged once per solve; compute_errors=False required)."""
    if devices is None:
        devices = jax.devices()
    n_x, n_y = _resolve_grid(mesh_shape, n_shards, devices)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _validate(problem, k, n_x, n_y, c2tau2_field, compute_errors)
    nsteps = problem.timesteps if stop_step is None else stop_step
    if not 1 <= nsteps <= problem.timesteps:
        raise ValueError(
            f"stop_step must be in [1, {problem.timesteps}], got {nsteps}"
        )
    mesh = build_mesh((n_x, n_y, 1), devices[: n_x * n_y])
    has_field = c2tau2_field is not None
    f = stencil_ref.compute_dtype(dtype)
    run_params = ()
    if _is_even(problem, k, n_x):
        runner, _ = _make_runner(
            problem, mesh, (n_x, n_y), dtype, k, compute_errors, nsteps,
            None, block_x, interpret, has_field,
        )
        sliced = False
        if has_field:
            run_params = (jax.device_put(
                jnp.asarray(c2tau2_field, dtype=f),
                NamedSharding(mesh, P("x", "y")),
            ),)
    else:
        runner, (dg, _) = _make_padded_runner(
            problem, mesh, n_x, dtype, k, compute_errors, nsteps,
            None, block_x, interpret, has_field,
        )
        sliced = True
        if has_field:
            fld = jnp.pad(
                jnp.asarray(c2tau2_field, dtype=f),
                ((0, dg - problem.N), (0, 0), (0, 0)),
            )
            run_params = (jax.device_put(
                fld, NamedSharding(mesh, P("x"))
            ),)
    (u_prev, u_cur, abs_all, rel_all), init_s, solve_s = (
        leapfrog._timed_compile_run(
            runner, run_params, sync=lambda out: np.asarray(out[2])
        )
    )
    if sliced:
        u_prev = _to_topology_layout(u_prev, problem, mesh, n_x)
        u_cur = _to_topology_layout(u_cur, problem, mesh, n_x)
    result = SolveResult(
        problem=problem,
        u_prev=u_prev,
        u_cur=u_cur,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=stop_step,
        final_step=stop_step if stop_step is not None else problem.timesteps,
    )
    obs_metrics.record_solve(
        result, "sharded_kfused", k=k,
        with_field=c2tau2_field is not None, block_x=block_x,
        # Roofline model: the block is chosen against the SHARD depth
        # with ghost buffers in the pipeline, same as the kernel's own
        # chooser call above (ceil covers the pad-and-mask layout).
        depth=-(-problem.N // n_x), ghosts=True,
    )
    return result


def resume_sharded_kfused(
    problem: Problem,
    u_prev,
    u_cur,
    start_step: int,
    n_shards: Optional[int] = None,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[Tuple[int, int, int]] = None,
    c2tau2_field=None,
) -> SolveResult:
    """Re-enter the sharded k-fused march at layer `start_step`.

    `u_prev`/`u_cur` may be global jax.Arrays (a live sharded result) or
    host arrays (a loaded checkpoint); they are placed P("x", "y") on the
    mesh (see `solve_sharded_kfused` for the mesh parameters).  A
    variable-c checkpoint resumes under the same re-passed
    `c2tau2_field` (checkpoints store state, not the field).
    """
    if devices is None:
        devices = jax.devices()
    n_x, n_y = _resolve_grid(mesh_shape, n_shards, devices)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _validate(problem, k, n_x, n_y, c2tau2_field, compute_errors)
    nsteps = problem.timesteps
    if not 1 <= start_step <= nsteps:
        raise ValueError(
            f"start_step must be in [1, {nsteps}], got {start_step}"
        )
    mesh = build_mesh((n_x, n_y, 1), devices[: n_x * n_y])
    sliced = not _is_even(problem, k, n_x)
    has_field = c2tau2_field is not None
    f = stencil_ref.compute_dtype(dtype)
    if not sliced:
        runner, _ = _make_runner(
            problem, mesh, (n_x, n_y), dtype, k, compute_errors, nsteps,
            start_step, block_x, interpret, has_field,
        )
        sharding = NamedSharding(mesh, P("x", "y"))
        args = (
            jax.device_put(jnp.asarray(u_prev, dtype), sharding),
            jax.device_put(jnp.asarray(u_cur, dtype), sharding),
        )
        if has_field:
            args = args + (jax.device_put(
                jnp.asarray(c2tau2_field, dtype=f), sharding
            ),)
    else:
        runner, (dg, _) = _make_padded_runner(
            problem, mesh, n_x, dtype, k, compute_errors, nsteps,
            start_step, block_x, interpret, has_field,
        )
        sharding = NamedSharding(mesh, P("x"))
        padw = ((0, dg - problem.N), (0, 0), (0, 0))
        args = (
            jax.device_put(
                jnp.pad(jnp.asarray(u_prev, dtype)[: problem.N], padw),
                sharding,
            ),
            jax.device_put(
                jnp.pad(jnp.asarray(u_cur, dtype)[: problem.N], padw),
                sharding,
            ),
        )
        if has_field:
            args = args + (jax.device_put(
                jnp.pad(jnp.asarray(c2tau2_field, dtype=f), padw),
                sharding,
            ),)
    (u_p, u_c, abs_all, rel_all), init_s, solve_s = (
        leapfrog._timed_compile_run(
            runner, args, sync=lambda out: np.asarray(out[2])
        )
    )
    if sliced:
        u_p = _to_topology_layout(u_p, problem, mesh, n_x)
        u_c = _to_topology_layout(u_c, problem, mesh, n_x)
    return SolveResult(
        problem=problem,
        u_prev=u_p,
        u_cur=u_c,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=nsteps - start_step,
        final_step=nsteps,
    )


def make_chunk_runner(
    problem: Problem,
    mesh,
    grid: Tuple[int, int],
    dtype=jnp.float32,
    length: int = 4,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    has_field: bool = False,
):
    """Fixed-length sharded k-fused re-entry for supervised solves.

    Returns `(runner, layout)` where `runner(u_prev, u_cur, start[,
    field])` marches layers start+1..start+length with a RUNTIME `start`
    (run/supervisor.py's cached chunk program).  On the even
    decomposition `layout` is None and state rides P("x","y") directly;
    on the pad-and-mask path `layout` is `(dg, pad)` and the caller
    feeds/receives the padded (MX*D, N, N) x-sharded globals (see
    `_make_padded_runner`; `_to_topology_layout` converts for
    checkpointing).
    """
    n_x, n_y = grid
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _validate(problem, k, n_x, n_y, None, True)
    if length < 1:
        raise ValueError(f"chunk length must be >= 1, got {length}")
    if _is_even(problem, k, n_x):
        runner, _ = _make_runner(
            problem, mesh, grid, dtype, k, compute_errors, None, None,
            block_x, interpret, has_field, chunk_len=length,
        )
        return runner, None
    runner, layout = _make_padded_runner(
        problem, mesh, n_x, dtype, k, compute_errors, None, None,
        block_x, interpret, has_field, chunk_len=length,
    )
    return runner, layout
