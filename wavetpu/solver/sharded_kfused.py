"""Temporally fused k-step solver over an (MX, MY, 1)-sharded device mesh.

Composes the repo's two flagship mechanisms: the k-step VMEM-onion kernel
(solver/kfused.py - the single-chip HBM-traffic win) and the shard_map
decomposition with ppermute halo exchange (solver/sharded.py - the
reference's MPI role, mpi_new.cpp:324-372).  Exchanging k-deep ghosts per
k LAYERS amortizes the per-step latency cost of the reference's per-layer
exchange (mpi_new.cpp:327-352) by k - halo BYTES per layer stay the same,
messages drop k-fold.

Two kernel regimes, dispatched on the mesh:

 * **x-only** ((P, 1, 1)): y/z stay full-domain per shard, so the
   in-kernel y/z rolls and Dirichlet mask are exactly the single-device
   kernel's; one cyclic x-ppermute pair per field per k-block.
 * **x/y** ((MX, MY, 1)): each block is first extended with k cyclic
   ghost ROWS per y side (one y-ppermute pair), then the x ghost planes
   are ppermute'd FROM THE EXTENDED blocks - the diagonal corner data a
   2D onion needs arrives through that sequencing with no extra
   collectives.  The kernel keeps the extended y width constant (rolls
   still deliver neighbours for every onion-valid row; staleness creeps
   only through ghost rows that are never written back) and re-imposes
   the Dirichlet zero on the WRAPPED global y index, so evolved ghost
   copies of the y=0 stored plane stay zero.  Ops per valid element are
   identical to the single-device kernel's - results stay bitwise equal
   across every mesh shape (tests/test_sharded_kfused.py).

z stays unsharded (MZ = 1): z is the 128-lane dimension, and cutting it
would shrink every vector register tile; BASELINE's target meshes up to
256 chips factor as (MX, MY, 1) without it.

Per-layer L-inf errors: each shard's kernel emits (k, N/MX) per-x-plane
maxes over its y range, pmax'd over the y axis and concatenated along x
(out_spec P(None, "x")) into global (layer, N) rows; the tiny per-plane
rescale + interior mask run on the replicated result.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from wavetpu.core.grid import build_mesh
from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.solver import kfused, leapfrog
from wavetpu.solver.leapfrog import SolveResult


def _validate(problem: Problem, k: int, n_x: int, n_y: int = 1):
    if k < 2:
        raise ValueError(f"k must be >= 2 (got {k})")
    if n_x < 1 or n_y < 1:
        raise ValueError(
            f"mesh axes must be >= 1 (got MX={n_x}, MY={n_y})"
        )
    if problem.N % n_x:
        raise ValueError(
            f"x-sharded k-fusion needs N % shards == 0 "
            f"(N={problem.N}, shards={n_x})"
        )
    if (problem.N // n_x) % k:
        raise ValueError(
            f"k={k} must divide the shard depth {problem.N // n_x}"
        )
    if problem.N % n_y:
        raise ValueError(
            f"y-sharded k-fusion needs N % y-shards == 0 "
            f"(N={problem.N}, y-shards={n_y})"
        )
    if problem.N // n_y < k:
        raise ValueError(
            f"k={k} exceeds the y shard depth {problem.N // n_y} "
            f"(the k-row ghost strip must fit one neighbour)"
        )


def _assemble_errors(oracle_parts, dmax_rows, rmax_rows):
    """Global per-layer abs/rel errors from (layers, N) plane-max rows.

    Thin adapter over the single source of the error-rescale contract
    (kfused._oracle_parts / _block_errors): the same exact-zero guards and
    x!=0 interior mask, applied to all layers' rows at once (ctk is just
    longer)."""
    _, ct, _, _, xmask, inv_absx = oracle_parts
    return kfused._block_errors(
        dmax_rows, rmax_rows, ct[: dmax_rows.shape[0]], xmask, inv_absx
    )


def _make_runner(
    problem: Problem,
    mesh,
    shard_grid: Tuple[int, int],
    dtype,
    k: int,
    compute_errors: bool,
    nsteps: int,
    start_step: Optional[int],
    block_x: Optional[int],
    interpret: bool,
):
    """One jitted program: [bootstrap +] k-block scan + 1-step remainder.

    `shard_grid` = (n_x, n_y) mesh extents.  n_y == 1 runs the x-only
    kernel (in-shard y rolls ARE the boundary condition); n_y > 1 extends
    each block with k ghost rows per side via a cyclic y-ppermute pair and
    runs the xy kernel - the x ghosts are then sliced FROM the extended
    blocks, which ships the diagonal corners without extra collectives.

    `start_step=None` builds the from-scratch solver (bootstrap included);
    an int builds the resume program re-entering at that layer.  Both use
    the same local march so the per-layer op sequence is identical (the
    bitwise-resume invariant, solver/kfused.py).
    """
    n_x, n_y = shard_grid
    f = stencil_ref.compute_dtype(dtype)
    nl = problem.N // n_x
    nl_y = problem.N // n_y
    oracle_parts = kfused._oracle_parts(problem, f)
    sx, ct, syz, rsyz, _, _ = oracle_parts
    sxct_all = ct[:, None] * sx[None, :]            # (T+1, N)
    perm_fwd = [(i, (i + 1) % n_x) for i in range(n_x)]
    perm_bwd = [(i, (i - 1) % n_x) for i in range(n_x)]
    perm_fwd_y = [(i, (i + 1) % n_y) for i in range(n_y)]
    perm_bwd_y = [(i, (i - 1) % n_y) for i in range(n_y)]
    coeff = problem.a2tau2
    start = 1 if start_step is None else start_step
    nblocks = (nsteps - start) // k
    rem = (nsteps - start) - nblocks * k

    def ghosts(a, depth):
        """(lo, hi) ghost planes from the cyclic x-neighbours."""
        lo = lax.ppermute(a[-depth:], "x", perm_fwd)
        hi = lax.ppermute(a[:depth], "x", perm_bwd)
        return lo, hi

    def extend_y(a, depth):
        """Block extended with `depth` cyclic ghost rows per y side."""
        lo = lax.ppermute(a[:, -depth:], "y", perm_fwd_y)
        hi = lax.ppermute(a[:, :depth], "y", perm_bwd_y)
        return jnp.concatenate([lo, a, hi], axis=1)

    def kcall(syz_c, rsyz_c, u_prev, u, sxct_k, kk, with_errors, bxo):
        if n_y == 1:
            return stencil_pallas.fused_kstep_sharded(
                u_prev, u, ghosts(u_prev, kk), ghosts(u, kk), syz_c,
                rsyz_c, sxct_k, k=kk, coeff=coeff, inv_h2=problem.inv_h2,
                block_x=bxo, interpret=interpret, with_errors=with_errors,
            )
        pe = extend_y(u_prev, kk)
        ce = extend_y(u, kk)
        y0 = lax.axis_index("y") * nl_y
        up, uc, dm, rm = stencil_pallas.fused_kstep_sharded_xy(
            pe, ce, ghosts(pe, kk), ghosts(ce, kk), syz_c, rsyz_c,
            sxct_k, y0, problem.N, k=kk, nl_y=nl_y, coeff=coeff,
            inv_h2=problem.inv_h2, block_x=bxo, interpret=interpret,
            with_errors=with_errors,
        )
        if with_errors:
            dm = lax.pmax(dm, "y")
            rm = lax.pmax(rm, "y")
        return up, uc, dm, rm

    def layer_rows(syz_c, rsyz_c, u, sxct_row):
        """(1, nl) plane-max rows of a stored layer (jnp path, used for
        the bootstrap layer only); max over this shard's y slice, pmax'd
        across the y mesh axis."""
        diff = jnp.abs(u.astype(f) - sxct_row[:, None, None] * syz_c[None])
        d = jnp.max(diff, axis=(1, 2))[None]
        r = jnp.max(diff * rsyz_c[None], axis=(1, 2))[None]
        if n_y > 1:
            d = lax.pmax(d, "y")
            r = lax.pmax(r, "y")
        return d, r

    def local_march(syz_c, rsyz_c, u_prev, u, sxct_loc, first):
        """Layers first+1..nsteps; returns carry + (rows_d, rows_r) for
        exactly nsteps - first layers."""
        rows_d, rows_r = [], []

        def body(carry, nstart):
            u_prev, u = carry
            sxct_k = lax.dynamic_slice(sxct_loc, (nstart + 1, 0), (k, nl))
            up, uc, dm, rm = kcall(
                syz_c, rsyz_c, u_prev, u, sxct_k, k, compute_errors,
                block_x,
            )
            if not compute_errors:
                dm = rm = jnp.zeros((k, nl), f)
            return (up, uc), (dm, rm)

        starts = first + k * jnp.arange(nblocks)
        (u_prev, u), (dmb, rmb) = lax.scan(body, (u_prev, u), starts)
        rows_d.append(dmb.reshape(-1, nl))
        rows_r.append(rmb.reshape(-1, nl))
        for t in range(rem):
            layer = nsteps - rem + 1 + t
            sxct_1 = lax.dynamic_slice(sxct_loc, (layer, 0), (1, nl))
            u_prev, u, dm, rm = kcall(
                syz_c, rsyz_c, u_prev, u, sxct_1, 1, compute_errors, None
            )
            if not compute_errors:
                dm = rm = jnp.zeros((1, nl), f)
            rows_d.append(dm)
            rows_r.append(rm)
        return u_prev, u, jnp.concatenate(rows_d), jnp.concatenate(rows_r)

    state_spec = P("x", "y")
    rows_spec = P(None, "x")
    plane_spec = P("y", None)

    if start_step is None:

        def local(u0, sxct_loc, syz_c, rsyz_c):
            # kcall returns (layer n+k-1, layer n+k, ...): the stepped
            # field u0 + C*lap(u0) is the SECOND output.
            _, s0, _, _ = kcall(
                syz_c, rsyz_c, u0, u0, jnp.zeros((1, nl), f), 1, False,
                None,
            )
            u1 = (0.5 * (u0.astype(f) + s0.astype(f))).astype(dtype)
            if compute_errors:
                d1, r1 = layer_rows(syz_c, rsyz_c, u1, sxct_loc[1])
            else:
                d1 = r1 = jnp.zeros((1, nl), f)
            u_prev, u, rows_d, rows_r = local_march(
                syz_c, rsyz_c, u0, u1, sxct_loc, 1
            )
            zero = jnp.zeros((1, nl), f)
            return (
                u_prev, u,
                jnp.concatenate([zero, d1, rows_d]),
                jnp.concatenate([zero, r1, rows_r]),
            )

        local_fn = jax.shard_map(
            local, mesh=mesh,
            in_specs=(state_spec, rows_spec, plane_spec, plane_spec),
            out_specs=(state_spec, state_spec, rows_spec, rows_spec),
            # vma inference cannot see through the pallas kernel's mixed
            # ghost/wraparound concat (same workaround as solver/timing.py)
            check_vma=False,
        )

        def run():
            u0 = lax.with_sharding_constraint(
                leapfrog.initial_layer0(problem, dtype),
                NamedSharding(mesh, state_spec),
            )
            u_prev, u, dmax, rmax = local_fn(u0, sxct_all, syz, rsyz)
            if compute_errors:
                abs_e, rel_e = _assemble_errors(oracle_parts, dmax, rmax)
            else:
                abs_e = rel_e = jnp.zeros((nsteps + 1,), f)
            return u_prev, u, abs_e, rel_e

        return jax.jit(run), ()

    def local_resume(u_prev, u, sxct_loc, syz_c, rsyz_c):
        u_prev, u, rows_d, rows_r = local_march(
            syz_c, rsyz_c, u_prev, u, sxct_loc, start_step
        )
        head = jnp.zeros((start_step + 1, nl), f)
        return (
            u_prev, u,
            jnp.concatenate([head, rows_d]),
            jnp.concatenate([head, rows_r]),
        )

    local_fn = jax.shard_map(
        local_resume, mesh=mesh,
        in_specs=(state_spec, state_spec, rows_spec, plane_spec,
                  plane_spec),
        out_specs=(state_spec, state_spec, rows_spec, rows_spec),
        check_vma=False,
    )

    def run(u_prev, u):
        u_prev, u, dmax, rmax = local_fn(u_prev, u, sxct_all, syz, rsyz)
        if compute_errors:
            abs_e, rel_e = _assemble_errors(oracle_parts, dmax, rmax)
        else:
            abs_e = rel_e = jnp.zeros((nsteps + 1,), f)
        return u_prev, u, abs_e, rel_e

    return jax.jit(run), None


def _resolve_grid(mesh_shape, n_shards, devices):
    """(n_x, n_y) from an explicit (MX, MY, 1) mesh_shape, the x-only
    n_shards shorthand, or all visible devices."""
    if mesh_shape is not None:
        if len(mesh_shape) != 3 or mesh_shape[2] != 1:
            raise ValueError(
                f"k-fusion supports (MX, MY, 1) meshes, got {mesh_shape}"
            )
        return mesh_shape[0], mesh_shape[1]
    if n_shards is None:
        n_shards = len(devices)
    return n_shards, 1


def solve_sharded_kfused(
    problem: Problem,
    n_shards: Optional[int] = None,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[Tuple[int, int, int]] = None,
) -> SolveResult:
    """k-fused solve over an (MX, MY, 1) mesh; reference timing phases as
    `leapfrog.solve`.  `n_shards` is the x-only shorthand (MX, 1, 1);
    `mesh_shape` selects a 2D decomposition (defaults to all devices on
    the x axis)."""
    if devices is None:
        devices = jax.devices()
    n_x, n_y = _resolve_grid(mesh_shape, n_shards, devices)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _validate(problem, k, n_x, n_y)
    nsteps = problem.timesteps if stop_step is None else stop_step
    if not 1 <= nsteps <= problem.timesteps:
        raise ValueError(
            f"stop_step must be in [1, {problem.timesteps}], got {nsteps}"
        )
    mesh = build_mesh((n_x, n_y, 1), devices[: n_x * n_y])
    runner, _ = _make_runner(
        problem, mesh, (n_x, n_y), dtype, k, compute_errors, nsteps,
        None, block_x, interpret,
    )
    (u_prev, u_cur, abs_all, rel_all), init_s, solve_s = (
        leapfrog._timed_compile_run(
            runner, (), sync=lambda out: np.asarray(out[2])
        )
    )
    return SolveResult(
        problem=problem,
        u_prev=u_prev,
        u_cur=u_cur,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=stop_step,
        final_step=stop_step if stop_step is not None else problem.timesteps,
    )


def resume_sharded_kfused(
    problem: Problem,
    u_prev,
    u_cur,
    start_step: int,
    n_shards: Optional[int] = None,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[Tuple[int, int, int]] = None,
) -> SolveResult:
    """Re-enter the sharded k-fused march at layer `start_step`.

    `u_prev`/`u_cur` may be global jax.Arrays (a live sharded result) or
    host arrays (a loaded checkpoint); they are placed P("x", "y") on the
    mesh (see `solve_sharded_kfused` for the mesh parameters).
    """
    if devices is None:
        devices = jax.devices()
    n_x, n_y = _resolve_grid(mesh_shape, n_shards, devices)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _validate(problem, k, n_x, n_y)
    nsteps = problem.timesteps
    if not 1 <= start_step <= nsteps:
        raise ValueError(
            f"start_step must be in [1, {nsteps}], got {start_step}"
        )
    mesh = build_mesh((n_x, n_y, 1), devices[: n_x * n_y])
    runner, _ = _make_runner(
        problem, mesh, (n_x, n_y), dtype, k, compute_errors, nsteps,
        start_step, block_x, interpret,
    )
    sharding = NamedSharding(mesh, P("x", "y"))
    args = (
        jax.device_put(jnp.asarray(u_prev, dtype), sharding),
        jax.device_put(jnp.asarray(u_cur, dtype), sharding),
    )
    (u_p, u_c, abs_all, rel_all), init_s, solve_s = (
        leapfrog._timed_compile_run(
            runner, args, sync=lambda out: np.asarray(out[2])
        )
    )
    return SolveResult(
        problem=problem,
        u_prev=u_p,
        u_cur=u_c,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=nsteps - start_step,
        final_step=nsteps,
    )
