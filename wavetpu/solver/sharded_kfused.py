"""Temporally fused k-step solver over an x-sharded device mesh.

Composes the repo's two flagship mechanisms: the k-step VMEM-onion kernel
(solver/kfused.py - the single-chip HBM-traffic win) and the shard_map
decomposition with ppermute halo exchange (solver/sharded.py - the
reference's MPI role, mpi_new.cpp:324-372).  The decomposition is x-only
((P, 1, 1) mesh, N % P == 0): each shard owns a contiguous slab of
x-planes with y/z full-domain, so the in-kernel y/z rolls and Dirichlet
mask are exactly the single-device kernel's, and one cyclic ppermute pair
per field delivers the k boundary planes a k-block needs.  Exchanging k
planes per k LAYERS also amortizes the per-step latency cost of the
reference's per-layer exchange (mpi_new.cpp:327-352) by k - halo BYTES
per layer stay the same, messages drop k-fold.

A full 3D mesh with k-fusion would need trapezoidal ghost regions on 6
faces + edges + corners (the y/z rolls stop being the boundary condition
once those axes are cut); measured single-chip gains come almost entirely
from the x-onion, so the x-only restriction keeps the kernel identical to
the proven one.  For 3D decompositions the 1-step sharded solver
(solver/sharded.py) remains the general path.

Per-layer L-inf errors: each shard's kernel emits (k, N/P) per-x-plane
maxes; shard_map concatenates them along x (out_spec P(None, "x")) into
global (layer, N) rows and the tiny per-plane rescale + interior mask run
on the replicated result - no pmax collective needed, the rows ARE the
reduction layout.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from wavetpu.core.grid import build_mesh
from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.solver import kfused, leapfrog
from wavetpu.solver.leapfrog import SolveResult


def _validate(problem: Problem, k: int, n_shards: int):
    if k < 2:
        raise ValueError(f"k must be >= 2 (got {k})")
    if problem.N % n_shards:
        raise ValueError(
            f"x-sharded k-fusion needs N % shards == 0 "
            f"(N={problem.N}, shards={n_shards})"
        )
    if (problem.N // n_shards) % k:
        raise ValueError(
            f"k={k} must divide the shard depth {problem.N // n_shards}"
        )


def _assemble_errors(oracle_parts, dmax_rows, rmax_rows):
    """Global per-layer abs/rel errors from (layers, N) plane-max rows.

    Thin adapter over the single source of the error-rescale contract
    (kfused._oracle_parts / _block_errors): the same exact-zero guards and
    x!=0 interior mask, applied to all layers' rows at once (ctk is just
    longer)."""
    _, ct, _, _, xmask, inv_absx = oracle_parts
    return kfused._block_errors(
        dmax_rows, rmax_rows, ct[: dmax_rows.shape[0]], xmask, inv_absx
    )


def _make_runner(
    problem: Problem,
    mesh,
    n_shards: int,
    dtype,
    k: int,
    compute_errors: bool,
    nsteps: int,
    start_step: Optional[int],
    block_x: Optional[int],
    interpret: bool,
):
    """One jitted program: [bootstrap +] k-block scan + 1-step remainder.

    `start_step=None` builds the from-scratch solver (bootstrap included);
    an int builds the resume program re-entering at that layer.  Both use
    the same local march so the per-layer op sequence is identical (the
    bitwise-resume invariant, solver/kfused.py).
    """
    f = stencil_ref.compute_dtype(dtype)
    nl = problem.N // n_shards
    oracle_parts = kfused._oracle_parts(problem, f)
    sx, ct, syz, rsyz, _, _ = oracle_parts
    sxct_all = ct[:, None] * sx[None, :]            # (T+1, N)
    perm_fwd = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    perm_bwd = [(i, (i - 1) % n_shards) for i in range(n_shards)]
    coeff = problem.a2tau2
    start = 1 if start_step is None else start_step
    nblocks = (nsteps - start) // k
    rem = (nsteps - start) - nblocks * k

    def ghosts(a, depth):
        """(lo, hi) ghost planes from the cyclic x-neighbours."""
        lo = lax.ppermute(a[-depth:], "x", perm_fwd)
        hi = lax.ppermute(a[:depth], "x", perm_bwd)
        return lo, hi

    def kcall(u_prev, u, sxct_k, kk, with_errors, bxo):
        return stencil_pallas.fused_kstep_sharded(
            u_prev, u, ghosts(u_prev, kk), ghosts(u, kk), syz, rsyz,
            sxct_k, k=kk, coeff=coeff, inv_h2=problem.inv_h2,
            block_x=bxo, interpret=interpret, with_errors=with_errors,
        )

    def layer_rows(u, sxct_row):
        """(1, nl) plane-max rows of a stored layer (jnp path, used for
        the bootstrap layer only)."""
        diff = jnp.abs(u.astype(f) - sxct_row[:, None, None] * syz[None])
        return (
            jnp.max(diff, axis=(1, 2))[None],
            jnp.max(diff * rsyz[None], axis=(1, 2))[None],
        )

    def local_march(u_prev, u, sxct_loc, first):
        """Layers first+1..nsteps; returns carry + (rows_d, rows_r) for
        exactly nsteps - first layers."""
        rows_d, rows_r = [], []

        def body(carry, nstart):
            u_prev, u = carry
            sxct_k = lax.dynamic_slice(sxct_loc, (nstart + 1, 0), (k, nl))
            up, uc, dm, rm = kcall(
                u_prev, u, sxct_k, k, compute_errors, block_x
            )
            if not compute_errors:
                dm = rm = jnp.zeros((k, nl), f)
            return (up, uc), (dm, rm)

        starts = first + k * jnp.arange(nblocks)
        (u_prev, u), (dmb, rmb) = lax.scan(body, (u_prev, u), starts)
        rows_d.append(dmb.reshape(-1, nl))
        rows_r.append(rmb.reshape(-1, nl))
        for t in range(rem):
            layer = nsteps - rem + 1 + t
            sxct_1 = lax.dynamic_slice(sxct_loc, (layer, 0), (1, nl))
            u_prev, u, dm, rm = kcall(
                u_prev, u, sxct_1, 1, compute_errors, None
            )
            if not compute_errors:
                dm = rm = jnp.zeros((1, nl), f)
            rows_d.append(dm)
            rows_r.append(rm)
        return u_prev, u, jnp.concatenate(rows_d), jnp.concatenate(rows_r)

    state_spec = P("x")
    rows_spec = P(None, "x")

    if start_step is None:

        def local(u0, sxct_loc):
            # kcall returns (layer n+k-1, layer n+k, ...): the stepped
            # field u0 + C*lap(u0) is the SECOND output.
            _, s0, _, _ = kcall(
                u0, u0, jnp.zeros((1, nl), f), 1, False, None
            )
            u1 = (0.5 * (u0.astype(f) + s0.astype(f))).astype(dtype)
            if compute_errors:
                d1, r1 = layer_rows(u1, sxct_loc[1])
            else:
                d1 = r1 = jnp.zeros((1, nl), f)
            u_prev, u, rows_d, rows_r = local_march(u0, u1, sxct_loc, 1)
            zero = jnp.zeros((1, nl), f)
            return (
                u_prev, u,
                jnp.concatenate([zero, d1, rows_d]),
                jnp.concatenate([zero, r1, rows_r]),
            )

        local_fn = jax.shard_map(
            local, mesh=mesh,
            in_specs=(state_spec, rows_spec),
            out_specs=(state_spec, state_spec, rows_spec, rows_spec),
            # vma inference cannot see through the pallas kernel's mixed
            # ghost/wraparound concat (same workaround as solver/timing.py)
            check_vma=False,
        )

        def run():
            u0 = lax.with_sharding_constraint(
                leapfrog.initial_layer0(problem, dtype),
                NamedSharding(mesh, state_spec),
            )
            u_prev, u, dmax, rmax = local_fn(u0, sxct_all)
            if compute_errors:
                abs_e, rel_e = _assemble_errors(oracle_parts, dmax, rmax)
            else:
                abs_e = rel_e = jnp.zeros((nsteps + 1,), f)
            return u_prev, u, abs_e, rel_e

        return jax.jit(run), ()

    def local_resume(u_prev, u, sxct_loc):
        u_prev, u, rows_d, rows_r = local_march(
            u_prev, u, sxct_loc, start_step
        )
        head = jnp.zeros((start_step + 1, nl), f)
        return (
            u_prev, u,
            jnp.concatenate([head, rows_d]),
            jnp.concatenate([head, rows_r]),
        )

    local_fn = jax.shard_map(
        local_resume, mesh=mesh,
        in_specs=(state_spec, state_spec, rows_spec),
        out_specs=(state_spec, state_spec, rows_spec, rows_spec),
        check_vma=False,
    )

    def run(u_prev, u):
        u_prev, u, dmax, rmax = local_fn(u_prev, u, sxct_all)
        if compute_errors:
            abs_e, rel_e = _assemble_errors(oracle_parts, dmax, rmax)
        else:
            abs_e = rel_e = jnp.zeros((nsteps + 1,), f)
        return u_prev, u, abs_e, rel_e

    return jax.jit(run), None


def solve_sharded_kfused(
    problem: Problem,
    n_shards: Optional[int] = None,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> SolveResult:
    """k-fused solve over an (n_shards, 1, 1) mesh (defaults to all
    devices); reference timing phases as `leapfrog.solve`."""
    if devices is None:
        devices = jax.devices()
    if n_shards is None:
        n_shards = len(devices)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _validate(problem, k, n_shards)
    nsteps = problem.timesteps if stop_step is None else stop_step
    if not 1 <= nsteps <= problem.timesteps:
        raise ValueError(
            f"stop_step must be in [1, {problem.timesteps}], got {nsteps}"
        )
    mesh = build_mesh((n_shards, 1, 1), devices[:n_shards])
    runner, _ = _make_runner(
        problem, mesh, n_shards, dtype, k, compute_errors, nsteps,
        None, block_x, interpret,
    )
    (u_prev, u_cur, abs_all, rel_all), init_s, solve_s = (
        leapfrog._timed_compile_run(
            runner, (), sync=lambda out: np.asarray(out[2])
        )
    )
    return SolveResult(
        problem=problem,
        u_prev=u_prev,
        u_cur=u_cur,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=stop_step,
        final_step=stop_step if stop_step is not None else problem.timesteps,
    )


def resume_sharded_kfused(
    problem: Problem,
    u_prev,
    u_cur,
    start_step: int,
    n_shards: Optional[int] = None,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> SolveResult:
    """Re-enter the x-sharded k-fused march at layer `start_step`.

    `u_prev`/`u_cur` may be global jax.Arrays (a live sharded result) or
    host arrays (a loaded checkpoint); they are placed P("x") on the mesh.
    """
    if devices is None:
        devices = jax.devices()
    if n_shards is None:
        n_shards = len(devices)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _validate(problem, k, n_shards)
    nsteps = problem.timesteps
    if not 1 <= start_step <= nsteps:
        raise ValueError(
            f"start_step must be in [1, {nsteps}], got {start_step}"
        )
    mesh = build_mesh((n_shards, 1, 1), devices[:n_shards])
    runner, _ = _make_runner(
        problem, mesh, n_shards, dtype, k, compute_errors, nsteps,
        start_step, block_x, interpret,
    )
    sharding = NamedSharding(mesh, P("x"))
    args = (
        jax.device_put(jnp.asarray(u_prev, dtype), sharding),
        jax.device_put(jnp.asarray(u_cur, dtype), sharding),
    )
    (u_p, u_c, abs_all, rel_all), init_s, solve_s = (
        leapfrog._timed_compile_run(
            runner, args, sync=lambda out: np.asarray(out[2])
        )
    )
    return SolveResult(
        problem=problem,
        u_prev=u_p,
        u_cur=u_c,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=nsteps - start_step,
        final_step=nsteps,
    )
