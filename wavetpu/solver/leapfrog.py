"""Single-device time-stepping driver.

Replaces the reference's `calculate_start` + `calculate_num_sol` loops
(openmp_sol.cpp:123-167, mpi_new.cpp:271-372) with one jitted program:
layer-0/1 bootstrap followed by a `lax.scan` over the remaining steps.

Design notes (TPU-first, not a translation):

 * The reference rotates three buffers `grids[n % 3]` (mpi_new.cpp:131,338).
   In functional JAX the scan carry is simply (u_prev, u_cur) - two live
   buffers, with XLA double-buffering the output of each step.
 * The reference's fused error path re-evaluates the analytic solution with
   three sines per point per step (mpi_new.cpp:340).  Here the separable
   oracle (verify/oracle.py) reduces that to broadcasted 1-D factors.
 * Per-layer L-inf errors are accumulated as scan outputs, the analog of
   `max_abs_errors.push_back` (mpi_new.cpp:350) - no host round-trips inside
   the loop.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_ref
from wavetpu.obs import metrics as obs_metrics
from wavetpu.verify import oracle


@dataclasses.dataclass
class SolveResult:
    problem: Problem
    u_prev: jax.Array          # layer timesteps-1 (fundamental (N,N,N) domain)
    u_cur: jax.Array           # layer timesteps
    abs_errors: np.ndarray     # per-layer L-inf abs error, shape (timesteps+1,)
    rel_errors: np.ndarray     # per-layer L-inf rel error, shape (timesteps+1,)
    init_seconds: float = 0.0
    solve_seconds: float = 0.0
    steps_computed: Optional[int] = None  # steps THIS run marched (throughput)
    final_step: Optional[int] = None      # layer index u_cur holds (checkpoint)
    # Compensated-scheme auxiliary state (None on the standard scheme):
    # the increment buffer v = u_n - u_{n-1} and the Kahan carry at
    # final_step - what a checkpoint must store for a bitwise resume.
    comp_v: Optional[jax.Array] = None
    comp_carry: Optional[jax.Array] = None

    @property
    def gcells_per_second(self) -> float:
        steps = (
            self.steps_computed
            if self.steps_computed is not None
            else self.problem.timesteps
        )
        total = self.problem.cells_per_step * steps
        return total / self.solve_seconds / 1e9 if self.solve_seconds else 0.0


class ParamStep(NamedTuple):
    """A step function with runtime array parameters.

    `fn(u_prev, u, problem, params) -> u_next`; `params` (a pytree of
    arrays, e.g. the variable-c field) is threaded through the jitted
    program as a runtime ARGUMENT, not closed over.  Closing over a large
    field would embed it as an HLO literal - at N=512 that is a 512 MB
    constant, which this image's remote-compile tunnel rejects outright
    (HTTP 413) and which any backend would recompile per field.
    """

    fn: Callable
    params: object

    def __call__(self, u_prev, u, problem):
        """Direct use outside a solver (tests, one-off steps)."""
        return self.fn(u_prev, u, problem, self.params)

    @staticmethod
    def materialize(array):
        """Convert a field to a device array and force the host->device
        transfer NOW.  On remote backends the upload is lazy and would
        otherwise land inside the first solve's timed region (a 512 MB
        field costs ~10-20 s through this image's tunnel, tripling the
        apparent solve time)."""
        dev = jnp.asarray(array)
        np.asarray(dev[:1, :1, :1] if dev.ndim == 3 else dev.ravel()[:1])
        return dev


def _as_param_step(step_fn):
    """Normalize the three accepted step_fn forms to (fn4, params)."""
    if step_fn is None:
        return (
            lambda up, u, p, _: stencil_ref.leapfrog_step(up, u, p)
        ), ()
    if isinstance(step_fn, ParamStep):
        return step_fn.fn, step_fn.params
    return (lambda up, u, p, _, f=step_fn: f(up, u, p)), ()


def _error_fn(problem: Problem, dtype, phase: float = oracle.TWO_PI):
    """Returns (u, n) -> (abs_e, rel_e) with precomputed factors closed over.

    The oracle always evaluates in the compute dtype (f32 for bf16 state):
    the error should measure the solver, not the bf16 quantization of the
    analytic field.  `phase` is the initial time phase of the analytic
    solution (default: the reference's 2*pi; per-lane in the ensemble).
    """
    f_dtype = stencil_ref.compute_dtype(dtype)
    sx, sy, sz = oracle.spatial_factors(problem, f_dtype)
    ct_table = oracle.time_factor_table(problem, f_dtype, phase)
    mask = jnp.asarray(oracle.interior_masks_1d(problem.N))

    def errors(u, n):
        f = oracle.analytic_field(sx, sy, sz, ct_table[n])
        return oracle.layer_errors(u.astype(f_dtype), f, mask, mask, mask)

    return errors


def analytic_layer(
    problem: Problem, dtype=jnp.float32, phase: float = oracle.TWO_PI,
    n: int = 0,
) -> jax.Array:
    """The analytic solution at layer n, Dirichlet re-imposed.

    n=0 is the reference's layer-0 fill (`calculate_start`,
    openmp_sol.cpp:126-133); n=1 is the EXACT two-level initialization a
    phase-shifted lane bootstraps with (see make_solver).  bf16 state
    evaluates in f32 and rounds once.
    """
    f = stencil_ref.compute_dtype(dtype)
    sx, sy, sz = oracle.spatial_factors(problem, f)
    ct = oracle.time_factor(problem, n, f, phase)
    u = oracle.analytic_field(sx, sy, sz, ct)
    return stencil_ref.apply_dirichlet(u).astype(dtype)


def initial_layer0(
    problem: Problem, dtype=jnp.float32, phase: float = oracle.TWO_PI
) -> jax.Array:
    """Layer 0: the analytic solution at t=0 (see `analytic_layer`)."""
    return analytic_layer(problem, dtype, phase, 0)


def analytic_increment_layer1(
    problem: Problem, dtype=jnp.float32, phase: float = oracle.TWO_PI
) -> jax.Array:
    """The exact analytic layer-0->1 increment Sx Sy Sz (ct(1) - ct(0)),
    Dirichlet re-imposed - the v1 a shifted-phase COMPENSATED solve
    bootstraps with (the increment of the exact two-level
    initialization).

    Deliberately a pure product, NOT u1 - u0: XLA-CPU FMA-contracts the
    field subtract with the analytic product feeding it differently
    between solo and vmapped program shapes (measured ~1 ulp on this
    jaxlib), which would break the ensemble's bitwise lane-parity
    contract; a product-only expression compiles identically everywhere
    (the same reasoning that picked the analytic bootstrap over a
    tau*u_t correction term - see make_solver)."""
    f = stencil_ref.compute_dtype(dtype)
    sx, sy, sz = oracle.spatial_factors(problem, f)
    dct = (
        oracle.time_factor(problem, 1, f, phase)
        - oracle.time_factor(problem, 0, f, phase)
    )
    u = oracle.analytic_field(sx, sy, sz, dct)
    return stencil_ref.apply_dirichlet(u).astype(dtype)


def initial_state(problem: Problem, dtype=jnp.float32) -> Tuple[jax.Array, jax.Array]:
    """Layers 0 and 1: analytic init + (constant-speed) Taylor half-step.

    Reference: `calculate_start` (openmp_sol.cpp:123-145).  Layer 0 fills the
    whole grid from the analytic solution; layer 1 is the half-step
    u1 = u0 + (a^2 tau^2 / 2) lap(u0), with boundary planes re-imposed.
    bf16 state bootstraps in f32 and rounds once at the end.

    Note: `make_solver` derives layer 1 from its step function instead (so
    variable-c kernels bootstrap with their own field); this helper is the
    standalone constant-speed form for tests and the driver entry hook.
    """
    u0 = initial_layer0(problem, dtype)
    u1 = stencil_ref.taylor_half_step(u0, problem)
    return u0, u1.astype(dtype)


def _scan_layers_xs(
    problem: Problem,
    step: Callable,
    step_params,
    errors: Callable,
    compute_errors: bool,
    dtype,
    u_prev,
    u_cur,
    xs,
):
    """March one layer per element of `xs` (the layer indices, which may be
    traced - the supervisor's chunk runners pass `start + 1 + arange(L)`
    with a runtime `start` so one compiled program serves every chunk).

    The single scan body shared by `make_solver`, `resume`, and
    `make_chunk_runner` - keeping it shared is what makes a resumed or
    supervised run's op sequence identical to the uninterrupted run's (the
    bitwise-equality invariant of tests/test_checkpoint.py and
    tests/test_supervisor.py).
    """

    err_dtype = stencil_ref.compute_dtype(dtype)

    def body(carry, n):
        u_prev, u = carry
        u_next = step(u_prev, u, problem, step_params)
        if compute_errors:
            ae, re = errors(u_next, n)
        else:
            ae = re = jnp.zeros((), err_dtype)
        return (u, u_next), (ae, re)

    return jax.lax.scan(body, (u_prev, u_cur), xs)


def _scan_layers(
    problem: Problem,
    step: Callable,
    step_params,
    errors: Callable,
    compute_errors: bool,
    dtype,
    u_prev,
    u_cur,
    start: int,
    stop: int,
):
    """March layers start+1..stop from carry (layer start-1, layer start)."""
    return _scan_layers_xs(
        problem, step, step_params, errors, compute_errors, dtype,
        u_prev, u_cur, jnp.arange(start + 1, stop + 1),
    )


def _timed_compile_run(runner, example_args=(), sync=None):
    """lower/compile then execute; returns (outputs, init_s, solve_s) with
    the reference's two timing phases (mpi_new.cpp:472-474, 354-357).

    `sync(out)` must force a (small) device-to-host transfer.  On remote
    backends (this image's axon tunnel) `block_until_ready` can return
    before execution for programs with runtime array arguments; only a
    readback proves the program ran, so the transfer sits INSIDE the timed
    region.  Keep it small (e.g. the per-layer error vector, not a field).
    """
    t0 = time.perf_counter()
    lowered = runner.lower(*example_args).compile()
    t1 = time.perf_counter()
    out = lowered(*example_args)
    jax.block_until_ready(out)
    if sync is not None:
        sync(out)
    t2 = time.perf_counter()
    return out, t1 - t0, t2 - t1


def make_solver(
    problem: Problem,
    dtype=jnp.float32,
    step_fn: Optional[Callable] = None,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    phase: float = oracle.TWO_PI,
) -> Tuple[Callable, object]:
    """Build the jitted end-to-end solver.

    Returns `(runner, step_params)`; call `runner(step_params)`.  For the
    default and plain-step paths `step_params` is just `()`; a `ParamStep`
    kernel's array parameters (e.g. the variable-c field) ride through as
    runtime arguments (see ParamStep for why they must not be closed over).

    `step_fn(u_prev, u, problem) -> u_next` defaults to the jnp-roll stencil;
    the Pallas kernel slots in via the same signature, and `ParamStep` adds
    a params argument.

    Layer 1 is derived FROM the step function - u1 = (u0 + step(u0, u0))/2
    equals the Taylor half-step u0 + (coeff/2)*lap(u0) for any leapfrog-form
    kernel - so a variable-c kernel bootstraps with its own c^2(x,y,z), not
    the constant a^2 (reference: openmp_sol.cpp:137-144).

    `stop_step` halts the march after that layer (default: run to
    `problem.timesteps`).  tau stays `T / timesteps` regardless, so a stopped
    run is the exact prefix of the full one - the state a checkpoint captures
    (io/checkpoint.py) and `resume` continues from.

    `phase` sets the analytic initial condition's time phase (lane identity
    in the ensemble engine); the default 2*pi reproduces the reference.
    A shifted phase has NONZERO initial velocity u_t(0) = -a_t sin(phase)
    * Sx Sy Sz, which the reference's velocity-less Taylor bootstrap
    u1 = u0 + (C/2) lap(u0) cannot represent - using it anyway would
    integrate a DIFFERENT initial-value problem than the oracle measures
    and report O(1) "error".  Shifted-phase solves therefore bootstrap
    layer 1 ANALYTICALLY (u1 = Sx Sy Sz cos(a_t tau + phase), the exact
    two-level initialization), which the oracle is exact for; the
    reference phase keeps the step-derived bootstrap, so the default
    program is bit-identical to the phase-less solver.  (An explicit
    tau * u_t(0) correction term was tried first: LLVM FMA-contracts
    the add differently between the solo and vmapped program shapes on
    XLA-CPU - even across optimization_barrier - breaking bitwise lane
    parity; the analytic bootstrap sidesteps fusion entirely.)
    """
    step, step_params = _as_param_step(step_fn)
    errors = _error_fn(problem, dtype, phase)
    analytic_bootstrap = phase != oracle.TWO_PI
    if analytic_bootstrap and jax.tree_util.tree_leaves(step_params):
        # Runtime step params mark a variable-c kernel (ParamStep); the
        # analytic bootstrap would silently initialize from the
        # constant-speed solution and solve a different IVP.
        raise ValueError(
            "a shifted phase bootstraps layer 1 from the analytic "
            "solution, which only exists for constant speed; use the "
            "reference phase with variable-c step functions"
        )
    nsteps = problem.timesteps if stop_step is None else stop_step
    if not 1 <= nsteps <= problem.timesteps:
        raise ValueError(
            f"stop_step must be in [1, {problem.timesteps}], got {nsteps}"
        )

    def run(step_params):
        u0 = initial_layer0(problem, dtype, phase)
        f = stencil_ref.compute_dtype(dtype)
        if analytic_bootstrap:
            u1 = analytic_layer(problem, dtype, phase, 1)
        else:
            u1 = (
                0.5 * (
                    u0.astype(f)
                    + step(u0, u0, problem, step_params).astype(f)
                )
            ).astype(dtype)
        # Layer 0 is *assigned from* the oracle, so its error is zero by
        # definition; the reference reads back the memory it just wrote and
        # reports exactly 0 (openmp_sol.cpp:126-133, 169-190).  Recomputing
        # the analytic product here and subtracting would measure XLA's FMA
        # rematerialization noise (~1 ulp), not solver error - u0's
        # correctness is pinned by tests/test_single_device.py instead.
        err_dtype = stencil_ref.compute_dtype(dtype)
        a0 = r0 = jnp.zeros((), err_dtype)
        if compute_errors:
            a1, r1 = errors(u1, 1)
        else:
            a1 = r1 = jnp.zeros((), err_dtype)

        (u_prev, u_cur), (abs_t, rel_t) = _scan_layers(
            problem, step, step_params, errors, compute_errors, dtype,
            u0, u1, 1, nsteps,
        )
        abs_all = jnp.concatenate([jnp.stack([a0, a1]), abs_t])
        rel_all = jnp.concatenate([jnp.stack([r0, r1]), rel_t])
        return u_prev, u_cur, abs_all, rel_all

    return jax.jit(run), step_params


def solve(
    problem: Problem,
    dtype=jnp.float32,
    step_fn: Optional[Callable] = None,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    phase: float = oracle.TWO_PI,
) -> SolveResult:
    """Compile + run, with the reference's two timing phases.

    "grids initialized in Xms" maps to compile time here (state allocation is
    part of the program); "numerical solution calculated in Xms" is the
    execution wall time (mpi_new.cpp:472-474, 354-357).
    """
    runner, step_params = make_solver(
        problem, dtype, step_fn, compute_errors, stop_step, phase
    )
    (u_prev, u_cur, abs_all, rel_all), init_s, solve_s = _timed_compile_run(
        runner, (step_params,), sync=lambda out: np.asarray(out[2])
    )
    result = SolveResult(
        problem=problem,
        u_prev=u_prev,
        u_cur=u_cur,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=stop_step,
        final_step=stop_step if stop_step is not None else problem.timesteps,
    )
    # A variable-c kernel arrives as a ParamStep (the field is a runtime
    # argument by construction), so field presence is detectable here -
    # the 1-step roofline model adds the field stream exactly when the
    # kernel reads one.
    obs_metrics.record_solve(
        result, "leapfrog",
        with_field=isinstance(step_fn, ParamStep),
    )
    return result


def make_compensated_solver(
    problem: Problem,
    dtype=jnp.float32,
    comp_step_fn: Optional[Callable] = None,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    phase: float = oracle.TWO_PI,
):
    """Jitted end-to-end solver on the compensated (Kahan) incremental
    scheme - see stencil_ref.compensated_step for the numerics and the
    measured ~7000x rounding reduction.

    `comp_step_fn(u, v, carry, problem, coeff) -> (u', v', carry')`
    defaults to the jnp-roll reference; the fused Pallas kernel slots in
    via `stencil_pallas.make_compensated_step_fn()`.  The scheme exists to
    push f32 to the discretization limit; bf16 state is rejected (its
    representation error alone dwarfs what compensation recovers).

    `phase` follows `make_solver`'s contract (lane identity in the
    ensemble engine): a shifted phase initializes layers 0/1 from the
    ANALYTIC solution, with v1 the exact analytic increment
    (`analytic_increment_layer1` - in exact arithmetic the next step
    then reproduces 2u1 - u0 + C lap(u1), the standard leapfrog update)
    and a zero Kahan carry; the reference phase keeps the step-derived
    half-step bootstrap bit-identically.
    """
    if dtype == jnp.bfloat16:
        raise ValueError(
            "compensated scheme requires f32/f64 state (bf16 representation "
            "error dominates anything the compensation recovers)"
        )
    step = (
        comp_step_fn if comp_step_fn is not None
        else stencil_ref.compensated_step
    )
    errors = _error_fn(problem, dtype, phase)
    analytic_bootstrap = phase != oracle.TWO_PI
    nsteps = problem.timesteps if stop_step is None else stop_step
    if not 1 <= nsteps <= problem.timesteps:
        raise ValueError(
            f"stop_step must be in [1, {problem.timesteps}], got {nsteps}"
        )

    def run():
        u0 = initial_layer0(problem, dtype, phase)
        if analytic_bootstrap:
            u1 = analytic_layer(problem, dtype, phase, 1)
            v1 = analytic_increment_layer1(problem, dtype, phase)
            c1 = jnp.zeros_like(u0)
        else:
            zero = jnp.zeros_like(u0)
            # Layer 1 = the same step with v = carry = 0 and coeff = C/2:
            # u1 = u0 + (C/2)lap(u0), the Taylor half-step, with v1/carry1
            # correctly primed for the loop.
            u1, v1, c1 = step(u0, zero, zero, problem, 0.5 * problem.a2tau2)
        a0 = r0 = jnp.zeros((), dtype)
        if compute_errors:
            a1, r1 = errors(u1, 1)
        else:
            a1 = r1 = jnp.zeros((), dtype)

        def body(carry, layer):
            u, v, c = carry
            u2, v2, c2 = step(u, v, c, problem, None)
            if compute_errors:
                ae, re = errors(u2, layer)
            else:
                ae = re = jnp.zeros((), dtype)
            return (u2, v2, c2), (ae, re)

        (u, v, c), (abs_t, rel_t) = jax.lax.scan(
            body, (u1, v1, c1), jnp.arange(2, nsteps + 1)
        )
        abs_all = jnp.concatenate([jnp.stack([a0, a1]), abs_t])
        rel_all = jnp.concatenate([jnp.stack([r0, r1]), rel_t])
        # u_prev reconstructed from the increment (v = u_n - u_{n-1}
        # exactly in exact arithmetic; here to f32 rounding) so the result
        # shape matches the standard solver's; v and carry ride along for
        # checkpointing.
        return u - v, u, v, c, abs_all, rel_all

    return jax.jit(run)


def solve_compensated(
    problem: Problem,
    dtype=jnp.float32,
    comp_step_fn: Optional[Callable] = None,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    phase: float = oracle.TWO_PI,
) -> SolveResult:
    """Compile + run the compensated-scheme solve (see
    make_compensated_solver)."""
    runner = make_compensated_solver(
        problem, dtype, comp_step_fn, compute_errors, stop_step, phase
    )
    (u_prev, u_cur, v, carry, abs_all, rel_all), init_s, solve_s = (
        _timed_compile_run(runner, (), sync=lambda out: np.asarray(out[4]))
    )
    result = SolveResult(
        problem=problem,
        u_prev=u_prev,
        u_cur=u_cur,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=stop_step,
        final_step=stop_step if stop_step is not None else problem.timesteps,
        comp_v=v,
        comp_carry=carry,
    )
    obs_metrics.record_solve(result, "compensated", scheme="compensated")
    return result


def resume_compensated(
    problem: Problem,
    u_cur,
    v,
    carry,
    start_step: int,
    dtype=jnp.float32,
    comp_step_fn: Optional[Callable] = None,
    compute_errors: bool = True,
) -> SolveResult:
    """Re-enter the compensated scan at layer `start_step`.

    `(u_cur, v, carry)` is the full compensated state a checkpoint stored
    (SolveResult.u_cur / .comp_v / .comp_carry of a stopped run); the
    per-step op sequence equals an uninterrupted run's, so the final state
    is bitwise-equal (tests/test_compensated.py).
    """
    if dtype == jnp.bfloat16:
        raise ValueError("compensated scheme requires f32/f64 state")
    step = (
        comp_step_fn if comp_step_fn is not None
        else stencil_ref.compensated_step
    )
    nsteps = problem.timesteps
    if not 1 <= start_step <= nsteps:
        raise ValueError(
            f"start_step must be in [1, {nsteps}], got {start_step}"
        )
    errors = _error_fn(problem, dtype)

    def run(u_cur, v, carry):
        def body(state, layer):
            u, vv, cc = state
            u2, v2, c2 = step(u, vv, cc, problem, None)
            if compute_errors:
                ae, re = errors(u2, layer)
            else:
                ae = re = jnp.zeros((), dtype)
            return (u2, v2, c2), (ae, re)

        (u, vv, cc), (abs_t, rel_t) = jax.lax.scan(
            body, (u_cur, v, carry), jnp.arange(start_step + 1, nsteps + 1)
        )
        head = jnp.zeros((start_step + 1,), dtype)
        return (
            u - vv, u, vv, cc,
            jnp.concatenate([head, abs_t]),
            jnp.concatenate([head, rel_t]),
        )

    args = (
        jnp.asarray(u_cur, dtype),
        jnp.asarray(v, dtype),
        jnp.asarray(carry, dtype),
    )
    (u_prev, u, vv, cc, abs_all, rel_all), init_s, solve_s = (
        _timed_compile_run(
            jax.jit(run), args, sync=lambda out: np.asarray(out[4])
        )
    )
    return SolveResult(
        problem=problem,
        u_prev=u_prev,
        u_cur=u,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=nsteps - start_step,
        final_step=nsteps,
        comp_v=vv,
        comp_carry=cc,
    )


def resume(
    problem: Problem,
    u_prev,
    u_cur,
    start_step: int,
    dtype=jnp.float32,
    step_fn: Optional[Callable] = None,
    compute_errors: bool = True,
) -> SolveResult:
    """Re-enter the time loop at layer `start_step` and march to the end.

    `u_prev` / `u_cur` are layers start_step-1 / start_step (what
    `solve(stop_step=start_step)` returned and io/checkpoint.py stored).
    Because the per-step operation sequence is identical to an uninterrupted
    run's, the final state is bitwise-equal to it (pinned by
    tests/test_checkpoint.py).

    The returned error arrays cover layers start_step+1..timesteps; earlier
    entries are zero (they belong to the pre-checkpoint run's report).
    """
    step, step_params = _as_param_step(step_fn)
    nsteps = problem.timesteps
    if not 1 <= start_step <= nsteps:
        raise ValueError(
            f"start_step must be in [1, {nsteps}], got {start_step}"
        )
    errors = _error_fn(problem, dtype)

    def run(u_prev, u_cur, step_params):
        (u_p, u_c), (abs_t, rel_t) = _scan_layers(
            problem, step, step_params, errors, compute_errors, dtype,
            u_prev, u_cur, start_step, nsteps,
        )
        head = jnp.zeros((start_step + 1,), stencil_ref.compute_dtype(dtype))
        return (
            u_p,
            u_c,
            jnp.concatenate([head, abs_t]),
            jnp.concatenate([head, rel_t]),
        )

    args = (jnp.asarray(u_prev, dtype), jnp.asarray(u_cur, dtype), step_params)
    (u_p, u_c, abs_all, rel_all), init_s, solve_s = _timed_compile_run(
        jax.jit(run), args, sync=lambda out: np.asarray(out[2])
    )
    return SolveResult(
        problem=problem,
        u_prev=u_p,
        u_cur=u_c,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=nsteps - start_step,
        final_step=nsteps,
    )


def make_chunk_runner(
    problem: Problem,
    dtype=jnp.float32,
    length: int = 1,
    step_fn: Optional[Callable] = None,
    compute_errors: bool = True,
):
    """Fixed-length re-entry program for supervised solves (run/supervisor).

    Returns `(runner, step_params)`; `runner(u_prev, u_cur, start,
    step_params)` marches layers start+1..start+length with `start` a
    RUNTIME scalar, so one compiled program serves every equal-length
    chunk of a supervised march - no per-chunk retracing.  The scan body
    is `_scan_layers_xs`, the same one `solve`/`resume` run, so chunked
    layers are bitwise-identical to an uninterrupted march's.  Error
    outputs cover exactly the chunk's layers (the supervisor assembles
    the full per-layer vectors on host).
    """
    if length < 1:
        raise ValueError(f"chunk length must be >= 1, got {length}")
    step, step_params = _as_param_step(step_fn)
    errors = _error_fn(problem, dtype)

    def run(u_prev, u_cur, start, step_params):
        xs = start + 1 + jnp.arange(length, dtype=jnp.int32)
        (u_p, u_c), (abs_t, rel_t) = _scan_layers_xs(
            problem, step, step_params, errors, compute_errors, dtype,
            u_prev, u_cur, xs,
        )
        return u_p, u_c, abs_t, rel_t

    return jax.jit(run), step_params


def make_comp_chunk_runner(
    problem: Problem,
    dtype=jnp.float32,
    length: int = 1,
    comp_step_fn: Optional[Callable] = None,
    compute_errors: bool = True,
):
    """Compensated-scheme counterpart of `make_chunk_runner`:
    `runner(u, v, carry, start)` marches `length` layers from the
    compensated state with a runtime `start` - the same scan body as
    `resume_compensated`, compiled once per chunk length."""
    if length < 1:
        raise ValueError(f"chunk length must be >= 1, got {length}")
    if dtype == jnp.bfloat16:
        raise ValueError("compensated scheme requires f32/f64 state")
    step = (
        comp_step_fn if comp_step_fn is not None
        else stencil_ref.compensated_step
    )
    errors = _error_fn(problem, dtype)

    def run(u_cur, v, carry, start):
        def body(state, layer):
            u, vv, cc = state
            u2, v2, c2 = step(u, vv, cc, problem, None)
            if compute_errors:
                ae, re = errors(u2, layer)
            else:
                ae = re = jnp.zeros((), dtype)
            return (u2, v2, c2), (ae, re)

        xs = start + 1 + jnp.arange(length, dtype=jnp.int32)
        (u, vv, cc), (abs_t, rel_t) = jax.lax.scan(
            body, (u_cur, v, carry), xs
        )
        return u, vv, cc, abs_t, rel_t

    return jax.jit(run)


def solve_history(problem: Problem, dtype=jnp.float32) -> np.ndarray:
    """Full time history (timesteps+1, N, N, N) - the openmp_sol storage model.

    The reference OpenMP/mpi_sol variants keep every layer in memory and
    compute errors post hoc (openmp_sol.cpp:216-219, 169-190).  Provided for
    parity testing and small-N debugging; O(T * N^3) memory.
    """

    @jax.jit
    def run():
        u0, u1 = initial_state(problem, dtype)

        def body(carry, _):
            u_prev, u = carry
            u_next = stencil_ref.leapfrog_step(u_prev, u, problem)
            return (u, u_next), u_next

        _, rest = jax.lax.scan(
            body, (u0, u1), None, length=problem.timesteps - 1
        )
        return jnp.concatenate([jnp.stack([u0, u1]), rest])

    return np.asarray(run())


def to_reference_grid(u: np.ndarray) -> np.ndarray:
    """Expand a fundamental-domain (N,N,N) field to the reference's (N+1)^3.

    Re-attaches the duplicated periodic seam plane x=N (= x=0) and the zero
    Dirichlet planes y=N, z=N, giving index-for-index comparability with the
    reference's `Grid` layout (openmp_sol.cpp:44-50).
    """
    u = np.asarray(u)
    n = u.shape[0]
    out = np.zeros((n + 1, n + 1, n + 1), dtype=u.dtype)
    out[:n, :n, :n] = u
    out[n, :n, :n] = u[0]
    return out
