"""Compensated (velocity-form) temporally fused k-step solver.

The round-4 flagship gap was fast OR accurate: the standard k-fused onion
(solver/kfused.py) runs 42.6 Gcell/s at L-inf ~1.1e-3 (rounding-dominated),
the 1-step compensated scheme 12.4 Gcell/s at 5.7e-6 (discretization-
limited).  This module is both at once - the reference's own contract,
whose flagship runs full speed at full accuracy (all-double,
cuda_sol_kernels.cu:24-47 with the error fused at :41-45).

Mechanism: the k-step VMEM onion marches the INCREMENT form

    v_{n+1} = v_n + C*lap(u_n)
    u_{n+1} = u_n + v_{n+1}      (Kahan two-sum through `carry`)

(`stencil_ref.compensated_step` semantics) instead of the standard
2u - u_prev form.  u and v ride the onion exactly like (u_prev, u) in the
standard onion - same HBM traffic for the pair - and the carry adds one
slab-only stream (no halos: halo-cone carries seed to zero, a
second-order approximation through the Laplacian; see
`stencil_pallas._kstep_comp_kernel`).  Measured on v5e at N=512/1000,
errors fused on every layer: 33.98 Gcell/s at L-inf 5.72e-6 (k=4, vs
the 1-step compensated path's 12.4 Gcell/s at 5.69e-6 - 2.7x at equal
accuracy; k=2 lands at 22.3).

With `v_dtype=bfloat16` and `carry=False` the same march becomes the
increment-form bf16 mode (BASELINE config 5 re-scoped to numbers that
mean something): the increment stream stores bf16, u stays the f32
carrier, and the bf16 quantization error ~|v|*2^-8 per step stays far
below the O(1) solution - unlike a bf16 u, whose per-step increments sit
below the bf16 ulp and whose trajectory is garbage (round-4 BENCH: 0.66
L-inf).  Measured: 44.19 Gcell/s at L-inf 6.39e-4 (k=4, N=512/1000).

Unlike the standard k-fused path there is NO bitwise-parity claim against
the 1-step scheme (intermediate layers skip the storage round-trip, halo
carries differ); the contract is tolerance parity vs f64
(tests/test_kfused_comp.py) and the remainder tail runs the SAME kernel
at k=1, so stop/resume stays self-consistent.

`solve_kfused_comp_sharded` distributes the scheme over (MX, MY, 1)
meshes with k-deep ghost exchange per k layers per axis (u and v ship;
the carry stays shard-local, zero-seeded in halos exactly as on one
device; on 2D meshes the y-row extension ships first and the x ghosts
ride the extended blocks, corner data via the sequencing).  x-only at
N=512 is VMEM-bound to k=2 (the four full-plane ghost buffers push k=4
to a measured 148.6 MB; k=2 runs 14.6 Gcell/s at 5.75e-6 on v5e vs 12.4
for the 1-step compensated sharded path); y-sharding shrinks every VMEM
plane by MY and restores k=4 (Mosaic-validated on chip at nl_y=64).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from wavetpu.core.problem import Problem
from wavetpu import compat
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.obs import metrics as obs_metrics
from wavetpu.solver import kfused, leapfrog
from wavetpu.verify import oracle


def _default_carry_dtype(dtype):
    """bf16 carry for f32 runs, else the state dtype.

    The carry holds ~ulp(u)-scale residuals; bf16 quantizes it at
    ~carry * 2^-8 per step (~1e-10 absolute for f32 runs) - invisible at
    the f32 discretization error scale while halving the carry's HBM
    stream.  Measured back-to-back on v5e at N=512/1000 k=4: 36.50 vs
    34.34 Gcell/s with a bit-identical reported max error (5.722e-6).
    f64 runs keep an f64 carry (conservatism; the stream is not the
    bottleneck there)."""
    return jnp.bfloat16 if jnp.dtype(dtype) == jnp.float32 else dtype


def _validate_carry_dtype(dtype, carry_dtype):
    """Allowed carry storages: the state dtype, or bf16 for f32 runs.

    A bf16 carry under f64 would quantize the f64 Kahan residual at 2^-8
    and destroy the accuracy contract the carry exists to uphold; any
    non-float dtype would fail opaquely inside the kernel."""
    cd = jnp.dtype(carry_dtype)
    ok = cd == jnp.dtype(dtype) or (
        cd == jnp.bfloat16 and jnp.dtype(dtype) == jnp.float32
    )
    if not ok:
        raise ValueError(
            f"carry_dtype {cd.name} is invalid for state dtype "
            f"{jnp.dtype(dtype).name}: use the state dtype, or bfloat16 "
            f"for float32 runs"
        )


def _normalize_carry(carry, dtype):
    """Resume-side carry normalization: preserve a valid stored dtype
    (bitwise resume of bf16-carry checkpoints) WITHOUT copying or
    touching a device (jnp.result_type probes dtype only - the caller's
    placement decides where the array lands); cast anything else to the
    state dtype (e.g. an f64-interpret checkpoint resumed as f32 - an
    f64 carry ref cannot lower on TPU)."""
    cd = jnp.result_type(carry)
    if cd == jnp.dtype(dtype) or (
        cd == jnp.bfloat16 and jnp.dtype(dtype) == jnp.float32
    ):
        return carry
    return jnp.asarray(carry, dtype)


def _validate(problem: Problem, dtype, v_dtype, carry, k: int,
              c2tau2_field=None, compute_errors: bool = True,
              phase: float = oracle.TWO_PI):
    if k < 2:
        raise ValueError(f"k must be >= 2 (got {k}); use "
                         "leapfrog.solve_compensated for k=1")
    if problem.N % k:
        raise ValueError(f"k={k} must divide N={problem.N}")
    if c2tau2_field is not None and compute_errors:
        raise ValueError(
            "variable-c runs have no analytic oracle; pass "
            "compute_errors=False with c2tau2_field"
        )
    if c2tau2_field is not None and phase != oracle.TWO_PI:
        raise ValueError(
            "a shifted phase bootstraps layers 0/1 from the analytic "
            "solution, which only exists for constant speed; use the "
            "reference phase with c2tau2_field"
        )
    if dtype == jnp.bfloat16:
        raise ValueError(
            "compensated/velocity scheme requires an f32/f64 carrier u "
            "(bf16 representation error dominates; use v_dtype=bfloat16 "
            "for the increment-form bf16 mode)"
        )
    if v_dtype != dtype and carry:
        raise ValueError(
            "carry compensation requires v_dtype == dtype (a narrowed "
            "increment stream quantizes far above what the carry "
            "recovers); pass carry=False"
        )


def _rel_guard_tol(f):
    """|sx| threshold below which a plane counts as an analytic zero for
    the REL metric (see the guard comment in `_make_march`)."""
    return 512 * jnp.finfo(f).eps


def _error_fn_guarded(problem: Problem, dtype,
                      phase: float = oracle.TWO_PI):
    """Layer-error fn with the representation-zero sx planes excluded,
    so the bootstrap layer's metric matches the in-kernel layers'.

    (The excluded plane's ABS contribution is ~1e-16 * |syz| - far below
    any solver error - so abs is unchanged in practice.)"""
    f_dtype = stencil_ref.compute_dtype(dtype)
    sx, sy, sz = oracle.spatial_factors(problem, f_dtype)
    ct_table = oracle.time_factor_table(problem, f_dtype, phase)
    mask = jnp.asarray(oracle.interior_masks_1d(problem.N))
    mask_x = mask & (jnp.abs(sx) > _rel_guard_tol(f_dtype))

    def errors(u, n):
        f = oracle.analytic_field(sx, sy, sz, ct_table[n])
        return oracle.layer_errors(u.astype(f_dtype), f, mask_x, mask, mask)

    return errors


def _make_march(problem, dtype, v_dtype, carry_on, k, compute_errors,
                block_x, interpret, nsteps, has_field=False,
                chunk_len=None, phase: float = oracle.TWO_PI):
    """Shared march: k-fused blocks + a k=1 tail through the SAME kernel.

    Returns `march(u, v, carry, start, *field_params)` ->
    (u, v, carry, abs, rel) covering layers start+1..nsteps (`start` a
    Python int).  Shared by solve and resume so a resumed run's op
    sequence equals the uninterrupted run's.  With `chunk_len` set the
    march covers exactly chunk_len layers from a RUNTIME `start`
    (run/supervisor.py's cached chunk program); on block-aligned starts
    the op sequence equals the uninterrupted march's prefix.  With
    `has_field` the c^2tau^2 field rides `field_params[0]` as a runtime
    argument (leapfrog.ParamStep reasoning) into every onion call.
    """
    f = stencil_ref.compute_dtype(dtype)
    sx, ct, syz, rsyz, xmask, inv_absx = kfused._oracle_parts(
        problem, f, phase
    )
    # Rel-metric guard: exclude REPRESENTATION-LEVEL zeros of the periodic
    # x factor (sin at the domain midpoint evaluates to ~1.2e-16, not 0,
    # so the exact-zero NaN-skip of the reference contract misses it and
    # 1/|sx| reaches ~8e15).  On bitwise-antisymmetric trajectories (all
    # 1-step paths, the standard onion) that plane's noise stays
    # proportional and the metric quietly reports a noise/noise ratio
    # (~0.22 at N=32 - it dominates the reported rel of EVERY path,
    # including the reference's own metric, mpi_new.cpp:340-344).  The
    # velocity-form onion's zero-seeded halo carries break the antisymmetry
    # by ~2e-9 absolute, which 8e15 would amplify into 1e7 garbage; this
    # path therefore applies the NaN-skip at representation level, where
    # it belongs.  Abs errors are untouched.  Honest min over real modes:
    # |sx| >= sin(2*pi/N), e.g. 0.012 at N=512 >> tol for any f32 run.
    inv_absx = jnp.where(jnp.abs(sx) > _rel_guard_tol(f), inv_absx,
                         jnp.asarray(0.0, f))

    def kblock(u, v, carry, nstart, kk, bxo, field=None):
        ctk = lax.dynamic_slice(ct, (nstart + 1,), (kk,))
        sxct = ctk[:, None] * sx[None, :]
        u2, v2, c2, dmax, rmax = stencil_pallas.fused_kstep_comp(
            u, v, carry, syz, rsyz, sxct,
            k=kk, coeff=problem.a2tau2, inv_h2=problem.inv_h2,
            c2tau2_field=field,
            block_x=bxo, interpret=interpret, with_errors=compute_errors,
        )
        if compute_errors:
            abs_e, rel_e = kfused._block_errors(
                dmax, rmax, ctk, xmask, inv_absx
            )
        else:
            abs_e = rel_e = jnp.zeros((kk,), f)
        return u2, v2, c2, abs_e, rel_e

    def march(u, v, carry, start, *field_params):
        field = field_params[0] if has_field else None
        if chunk_len is None:
            nblocks = (nsteps - start) // k
            rem = (nsteps - start) - nblocks * k
        else:
            nblocks = chunk_len // k
            rem = chunk_len - nblocks * k

        def body(state, nstart):
            u, v, carry = state
            u2, v2, c2, abs_e, rel_e = kblock(
                u, v, carry, nstart, k, block_x, field
            )
            return (u2, v2, c2), (abs_e, rel_e)

        starts = start + k * jnp.arange(nblocks)
        (u, v, carry), (abs_b, rel_b) = lax.scan(
            body, (u, v, carry), starts
        )
        abs_parts = [abs_b.reshape(-1)]
        rel_parts = [rel_b.reshape(-1)]
        for t in range(rem):
            rem_start = (
                nsteps - rem if chunk_len is None
                else start + chunk_len - rem
            )
            u, v, carry, abs_1, rel_1 = kblock(
                u, v, carry, rem_start + t, 1, None, field
            )
            abs_parts.append(abs_1)
            rel_parts.append(rel_1)
        return u, v, carry, jnp.concatenate(abs_parts), jnp.concatenate(
            rel_parts)

    return march


def _bootstrap(problem, dtype, v_dtype, carry_on, carry_dtype, interpret,
               field=None, phase: float = oracle.TWO_PI):
    """Layers 0/1: analytic init + the compensated kernel's half-step.

    u1 = u0 + (C/2)lap(u0) with v = carry = 0 primes (u1, v1, carry1)
    exactly as `leapfrog.make_compensated_solver` (reference bootstrap:
    openmp_sol.cpp:123-145).  With a `field` the half-step coefficient is
    tau^2 c^2(x)/2 and the k=1 onion kernel runs it (op-for-op the same
    Kahan sequence, with the field as the Laplacian coefficient).

    A shifted `phase` (constant speed only - _validate) takes the exact
    analytic two-level initialization instead: u0/u1 analytic, v1 the
    exact analytic increment (leapfrog.analytic_increment_layer1, a
    pure product - never u1 - u0, whose FMA contraction drifts between
    program shapes), zero Kahan carry - the leapfrog analytic bootstrap
    with the onion's storage dtypes."""
    if phase != oracle.TWO_PI:
        u1 = leapfrog.analytic_layer(problem, dtype, phase, 1)
        v1 = leapfrog.analytic_increment_layer1(problem, v_dtype, phase)
        c1 = (
            jnp.zeros(u1.shape, carry_dtype) if carry_on else None
        )
        return u1, v1, c1
    u0 = leapfrog.initial_layer0(problem, dtype)
    if field is None:
        zero = jnp.zeros_like(u0)
        u1, v1, c1 = stencil_pallas.compensated_step(
            u0, zero, zero, problem, 0.5 * problem.a2tau2,
            interpret=interpret
        )
        v1 = v1.astype(v_dtype)
        c1 = c1.astype(carry_dtype) if carry_on else None
        return u1, v1, c1
    f = stencil_ref.compute_dtype(dtype)
    n = problem.N
    zero_plane = jnp.zeros((n, n), f)
    u1, v1, c1, _, _ = stencil_pallas.fused_kstep_comp(
        u0, jnp.zeros(u0.shape, v_dtype),
        jnp.zeros(u0.shape, carry_dtype) if carry_on else None,
        zero_plane, zero_plane, jnp.zeros((1, n), f),
        k=1, coeff=None, inv_h2=problem.inv_h2,
        c2tau2_field=0.5 * field, interpret=interpret, with_errors=False,
    )
    return u1, v1, c1


def make_kfused_comp_solver(
    problem: Problem,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    block_x: Optional[int] = None,
    interpret: bool = False,
    v_dtype=None,
    carry: bool = True,
    carry_dtype=None,
    c2tau2_field=None,
    phase: float = oracle.TWO_PI,
):
    """Build the jitted compensated k-fused solver; returns
    `(runner, run_params)` yielding (u, v, carry|None, abs_errors,
    rel_errors).  `run_params` is () for constant speed (zero-arg runner,
    as before) or the materialized device field for variable c (a runtime
    argument, never an HLO literal - leapfrog.ParamStep).

    `carry_dtype` (default: `_default_carry_dtype`, i.e. bf16 for f32
    runs) narrows only the carry's HBM stream - see that helper for the
    numerics and the measured +6%.  `phase` is the lane identity of the
    ensemble engine (analytic two-level bootstrap when shifted; constant
    speed only - see `_bootstrap`).
    """
    v_dtype = dtype if v_dtype is None else jnp.dtype(v_dtype)
    carry_dtype = (
        _default_carry_dtype(dtype) if carry_dtype is None
        else jnp.dtype(carry_dtype)
    )
    if carry:
        _validate_carry_dtype(dtype, carry_dtype)
    _validate(problem, dtype, v_dtype, carry, k, c2tau2_field,
              compute_errors, phase)
    nsteps = problem.timesteps if stop_step is None else stop_step
    if not 1 <= nsteps <= problem.timesteps:
        raise ValueError(
            f"stop_step must be in [1, {problem.timesteps}], got {nsteps}"
        )
    f = stencil_ref.compute_dtype(dtype)
    has_field = c2tau2_field is not None
    errors = _error_fn_guarded(problem, dtype, phase)
    march = _make_march(
        problem, dtype, v_dtype, carry, k, compute_errors, block_x,
        interpret, nsteps, has_field, phase=phase,
    )

    def run(*field_params):
        u1, v1, c1 = _bootstrap(
            problem, dtype, v_dtype, carry, carry_dtype, interpret,
            field_params[0] if has_field else None, phase,
        )
        a0 = r0 = jnp.zeros((), f)
        if compute_errors:
            a1, r1 = errors(u1, 1)
        else:
            a1 = r1 = jnp.zeros((), f)
        u, v, c, abs_t, rel_t = march(u1, v1, c1, 1, *field_params)
        abs_all = jnp.concatenate([jnp.stack([a0, a1]), abs_t])
        rel_all = jnp.concatenate([jnp.stack([r0, r1]), rel_t])
        return u, v, c, abs_all, rel_all

    run_params = ()
    if has_field:
        run_params = (leapfrog.ParamStep.materialize(
            jnp.asarray(c2tau2_field, dtype=f)
        ),)
    return jax.jit(run), run_params


def _as_result(problem, out, init_s, solve_s, steps_computed, final_step):
    u, v, c, abs_all, rel_all = out
    f = stencil_ref.compute_dtype(u.dtype)
    return leapfrog.SolveResult(
        problem=problem,
        u_prev=(u.astype(f) - v.astype(f)).astype(u.dtype),
        u_cur=u,
        abs_errors=np.asarray(abs_all, dtype=np.float64),
        rel_errors=np.asarray(rel_all, dtype=np.float64),
        init_seconds=init_s,
        solve_seconds=solve_s,
        steps_computed=steps_computed,
        final_step=final_step,
        comp_v=v,
        comp_carry=c,
    )


def solve_kfused_comp(
    problem: Problem,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    block_x: Optional[int] = None,
    interpret: bool = False,
    v_dtype=None,
    carry: bool = True,
    carry_dtype=None,
    c2tau2_field=None,
    phase: float = oracle.TWO_PI,
) -> leapfrog.SolveResult:
    """Compile + run the compensated k-fused solve (reference timing
    phases as `leapfrog.solve`).  `c2tau2_field` selects the variable-c
    velocity-form onion (composes with the carry and the bf16-increment
    mode); pair it with compute_errors=False.  `phase` shifts the
    analytic initial condition (constant speed only)."""
    runner, run_params = make_kfused_comp_solver(
        problem, dtype, k, compute_errors, stop_step, block_x, interpret,
        v_dtype, carry, carry_dtype, c2tau2_field, phase,
    )
    out, init_s, solve_s = leapfrog._timed_compile_run(
        runner, run_params, sync=lambda o: np.asarray(o[3])
    )
    result = _as_result(
        problem, out, init_s, solve_s, stop_step,
        stop_step if stop_step is not None else problem.timesteps,
    )
    obs_metrics.record_solve(
        result, "kfused_comp", scheme="compensated", k=k,
        v_itemsize=(
            None if v_dtype is None else jnp.dtype(v_dtype).itemsize
        ),
        carry=carry, with_field=c2tau2_field is not None,
        block_x=block_x,
    )
    return result


def _validate_sharded(problem: Problem, dtype, v_dtype, carry, k, n_x,
                      n_y: int = 1, c2tau2_field=None,
                      compute_errors: bool = True):
    _validate(problem, dtype, v_dtype, carry, k, c2tau2_field,
              compute_errors)
    if n_x < 1 or n_y < 1:
        raise ValueError(
            f"mesh axes must be >= 1 (got MX={n_x}, MY={n_y})"
        )
    if problem.N % n_x:
        raise ValueError(
            f"sharded compensated k-fusion needs N % shards == 0 "
            f"(N={problem.N}, shards={n_x})"
        )
    if (problem.N // n_x) % k:
        raise ValueError(
            f"k={k} must divide the shard depth {problem.N // n_x}"
        )
    if problem.N % n_y:
        raise ValueError(
            f"y-sharded compensated k-fusion needs N % y-shards == 0 "
            f"(N={problem.N}, y-shards={n_y})"
        )
    if problem.N // n_y < k:
        raise ValueError(
            f"k={k} exceeds the y shard depth {problem.N // n_y}"
        )


def _make_sharded_runner(problem, mesh, grid, dtype, v_dtype, carry_on, k,
                         compute_errors, nsteps, start_step, block_x,
                         interpret, carry_dtype=None, has_field=False,
                         chunk_len=None):
    """Sharded velocity-form runner over (MX, MY, 1): the distributed
    flagship.

    One cyclic k-deep ppermute pair per mesh axis per field (u, v) per
    k-block; on 2D grids the y-row extension happens FIRST and the x
    ghost planes are sliced from the extended blocks (the corner
    sequencing of solver/sharded_kfused.py).  The carry stays
    shard-local with zero-seeded halos exactly as on a single device.
    y-sharding shrinks every VMEM plane by MY - which is what lifts the
    VMEM bound on k (x-only at N=512 is k<=2; (8,8,1) runs k=4).  The
    bootstrap and the remainder tail run the same kernel at k=1 (the
    bootstrap with coeff C/2 on zero v/carry IS the compensated
    half-step).

    With `has_field` the c^2tau^2 field rides as an extra P("x","y")
    runtime argument; it is time-invariant, so its y extension and
    x-ghost exchange happen ONCE per solve per needed ghost depth
    (k-blocks; k=1 for bootstrap/remainder), outside the layer scan.

    With `chunk_len` set (start_step must be None) the runner is the
    supervised chunk program `run(u, v, carry, start, ...)`: exactly
    chunk_len layers from a RUNTIME start, one compiled program reused
    across every chunk (run/supervisor.py).
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    n_x, n_y = grid
    if carry_dtype is None:
        carry_dtype = _default_carry_dtype(dtype)
    f = stencil_ref.compute_dtype(dtype)
    nl = problem.N // n_x
    nl_y = problem.N // n_y
    sx, ct, syz, rsyz, xmask, inv_absx = kfused._oracle_parts(problem, f)
    inv_absx = jnp.where(jnp.abs(sx) > _rel_guard_tol(f), inv_absx,
                         jnp.asarray(0.0, f))
    sxct_all = ct[:, None] * sx[None, :]
    perm_fwd = [(i, (i + 1) % n_x) for i in range(n_x)]
    perm_bwd = [(i, (i - 1) % n_x) for i in range(n_x)]
    perm_fwd_y = [(i, (i + 1) % n_y) for i in range(n_y)]
    perm_bwd_y = [(i, (i - 1) % n_y) for i in range(n_y)]
    if chunk_len is None:
        start = 1 if start_step is None else start_step
        nblocks = (nsteps - start) // k
        rem = (nsteps - start) - nblocks * k
    else:
        nblocks = chunk_len // k
        rem = chunk_len - nblocks * k
    # One block_x for every kk so the op sequence matches the
    # single-device kernel's block partitioning (bitwise contract).
    itemsizes = (
        jnp.dtype(dtype).itemsize, jnp.dtype(v_dtype).itemsize,
        jnp.dtype(carry_dtype).itemsize if carry_on else None,
    )
    if n_y == 1:
        bx = block_x or stencil_pallas.choose_kstep_comp_block(
            problem.N, k, *itemsizes, depth=nl, ghosts=True,
            field=has_field,
        )
    else:
        bx = block_x or stencil_pallas.choose_kstep_comp_block(
            problem.N, k, *itemsizes, depth=nl, ghosts=True,
            plane_elems=(nl_y + 2 * k) * problem.N, field=has_field,
        )
    if bx is None:
        raise ValueError(
            f"k={k} does not fit VMEM for N={problem.N} over "
            f"({n_x}, {n_y}, 1) shards"
        )

    def ghosts(a, kk):
        if n_x == 1:
            return a[-kk:], a[:kk]
        return (
            lax.ppermute(a[-kk:], "x", perm_fwd),
            lax.ppermute(a[:kk], "x", perm_bwd),
        )

    def extend_y(a, kk):
        # Only called on the n_y > 1 path (kcall dispatches the x-only
        # kernel otherwise, matching solver/sharded_kfused.py).
        lo = lax.ppermute(a[:, -kk:], "y", perm_fwd_y)
        hi = lax.ppermute(a[:, :kk], "y", perm_bwd_y)
        return jnp.concatenate([lo, a, hi], axis=1)

    def field_pack(fld, kk):
        """(block_or_ext, x-ghost pair) for the time-invariant field at
        ghost depth kk - built once per solve per needed depth."""
        if fld is None:
            return None
        if n_y == 1:
            return fld, ghosts(fld, kk)
        fe = extend_y(fld, kk)
        return fe, ghosts(fe, kk)

    def kcall(syz_c, rsyz_c, u, v, c, sxct_k, kk, coeff, with_err,
              fp=None):
        c2b = fp[0] if fp is not None else None
        c2g = fp[1] if fp is not None else None
        if n_y == 1:
            return stencil_pallas.fused_kstep_comp_sharded(
                u, v, c, ghosts(u, kk), ghosts(v, kk), syz_c, rsyz_c,
                sxct_k, k=kk, coeff=coeff, inv_h2=problem.inv_h2,
                c2tau2_block=c2b, c2_ghosts=c2g,
                block_x=bx, interpret=interpret, with_errors=with_err,
            )
        ue, ve = extend_y(u, kk), extend_y(v, kk)
        y0 = lax.axis_index("y") * nl_y
        u2, v2, c2, dm, rm = stencil_pallas.fused_kstep_comp_sharded_xy(
            ue, ve, c, ghosts(ue, kk), ghosts(ve, kk), syz_c, rsyz_c,
            sxct_k, y0, problem.N, k=kk, nl_y=nl_y, coeff=coeff,
            inv_h2=problem.inv_h2, c2tau2_ext=c2b, c2_ghosts=c2g,
            block_x=bx, interpret=interpret,
            with_errors=with_err,
        )
        if with_err:
            dm = lax.pmax(dm, "y")
            rm = lax.pmax(rm, "y")
        return u2, v2, c2, dm, rm

    def layer_rows(syz_c, rsyz_c, u, sxct_row):
        d, r = kfused._layer_rows_local(u, sxct_row, syz_c, rsyz_c, f)
        if n_y > 1:
            d = lax.pmax(d, "y")
            r = lax.pmax(r, "y")
        return d, r

    def local_march(syz_c, rsyz_c, u, v, c, sxct_loc, first, fld=None):
        rows_d, rows_r = [], []
        fp_k = field_pack(fld, k)
        fp_1 = field_pack(fld, 1) if rem else None

        def body(state, nstart):
            u, v, c = state
            sxct_k = lax.dynamic_slice(sxct_loc, (nstart + 1, 0), (k, nl))
            u2, v2, c2, dm, rm = kcall(
                syz_c, rsyz_c, u, v, c, sxct_k, k, problem.a2tau2,
                compute_errors, fp_k,
            )
            if not compute_errors:
                dm = rm = jnp.zeros((k, nl), f)
            return (u2, v2, c2), (dm, rm)

        starts = first + k * jnp.arange(nblocks)
        (u, v, c), (dmb, rmb) = lax.scan(body, (u, v, c), starts)
        rows_d.append(dmb.reshape(-1, nl))
        rows_r.append(rmb.reshape(-1, nl))
        for t in range(rem):
            # == nsteps - rem + 1 + t on the full march; phrasing it off
            # `first` keeps the identical arithmetic valid for a traced
            # chunk start.
            layer = jnp.asarray(first + nblocks * k + 1 + t, jnp.int32)
            sxct_1 = lax.dynamic_slice(
                sxct_loc, (layer, jnp.int32(0)), (1, nl)
            )
            u, v, c, dm, rm = kcall(
                syz_c, rsyz_c, u, v, c, sxct_1, 1, problem.a2tau2,
                compute_errors, fp_1,
            )
            if not compute_errors:
                dm = rm = jnp.zeros((1, nl), f)
            rows_d.append(dm)
            rows_r.append(rm)
        return u, v, c, jnp.concatenate(rows_d), jnp.concatenate(rows_r)

    def assemble(dmax, rmax):
        if not compute_errors:
            z = jnp.zeros((nsteps + 1,), f)
            return z, z
        return kfused._block_errors(
            dmax, rmax, ct[: dmax.shape[0]], xmask, inv_absx
        )

    state_spec = P("x", "y")
    rows_spec = P(None, "x")
    plane_spec = P("y", None)

    field_specs = (state_spec,) if has_field else ()

    if chunk_len is not None:
        assert start_step is None

        def local_chunk(u, v, c, start, sxct_loc, syz_c, rsyz_c, *fargs):
            return local_march(
                syz_c, rsyz_c, u, v, c, sxct_loc, start,
                fargs[0] if has_field else None,
            )

        local_fn = compat.shard_map(
            local_chunk, mesh=mesh,
            in_specs=(state_spec, state_spec,
                      state_spec if carry_on else None,
                      P(), rows_spec, plane_spec, plane_spec)
            + field_specs,
            out_specs=(state_spec, state_spec,
                       state_spec if carry_on else None,
                       rows_spec, rows_spec),
            check_vma=False,
        )

        def run_chunk(u, v, c, start, *fargs):
            u, v, c, dmax, rmax = local_fn(
                u, v, c, start, sxct_all, syz, rsyz, *fargs
            )
            if compute_errors:
                ctk = lax.dynamic_slice(ct, (start + 1,), (chunk_len,))
                abs_e, rel_e = kfused._block_errors(
                    dmax, rmax, ctk, xmask, inv_absx
                )
            else:
                abs_e = rel_e = jnp.zeros((chunk_len,), f)
            return u, v, c, abs_e, rel_e

        return jax.jit(run_chunk)

    if start_step is None:

        def local(u0, sxct_loc, syz_c, rsyz_c, *fargs):
            fld = fargs[0] if has_field else None
            zero_v = jnp.zeros(u0.shape, v_dtype)
            zero_c = (
                jnp.zeros(u0.shape, carry_dtype) if carry_on else None
            )
            u1, v1, c1, _, _ = kcall(
                syz_c, rsyz_c, u0, zero_v, zero_c,
                jnp.zeros((1, nl), f), 1, 0.5 * problem.a2tau2, False,
                field_pack(0.5 * fld, 1) if has_field else None,
            )
            if compute_errors:
                d1, r1 = layer_rows(syz_c, rsyz_c, u1, sxct_loc[1])
            else:
                d1 = r1 = jnp.zeros((1, nl), f)
            u, v, c, rows_d, rows_r = local_march(
                syz_c, rsyz_c, u1, v1, c1, sxct_loc, 1, fld
            )
            zero = jnp.zeros((1, nl), f)
            return (
                u, v, c,
                jnp.concatenate([zero, d1, rows_d]),
                jnp.concatenate([zero, r1, rows_r]),
            )

        local_fn = compat.shard_map(
            local, mesh=mesh,
            in_specs=(state_spec, rows_spec, plane_spec, plane_spec)
            + field_specs,
            out_specs=(state_spec, state_spec,
                       state_spec if carry_on else None,
                       rows_spec, rows_spec),
            check_vma=False,
        )

        def run(*fargs):
            u0 = lax.with_sharding_constraint(
                leapfrog.initial_layer0(problem, dtype),
                NamedSharding(mesh, state_spec),
            )
            u, v, c, dmax, rmax = local_fn(
                u0, sxct_all, syz, rsyz, *fargs
            )
            abs_e, rel_e = assemble(dmax, rmax)
            return u, v, c, abs_e, rel_e

        return jax.jit(run)

    def local_resume(u, v, c, sxct_loc, syz_c, rsyz_c, *fargs):
        u, v, c, rows_d, rows_r = local_march(
            syz_c, rsyz_c, u, v, c, sxct_loc, start_step,
            fargs[0] if has_field else None,
        )
        head = jnp.zeros((start_step + 1, nl), f)
        return (
            u, v, c,
            jnp.concatenate([head, rows_d]),
            jnp.concatenate([head, rows_r]),
        )

    local_fn = compat.shard_map(
        local_resume, mesh=mesh,
        in_specs=(state_spec, state_spec,
                  state_spec if carry_on else None,
                  rows_spec, plane_spec, plane_spec) + field_specs,
        out_specs=(state_spec, state_spec,
                   state_spec if carry_on else None,
                   rows_spec, rows_spec),
        check_vma=False,
    )

    def run(u, v, c, *fargs):
        u, v, c, dmax, rmax = local_fn(u, v, c, sxct_all, syz, rsyz,
                                       *fargs)
        abs_e, rel_e = assemble(dmax, rmax)
        return u, v, c, abs_e, rel_e

    return jax.jit(run)


def solve_kfused_comp_sharded(
    problem: Problem,
    n_shards: Optional[int] = None,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    stop_step: Optional[int] = None,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    devices=None,
    v_dtype=None,
    carry: bool = True,
    mesh_shape=None,
    carry_dtype=None,
    c2tau2_field=None,
) -> leapfrog.SolveResult:
    """Distributed velocity-form compensated k-fused solve over an
    (MX, MY, 1) mesh - the flagship scheme at the reference's
    distributed scale (mpi_new.cpp's role), with the compensated
    accuracy contract.  `n_shards` is the x-only shorthand.  Requires
    MX | N, k | N/MX, MY | N, k <= N/MY.  `carry_dtype` as
    `solve_kfused_comp`; `c2tau2_field` threads the variable-c field
    through the sharded onion (compute_errors=False required) - the c^2
    slab is sharded on the same mesh with its ghost exchange hoisted out
    of the layer scan (the field is time-invariant)."""
    from wavetpu.core.grid import build_mesh
    from wavetpu.solver.sharded_kfused import _resolve_grid

    if devices is None:
        devices = jax.devices()
    n_x, n_y = _resolve_grid(mesh_shape, n_shards, devices)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v_dtype = dtype if v_dtype is None else jnp.dtype(v_dtype)
    if carry and carry_dtype is not None:
        _validate_carry_dtype(dtype, carry_dtype)
    _validate_sharded(problem, dtype, v_dtype, carry, k, n_x, n_y,
                      c2tau2_field, compute_errors)
    nsteps = problem.timesteps if stop_step is None else stop_step
    if not 1 <= nsteps <= problem.timesteps:
        raise ValueError(
            f"stop_step must be in [1, {problem.timesteps}], got {nsteps}"
        )
    mesh = build_mesh((n_x, n_y, 1), devices[: n_x * n_y])
    has_field = c2tau2_field is not None
    runner = _make_sharded_runner(
        problem, mesh, (n_x, n_y), dtype, v_dtype, carry, k,
        compute_errors, nsteps, None, block_x, interpret,
        carry_dtype=carry_dtype, has_field=has_field,
    )
    run_params = ()
    if has_field:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        f = stencil_ref.compute_dtype(dtype)
        run_params = (jax.device_put(
            jnp.asarray(c2tau2_field, dtype=f),
            NamedSharding(mesh, P("x", "y")),
        ),)
    out, init_s, solve_s = leapfrog._timed_compile_run(
        runner, run_params, sync=lambda o: np.asarray(o[3])
    )
    result = _as_result(
        problem, out, init_s, solve_s, stop_step,
        stop_step if stop_step is not None else problem.timesteps,
    )
    obs_metrics.record_solve(
        result, "kfused_comp_sharded", scheme="compensated", k=k,
        v_itemsize=(
            None if v_dtype is None else jnp.dtype(v_dtype).itemsize
        ),
        carry=carry, with_field=c2tau2_field is not None,
        block_x=block_x,
        # Same depth/ghosts arguments the sharded chooser above used,
        # so the roofline model reads the block the kernel runs.
        depth=problem.N // n_x, ghosts=True,
    )
    return result


def resume_kfused_comp_sharded(
    problem: Problem,
    u_cur,
    v,
    carry,
    start_step: int,
    n_shards: Optional[int] = None,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    devices=None,
    v_dtype=None,
    mesh_shape=None,
    c2tau2_field=None,
) -> leapfrog.SolveResult:
    """Re-enter the sharded velocity-form march at layer `start_step`
    from compensated checkpoint state (carry=None resumes the carry-less
    increment form).  A variable-c checkpoint resumes under the same
    re-passed `c2tau2_field`."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from wavetpu.core.grid import build_mesh
    from wavetpu.solver.sharded_kfused import _resolve_grid

    if devices is None:
        devices = jax.devices()
    n_x, n_y = _resolve_grid(mesh_shape, n_shards, devices)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v_dtype = dtype if v_dtype is None else jnp.dtype(v_dtype)
    carry_on = carry is not None
    _validate_sharded(problem, dtype, v_dtype, carry_on, k, n_x, n_y,
                      c2tau2_field, compute_errors)
    nsteps = problem.timesteps
    if not 1 <= start_step <= nsteps:
        raise ValueError(
            f"start_step must be in [1, {nsteps}], got {start_step}"
        )
    mesh = build_mesh((n_x, n_y, 1), devices[: n_x * n_y])
    if carry_on:
        # No-copy dtype probe + the same preserve-or-cast rule as
        # resume_kfused_comp.
        carry = _normalize_carry(carry, dtype)
    has_field = c2tau2_field is not None
    runner = _make_sharded_runner(
        problem, mesh, (n_x, n_y), dtype, v_dtype, carry_on, k,
        compute_errors, nsteps, start_step, block_x, interpret,
        carry_dtype=jnp.result_type(carry) if carry_on else None,
        has_field=has_field,
    )
    sharding = NamedSharding(mesh, P("x", "y"))
    args = (
        jax.device_put(jnp.asarray(u_cur, dtype), sharding),
        jax.device_put(jnp.asarray(v, v_dtype), sharding),
        jax.device_put(carry, sharding) if carry_on else None,
    )
    if has_field:
        f = stencil_ref.compute_dtype(dtype)
        args = args + (jax.device_put(
            jnp.asarray(c2tau2_field, dtype=f), sharding
        ),)
    out, init_s, solve_s = leapfrog._timed_compile_run(
        runner, args, sync=lambda o: np.asarray(o[3])
    )
    return _as_result(
        problem, out, init_s, solve_s, nsteps - start_step, nsteps
    )


def resume_kfused_comp(
    problem: Problem,
    u_cur,
    v,
    carry,
    start_step: int,
    dtype=jnp.float32,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: bool = False,
    v_dtype=None,
    c2tau2_field=None,
) -> leapfrog.SolveResult:
    """Re-enter the compensated k-fused march at layer `start_step`.

    `(u_cur, v, carry)` is the compensated checkpoint state
    (SolveResult.u_cur / .comp_v / .comp_carry); `carry=None` resumes the
    carry-less increment form.  The march is the same op sequence as an
    uninterrupted run's from that layer, so a same-path resume is
    self-consistent; a cross-path resume (1-step compensated <-> k-fused)
    agrees to scheme tolerance, not bitwise.  A variable-c checkpoint
    resumes under the same re-passed `c2tau2_field` (checkpoints store
    state, not the field).
    """
    v_dtype = dtype if v_dtype is None else jnp.dtype(v_dtype)
    carry_on = carry is not None
    _validate(problem, dtype, v_dtype, carry_on, k, c2tau2_field,
              compute_errors)
    nsteps = problem.timesteps
    if not 1 <= start_step <= nsteps:
        raise ValueError(
            f"start_step must be in [1, {nsteps}], got {start_step}"
        )
    f = stencil_ref.compute_dtype(dtype)
    has_field = c2tau2_field is not None
    march = _make_march(
        problem, dtype, v_dtype, carry_on, k, compute_errors, block_x,
        interpret, nsteps, has_field,
    )

    def run(u_cur, v, carry, *field_params):
        u, vv, cc, abs_t, rel_t = march(
            u_cur, v, carry, start_step, *field_params
        )
        head = jnp.zeros((start_step + 1,), f)
        return (
            u, vv, cc,
            jnp.concatenate([head, abs_t]),
            jnp.concatenate([head, rel_t]),
        )

    args = (
        jnp.asarray(u_cur, dtype),
        jnp.asarray(v, v_dtype),
        # Preserve a valid stored carry dtype (bf16-carry checkpoints
        # resume bitwise; legacy f32 carries stay f32); invalid combos
        # (e.g. f64 carry into an f32 run) cast to the state dtype.
        _normalize_carry(carry, dtype) if carry_on else None,
    )
    if has_field:
        args = args + (leapfrog.ParamStep.materialize(
            jnp.asarray(c2tau2_field, dtype=f)
        ),)
    out, init_s, solve_s = leapfrog._timed_compile_run(
        jax.jit(run), args, sync=lambda o: np.asarray(o[3])
    )
    return _as_result(
        problem, out, init_s, solve_s, nsteps - start_step, nsteps
    )


def make_chunk_runner(
    problem: Problem,
    dtype=jnp.float32,
    length: int = 4,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: bool = False,
    v_dtype=None,
    carry: bool = True,
    c2tau2_field=None,
):
    """Fixed-length compensated k-fused re-entry for supervised solves.

    Returns `(runner, run_params)`; `runner(u, v, carry, start,
    *run_params)` (carry=None resumes the carry-less increment form)
    marches layers start+1..start+length with a RUNTIME `start` - one
    compiled program per chunk length (run/supervisor.py).  On
    block-aligned starts with length a multiple of k the op sequence
    equals the uninterrupted march's prefix, so supervision preserves
    the velocity-form onion's exact trajectory.
    """
    v_dtype = dtype if v_dtype is None else jnp.dtype(v_dtype)
    _validate(problem, dtype, v_dtype, carry, k, c2tau2_field,
              compute_errors)
    if length < 1:
        raise ValueError(f"chunk length must be >= 1, got {length}")
    f = stencil_ref.compute_dtype(dtype)
    has_field = c2tau2_field is not None
    march = _make_march(
        problem, dtype, v_dtype, carry, k, compute_errors, block_x,
        interpret, None, has_field, chunk_len=length,
    )

    def run(u_cur, v, carry, start, *field_params):
        return march(u_cur, v, carry, start, *field_params)

    run_params = ()
    if has_field:
        run_params = (leapfrog.ParamStep.materialize(
            jnp.asarray(c2tau2_field, dtype=f)
        ),)
    return jax.jit(run), run_params


def make_sharded_chunk_runner(
    problem: Problem,
    mesh,
    grid,
    dtype=jnp.float32,
    length: int = 4,
    k: int = 4,
    compute_errors: bool = True,
    block_x: Optional[int] = None,
    interpret: Optional[bool] = None,
    v_dtype=None,
    carry: bool = True,
    carry_dtype=None,
    has_field: bool = False,
):
    """Sharded counterpart of `make_chunk_runner` over an (MX, MY, 1)
    mesh: `runner(u, v, carry, start[, field])` with all state P("x","y")
    on `mesh` and a RUNTIME `start` - the supervised chunk program for
    the distributed velocity-form flagship."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v_dtype = dtype if v_dtype is None else jnp.dtype(v_dtype)
    _validate_sharded(problem, dtype, v_dtype, carry, k, grid[0], grid[1],
                      None, True)
    if length < 1:
        raise ValueError(f"chunk length must be >= 1, got {length}")
    return _make_sharded_runner(
        problem, mesh, grid, dtype, v_dtype, carry, k, compute_errors,
        None, None, block_x, interpret, carry_dtype=carry_dtype,
        has_field=has_field, chunk_len=length,
    )
