"""Phase-timing probes: loop (stencil) vs halo-exchange cost.

The reference accumulates `total_loop_time` / `total_exchange_time` with
host timers around each phase of every step (mpi_new.cpp:33-34, 200-240,
368-371).  A TPU program cannot be timed that way - the whole solve is one
fused XLA computation with no host boundary to put a timer on (that fusion
IS the design, solver/sharded.py).  Instead, the breakdown is measured the
way one profiles jitted code: two probe programs over identical state,

  * full   - halo exchange (`ppermute`) + stencil update, the real step body;
  * compute - the same stencil with a zero-ghost local pad instead of the
    exchange (identical FLOPs and memory traffic shape, no ICI);

each run as a `lax.scan` of `iters` steps inside one jitted shard_map call.
`exchange = full - compute` (clamped at 0: on a single-superchip mesh the
difference sits inside timer noise).  The numbers feed the report writer's
"total ICI exchange time" / "total loop time" lines so output files stay
diffable against the reference's.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from wavetpu.comm import halo
from wavetpu.core.grid import AXIS_NAMES, Topology, build_mesh, choose_mesh_shape
from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_ref


@dataclasses.dataclass(frozen=True)
class PhaseBreakdown:
    """Per-solve phase attribution, scaled to `timesteps` steps."""

    loop_seconds: float       # stencil update cost (compute probe)
    exchange_seconds: float   # halo `ppermute` cost (full - compute, >= 0)
    steps_measured: int       # probe scan length behind the extrapolation

    @property
    def total_seconds(self) -> float:
        return self.loop_seconds + self.exchange_seconds


def _probe_runner(problem: Problem, topo: Topology, mesh, dtype, with_halo,
                  iters: int):
    """Jitted scan of `iters` leapfrog steps over the sharded state."""
    c_full = problem.a2tau2
    inv_h2 = problem.inv_h2

    def local(u_prev, u, salt):
        def body(carry, _):
            u_prev, u = carry
            if with_halo:
                ext = halo.halo_extend(u, topo)
            else:
                ext = jnp.pad(u, 1)
            lap = stencil_ref.laplacian_ext(ext, inv_h2)
            u_next = 2.0 * u - u_prev + jnp.asarray(c_full, dtype) * lap
            return (u, u_next), None

        (u_prev, u), _ = jax.lax.scan(
            body, (u_prev + salt, u), None, length=iters
        )
        # Scalar checksum output: reading it back on the host both forces
        # execution (remote backends can defer past block_until_ready) and
        # keeps the transfer tiny.
        return jax.lax.psum(jnp.sum(u), AXIS_NAMES)

    spec = P(*AXIS_NAMES)
    return jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, P()),
            out_specs=P(),
        )
    )


def _time_best(fn, args, repeats: int) -> float:
    """Best-of-N wall time of the compiled callable (compile excluded).

    Each call gets a distinct `salt` input so remote backends cannot serve
    a memoized result, and the scalar output is read back to force
    completion.
    """
    np.asarray(fn(*args, jnp.zeros((), args[0].dtype)))  # compile + warm up
    best = float("inf")
    for i in range(repeats):
        salt = jnp.asarray(1e-6 * (i + 1), args[0].dtype)
        t0 = time.perf_counter()
        np.asarray(fn(*args, salt))
        best = min(best, time.perf_counter() - t0)
    return best


def measure_phase_breakdown(
    problem: Problem,
    mesh_shape: Optional[Tuple[int, int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    dtype=jnp.float32,
    iters: int = 10,
    repeats: int = 3,
) -> PhaseBreakdown:
    """Measure the loop/exchange split and scale it to the full solve length.

    Runs on zero state - leapfrog cost is data-independent, and the probes
    exist for timing, not numerics.
    """
    if devices is None:
        devices = jax.devices()
    if mesh_shape is None:
        mesh_shape = choose_mesh_shape(len(devices))
    topo = Topology(N=problem.N, mesh_shape=mesh_shape)
    mesh = build_mesh(mesh_shape, devices[: topo.n_devices])

    shape = topo.padded
    u_prev = jnp.zeros(shape, dtype)
    u = jnp.zeros(shape, dtype)
    sharding = jax.sharding.NamedSharding(mesh, P(*AXIS_NAMES))
    u_prev = jax.device_put(u_prev, sharding)
    u = jax.device_put(u, sharding)

    t_full = _time_best(
        _probe_runner(problem, topo, mesh, dtype, True, iters),
        (u_prev, u), repeats,
    )
    t_comp = _time_best(
        _probe_runner(problem, topo, mesh, dtype, False, iters),
        (u_prev, u), repeats,
    )
    scale = problem.timesteps / iters
    return PhaseBreakdown(
        loop_seconds=t_comp * scale,
        exchange_seconds=max(0.0, (t_full - t_comp)) * scale,
        steps_measured=iters,
    )
