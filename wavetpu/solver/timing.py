"""Phase-timing probes: loop (stencil) vs halo-exchange cost.

The reference accumulates `total_loop_time` / `total_exchange_time` with
host timers around each phase of every step (mpi_new.cpp:33-34, 200-240,
368-371).  A TPU program cannot be timed that way - the whole solve is one
fused XLA computation with no host boundary to put a timer on (that fusion
IS the design, solver/sharded.py).  Instead, the breakdown is measured the
way one profiles jitted code: two probe programs over identical state,

  * full    - the PRODUCTION step body (`sharded._make_local_step`: the
    selected kernel, bc masking, ppermute halo exchange), errors off;
  * compute - the same step builder with `exchange=False`: the identical
    program with local wrap planes substituted for the ppermute'd ghosts -
    same FLOPs and memory-traffic shape, no ICI;

each run as a `lax.scan` of `iters` steps inside one jitted shard_map call.
`exchange = full - compute` (clamped at 0: on a single-superchip mesh the
difference sits inside timer noise).  Because both probes reuse the solver's
own step function, the kernel choice (`--kernel`) is timed as shipped -
the round-3 verdict's item 10 (the old probe hand-rolled a maskless
jnp-only step and so timed a different program than it reported on).

One residual approximation: a single-device (--backend single) run uses
the full-domain Pallas kernel, while its probe runs the sharded kernel on
a (1,1,1) mesh.  The static mesh specialization makes those nearly the
same program (no ppermutes, no ghost operands; measured 19.9 vs 20.3
Gcell/s at N=512 on v5e, ~2%) - accepted and documented rather than
maintaining a third probe variant.  The compensated scheme has no probe;
the CLI rejects that flag combination.

The numbers are extrapolated from `iters` probe steps to the full solve
length; the report writer labels them as such.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from wavetpu.core.grid import AXIS_NAMES, Topology, build_mesh, choose_mesh_shape
from wavetpu.core.problem import Problem
from wavetpu import compat
from wavetpu.kernels import stencil_ref
from wavetpu.solver import sharded as _sharded


@dataclasses.dataclass(frozen=True)
class PhaseBreakdown:
    """Per-solve phase attribution, scaled to `timesteps` steps."""

    loop_seconds: float       # stencil update cost (compute probe)
    exchange_seconds: float   # halo `ppermute` cost (full - compute, >= 0)
    steps_measured: int       # probe scan length behind the extrapolation

    @property
    def total_seconds(self) -> float:
        return self.loop_seconds + self.exchange_seconds


def _probe_runner(problem: Problem, topo: Topology, mesh, dtype, kernel,
                  overlap, interpret, with_halo, iters: int):
    """Jitted scan of `iters` PRODUCTION leapfrog steps over sharded state."""
    step = _sharded._make_local_step(
        problem, topo, dtype, kernel, overlap, interpret,
        exchange=with_halo,
    )

    def local(u_prev, u, bcx, bcy, bcz, salt):
        bc = bcx[:, None, None] * bcy[None, :, None] * bcz[None, None, :]

        def body(carry, _):
            u_prev, u = carry
            u_next = step(u_prev, u, bc, None)
            return (u, u_next), None

        (u_prev, u), _ = jax.lax.scan(
            body, (u_prev + salt, u), None, length=iters
        )
        # Scalar checksum output: reading it back on the host both forces
        # execution (remote backends can defer past block_until_ready) and
        # keeps the transfer tiny.
        return jax.lax.psum(jnp.sum(u), AXIS_NAMES)

    spec = P(*AXIS_NAMES)
    return jax.jit(
        compat.shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, P("x"), P("y"), P("z"), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def _time_best(fn, args, repeats: int) -> float:
    """Best-of-N wall time of the compiled callable (compile excluded).

    Each call gets a distinct `salt` input so remote backends cannot serve
    a memoized result, and the scalar output is read back to force
    completion.
    """
    np.asarray(fn(*args, jnp.zeros((), args[0].dtype)))  # compile + warm up
    best = float("inf")
    for i in range(repeats):
        salt = jnp.asarray(1e-6 * (i + 1), args[0].dtype)
        t0 = time.perf_counter()
        np.asarray(fn(*args, salt))
        best = min(best, time.perf_counter() - t0)
    return best


def _kfused_probe_runner(problem, grid, mesh, dtype, k, interpret,
                         with_halo, iters: int):
    """Jitted scan of `iters` PRODUCTION k-blocks over (MX, MY)-sharded
    state.

    `with_halo=False` substitutes the shard's own wrap planes/rows for
    EVERY ppermute (x ghosts, and on 2D meshes the y-row extension whose
    x ghosts are then sliced from the extended blocks) - identical FLOPs
    and kernel, no ICI - mirroring `_probe_runner`'s exchange=False
    contract for the k-fused solver (whose exchange is one k-deep
    ppermute pair per axis per field per k layers).
    """
    from wavetpu.kernels import stencil_pallas as _sp

    n_x, n_y = grid
    f = stencil_ref.compute_dtype(dtype)
    nl = problem.N // n_x
    nl_y = problem.N // n_y
    perm_fwd = [(i, (i + 1) % n_x) for i in range(n_x)]
    perm_bwd = [(i, (i - 1) % n_x) for i in range(n_x)]
    perm_fwd_y = [(i, (i + 1) % n_y) for i in range(n_y)]
    perm_bwd_y = [(i, (i - 1) % n_y) for i in range(n_y)]

    def local(u_prev, u, syz_c, rsyz_c, salt):
        def ghosts(a):
            if with_halo:
                return (
                    lax.ppermute(a[-k:], "x", perm_fwd),
                    lax.ppermute(a[:k], "x", perm_bwd),
                )
            return a[-k:], a[:k]

        def extend_y(a):
            if with_halo:
                lo = lax.ppermute(a[:, -k:], "y", perm_fwd_y)
                hi = lax.ppermute(a[:, :k], "y", perm_bwd_y)
            else:
                lo, hi = a[:, -k:], a[:, :k]
            return jnp.concatenate([lo, a, hi], axis=1)

        def body(carry, _):
            u_prev, u = carry
            if n_y == 1:
                up, uc, _, _ = _sp.fused_kstep_sharded(
                    u_prev, u, ghosts(u_prev), ghosts(u), syz_c, rsyz_c,
                    jnp.zeros((k, nl), f), k=k, coeff=problem.a2tau2,
                    inv_h2=problem.inv_h2, interpret=interpret,
                    with_errors=False,
                )
            else:
                pe, ce = extend_y(u_prev), extend_y(u)
                y0 = lax.axis_index("y") * nl_y
                up, uc, _, _ = _sp.fused_kstep_sharded_xy(
                    pe, ce, ghosts(pe), ghosts(ce), syz_c, rsyz_c,
                    jnp.zeros((k, nl), f), y0, problem.N, k=k,
                    nl_y=nl_y, coeff=problem.a2tau2,
                    inv_h2=problem.inv_h2, interpret=interpret,
                    with_errors=False,
                )
            return (up, uc), None

        (u_prev, u), _ = jax.lax.scan(
            body, (u_prev + salt, u), None, length=iters
        )
        return jax.lax.psum(jnp.sum(u), AXIS_NAMES)

    state_spec = P("x", "y")
    plane_spec = P("y", None)
    return jax.jit(
        compat.shard_map(
            local,
            mesh=mesh,
            in_specs=(state_spec, state_spec, plane_spec, plane_spec,
                      P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def _kfused_comp_probe_runner(problem, grid, mesh, dtype, v_dtype,
                              carry_dtype, k, interpret, with_halo,
                              iters: int):
    """`_kfused_probe_runner` for the velocity-form compensated onion
    (solver/kfused_comp.py): the scan carries (u, v, carry) and both u
    and v exchange k-deep ghosts per block (the carry stays shard-local,
    exactly as in production).  `with_halo=False` substitutes local wrap
    planes/rows for every ppermute - identical FLOPs and kernel, no ICI.
    `carry_dtype=None` probes the carry-less increment form (the bf16-v
    mode)."""
    from wavetpu.kernels import stencil_pallas as _sp

    n_x, n_y = grid
    f = stencil_ref.compute_dtype(dtype)
    nl = problem.N // n_x
    nl_y = problem.N // n_y
    carry_on = carry_dtype is not None
    perm_fwd = [(i, (i + 1) % n_x) for i in range(n_x)]
    perm_bwd = [(i, (i - 1) % n_x) for i in range(n_x)]
    perm_fwd_y = [(i, (i + 1) % n_y) for i in range(n_y)]
    perm_bwd_y = [(i, (i - 1) % n_y) for i in range(n_y)]

    def local(u, v, carry, syz_c, rsyz_c, salt):
        def ghosts(a):
            if with_halo:
                return (
                    lax.ppermute(a[-k:], "x", perm_fwd),
                    lax.ppermute(a[:k], "x", perm_bwd),
                )
            return a[-k:], a[:k]

        def extend_y(a):
            if with_halo:
                lo = lax.ppermute(a[:, -k:], "y", perm_fwd_y)
                hi = lax.ppermute(a[:, :k], "y", perm_bwd_y)
            else:
                lo, hi = a[:, -k:], a[:, :k]
            return jnp.concatenate([lo, a, hi], axis=1)

        def body(state, _):
            u, v, c = state
            if n_y == 1:
                u2, v2, c2, _, _ = _sp.fused_kstep_comp_sharded(
                    u, v, c, ghosts(u), ghosts(v), syz_c, rsyz_c,
                    jnp.zeros((k, nl), f), k=k, coeff=problem.a2tau2,
                    inv_h2=problem.inv_h2, interpret=interpret,
                    with_errors=False,
                )
            else:
                ue, ve = extend_y(u), extend_y(v)
                y0 = lax.axis_index("y") * nl_y
                u2, v2, c2, _, _ = _sp.fused_kstep_comp_sharded_xy(
                    ue, ve, c, ghosts(ue), ghosts(ve), syz_c, rsyz_c,
                    jnp.zeros((k, nl), f), y0, problem.N, k=k,
                    nl_y=nl_y, coeff=problem.a2tau2,
                    inv_h2=problem.inv_h2, interpret=interpret,
                    with_errors=False,
                )
            return (u2, v2, c2), None

        (u, v, carry), _ = jax.lax.scan(
            body, (u + salt, v, carry), None, length=iters
        )
        return jax.lax.psum(jnp.sum(u), AXIS_NAMES)

    state_spec = P("x", "y")
    plane_spec = P("y", None)
    return jax.jit(
        compat.shard_map(
            local,
            mesh=mesh,
            in_specs=(state_spec, state_spec,
                      state_spec if carry_on else None,
                      plane_spec, plane_spec, P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def measure_phase_breakdown(
    problem: Problem,
    mesh_shape: Optional[Tuple[int, int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    dtype=jnp.float32,
    kernel: str = "roll",
    overlap: bool = False,
    interpret: Optional[bool] = None,
    iters: int = 10,
    repeats: int = 3,
    fuse_steps: int = 1,
    scheme: str = "standard",
    v_dtype=None,
) -> PhaseBreakdown:
    """Measure the loop/exchange split and scale it to the full solve length.

    Runs on zero state - leapfrog cost is data-independent, and the probes
    exist for timing, not numerics.  `kernel`/`overlap` select the same
    step the production solver would run; `fuse_steps > 1` probes the
    sharded k-fused program instead (any even (MX, MY, 1) decomposition;
    `iters` then counts k-blocks and the breakdown is scaled by the
    layers they cover).  `scheme="compensated"` with `fuse_steps > 1`
    probes the velocity-form onion - (u, v, carry) state, u AND v
    exchanging ghosts - including the carry-less bf16-increment mode via
    `v_dtype=bfloat16` (the 1-step compensated scheme has no probe; the
    CLI rejects that combination).
    """
    if devices is None:
        devices = jax.devices()
    if mesh_shape is None:
        mesh_shape = choose_mesh_shape(len(devices))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if fuse_steps > 1:
        from wavetpu.solver import kfused as _kfused
        from wavetpu.solver import sharded_kfused as _skf

        k = fuse_steps
        n_x, n_y = mesh_shape[0], mesh_shape[1]
        if mesh_shape[2] != 1:
            raise ValueError(
                f"k-fused probe needs an (MX, MY, 1) mesh, got {mesh_shape}"
            )
        _skf._validate(problem, k, n_x, n_y)  # same errors as production
        if not _skf._is_even(problem, k, n_x):
            raise ValueError(
                f"k-fused probe covers even decompositions "
                f"(k | N/MX); got N={problem.N}, MX={n_x}, k={k}"
            )
        mesh = build_mesh(mesh_shape, devices[: n_x * n_y])
        f = stencil_ref.compute_dtype(dtype)
        _, _, syz, rsyz, _, _ = _kfused._oracle_parts(problem, f)
        sharding = jax.sharding.NamedSharding(mesh, P("x", "y"))
        if scheme == "compensated":
            from wavetpu.solver import kfused_comp as _kc

            vd = jnp.dtype(dtype) if v_dtype is None else jnp.dtype(
                v_dtype)
            carry_on = vd != jnp.bfloat16 or jnp.dtype(
                dtype) == jnp.bfloat16
            cd = _kc._default_carry_dtype(dtype) if carry_on else None
            u = jax.device_put(
                jnp.zeros((problem.N,) * 3, dtype), sharding
            )
            v = jax.device_put(jnp.zeros((problem.N,) * 3, vd), sharding)
            carry = (
                jax.device_put(jnp.zeros((problem.N,) * 3, cd), sharding)
                if carry_on else None
            )
            args = (u, v, carry, syz, rsyz)

            def runner(with_halo):
                return _kfused_comp_probe_runner(
                    problem, (n_x, n_y), mesh, dtype, vd, cd, k,
                    interpret, with_halo, iters,
                )
        else:
            u_prev = jax.device_put(
                jnp.zeros((problem.N,) * 3, dtype), sharding
            )
            u = jax.device_put(
                jnp.zeros((problem.N,) * 3, dtype), sharding
            )
            args = (u_prev, u, syz, rsyz)

            def runner(with_halo):
                return _kfused_probe_runner(
                    problem, (n_x, n_y), mesh, dtype, k, interpret,
                    with_halo, iters,
                )

        t_full = _time_best(runner(True), args, repeats)
        t_comp = _time_best(runner(False), args, repeats)
        scale = problem.timesteps / (iters * k)
        return PhaseBreakdown(
            loop_seconds=t_comp * scale,
            exchange_seconds=max(0.0, (t_full - t_comp)) * scale,
            steps_measured=iters * k,
        )
    topo = Topology(N=problem.N, mesh_shape=mesh_shape)
    mesh = build_mesh(mesh_shape, devices[: topo.n_devices])

    f = stencil_ref.compute_dtype(dtype)
    shape = topo.padded
    sharding = jax.sharding.NamedSharding(mesh, P(*AXIS_NAMES))
    u_prev = jax.device_put(jnp.zeros(shape, dtype), sharding)
    u = jax.device_put(jnp.zeros(shape, dtype), sharding)
    bcs, _ = _sharded._masks(problem, topo, f)

    t_full = _time_best(
        _probe_runner(
            problem, topo, mesh, dtype, kernel, overlap, interpret,
            True, iters,
        ),
        (u_prev, u, *bcs), repeats,
    )
    t_comp = _time_best(
        _probe_runner(
            problem, topo, mesh, dtype, kernel, overlap, interpret,
            False, iters,
        ),
        (u_prev, u, *bcs), repeats,
    )
    scale = problem.timesteps / iters
    return PhaseBreakdown(
        loop_seconds=t_comp * scale,
        exchange_seconds=max(0.0, (t_full - t_comp)) * scale,
        steps_measured=iters,
    )
