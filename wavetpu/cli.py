"""Command-line entry point, argv-compatible with every reference variant.

Positional contract (must never break): `N Np Lx Ly Lz [T] [timesteps]`,
where Lx/Ly/Lz accept the literal string "pi" and T/timesteps default to
1 and 20 (openmp_sol.cpp:192-204, mpi_new.cpp:376-404, README.txt:7-8).
Np is parsed for compatibility; like the reference MPI/CUDA variants it does
not influence the computation (mpi_sol.cpp:381).

Beyond the positional contract, optional flags select the TPU backend
pieces (the reference picks variants by compiling different binaries; we
pick at runtime):

  --backend {auto,single,sharded}   auto = sharded iff >1 device
  --mesh MX,MY,MZ                   explicit 3D mesh shape (sharded)
  --dtype {f32,f64,bf16}            state dtype (f64 only meaningful on CPU)
  --no-errors                       skip the fused analytic-error oracle
  --out-dir DIR                     where the report file goes
  --platform NAME                   jax platform (e.g. cpu); also honors the
                                    JAX_PLATFORMS env var, which this image's
                                    sitecustomize would otherwise override
  --profile DIR                     capture a jax.profiler device trace of
                                    the solve into DIR (TensorBoard/xprof
                                    format) - the deep-dive complement to
                                    --phase-timing's summary numbers
  --phase-timing                    measure the loop vs ICI-exchange split
                                    (probe programs; see solver/timing.py) and
                                    add it to the report, like the reference's
                                    "new" variants (mpi_new.cpp:368-371);
                                    covers the standard step and both k-fused
                                    onions (incl. --scheme compensated with
                                    --fuse-steps K)
  --scheme {standard,compensated}   time-integration scheme: compensated =
                                    Kahan incremental leapfrog, pushing f32
                                    to the discretization limit (5.7e-6 vs
                                    1.1e-3 L-inf at N=512/1000 on v5e);
                                    composes with --fuse-steps K into the
                                    FLAGSHIP velocity-form onion (~42
                                    Gcell/s at 5.7e-6 single-device, and
                                    sharded over --mesh MX,1,1 at K=2 for
                                    N=512 - VMEM bounds K;
                                    solver/kfused_comp.py); f32/f64, 1-step
                                    form also on any sharded mesh
                                    (checkpointable; no --overlap /
                                    --phase-timing)
  --v-dtype {f32,bf16}              increment-stream dtype for the
                                    compensated k-fused mode: bf16 = the
                                    increment-form bf16 config (bf16 v +
                                    f32 carrier u, carry-less; ~46 Gcell/s
                                    at L-inf ~6e-4 - the bf16 mode whose
                                    numbers mean something, vs the 0.66
                                    garbage of a bf16 carrier state)
  --c2-field PRESET|FILE.npy        spatially varying wave speed c^2(x,y,z):
                                    a preset (constant, gaussian-lens,
                                    two-layer) or an .npy file of c^2 values
                                    on the fundamental (N,N,N) grid
                                    (tau^2 applied internally).  Disables
                                    the analytic-error oracle (no closed
                                    form).  Composes with --fuse-steps K
                                    (the c^2tau^2 slab rides the k-step
                                    onion as its own slab + k-plane halos)
                                    and with --scheme compensated when
                                    K >= 2 (the velocity-form onion takes
                                    the field coefficient in the increment,
                                    incl. --v-dtype bf16); single or
                                    sharded backend, even or pad-and-mask
                                    decompositions
  --kernel {auto,roll,pallas}       hot-kernel selection: pallas = the fused
                                    slab kernel (kernels/stencil_pallas.py,
                                    the analog of the reference shipping its
                                    CUDA kernel in every binary,
                                    Makefile:4-8); roll = the XLA reference
                                    stencil; auto = pallas on TPU, roll
                                    elsewhere (off-TPU pallas runs in
                                    interpret mode - correct but slow)
  --fuse-steps K                    temporal blocking: K leapfrog layers per
                                    HBM pass (solver/kfused.py; ~44 vs ~20
                                    Gcell/s at K=4, N=512/1000 on v5e, with
                                    per-layer errors still reported).
                                    Requires the pallas kernel; single device
                                    or an (MX,MY,1) mesh (--mesh ->
                                    solver/sharded_kfused.py, K-deep ghost
                                    exchange per K layers, corners via
                                    sequenced y-then-x ppermute); layers are
                                    bitwise identical to K=1, including the
                                    uneven pad-and-mask path when K does not
                                    divide N/MX (x-only meshes)
  --overlap                         overlap halo exchange with the bulk
                                    stencil update (sharded backend, even
                                    shard splits only)
  --debug-nans                      enable jax debug_nans: the solve traps
                                    on the first NaN instead of reporting
                                    a garbage error norm (SURVEY section 5
                                    sanitizer row - e.g. a Courant-unstable
                                    config, or a VMEM overflow that
                                    silently NaNs inside lax.scan)
  --distributed                     multi-process launch: call
                                    jax.distributed.initialize() (explicit
                                    JAX_COORDINATOR_ADDRESS /
                                    JAX_NUM_PROCESSES / JAX_PROCESS_ID env
                                    vars, or the TPU-pod auto-detection)
                                    and gate stdout + the report file on
                                    process 0 - the rank-0 gating of every
                                    reference variant (mpi_new.cpp:356-371)
  --stop-step S                     halt after layer S (tau unchanged); pairs
                                    with --save-state for preemptible runs
  --save-state PATH                 write the final (u_prev, u_cur, step)
                                    checkpoint: one .npz (single backend) or
                                    a per-shard directory (sharded backend)
                                    (io/checkpoint.py)
  --resume PATH                     continue a checkpointed run to its
                                    timesteps (positionals then unnecessary);
                                    a directory resumes on the sharded
                                    backend, a .npz on the single-device one.
                                    A checkpoint ROTATION root (what
                                    --ckpt-dir maintains) resolves through
                                    its `latest` pointer automatically, so
                                    `--resume DIR --ckpt-every S` composes
                                    across repeated preemptions
  --ckpt-every S                    SUPERVISED solve (run/supervisor.py):
                                    march in ~S-layer chunks (snapped to the
                                    --fuse-steps block so supervised layers
                                    stay bitwise-identical), checkpointing
                                    each boundary into a fresh rotation
                                    entry under --ckpt-dir with an atomic
                                    `latest` pointer and keep-last-2 GC;
                                    SIGTERM/SIGINT finish the chunk, save,
                                    and exit resumable (code 3); each chunk
                                    is health-checked (run/health.py) and a
                                    NaN/amplitude blowup halts with the
                                    last-good checkpoint (code 4)
  --ckpt-dir DIR                    the rotation root for --ckpt-every
                                    (defaults to the --resume rotation root
                                    when resuming one)
  --retries N                       bounded auto-retry: reload the last-good
                                    checkpoint after a watchdog trip and
                                    re-run the chunk up to N times (the
                                    transient-fault model) before halting
  --max-amp X                       watchdog amplitude bound (default 1e3;
                                    the analytic solution is |u| <= 1, so
                                    the default only trips real blowups)
  --no-watchdog                     disable the per-chunk health check
  --telemetry-dir DIR               unified telemetry (wavetpu/obs/,
                                    docs/observability.md): structured
                                    JSONL spans into DIR/trace.jsonl
                                    (supervisor chunks, health checks,
                                    checkpoint writes - aligned with
                                    --profile device traces via
                                    jax.profiler.TraceAnnotation) plus
                                    periodic registry snapshots
                                    (DIR/heartbeat.jsonl to tail,
                                    DIR/metrics.prom to scrape) plus the
                                    append-only compile-cost ledger
                                    (DIR/compile_ledger.jsonl);
                                    summarize with `wavetpu trace-report
                                    DIR/trace.jsonl` and
                                    `wavetpu ledger-report DIR`

Exit codes (docs/robustness.md): 0 complete; 2 usage or checkpoint-load
error; 3 preempted but checkpointed (requeue + --resume); 4 numerical-
health halt with the last-good checkpoint preserved (page an operator).
Non-zero supervised exits print `resumable checkpoint: PATH`.

Subcommands: `wavetpu serve [...]` starts the batched-inference HTTP
front end (wavetpu/serve/api.py, also installed as `wavetpu-serve`;
endpoint contract in docs/serving.md; request-path resilience -
deadlines, Retry-After, circuit breaker, worker supervision, chaos
injection via WAVETPU_FAULT serve-* specs - in docs/robustness.md,
with `wavetpu.client.WavetpuClient` as the retrying client half).
`wavetpu trace-report
[TRACE.jsonl ...] [--dir DIR ...] [--kind K] [--request ID]` summarizes
--telemetry-dir span traces (per-kind count/total/p50/p95; critical-path
view of one request - wavetpu/obs/report.py; rotated segment sets are
read whole); with several sources (router + replicas) it joins W3C
traceparent-linked spans into ONE cross-process tree, including solves
preempted on one replica and resumed on another (docs/observability.md
"Distributed tracing").
`wavetpu ledger-report TELEMETRY_DIR [--json]
[--emit-warmup-manifest OUT.json]` aggregates the compile-cost ledger
(wavetpu/obs/ledger.py): per-ProgramKey compile spend, keys recompiled
across restarts, a what-if simulation of the persistent AOT cache
(ROADMAP direction 2), and the warmup-manifest export that direction's
`wavetpu warmup --manifest` will consume.
`wavetpu plan-report TELEMETRY_DIR [--json]
[--emit-plan-table OUT.json]` joins the accuracy ledger
(wavetpu/obs/accuracy.py - oracle errors + shadow-solve divergence)
with the compile ledger and the obs/perf.py roofline model into the
measured speed-accuracy frontier per (plan, N-bucket): Gcell/s, wall
s/request, error percentiles, Pareto-dominated plans flagged; the
emitted plan_table.json is the input ROADMAP direction 4's planner
consumes.  `wavetpu profile --out DIR
ARGS...` runs a full wavetpu command line under `jax.profiler` so the
telemetry spans land inside the device trace, then prints a
post-capture summary.
`wavetpu loadgen generate|replay|gate` is the traffic-realism harness
(wavetpu/loadgen/, docs/observability.md): generate or record mixed-
scenario JSONL traces, replay them open-/closed-loop against a live
`wavetpu serve`, emit loadgen_report.json with per-tier p50/p95/p99 +
occupancy + Server-Timing attribution, and diff two reports as a
perf-regression gate (exit 1 on SLO violation); `replay --retries N`
drives the retrying client (chaos drills), `--duration S` soaks a
looped trace against a wall-clock budget.  `wavetpu --version`
prints the package version (both entry points accept it).
"""

from __future__ import annotations

import sys
from typing import List, Optional, Sequence, Tuple

from wavetpu.core.problem import Problem


_KNOWN_FLAGS = (
    "backend", "mesh", "dtype", "no-errors", "out-dir", "platform",
    "phase-timing", "stop-step", "save-state", "resume",
    "kernel", "overlap", "scheme", "distributed", "profile",
    "fuse-steps", "debug-nans", "v-dtype", "c2-field",
    "ckpt-every", "ckpt-dir", "retries", "max-amp", "no-watchdog",
    "telemetry-dir", "program-cache-dir",
)
_VALUELESS = (
    "no-errors", "phase-timing", "overlap", "distributed", "debug-nans",
    "no-watchdog",
)


# resolve_kernel moved to `wavetpu.progkey` (the fleet router resolves
# kernel=auto from polled replica backends without jax); re-exported
# here for the existing callers.
from wavetpu.progkey import resolve_kernel  # noqa: E402,F401


def _split_flags(argv: Sequence[str]) -> Tuple[List[str], dict]:
    """Separate reference-style positionals from --flag[=value] options
    (the shared core.flags parser bound to this CLI's flag table)."""
    from wavetpu.core.flags import split_flags

    return split_flags(argv, _KNOWN_FLAGS, _VALUELESS)


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "serve":
        # The serving front end is its own flag namespace; dispatch before
        # the solver CLI's parser can reject it.
        from wavetpu.serve import api as serve_api

        return serve_api.main(argv[1:])
    if argv and argv[0] == "trace-report":
        # Telemetry trace summarizer (stdlib-only; never touches jax).
        from wavetpu.obs import report as obs_report

        return obs_report.main(argv[1:])
    if argv and argv[0] == "loadgen":
        # Trace-replay load generator + SLO regression gate (stdlib
        # HTTP client; never touches jax - runnable off-accelerator).
        from wavetpu.loadgen import cli as loadgen_cli

        return loadgen_cli.main(argv[1:])
    if argv and argv[0] == "ledger-report":
        # Compile-cost ledger aggregator + persistent-cache what-if +
        # warmup-manifest export (stdlib-only; never touches jax).
        from wavetpu.obs import ledger as compile_ledger

        return compile_ledger.main(argv[1:])
    if argv and argv[0] == "plan-report":
        # Measured speed-accuracy plan table: joins the accuracy ledger
        # with the compile ledger and the roofline model (stdlib-only
        # unless the roofline join needs perf constants; never jax).
        from wavetpu.obs import accuracy as obs_accuracy

        return obs_accuracy.main(argv[1:])
    if argv and argv[0] == "profile":
        # jax.profiler bracket around one solve or a serve window, so
        # the telemetry span annotations land in a device trace.
        from wavetpu.obs import perf as obs_perf

        return obs_perf.profile_main(argv[1:])
    if argv and argv[0] == "router":
        # Fleet front tier: ProgramKey-affinity proxy over N serve
        # replicas (stdlib-only; never touches jax - routers run on
        # hosts with no accelerator stack).
        from wavetpu.fleet import router as fleet_router

        return fleet_router.main(argv[1:])
    if argv and argv[0] == "fleet":
        # Fleet operations; currently `fleet roll`, the warm-handoff
        # zero-cold-compile rolling-deploy driver (stdlib-only).
        if len(argv) > 1 and argv[1] == "roll":
            from wavetpu.fleet import roll as fleet_roll

            return fleet_roll.main(argv[2:])
        print("error: fleet wants a subcommand: roll", file=sys.stderr)
        print("usage: wavetpu fleet roll ...", file=sys.stderr)
        return 2
    if argv and argv[0] == "warmup":
        # Manifest-driven replica warmup: pre-populate a persistent
        # program cache from a ledger-report warmup manifest.
        from wavetpu.serve import progcache

        return progcache.main(argv[1:])
    if "--version" in argv:
        from wavetpu import __version__

        print(f"wavetpu {__version__}")
        return 0
    try:
        pos, flags = _split_flags(argv)
        if flags.get("dtype", "f32") not in ("f32", "f64", "bf16"):
            raise ValueError(f"--dtype must be f32|f64|bf16, got {flags['dtype']}")
        if flags.get("kernel", "auto") not in ("auto", "roll", "pallas"):
            raise ValueError(
                f"--kernel must be auto|roll|pallas, got {flags['kernel']}"
            )
        scheme = flags.get("scheme", "standard")
        if scheme not in ("standard", "compensated"):
            raise ValueError(
                f"--scheme must be standard|compensated, got {scheme}"
            )
        fuse_steps = int(flags.get("fuse-steps", "1"))
        if fuse_steps < 1:
            raise ValueError(f"--fuse-steps must be >= 1, got {fuse_steps}")
        v_dtype_flag = flags.get("v-dtype")
        if v_dtype_flag is not None and v_dtype_flag not in ("f32", "bf16"):
            raise ValueError(
                f"--v-dtype must be f32|bf16, got {v_dtype_flag}"
            )
        if v_dtype_flag == "bf16" and (
            scheme != "compensated" or fuse_steps < 2
        ):
            raise ValueError(
                "--v-dtype bf16 is the increment-form bf16 mode: it "
                "requires --scheme compensated --fuse-steps K (the bf16 "
                "increment stream rides the velocity-form onion)"
            )
        if fuse_steps > 1:
            if flags.get("kernel", "auto") == "roll":
                raise ValueError("--fuse-steps needs the pallas kernel")
            if "mesh" in flags:
                # k-fusion composes with (MX, MY, 1) decompositions; z is
                # the lane dimension and stays whole
                # (solver/sharded_kfused.py).
                try:
                    _m = tuple(int(x) for x in flags["mesh"].split(","))
                except ValueError:
                    _m = ()
                if len(_m) == 3 and (
                    _m[2] != 1 or _m[0] < 1 or _m[1] < 1
                ):
                    raise ValueError(
                        "--fuse-steps supports (MX,MY,1) meshes "
                        f"(MX, MY >= 1, MZ = 1); got {flags['mesh']}"
                    )
            if "overlap" in flags:
                raise ValueError(
                    "--overlap applies to the 1-step sharded backend, not "
                    "--fuse-steps (whose exchange is amortized over k "
                    "layers)"
                )
        if "c2-field" in flags:
            if scheme == "compensated" and fuse_steps < 2:
                raise ValueError(
                    "--c2-field with the compensated scheme rides the "
                    "velocity-form onion: add --fuse-steps K (the 1-step "
                    "compensated kernels carry a scalar coefficient)"
                )
            if "phase-timing" in flags:
                raise ValueError(
                    "--phase-timing's probe times the constant-c step; "
                    "drop it for --c2-field runs"
                )
        if flags.get("backend") == "single" and "mesh" in flags:
            raise ValueError("--mesh contradicts --backend single")
        if flags.get("backend") == "single" and "overlap" in flags:
            raise ValueError("--overlap applies to the sharded backend")
        supervised = "ckpt-every" in flags
        if supervised:
            ckpt_every = int(flags["ckpt-every"])
            if ckpt_every < 1:
                raise ValueError(
                    f"--ckpt-every must be >= 1, got {ckpt_every}"
                )
            if "stop-step" in flags:
                raise ValueError(
                    "--ckpt-every supervises the run to completion; it "
                    "is exclusive with --stop-step (preempt a supervised "
                    "run with SIGTERM instead)"
                )
        else:
            for dep in ("ckpt-dir", "retries", "max-amp", "no-watchdog"):
                if dep in flags:
                    raise ValueError(
                        f"--{dep} requires --ckpt-every S (the "
                        f"supervised-solve mode)"
                    )
        sup_retries = int(flags.get("retries", "0"))
        if sup_retries < 0:
            raise ValueError(f"--retries must be >= 0, got {sup_retries}")
        sup_max_amp = (
            float(flags["max-amp"]) if "max-amp" in flags else None
        )
        if sup_max_amp is not None and not sup_max_amp > 0:
            raise ValueError(
                f"--max-amp must be > 0, got {sup_max_amp}"
            )
        if "resume" in flags:
            if "stop-step" in flags:
                raise ValueError("--resume and --stop-step are exclusive")
            problem = None  # comes from the checkpoint
        else:
            problem = Problem.from_argv(pos)
        stop_step = int(flags["stop-step"]) if "stop-step" in flags else None
        if stop_step is not None and not (
            1 <= stop_step <= problem.timesteps
        ):
            raise ValueError(
                f"--stop-step must be in [1, {problem.timesteps}], "
                f"got {stop_step}"
            )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        print(
            "usage: wavetpu N Np Lx Ly Lz [T] [timesteps] | "
            "wavetpu serve [...] | "
            "wavetpu trace-report [TRACE.jsonl ...] [--dir DIR ...] | "
            "wavetpu loadgen generate|replay|gate [...] | "
            "wavetpu ledger-report DIR [...] | "
            "wavetpu plan-report DIR [...] | "
            "wavetpu profile --out DIR ARGS... | "
            "wavetpu warmup --manifest MANIFEST.json [...] | "
            "wavetpu --version\n"
            "       wavetpu N Np Lx Ly Lz [T] [timesteps] "
            "[--backend auto|single|sharded] [--mesh MX,MY,MZ] "
            "[--dtype f32|f64|bf16] [--kernel auto|roll|pallas] "
            "[--fuse-steps K] [--scheme standard|compensated] "
            "[--v-dtype f32|bf16] [--c2-field PRESET|FILE.npy] "
            "[--overlap] [--no-errors] [--phase-timing] [--profile DIR] "
            "[--debug-nans] [--distributed] [--stop-step S] "
            "[--save-state PATH] [--resume PATH] "
            "[--ckpt-every S] [--ckpt-dir DIR] [--retries N] "
            "[--max-amp X] [--no-watchdog] [--telemetry-dir DIR] "
            "[--program-cache-dir DIR] "
            "[--out-dir DIR] [--platform NAME]",
            file=sys.stderr,
        )
        return 2

    resume_state = None
    resume_is_sharded = False
    rotation_root = None
    if "resume" in flags:
        import os as _os

        from wavetpu.io import checkpoint as _ckpt
        from wavetpu.run import supervisor as _sup

        if _sup.looks_like_rotation_root(flags["resume"]):
            # A --ckpt-dir rotation root: follow its `latest` pointer to
            # the newest checkpoint (and remember the root so a
            # supervised resume keeps rotating in place).
            rotation_root = flags["resume"]
            resolved = _sup.resolve_latest(rotation_root)
            if resolved is None:
                print(
                    f"error: {rotation_root} holds no resumable "
                    f"checkpoint",
                    file=sys.stderr,
                )
                return 2
            flags["resume"] = resolved
        resume_is_sharded = _os.path.isdir(flags["resume"])
        try:
            if resume_is_sharded:
                if flags.get("backend") == "single":
                    print(
                        "error: checkpoint is a per-shard directory; "
                        "--backend single cannot resume it",
                        file=sys.stderr,
                    )
                    return 2
                # Meta only (numpy): the shard arrays are loaded after the
                # jax platform is configured below.
                problem, _start, _ck_mesh, _ck_dtype, _ck_scheme = (
                    _ckpt.load_sharded_meta(flags["resume"])
                )
                if "mesh" in flags and tuple(
                    int(x) for x in flags["mesh"].split(",")
                ) != _ck_mesh:
                    print(
                        f"error: --mesh contradicts the checkpoint's mesh "
                        f"{_ck_mesh}",
                        file=sys.stderr,
                    )
                    return 2
                if fuse_steps > 1 and _ck_mesh[2] != 1:
                    print(
                        f"error: --fuse-steps supports (MX,MY,1) meshes; "
                        f"the checkpoint was saved on {_ck_mesh}",
                        file=sys.stderr,
                    )
                    return 2
            else:
                if flags.get("backend") == "sharded" or "mesh" in flags:
                    print(
                        "error: checkpoint is a single-device .npz; "
                        "--backend sharded/--mesh cannot resume it",
                        file=sys.stderr,
                    )
                    return 2
                problem, _u_prev0, _u_cur0, _start = _ckpt.load_checkpoint(
                    flags["resume"]
                )
                _ck_scheme = _ckpt.checkpoint_scheme(flags["resume"])
                _ck_aux = (
                    _ckpt.load_checkpoint_aux(flags["resume"])
                    if _ck_scheme == "compensated"
                    else None
                )
                resume_state = (_u_prev0, _u_cur0, _start)
        except Exception as e:
            # OSError, KeyError, ValueError, zipfile.BadZipFile (truncated
            # .npz from a mid-save preemption - the exact case --resume
            # exists for), ... all mean the same thing to the user.
            print(f"error: cannot load checkpoint: {e}", file=sys.stderr)
            return 2

    distributed = "distributed" in flags
    # Courant printout before solving (openmp_sol.cpp:214, mpi_new.cpp:404).
    # Under --distributed it waits until the process index is known so only
    # process 0 speaks (rank-0 gating, mpi_new.cpp:356-371).
    if not distributed:
        print(f"C = {problem.courant:.6g}")

    import os

    import jax
    import jax.numpy as jnp

    # Honor --platform / the caller's JAX_PLATFORMS. This image pre-imports
    # jax via a sitecustomize hook that sets jax_platforms itself; backend
    # init is lazy, so re-applying the user's choice here (before any device
    # is touched) restores the documented `JAX_PLATFORMS=cpu wavetpu ...`
    # behavior (same trick as tests/conftest.py).
    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform and platform != jax.config.jax_platforms:
        jax.config.update("jax_platforms", platform)
    if "debug-nans" in flags:
        jax.config.update("jax_debug_nans", True)

    if distributed:
        dist_kwargs = {}
        addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
        if addr:
            # Explicit env-var cluster (the CPU smoke-test path and any
            # launcher that exports these); without them initialize()
            # auto-detects TPU pod / GKE / SLURM environments.
            dist_kwargs = dict(
                coordinator_address=addr,
                num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
                process_id=int(os.environ["JAX_PROCESS_ID"]),
            )
        jax.distributed.initialize(**dist_kwargs)
    is_main = jax.process_index() == 0
    say = print if is_main else (lambda *a, **k: None)
    if distributed:
        say(f"C = {problem.courant:.6g}")

    dtype = {
        "f32": jnp.float32,
        "f64": jnp.float64,
        "bf16": jnp.bfloat16,
    }[flags.get("dtype", "f32")]
    resume_dtype_name = None
    if resume_state is not None:
        resume_dtype_name = resume_state[1].dtype.name
    elif resume_is_sharded:
        resume_dtype_name = _ck_dtype
    if dtype == jnp.float64 or (
        "dtype" not in flags and resume_dtype_name == "float64"
    ):
        # Without x64, device_put would silently canonicalize a checkpointed
        # f64 state to f32 and break the bitwise-equal-resume guarantee.
        jax.config.update("jax_enable_x64", True)
    compute_errors = "no-errors" not in flags
    out_dir = flags.get("out-dir", ".")

    n_devices = len(jax.devices())
    backend = flags.get("backend", "auto")
    mesh_shape = None
    if "mesh" in flags:
        mesh_shape = tuple(int(x) for x in flags["mesh"].split(","))
        if len(mesh_shape) != 3:
            print("error: --mesh wants MX,MY,MZ", file=sys.stderr)
            return 2
        backend = "sharded"
    elif resume_is_sharded:
        backend = "sharded"
    elif resume_state is not None:
        backend = "single"
    elif backend == "auto":
        backend = "sharded" if n_devices > 1 else "single"
    if fuse_steps > 1:
        # k-fusion goes sharded only on EXPLICIT request (--mesh MX,1,1,
        # --backend sharded, or a sharded checkpoint); plain auto stays
        # single-device, preserving the K=1 CLI's behavior.
        explicit_sharded = (
            "mesh" in flags or resume_is_sharded
            or flags.get("backend") == "sharded"
        )
        backend = "sharded" if explicit_sharded else "single"
        _grid = (
            (mesh_shape or (_ck_mesh if resume_is_sharded else None)
             or (n_devices, 1, 1)) if backend == "sharded" else (1, 1, 1)
        )
        _even_x = (
            problem.N % _grid[0] == 0
            and (problem.N // _grid[0]) % fuse_steps == 0
        )
        if (
            problem.N % _grid[1]
            or problem.N // _grid[1] < fuse_steps
            or (_grid[1] > 1 and not _even_x)
        ):
            print(
                f"error: --fuse-steps {fuse_steps} must fit the y depth "
                f"N/MY = {problem.N}/{_grid[1]}; on 2D meshes it must "
                f"also divide the x depth N/MX = {problem.N}/{_grid[0]} "
                f"(uneven N is supported on (MX,1,1) meshes)",
                file=sys.stderr,
            )
            return 2
        if not _even_x:
            if scheme == "compensated":
                print(
                    f"error: compensated k-fusion requires MX | N and "
                    f"--fuse-steps {fuse_steps} | N/MX "
                    f"(N={problem.N}, MX={_grid[0]})",
                    file=sys.stderr,
                )
                return 2
            if "phase-timing" in flags:
                print(
                    "error: --phase-timing's k-fused probe covers even "
                    "decompositions (k | N/MX); drop it for uneven N",
                    file=sys.stderr,
                )
                return 2
            # Uneven x decomposition: verify a pad-and-mask layout
            # exists BEFORE compiling anything (solver/sharded_kfused.py
            # handles the actual march; a (1,1,1) grid covers the
            # single-device k-does-not-divide-N case).
            from wavetpu.solver import sharded_kfused as _sk

            try:
                _sk.uneven_layout(problem, fuse_steps, _grid[0])
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2

    c2_field = None
    if "c2-field" in flags:
        import numpy as np

        from wavetpu.kernels import stencil_ref

        spec = flags["c2-field"]
        # Preset table shared with the serving API
        # (stencil_ref.make_preset_c2tau2_field): one source of truth,
        # so a preset name means the same physics on both surfaces.
        if spec in stencil_ref.C2_PRESET_NAMES:
            c2_field = stencil_ref.make_preset_c2tau2_field(problem, spec)
        else:
            try:
                arr = np.load(spec)
            except Exception as e:
                print(
                    f"error: --c2-field {spec!r} is neither a preset "
                    f"({', '.join(sorted(stencil_ref.C2_PRESET_NAMES))}) "
                    f"nor a loadable .npy file: {e}",
                    file=sys.stderr,
                )
                return 2
            if arr.shape != (problem.N,) * 3:
                print(
                    f"error: --c2-field array shape {arr.shape} != "
                    f"{(problem.N,) * 3} (c^2 values on the fundamental "
                    f"grid)",
                    file=sys.stderr,
                )
                return 2
            c2_field = np.asarray(arr, np.float64) * problem.tau**2
        if compute_errors:
            # The analytic oracle only holds for constant speed; a report
            # of "errors" against it would be meaningless.  The constant
            # preset keeps the same contract for uniformity (its library
            # collapse to a2tau2 is pinned by tests/test_variable_c.py).
            say("errors: disabled (--c2-field has no analytic oracle)")
            compute_errors = False

    kernel = resolve_kernel(
        flags.get("kernel", "auto"), jax.default_backend()
    )
    if fuse_steps > 1:
        kernel = "pallas"  # k-fusion IS a pallas kernel (interpret off-TPU)
    if "resume" in flags:
        # A checkpoint is resumed under the scheme it was saved with; a
        # contradicting explicit --scheme is a user error.
        if "scheme" in flags and scheme != _ck_scheme:
            print(
                f"error: checkpoint was saved with scheme {_ck_scheme}; "
                f"--scheme {scheme} cannot resume it",
                file=sys.stderr,
            )
            return 2
        scheme = _ck_scheme
    # Scheme-conditional flag checks run HERE - after a resumed run has
    # inherited its scheme from the checkpoint - so they also cover
    # `--resume comp_ck --phase-timing` etc., not just explicit --scheme.
    if scheme == "compensated":
        bad = None
        if flags.get("dtype") == "bf16":
            bad = ("--dtype bf16 (compensated requires an f32/f64 carrier; "
                   "for a bf16 increment stream use --v-dtype bf16)")
        elif "overlap" in flags:
            bad = "--overlap"
        elif "phase-timing" in flags and fuse_steps < 2:
            # The velocity-form probe covers the k-fused program only
            # (solver/timing.py); a 1-step compensated solve still has no
            # probe, and reporting the standard step's numbers against it
            # would describe a program that never ran.
            bad = ("--phase-timing (the compensated probe covers "
                   "--fuse-steps K programs; the 1-step scheme has none)")
        elif "c2-field" in flags and fuse_steps < 2:
            # Covers `--resume comp_ck --c2-field ...` without
            # --fuse-steps: the scheme arrives from the checkpoint after
            # the flag-level check, which only sees scheme == "standard".
            bad = ("--c2-field without --fuse-steps K (the 1-step "
                   "compensated kernels carry a scalar coefficient)")
        elif fuse_steps > 1 and (
            problem.N % _grid[0]
            or (problem.N // _grid[0]) % fuse_steps
        ):
            # Covers `--resume comp_ck --fuse-steps K` with K (or MX)
            # not dividing: the scheme arrives from the checkpoint AFTER
            # the flag-level divisibility check, which only sees
            # scheme == "standard" there.
            bad = (f"--fuse-steps {fuse_steps} (compensated k-fusion "
                   f"requires MX | N and K | N/MX; N={problem.N}, "
                   f"MX={_grid[0]})")
        if bad:
            print(
                f"error: {bad} is not available for the compensated "
                f"scheme",
                file=sys.stderr,
            )
            return 2
    say(f"kernel: {kernel}")
    say(f"scheme: {scheme}")
    if fuse_steps > 1:
        say(f"fuse-steps: {fuse_steps}")
    overlap = "overlap" in flags

    profile_dir = flags.get("profile")
    if profile_dir and is_main:
        # jax.profiler hook (SURVEY section 5 tracing row): full XLA device
        # traces; the phase probes give the summary split, this gives the
        # op-level picture.
        jax.profiler.start_trace(profile_dir)

    from wavetpu.obs import tracing as _tracing

    telemetry = None
    if "telemetry-dir" in flags and is_main:
        # Unified telemetry: spans to DIR/trace.jsonl + heartbeat
        # registry snapshots (docs/observability.md).  Spans open
        # jax.profiler.TraceAnnotations, so with --profile the
        # application structure lands inside the device trace too.
        from wavetpu.obs import telemetry as _telemetry

        telemetry = _telemetry.start(flags["telemetry-dir"])
        say(f"telemetry: {flags['telemetry-dir']}")
    xla_cache_hits = None
    if "program-cache-dir" in flags and is_main:
        # Solo solvers jit internally (no executable object to adopt),
        # so persistence here is JAX's own compilation cache scoped to
        # DIR/xla - same directory layout the serve engine's fallback
        # tier uses, so one --program-cache-dir serves both surfaces.
        # The hit counter marks the ledger entry `source: disk` when
        # the cache actually served this solve's compile.
        from wavetpu.serve import progcache as _progcache

        if _progcache.enable_xla_cache(
            __import__("os").path.join(
                flags["program-cache-dir"], "xla"
            )
        ):
            xla_cache_hits = _progcache.shared_xla_hit_counter()
            say(f"program cache: {flags['program-cache-dir']} "
                f"[XLA persistent compilation cache]")
        else:
            say("program cache: unavailable on this jax")
    solve_span = _tracing.begin_span(
        "cli.solve", backend=backend, scheme=scheme, kernel=kernel,
        fuse_steps=fuse_steps, n=problem.N,
        timesteps=problem.timesteps, supervised=supervised,
        resumed="resume" in flags,
    )

    def _abort_telemetry():
        # Error exits after telemetry started must still emit the open
        # span and the final heartbeat (atexit only covers process
        # death, not in-process callers like the tests).
        _tracing.end_span(solve_span, aborted=True)
        if telemetry is not None:
            telemetry.stop()

    try:
        if backend == "sharded" and resume_is_sharded:
            # Shared load for both sharded resume paths (1-step and k-fused).
            from wavetpu.io import checkpoint as _ckpt

            try:
                (problem, _u_prev0, _u_cur0, _start, _ck_mesh,
                 _ck_scheme, _ck_aux) = (
                    _ckpt.load_sharded_checkpoint(flags["resume"])
                )
            except Exception as e:
                # Missing/truncated shard files, step/meta mismatch from a
                # mid-save preemption, or too few devices for the stored
                # mesh - same clean exit as a corrupt .npz.
                print(f"error: cannot load checkpoint: {e}", file=sys.stderr)
                _abort_telemetry()
                return 2
            resume_dtype = (
                dtype if "dtype" in flags else jnp.dtype(_u_cur0.dtype)
            )

        sup_out = None
        if supervised:
            # Supervised solve (run/supervisor.py): every solver path below
            # has a supervised twin - chunked march through cached chunk
            # programs, rotating checkpoints, watchdog, signal handling.
            from wavetpu.run import supervisor as _sup

            ckpt_dir = flags.get("ckpt-dir") or rotation_root
            if not ckpt_dir:
                print(
                    "error: --ckpt-every needs --ckpt-dir DIR (or --resume "
                    "of an existing rotation root)",
                    file=sys.stderr,
                )
                _abort_telemetry()
                return 2
            spec_vdtype = None
            spec_carry = True
            sup_state = None
            sup_start = None
            sup_mesh = mesh_shape
            sup_dtype = dtype
            if scheme == "compensated" and fuse_steps > 1 and \
                    "resume" not in flags:
                v_bf16 = flags.get("v-dtype") == "bf16"
                spec_vdtype = jnp.bfloat16 if v_bf16 else None
                spec_carry = not v_bf16
            def _comp_resume_state(u_cur0, aux, st_dtype):
                # Shared bf16-increment detection: a bf16 v stream beside a
                # non-bf16 carrier marks the carry-less increment form
                # (k-fused only); the sidecar must record the mode that ran.
                _v, _c = aux
                inc = (
                    fuse_steps > 1
                    and jnp.dtype(_v.dtype) == jnp.bfloat16
                    and jnp.dtype(st_dtype) != jnp.bfloat16
                )
                if inc:
                    flags["v-dtype"] = "bf16"
                return (
                    (u_cur0, _v, None if inc else _c),
                    jnp.bfloat16 if inc else None,
                    not inc,
                )

            if "resume" in flags:
                if resume_is_sharded:
                    sup_dtype = resume_dtype
                    sup_mesh = _ck_mesh
                    sup_start = _start
                    if scheme == "compensated":
                        sup_state, spec_vdtype, spec_carry = (
                            _comp_resume_state(_u_cur0, _ck_aux, sup_dtype)
                        )
                    else:
                        sup_state = (_u_prev0, _u_cur0)
                else:
                    u_prev0, u_cur0, sup_start = resume_state
                    sup_dtype = (
                        dtype if "dtype" in flags
                        else jnp.dtype(u_cur0.dtype)
                    )
                    if scheme == "compensated":
                        sup_state, spec_vdtype, spec_carry = (
                            _comp_resume_state(u_cur0, _ck_aux, sup_dtype)
                        )
                    else:
                        sup_state = (u_prev0, u_cur0)
            if backend == "sharded":
                if sup_mesh is None and fuse_steps > 1:
                    sup_mesh = (n_devices, 1, 1)
                if sup_mesh is None:
                    from wavetpu.core.grid import choose_mesh_shape

                    shape = choose_mesh_shape(n_devices)
                else:
                    shape = sup_mesh
                n_procs = shape[0] * shape[1] * shape[2]
            else:
                sup_mesh = None
                n_procs = 1
            variant = "TPU"
            spec = _sup.PathSpec(
                backend=backend,
                scheme=scheme,
                fuse_steps=fuse_steps,
                kernel=kernel,
                dtype=sup_dtype,
                v_dtype=spec_vdtype,
                carry=spec_carry,
                mesh_shape=sup_mesh,
                c2tau2_field=c2_field,
                compute_errors=compute_errors,
                overlap=overlap,
            )
            opts = _sup.SupervisorOptions(
                ckpt_every=ckpt_every,
                ckpt_dir=ckpt_dir,
                retries=sup_retries,
                watchdog="no-watchdog" not in flags,
                max_amp=sup_max_amp,
            )
            sup_out = _sup.supervise(
                problem, spec, opts, state=sup_state, start_step=sup_start
            )
            result = sup_out.result
            say(
                f"supervisor: {sup_out.status}; "
                f"{sup_out.checkpoints_written} checkpoint(s), "
                f"{sup_out.retries_used} retr"
                f"{'y' if sup_out.retries_used == 1 else 'ies'}, "
                f"overhead {sup_out.overhead_seconds * 1000:.0f}ms"
            )
        elif backend == "sharded" and fuse_steps > 1 and \
                scheme == "compensated":
            # Distributed velocity-form flagship ((MX, 1, 1) meshes).
            from wavetpu.solver import kfused_comp

            if resume_is_sharded:
                _v, _c = _ck_aux
                inc = (
                    jnp.dtype(_v.dtype) == jnp.bfloat16
                    and jnp.dtype(resume_dtype) != jnp.bfloat16
                )
                if inc:
                    flags["v-dtype"] = "bf16"
                result = kfused_comp.resume_kfused_comp_sharded(
                    problem,
                    _u_cur0,
                    _v,
                    None if inc else _c,
                    start_step=_start,
                    mesh_shape=_ck_mesh,
                    dtype=resume_dtype,
                    k=fuse_steps,
                    compute_errors=compute_errors,
                    v_dtype=jnp.bfloat16 if inc else None,
                    c2tau2_field=c2_field,
                )
                shape = _ck_mesh
            else:
                shape = mesh_shape or (n_devices, 1, 1)
                v_bf16 = flags.get("v-dtype") == "bf16"
                result = kfused_comp.solve_kfused_comp_sharded(
                    problem,
                    mesh_shape=shape,
                    dtype=dtype,
                    k=fuse_steps,
                    compute_errors=compute_errors,
                    stop_step=stop_step,
                    v_dtype=jnp.bfloat16 if v_bf16 else None,
                    carry=not v_bf16,
                    c2tau2_field=c2_field,
                )
            n_procs = shape[0] * shape[1] * shape[2]
            variant = "TPU"
        elif backend == "sharded" and fuse_steps > 1:
            from wavetpu.solver import sharded_kfused

            if resume_is_sharded:
                result = sharded_kfused.resume_sharded_kfused(
                    problem,
                    _u_prev0,
                    _u_cur0,
                    start_step=_start,
                    mesh_shape=_ck_mesh,
                    dtype=resume_dtype,
                    k=fuse_steps,
                    compute_errors=compute_errors,
                    c2tau2_field=c2_field,
                )
                shape = _ck_mesh
            else:
                shape = mesh_shape or (n_devices, 1, 1)
                result = sharded_kfused.solve_sharded_kfused(
                    problem,
                    mesh_shape=shape,
                    dtype=dtype,
                    k=fuse_steps,
                    compute_errors=compute_errors,
                    stop_step=stop_step,
                    c2tau2_field=c2_field,
                )
            n_procs = shape[0] * shape[1] * shape[2]
            variant = "TPU"
        elif backend == "sharded":
            from wavetpu.solver import sharded

            if resume_is_sharded:
                _v, _c = _ck_aux if _ck_aux is not None else (None, None)
                result = sharded.resume_sharded(
                    problem,
                    _u_prev0,
                    _u_cur0,
                    start_step=_start,
                    mesh_shape=_ck_mesh,
                    dtype=resume_dtype,
                    kernel=kernel,
                    overlap=overlap,
                    compute_errors=compute_errors,
                    scheme=scheme,
                    comp_v=_v,
                    comp_carry=_c,
                    c2tau2_field=c2_field,
                )
                shape = _ck_mesh
            else:
                result = sharded.solve_sharded(
                    problem,
                    mesh_shape=mesh_shape,
                    dtype=dtype,
                    compute_errors=compute_errors,
                    kernel=kernel,
                    overlap=overlap,
                    stop_step=stop_step,
                    scheme=scheme,
                    c2tau2_field=c2_field,
                )
                from wavetpu.core.grid import choose_mesh_shape

                shape = mesh_shape or choose_mesh_shape(n_devices)
            n_procs = shape[0] * shape[1] * shape[2]
            variant = "TPU"
        else:
            from wavetpu.solver import leapfrog

            step_fn = None
            interpret = jax.default_backend() != "tpu"
            if kernel == "pallas":
                from wavetpu.kernels import stencil_pallas

                step_fn = stencil_pallas.make_step_fn(
                    interpret=interpret, c2tau2_field=c2_field
                )
            elif c2_field is not None:
                from wavetpu.kernels import stencil_ref as _sr

                step_fn = _sr.make_variable_c_step(c2_field)
            if resume_state is not None:
                u_prev0, u_cur0, start = resume_state
                # Unless --dtype was given explicitly, resume in the dtype the
                # checkpoint was saved with - casting would break the
                # bitwise-equal-resume guarantee (io/checkpoint.py).
                resume_dtype = (
                    dtype if "dtype" in flags else jnp.dtype(u_cur0.dtype)
                )
                if scheme == "compensated" and fuse_steps > 1:
                    from wavetpu.solver import kfused_comp

                    _v, _c = _ck_aux
                    # A bf16 increment stream marks the carry-less
                    # increment-form checkpoint; its stored carry (zeros) is
                    # dropped.
                    inc = (
                        jnp.dtype(_v.dtype) == jnp.bfloat16
                        and jnp.dtype(resume_dtype) != jnp.bfloat16
                    )
                    if inc:
                        # The sidecar must record the mode that actually ran,
                        # not the (absent) flag.
                        flags["v-dtype"] = "bf16"
                    result = kfused_comp.resume_kfused_comp(
                        problem,
                        u_cur0,
                        _v,
                        None if inc else _c,
                        start_step=start,
                        dtype=resume_dtype,
                        k=fuse_steps,
                        compute_errors=compute_errors,
                        interpret=interpret,
                        v_dtype=jnp.bfloat16 if inc else None,
                        c2tau2_field=c2_field,
                    )
                elif scheme == "compensated":
                    comp_step_fn = None
                    if kernel == "pallas":
                        from wavetpu.kernels import stencil_pallas as _sp

                        comp_step_fn = _sp.make_compensated_step_fn(
                            interpret=interpret
                        )
                    _v, _c = _ck_aux
                    result = leapfrog.resume_compensated(
                        problem,
                        u_cur0,
                        _v,
                        _c,
                        start_step=start,
                        dtype=resume_dtype,
                        comp_step_fn=comp_step_fn,
                        compute_errors=compute_errors,
                    )
                elif fuse_steps > 1 and problem.N % fuse_steps:
                    # Uneven single-device k-fusion runs the pad-and-mask
                    # path on a (1,1,1) grid (bitwise equal to the 1-step
                    # pallas march on real planes).
                    from wavetpu.solver import sharded_kfused

                    result = sharded_kfused.resume_sharded_kfused(
                        problem,
                        u_prev0,
                        u_cur0,
                        start_step=start,
                        n_shards=1,
                        dtype=resume_dtype,
                        k=fuse_steps,
                        compute_errors=compute_errors,
                        interpret=interpret,
                        c2tau2_field=c2_field,
                    )
                elif fuse_steps > 1:
                    from wavetpu.solver import kfused

                    result = kfused.resume_kfused(
                        problem,
                        u_prev0,
                        u_cur0,
                        start_step=start,
                        dtype=resume_dtype,
                        k=fuse_steps,
                        compute_errors=compute_errors,
                        interpret=interpret,
                        c2tau2_field=c2_field,
                    )
                else:
                    result = leapfrog.resume(
                        problem,
                        u_prev0,
                        u_cur0,
                        start_step=start,
                        dtype=resume_dtype,
                        step_fn=step_fn,
                        compute_errors=compute_errors,
                    )
            elif scheme == "compensated" and fuse_steps > 1:
                from wavetpu.solver import kfused_comp

                v_bf16 = flags.get("v-dtype") == "bf16"
                result = kfused_comp.solve_kfused_comp(
                    problem,
                    dtype=dtype,
                    k=fuse_steps,
                    compute_errors=compute_errors,
                    stop_step=stop_step,
                    interpret=interpret,
                    v_dtype=jnp.bfloat16 if v_bf16 else None,
                    carry=not v_bf16,
                    c2tau2_field=c2_field,
                )
            elif scheme == "compensated":
                comp_step_fn = None
                if kernel == "pallas":
                    comp_step_fn = stencil_pallas.make_compensated_step_fn(
                        interpret=interpret
                    )
                result = leapfrog.solve_compensated(
                    problem,
                    dtype=dtype,
                    comp_step_fn=comp_step_fn,
                    compute_errors=compute_errors,
                    stop_step=stop_step,
                )
            elif fuse_steps > 1 and problem.N % fuse_steps:
                from wavetpu.solver import sharded_kfused

                result = sharded_kfused.solve_sharded_kfused(
                    problem,
                    n_shards=1,
                    dtype=dtype,
                    k=fuse_steps,
                    compute_errors=compute_errors,
                    stop_step=stop_step,
                    interpret=interpret,
                    c2tau2_field=c2_field,
                )
            elif fuse_steps > 1:
                from wavetpu.solver import kfused

                result = kfused.solve_kfused(
                    problem,
                    dtype=dtype,
                    k=fuse_steps,
                    compute_errors=compute_errors,
                    stop_step=stop_step,
                    interpret=interpret,
                    c2tau2_field=c2_field,
                )
            else:
                result = leapfrog.solve(
                    problem,
                    dtype=dtype,
                    step_fn=step_fn,
                    compute_errors=compute_errors,
                    stop_step=stop_step,
                )
            n_procs = 1
            variant = "TPU"

        # Roofline attribution on the cli.solve span: read back the
        # gauges record_solve just stamped at the solver entry point
        # (ONE computation, no second model that could drift), under
        # the same path label the solver used.  Traced runs only -
        # untraced runs skip even the lookup.
        span_extra = {}
        if solve_span is not None:
            try:
                from wavetpu.obs.registry import get_registry as _greg

                if backend == "sharded":
                    _perf_path = (
                        ("kfused_comp_sharded"
                         if scheme == "compensated"
                         else "sharded_kfused")
                        if fuse_steps > 1 else "sharded"
                    )
                else:
                    _perf_path = (
                        ("kfused_comp" if scheme == "compensated"
                         else "kfused")
                        if fuse_steps > 1
                        else ("compensated" if scheme == "compensated"
                              else "leapfrog")
                    )
                _reg = _greg()
                _gbps = _reg.gauge(
                    "wavetpu_solve_model_gbps", "", ("path",)
                ).value(path=_perf_path)
                if _gbps:
                    span_extra = {
                        "model_gbps": _gbps,
                        "roofline_fraction": _reg.gauge(
                            "wavetpu_solve_roofline_fraction", "",
                            ("path",)
                        ).value(path=_perf_path),
                    }
            except Exception:
                pass  # the X-ray must never fail a finished solve
        _tracing.end_span(
            solve_span, final_step=result.final_step,
            gcells_per_s=round(result.gcells_per_second, 3),
            **span_extra,
        )
        # Compile-cost ledger entry for the solo solve (no-op without
        # --telemetry-dir): `init_seconds` is the CLI's compile proxy -
        # grid init + build + XLA compile - the same figure bench.py
        # records as compile_seconds per row.
        from wavetpu.obs import ledger as _ledger

        if _ledger.enabled():
            try:
                _dtype_names = {
                    "float32": "f32", "float64": "f64",
                    "bfloat16": "bf16",
                }
                _ledger.record_compile(_ledger.solo_key(
                    problem, scheme,
                    "kfused" if fuse_steps > 1 else kernel, fuse_steps,
                    _dtype_names.get(
                        jnp.dtype(result.u_cur.dtype).name, "f32"
                    ),
                    c2_field is not None, compute_errors,
                    mesh=shape if backend == "sharded" else None,
                ), result.init_seconds, source=(
                    # The persistent XLA cache serves inside init (no
                    # adoptable executable on the solo path): hits on
                    # the monitoring listener mean disk paid for this
                    # compile, so the ledger attributes it there.
                    "disk" if (xla_cache_hits is not None
                               and xla_cache_hits.hits > 0)
                    else ("fresh" if xla_cache_hits is not None
                          else None)
                ))
            except Exception:
                pass  # ledger bookkeeping must never fail the run

        if "save-state" in flags:
            from wavetpu.io import checkpoint as _ckpt

            if backend == "sharded":
                # Multi-process aware internally: each process writes only its
                # addressable shards, meta is gated on process 0.
                ck_path = _ckpt.save_sharded_checkpoint(
                    flags["save-state"], result
                )
                say(f"checkpoint: {ck_path}")
            elif is_main:
                # Single-device state is fully replicated; one writer suffices
                # (concurrent np.savez to one path is not atomic).
                ck_path = _ckpt.save_checkpoint(flags["save-state"], result)
                say(f"checkpoint: {ck_path}")

        if profile_dir and is_main:
            jax.profiler.stop_trace()
            say(f"profile trace: {profile_dir}")

        exchange_seconds = loop_seconds = None
        probe_steps = None
        if "phase-timing" in flags:
            from wavetpu.solver import timing

            # `shape` is the mesh the solve actually ran on (incl. a resumed
            # checkpoint's mesh); the probe must time the same program.
            pb = timing.measure_phase_breakdown(
                problem,
                mesh_shape=shape if backend == "sharded" else (1, 1, 1),
                dtype=dtype,
                kernel=kernel,
                overlap=overlap,
                fuse_steps=fuse_steps,
                scheme=scheme,
                v_dtype=(
                    jnp.bfloat16 if flags.get("v-dtype") == "bf16" else None
                ),
            )
            exchange_seconds = pb.exchange_seconds
            loop_seconds = pb.loop_seconds
            probe_steps = pb.steps_measured

        if is_main:
            from wavetpu.io import report

            path = report.write_report(
                result,
                out_dir=out_dir,
                n_procs=n_procs,
                variant=variant,
                errors_computed=compute_errors,
                exchange_seconds=exchange_seconds,
                loop_seconds=loop_seconds,
                probe_steps=probe_steps,
                run_config={
                    "backend": backend,
                    "kernel": kernel,
                    "scheme": scheme,
                    "fuse_steps": fuse_steps,
                    "mesh": list(shape) if backend == "sharded" else None,
                    # The state's actual dtype (a resumed run inherits the
                    # checkpoint's, which may differ from the flag default).
                    "dtype": jnp.dtype(result.u_cur.dtype).name,
                    "v_dtype": flags.get("v-dtype"),
                    "c2_field": flags.get("c2-field"),
                    "distributed": distributed,
                    "resumed": "resume" in flags,
                    "supervised": supervised,
                    "ckpt_every": ckpt_every if supervised else None,
                    "supervisor_status": (
                        sup_out.status if sup_out is not None else None
                    ),
                },
            )
        say(f"grids initialized in {int(result.init_seconds * 1000)}ms")
        say(
            f"numerical solution calculated in "
            f"{int(result.solve_seconds * 1000)}ms"
        )
        if exchange_seconds is not None:
            say(f"total ICI exchange time: {int(exchange_seconds * 1000)}ms")
            say(f"total loop time: {int(loop_seconds * 1000)}ms")
        if compute_errors:
            say(f"max abs error: {result.abs_errors.max():.6g}")
        say(f"throughput: {result.gcells_per_second:.3f} Gcell-updates/s")
        if is_main:
            say(f"report: {path}")
        if sup_out is not None and sup_out.status != "complete":
            # Orchestration contract: distinct exit codes (3 = requeue with
            # --resume, 4 = page an operator) and the resumable path in the
            # output (docs/robustness.md).
            if sup_out.status == "preempted":
                say(f"preempted: checkpointed at step {sup_out.final_step}")
            else:
                say(
                    f"watchdog: numerical-health trip "
                    f"(guarded amax {sup_out.amax_last:g}); "
                    f"last good step {sup_out.final_step}"
                )
            if sup_out.checkpoint_path:
                say(f"resumable checkpoint: {sup_out.checkpoint_path}")
            if telemetry is not None:
                telemetry.stop()
            return sup_out.exit_code
        if telemetry is not None:
            telemetry.stop()
        return 0
    except BaseException:
        # A crash mid-dispatch (XLA error, bad mesh, report I/O)
        # must still emit the open cli.solve span and the final
        # heartbeat, and must not leave the process tracer bound to
        # this run's trace file: in-process callers (tests, library
        # use of cli.main) never reach the atexit net, and their
        # next cli.main call must not inherit a stale tracer.
        # (Span end and telemetry.stop() are both idempotent, so a
        # raise after the success-path end_span is safe too.)
        _abort_telemetry()
        raise


if __name__ == "__main__":
    sys.exit(main())
