"""Problem specification for the 3D acoustic wave equation.

The PDE solved is  u_tt = a^2 * laplace(u)  on [0,Lx] x [0,Ly] x [0,Lz] x [0,T]
with a^2 = 1/(4*pi^2), periodic boundary in x and homogeneous Dirichlet in y/z,
validated against the closed-form analytic solution

    u(t,x,y,z) = sin(2*pi*x/Lx) * sin(pi*y/Ly) * sin(pi*z/Lz) * cos(a_t*t + 2*pi)
    a_t = 0.5 * sqrt(4/Lx^2 + 1/Ly^2 + 1/Lz^2)

This mirrors the reference solver's constants and derived quantities
(reference: openmp_sol.cpp:192-214, mpi_new.cpp:376-404) but is organised as a
single immutable spec shared by every backend instead of file-scope globals.

Grid representation (TPU-native design decision, not a translation):

The reference stores an (N+1)^3 grid in which the periodic x seam node is
duplicated (global x index 0 and N hold the same value; openmp_sol.cpp:114-120)
and the Dirichlet planes y,z in {0,N} are explicitly zeroed every step
(openmp_sol.cpp:104-112).  Here the state is an (N, N, N) cube:

 * x: the fundamental periodic domain, indices 0..N-1.  The reference's
   special seam update (its `prepare_layer`) is mathematically the ordinary
   leapfrog update with a cyclic neighbour, so no seam code exists at all.
 * y, z: indices 0..N-1.  The y=N and z=N Dirichlet planes are identically
   zero and therefore not stored; the y=0 / z=0 planes are stored and forced
   to zero ("Dirichlet invariant").  Because of that invariant, a *cyclic*
   shift in y/z yields the correct zero neighbour at j=N-1 (it wraps to the
   zero plane j=0), which makes all three axes pure rolls - the property the
   whole framework (XLA rolls, cyclic ppermute halos, Pallas kernel) builds on.

A pleasant side effect: for the benchmark sizes N in {128, 256, 512, 1024} the
state is exactly (8,128)-tile aligned on TPU, with no padding waste.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence, Tuple

# Deliberate deviation from the reference: its CPU variants hardcode the
# 10-digit truncation PI = 3.1415926535 (openmp_sol.cpp:20) while its CUDA
# variant uses full precision (cuda_sol_kernels.cu:3).  We use math.pi
# everywhere - self-consistent and at least as accurate - so error parity
# with reference *output files* can diverge around the 10th digit.
PI = math.pi


def parse_length(token: str | float) -> float:
    """Parse a CLI length argument; the literal string "pi" means math.pi.

    Mirrors the reference CLI contract (openmp_sol.cpp:195-200).
    """
    if isinstance(token, str):
        if token.strip().lower() == "pi":
            return PI
        return float(token)
    return float(token)


@dataclasses.dataclass(frozen=True)
class Problem:
    """Immutable problem spec; all derived constants are properties.

    Fields mirror the reference positional CLI `N Np Lx Ly Lz T timesteps`
    (openmp_sol.cpp:192-204).  `Np` is kept for CLI compatibility; like the
    reference MPI/CUDA variants it does not influence the computation
    (mpi_sol.cpp:381 parses it and never uses it).
    """

    N: int = 32
    Np: int = 1
    Lx: float = 1.0
    Ly: float = 1.0
    Lz: float = 1.0
    T: float = 1.0
    timesteps: int = 20

    def __post_init__(self):
        if self.N < 4:
            raise ValueError(f"N must be >= 4, got {self.N}")
        if self.timesteps < 1:
            raise ValueError(f"timesteps must be >= 1, got {self.timesteps}")

    # ---- derived constants (reference: openmp_sol.cpp:207-214) ----
    @property
    def a2(self) -> float:
        return 1.0 / (4.0 * PI * PI)

    @property
    def a(self) -> float:
        return math.sqrt(self.a2)

    @property
    def a_t(self) -> float:
        return 0.5 * math.sqrt(
            4.0 / (self.Lx * self.Lx)
            + 1.0 / (self.Ly * self.Ly)
            + 1.0 / (self.Lz * self.Lz)
        )

    @property
    def tau(self) -> float:
        return self.T / self.timesteps

    @property
    def hx(self) -> float:
        return self.Lx / self.N

    @property
    def hy(self) -> float:
        return self.Ly / self.N

    @property
    def hz(self) -> float:
        return self.Lz / self.N

    @property
    def courant(self) -> float:
        """Stability number C = a*tau/min(h); printed before every run
        (openmp_sol.cpp:214)."""
        return self.a * self.tau / min(self.hx, self.hy, self.hz)

    @property
    def inv_h2(self) -> Tuple[float, float, float]:
        return (1.0 / self.hx**2, 1.0 / self.hy**2, 1.0 / self.hz**2)

    @property
    def a2tau2(self) -> float:
        return self.a2 * self.tau * self.tau

    @property
    def cells_per_step(self) -> int:
        """Cell updates per time step for throughput accounting.

        Uses the reference's (N+1)^3 grid-point count (BASELINE.md throughput
        definition) even though the stored state is N^3.
        """
        return (self.N + 1) ** 3

    @classmethod
    def from_argv(cls, argv: Sequence[str]) -> "Problem":
        """Build from reference-style positional args: N Np Lx Ly Lz T timesteps.

        T and timesteps are optional with defaults 1 and 20
        (openmp_sol.cpp:201-204).
        """
        if len(argv) < 5:
            raise ValueError(
                "usage: N Np Lx Ly Lz [T] [timesteps]  (Lx/Ly/Lz accept 'pi')"
            )
        return cls(
            N=int(argv[0]),
            Np=int(argv[1]),
            Lx=parse_length(argv[2]),
            Ly=parse_length(argv[3]),
            Lz=parse_length(argv[4]),
            T=float(argv[5]) if len(argv) >= 6 else 1.0,
            timesteps=int(argv[6]) if len(argv) >= 7 else 20,
        )
