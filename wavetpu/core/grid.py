"""Sharded state and 3D device topology.

The TPU-native counterpart of the reference's L0 layer: `MPI_Dims_create` 3D
factorization, per-rank extents with the remainder folded into the last rank,
and ghost-cell padding (reference: mpi_sol.cpp:405-459, mpi_new.cpp:409-423,
cuda_sol.cpp:477-489).  Here the topology is a `jax.sharding.Mesh` over the
axis names ("x", "y", "z") and the "rank extents" are shard_map block shapes.

Uneven grids: shard_map needs equal blocks, so instead of the reference's
bigger-last-rank scheme (mpi_sol.cpp:417-421) the fundamental (N, N, N)
domain is zero-padded per axis to `block * mesh_dim` and the pad cells are
masked out of the update and the error reduction.  The last shard therefore
owns `r_last <= block` real planes; `r_last` drives the halo-exchange index
arithmetic in `wavetpu.comm.halo`.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax

AXIS_NAMES = ("x", "y", "z")


def choose_mesh_shape(n_devices: int) -> Tuple[int, int, int]:
    """Near-cubic 3D factorization of `n_devices` (MPI_Dims_create analog).

    Returns (mx, my, mz) with mx >= my >= mz, as balanced as possible
    (reference relies on MPI_Dims_create the same way, mpi_sol.cpp:407).
    """
    best = (n_devices, 1, 1)
    best_score = n_devices  # max/min spread proxy: the max dim
    for a in range(1, int(round(n_devices ** (1 / 3))) + 2):
        if n_devices % a:
            continue
        rest = n_devices // a
        for b in range(a, int(math.isqrt(rest)) + 1):
            if rest % b:
                continue
            c = rest // b
            dims = tuple(sorted((a, b, c), reverse=True))
            if dims[0] < best_score:
                best, best_score = dims, dims[0]
    return best


@dataclasses.dataclass(frozen=True)
class Topology:
    """Static decomposition of the fundamental (N, N, N) domain over a mesh.

    block[a]   - shard extent along axis a (equal for every shard)
    padded[a]  - block[a] * mesh_shape[a] >= N (zero-padded global extent)
    r_last[a]  - number of *real* (non-pad) planes owned by the last shard
    """

    N: int
    mesh_shape: Tuple[int, int, int]

    def __post_init__(self):
        for m, name in zip(self.mesh_shape, AXIS_NAMES):
            if m < 1:
                raise ValueError(f"mesh dim {name} must be >= 1, got {m}")
            b = -(-self.N // m)  # ceil
            if self.N - (m - 1) * b < 1:
                raise ValueError(
                    f"mesh dim {name}={m} too large for N={self.N}: "
                    f"last shard would own no real planes"
                )

    @property
    def block(self) -> Tuple[int, int, int]:
        return tuple(-(-self.N // m) for m in self.mesh_shape)

    @property
    def padded(self) -> Tuple[int, int, int]:
        return tuple(b * m for b, m in zip(self.block, self.mesh_shape))

    @property
    def r_last(self) -> Tuple[int, int, int]:
        return tuple(
            self.N - (m - 1) * b for b, m in zip(self.block, self.mesh_shape)
        )

    @property
    def n_devices(self) -> int:
        mx, my, mz = self.mesh_shape
        return mx * my * mz


def build_mesh(
    mesh_shape: Tuple[int, int, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> jax.sharding.Mesh:
    """3D device mesh with the framework's canonical axis names.

    The ICI counterpart of `MPI_Cart_create` with periods {1,0,0}
    (mpi_sol.cpp:409-410) - except periodicity lives in the ppermute
    permutations (comm/halo.py), not in the mesh itself.
    """
    if devices is not None:
        import numpy as np

        arr = np.asarray(devices).reshape(mesh_shape)
        return jax.sharding.Mesh(arr, AXIS_NAMES)
    return jax.make_mesh(mesh_shape, AXIS_NAMES)
