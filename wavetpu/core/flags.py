"""One `--flag[=value]` argv parser for every wavetpu CLI surface.

The solver CLI, `wavetpu serve`, and `wavetpu loadgen` all speak the
same flag dialect (`--flag value`, `--flag=value`, valueless switches,
reference-style positionals); this is the single implementation so
error wording and edge cases (`--flag` at end of argv, unknown flags as
loud usage errors instead of silent drops) cannot drift between them.

Imports nothing (same before-the-backend discipline as core.problem).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple


def split_flags(
    argv: Sequence[str],
    known: Sequence[str],
    valueless: Sequence[str] = (),
    allow_positionals: bool = True,
    repeatable: Sequence[str] = (),
) -> Tuple[List[str], Dict[str, object]]:
    """Separate positionals from --flag[=value] options.

    Raises ValueError for unknown flags, a flag missing its value, or
    (with `allow_positionals=False`) any positional - so typos surface
    as the caller's usage error instead of being silently ignored.

    A repeated flag is last-wins (the shell-override idiom) UNLESS it
    is listed in `repeatable`, in which case its value is a LIST of
    every occurrence in argv order (the multi-replica `--target` /
    `--backend` dialect of loadgen and the fleet router)."""
    pos: List[str] = []
    flags: Dict[str, object] = {}
    it = iter(argv)
    for a in it:
        if a.startswith("--"):
            if "=" in a:
                k, v = a[2:].split("=", 1)
            else:
                k = a[2:]
                if k in valueless:
                    v = ""
                else:
                    v = next(it, None)
                    if v is None:
                        raise ValueError(f"flag --{k} needs a value")
            if k not in known:
                raise ValueError(f"unknown flag --{k}")
            if k in repeatable:
                flags.setdefault(k, []).append(v)
            else:
                flags[k] = v
        else:
            if not allow_positionals:
                raise ValueError(f"unexpected positional {a!r}")
            pos.append(a)
    return pos, flags
