"""Pallas fused leapfrog stencil kernel - the TPU-native hot kernel.

The analog of the reference's CUDA kernel layer (`calculate_layer`,
cuda_sol_kernels.cu:24-47, and the BC/seam handling of `prepare_layer`,
cuda_sol_kernels.cu:230-259) redesigned for the TPU memory system instead of
translated:

 * The grid marches over slabs of `block_x` x-planes.  Each program reads its
   slab of u / u_prev plus exactly TWO single-plane x-halos fetched through
   wrap-around BlockSpec index maps ((i*bx - 1) mod N) - the periodic-x
   topology costs nothing and there is no seam special case (the fundamental
   (N, N, N) domain of `wavetpu.core.problem` has no duplicated plane).
 * y/z neighbours come from in-VMEM cyclic rolls (`pltpu.roll`): the y/z
   wrap delivers the stored zero Dirichlet plane, so one uniform data path
   covers interior + all boundaries, where the reference needs a separate
   boundary kernel with a face bitmask (and shipped a precedence bug in it,
   SURVEY.md section 2.4.1).
 * The Dirichlet re-zeroing of the y=0 / z=0 stored planes is fused as a
   mask on the result - no second kernel, no extra memory pass.
 * The update 2u - u_prev + c*lap and the boundary mask execute in f32 on
   the VPU regardless of the storage dtype, so a bf16 state (BASELINE.md
   stretch config) keeps an f32 update path.

Layout: z is the lane dimension (128), y the sublane dimension (8); an
(N, N) plane of f32 is tile-aligned for any N multiple of 128.  `block_x`
is chosen so the pipeline's working set fits comfortably in VMEM
(~16 MB/core).

Semantics are pinned to `stencil_ref.leapfrog_step` / `taylor_half_step`
(tested in tests/test_pallas.py, interpret mode on CPU plus allclose on
chip): identical inputs must agree to rounding error.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from wavetpu.core.problem import Problem
from wavetpu import compat
from wavetpu.kernels import stencil_ref

# Per-core VMEM working-set budget (bytes) used to pick block_x: the
# pipeline double-buffers (3*bx + 2) planes (u slab + u_prev slab + out slab
# + 2 halo planes), and the kernel body needs room again for temporaries
# (ext/lap).  The Mosaic scoped-vmem ceiling is raised to _VMEM_LIMIT
# accordingly (the default 16 MB rejects even a one-plane slab at N=512,
# and the overflow is not graceful: it NaN'd inside lax.scan in testing).
# bx=8 at N=512 measured fastest on v5e (20.3 Gcell/s vs 14.6 at bx=1).
_VMEM_BUDGET = 56 * 1024 * 1024
_VMEM_LIMIT = 100 * 1024 * 1024


def _choose_block_depth(
    depth: int,
    plane_elems: int,
    itemsize: int = 4,
    field_itemsize: Optional[int] = None,
    slabs: int = 3,
) -> int:
    """Largest power-of-two slab depth (<= 8) whose double-buffered pipeline
    working set fits the VMEM budget (and divides `depth`).

    The bx-deep buffers in flight are u_prev + u + out (state `itemsize`
    each) plus, for the variable-c kernel, the field slab at
    `field_itemsize` - the COMPUTE dtype's width (f32), which differs from
    the state width under bf16.  Getting the accounting wrong is a real
    cliff, not a tweak: the var-c kernel at N=512 ran 2.7x slower with the
    constant-kernel choice (bx=8, 68 MB pipeline) than with the correct
    bx=4 (measured 8.1 vs 19.5 Gcell/s on v5e).

    `plane_elems` is the (y, z) plane size in elements - n*n for the full
    fundamental domain, by*bz for a shard block.  `slabs` is the number of
    bx-deep state buffers in flight (3 for the standard kernel, 6 for the
    compensated one: u/v/carry in + out).
    """
    per_bx = slabs * itemsize + (field_itemsize or 0)  # bytes per plane
    halo = 2 * itemsize                             # two 1-plane halos
    bx = 1
    while (
        bx < 8
        and depth % (bx * 2) == 0
        and 2 * (per_bx * (bx * 2) + halo) * plane_elems <= _VMEM_BUDGET
    ):
        bx *= 2
    return bx


def choose_block_x(
    n: int, itemsize: int = 4, field_itemsize: Optional[int] = None
) -> int:
    """Slab depth for the single-device (N, N, N) kernels (see
    `_choose_block_depth`)."""
    return _choose_block_depth(n, n * n, itemsize, field_itemsize)


def _slab_laplacian(c, ulo_ref, uhi_ref, inv_h2, f):
    """7-pt Laplacian of a slab: x-neighbours from the halo-plane refs,
    y/z neighbours from in-VMEM cyclic rolls (the wrap delivers the stored
    zero Dirichlet plane / the periodic value - rolls ARE the BC)."""
    ix, iy, iz = (jnp.asarray(v, f) for v in inv_h2)
    # Halo planes stacked onto the slab (axis 0 is neither lane nor sublane,
    # so this is free of relayouts).
    ext = jnp.concatenate([ulo_ref[:].astype(f), c, uhi_ref[:].astype(f)], 0)
    lap = (ext[:-2] + ext[2:] - 2.0 * c) * ix
    # pltpu.roll wants non-negative shifts: roll by size-1 == roll by -1.
    ny, nz = c.shape[1], c.shape[2]
    lap = lap + (pltpu.roll(c, 1, 1) + pltpu.roll(c, ny - 1, 1) - 2.0 * c) * iy
    lap = lap + (pltpu.roll(c, 1, 2) + pltpu.roll(c, nz - 1, 2) - 2.0 * c) * iz
    return lap


def _finish_update(u_next, out_ref, f):
    """Fused Dirichlet mask + store: zero the stored y=0 / z=0 planes (the
    reference's whole `prepare_layer` pass, openmp_sol.cpp:104-112)."""
    shape = u_next.shape
    ym = lax.broadcasted_iota(jnp.int32, shape, 1) != 0
    zm = lax.broadcasted_iota(jnp.int32, shape, 2) != 0
    out_ref[:] = jnp.where(
        ym & zm, u_next, jnp.asarray(0.0, f)
    ).astype(out_ref.dtype)


def _step_kernel(uprev_ref, uc_ref, ulo_ref, uhi_ref, out_ref,
                 *, alpha, beta, coeff, inv_h2, compute_dtype):
    """One fused update slab: out = alpha*u - beta*u_prev + coeff*lap(u).

    (alpha, beta, coeff) = (2, 1, a2tau2)  -> leapfrog (openmp_sol.cpp:160)
    (alpha, beta, coeff) = (1, 0, a2tau2/2) -> layer-1 Taylor half-step
                                               (openmp_sol.cpp:137-144)
    """
    f = compute_dtype
    c = uc_ref[:].astype(f)
    lap = _slab_laplacian(c, ulo_ref, uhi_ref, inv_h2, f)
    u_next = jnp.asarray(alpha, f) * c + jnp.asarray(coeff, f) * lap
    if beta:
        u_next = u_next - jnp.asarray(beta, f) * uprev_ref[:].astype(f)
    _finish_update(u_next, out_ref, f)


def _var_step_kernel(c2_ref, uprev_ref, uc_ref, ulo_ref, uhi_ref, out_ref,
                     *, inv_h2, compute_dtype):
    """Variable-speed leapfrog slab: out = 2u + tau^2 c^2(x) lap(u) - u_prev.

    The c^2 tau^2 field rides its own slab input - the capability extension
    over the reference's hardcoded __constant__ a2 (cuda_sol_kernels.cu:3).
    The summation order (2u + coeff*lap) - u_prev matches `_sharded_kernel`'s
    field path and the k-step onion's variable-c substep, so variable-c
    layers are op-identical across the 1-step, sharded, and k-fused paths
    (the same bitwise-mixing contract as the constant-c kernels)."""
    f = compute_dtype
    c = uc_ref[:].astype(f)
    lap = _slab_laplacian(c, ulo_ref, uhi_ref, inv_h2, f)
    u_next = 2.0 * c + c2_ref[:].astype(f) * lap - uprev_ref[:].astype(f)
    _finish_update(u_next, out_ref, f)


def _specs(n: int, bx: int):
    """Slab + wrap-around halo BlockSpecs for an (N, N, N) field.

    Single-plane halos via wrap-around maps: with block shape (1, N, N)
    the x block index IS the plane index, so these express the cyclic
    neighbour relation directly (jnp mod is floor-mod: (0-1) % N = N-1).
    """
    slab = pl.BlockSpec((bx, n, n), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    lo = pl.BlockSpec((1, n, n), lambda i: ((i * bx - 1) % n, 0, 0),
                      memory_space=pltpu.VMEM)
    hi = pl.BlockSpec((1, n, n), lambda i: (((i + 1) * bx) % n, 0, 0),
                      memory_space=pltpu.VMEM)
    return slab, lo, hi


def _fused_step(u_prev, u, *, inv_h2, alpha=2.0, beta=1.0, coeff=None,
                c2tau2_field=None, block_x=None, interpret=False,
                compute_dtype=None):
    """Shared pallas_call wrapper for the constant- and variable-speed
    kernels; `c2tau2_field` selects the variable kernel (its slab is
    prepended as an extra input)."""
    n = u.shape[0]
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(u.dtype)
    field_itemsize = (
        None if c2tau2_field is None else jnp.dtype(compute_dtype).itemsize
    )
    bx = block_x or choose_block_x(n, u.dtype.itemsize, field_itemsize)
    if n % bx:
        raise ValueError(f"block_x={bx} must divide N={n}")
    slab, lo, hi = _specs(n, bx)
    if c2tau2_field is None:
        kernel = functools.partial(
            _step_kernel, alpha=alpha, beta=beta, coeff=coeff,
            inv_h2=inv_h2, compute_dtype=compute_dtype,
        )
        in_specs, operands = [slab, slab, lo, hi], (u_prev, u, u, u)
    else:
        kernel = functools.partial(
            _var_step_kernel, inv_h2=inv_h2, compute_dtype=compute_dtype,
        )
        field = jnp.asarray(c2tau2_field, dtype=compute_dtype)
        in_specs = [slab, slab, slab, lo, hi]
        operands = (field, u_prev, u, u, u)
    return pl.pallas_call(
        kernel,
        grid=(n // bx,),
        in_specs=in_specs,
        out_specs=slab,
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        compiler_params=compat.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(*operands)


def leapfrog_step(u_prev, u, problem: Problem, *,
                  block_x=None, interpret=False):
    """Fused u_next = 2u - u_prev + a2tau2*lap(u) with Dirichlet re-imposed.

    Drop-in for `stencil_ref.leapfrog_step` (`make_solver(step_fn=...)`).
    """
    return _fused_step(
        u_prev, u, alpha=2.0, beta=1.0, coeff=problem.a2tau2,
        inv_h2=problem.inv_h2, block_x=block_x, interpret=interpret,
    )


def taylor_half_step(u0, problem: Problem, *, block_x=None, interpret=False):
    """Fused layer-1 bootstrap u1 = u0 + (a2tau2/2)*lap(u0).

    Drop-in for `stencil_ref.taylor_half_step`.
    """
    return _fused_step(
        u0, u0, alpha=1.0, beta=0.0, coeff=0.5 * problem.a2tau2,
        inv_h2=problem.inv_h2, block_x=block_x, interpret=interpret,
    )


def _ghost_lap(c, ulo_ref, uhi_ref, ghost_refs, need, inv_h2, f):
    """7-pt Laplacian of a shard slab with statically-specialized ghost
    handling (see `_sharded_kernel` for the per-axis semantics).

    `ghost_refs` is (xlo, xhi, ylo, yhi, zlo, zhi) with None entries on
    axes whose mesh dim is 1 (`need[a]` False).
    """
    xlo_ref, xhi_ref, ylo_ref, yhi_ref, zlo_ref, zhi_ref = ghost_refs
    shape = c.shape
    ix, iy, iz = (jnp.asarray(v, f) for v in inv_h2)
    i = pl.program_id(0)

    # x neighbours: slab halo planes, ghost-overridden at the grid edges.
    lo = ulo_ref[:].astype(f)
    hi = uhi_ref[:].astype(f)
    if need[0]:
        last = pl.num_programs(0) - 1
        lo = jnp.where(i == 0, xlo_ref[:].astype(f), lo)
        hi = jnp.where(i == last, xhi_ref[:].astype(f), hi)
    ext = jnp.concatenate([lo, c, hi], 0)
    lap = (ext[:-2] + ext[2:] - 2.0 * c) * ix

    # y/z neighbours: in-VMEM cyclic rolls (pltpu.roll wants non-negative
    # shifts: roll by size-1 == roll by -1), ghost-overridden at the wrap.
    ny, nz = shape[1], shape[2]
    dn, up = pltpu.roll(c, 1, 1), pltpu.roll(c, ny - 1, 1)
    if need[1]:
        iota_y = lax.broadcasted_iota(jnp.int32, shape, 1)
        dn = jnp.where(iota_y == 0, ylo_ref[:].astype(f), dn)
        up = jnp.where(iota_y == ny - 1, yhi_ref[:].astype(f), up)
    lap = lap + (dn + up - 2.0 * c) * iy
    dn, up = pltpu.roll(c, 1, 2), pltpu.roll(c, nz - 1, 2)
    if need[2]:
        iota_z = lax.broadcasted_iota(jnp.int32, shape, 2)
        dn = jnp.where(iota_z == 0, zlo_ref[:].astype(f), dn)
        up = jnp.where(iota_z == nz - 1, zhi_ref[:].astype(f), up)
    return lap + (dn + up - 2.0 * c) * iz


def _global_mask(off_ref, shape, pad, n_global, block_x):
    """Fused boundary/pad mask (reference: the whole prepare_layer pass,
    openmp_sol.cpp:104-112, plus pad-cell re-zeroing): the y/z Dirichlet
    zeroing (global index != 0) always, the global-index < N pad component
    only on axes that actually carry pad planes."""
    gy = off_ref[1] + lax.broadcasted_iota(jnp.int32, shape, 1)
    gz = off_ref[2] + lax.broadcasted_iota(jnp.int32, shape, 2)
    mask = (gy != 0) & (gz != 0)
    if pad[0]:
        gx = (
            off_ref[0] + pl.program_id(0) * block_x
            + lax.broadcasted_iota(jnp.int32, shape, 0)
        )
        mask &= gx < n_global
    if pad[1]:
        mask &= gy < n_global
    if pad[2]:
        mask &= gz < n_global
    return mask


def _take_ghost_refs(it, need):
    """Pull the present ghost refs off the operand iterator, None-filling
    the axes that need none (mesh dim 1)."""
    refs = []
    for a in range(3):
        if need[a]:
            refs.append(next(it))
            refs.append(next(it))
        else:
            refs.extend((None, None))
    return tuple(refs)


def _sharded_kernel(*refs, alpha, beta, coeff, has_field, need, pad,
                    n_global, block_x, inv_h2, compute_dtype):
    """Per-shard fused update slab - the distributed counterpart of
    `_step_kernel`, the analog of the reference's per-rank CUDA kernel
    launch (cuda_sol.cpp:381-443 driving calculate_layer,
    cuda_sol_kernels.cu:24-47).

    Statically specialized per axis on the mesh shape:

     * `need[a]` (mesh dim > 1): the axis's shard-boundary neighbours come
       from ppermute'd ghost operands - the x halo overrides the wraparound
       BlockSpec planes at the grid edges, y/z ghosts override the wrapped
       row/lane of the in-VMEM roll via an iota select.  On a 1-shard axis
       the in-shard wrap IS the global neighbour (periodic x / stored zero
       Dirichlet plane in y/z), so no ghost operands and no selects exist
       at all - a (1,1,1) mesh compiles to the single-device kernel's data
       path.
     * `pad[a]` (uneven shards): the global-index < N mask component only
       exists on axes that actually carry pad planes.

    All masking stays fused in the store: no HBM traffic.
    """
    f = compute_dtype
    it = iter(refs[:-1])
    out_ref = refs[-1]
    off_ref = next(it)
    c2_ref = next(it) if has_field else None
    uprev_ref = next(it)
    uc_ref = next(it)
    ulo_ref = next(it)
    uhi_ref = next(it)
    ghost_refs = _take_ghost_refs(it, need)

    c = uc_ref[:].astype(f)
    lap = _ghost_lap(c, ulo_ref, uhi_ref, ghost_refs, need, inv_h2, f)
    if has_field:
        u_next = jnp.asarray(alpha, f) * c + c2_ref[:].astype(f) * lap
    else:
        u_next = jnp.asarray(alpha, f) * c + jnp.asarray(coeff, f) * lap
    if beta:
        u_next = u_next - jnp.asarray(beta, f) * uprev_ref[:].astype(f)

    mask = _global_mask(off_ref, u_next.shape, pad, n_global, block_x)
    out_ref[:] = jnp.where(mask, u_next, jnp.asarray(0.0, f)).astype(
        out_ref.dtype
    )


def _sharded_comp_kernel(*refs, coeff, need, pad, n_global, block_x,
                         inv_h2, compute_dtype):
    """Per-shard fused compensated (Kahan) leapfrog slab - `_comp_step_kernel`
    with the sharded ghost handling and global mask of `_sharded_kernel`.
    Reads v/carry/u (+ghosts), writes u'/v'/carry' in one HBM pass."""
    f = compute_dtype
    it = iter(refs[:-3])
    u_out, v_out, carry_out = refs[-3:]
    off_ref = next(it)
    v_ref = next(it)
    carry_ref = next(it)
    uc_ref = next(it)
    ulo_ref = next(it)
    uhi_ref = next(it)
    ghost_refs = _take_ghost_refs(it, need)

    c = uc_ref[:].astype(f)
    lap = _ghost_lap(c, ulo_ref, uhi_ref, ghost_refs, need, inv_h2, f)
    d = jnp.asarray(coeff, f) * lap
    # Mask the increment (u/v/carry start masked and sums of masked fields
    # stay masked, stencil_ref.compensated_step) AND the stored u: the pad
    # plane of the input block holds the absorbed hi ghost on uneven axes
    # (halo.absorb_hi_ghosts) and must not leak into the carry state.  At
    # masked cells y = 0, so carry_next there is 0 regardless.
    mask = _global_mask(off_ref, d.shape, pad, n_global, block_x)
    d = jnp.where(mask, d, jnp.asarray(0.0, f))
    v_next = v_ref[:].astype(f) + d
    y = v_next - carry_ref[:].astype(f)
    t = c + y
    carry_next = (t - c) - y
    u_out[:] = jnp.where(mask, t, jnp.asarray(0.0, f)).astype(u_out.dtype)
    v_out[:] = v_next.astype(v_out.dtype)
    carry_out[:] = carry_next.astype(carry_out.dtype)


def _sharded_geometry(u, bx, mesh_shape, r_last):
    """BlockSpecs and per-axis static flags shared by the sharded kernels."""
    bx_tot, by, bz = u.shape
    need = tuple(m > 1 for m in mesh_shape)
    if r_last is None:
        pads = (False, False, False)
    else:
        pads = tuple(r != b for r, b in zip(r_last, u.shape))
    specs = dict(
        slab=pl.BlockSpec((bx, by, bz), lambda i: (i, 0, 0),
                          memory_space=pltpu.VMEM),
        lo=pl.BlockSpec((1, by, bz),
                        lambda i: ((i * bx - 1) % bx_tot, 0, 0),
                        memory_space=pltpu.VMEM),
        hi=pl.BlockSpec((1, by, bz),
                        lambda i: (((i + 1) * bx) % bx_tot, 0, 0),
                        memory_space=pltpu.VMEM),
        gx=pl.BlockSpec((1, by, bz), lambda i: (0, 0, 0),
                        memory_space=pltpu.VMEM),
        gy=pl.BlockSpec((bx, 1, bz), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM),
        gz=pl.BlockSpec((bx, by, 1), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM),
        smem=pl.BlockSpec(memory_space=pltpu.SMEM),
    )
    return need, pads, specs


def _append_ghosts(in_specs, operands, specs, need, ghosts):
    for needed, spec_name, (g_lo, g_hi) in zip(
        need, ("gx", "gy", "gz"), ghosts
    ):
        if needed:
            in_specs += [specs[spec_name], specs[spec_name]]
            operands += [g_lo, g_hi]


def _out_struct(u, shape=None, dtype=None):
    """Output aval matching the state it replaces (or the given
    shape/dtype override); under shard_map with check_vma it must declare
    which mesh axes it varies over."""
    shape = u.shape if shape is None else shape
    dtype = u.dtype if dtype is None else dtype
    vma = getattr(getattr(u, "aval", None), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def sharded_fused_step(u_prev, u, ghosts, offsets, n_global, *, inv_h2,
                       mesh_shape, r_last=None,
                       alpha=2.0, beta=1.0, coeff=None, c2tau2_block=None,
                       block_x=None, interpret=False, compute_dtype=None):
    """One fused leapfrog-form update of a shard block with pre-exchanged
    ghosts - the Pallas hot kernel of the distributed solver.

    Must run inside `shard_map`.  `ghosts` is `comm.halo.collect_ghosts`
    output ((xlo, xhi), (ylo, yhi), (zlo, zhi)); for an unevenly sharded
    axis the hi ghost must additionally be absorbed into the block first
    (`comm.halo.absorb_hi_ghosts`).  `offsets` is an int32 (3,) array of
    the shard's global cell offsets; `n_global` the fundamental N.
    `mesh_shape` / `r_last` drive the static per-axis specialization (see
    `_sharded_kernel`).  With `c2tau2_block` (this shard's slice of the
    tau^2 c^2 field) the variable-speed kernel runs and `coeff` is ignored.
    """
    bx_tot, by, bz = u.shape
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(u.dtype)
    has_field = c2tau2_block is not None
    field_itemsize = (
        None if not has_field else jnp.dtype(compute_dtype).itemsize
    )
    bx = block_x or _choose_block_depth(
        bx_tot, by * bz, u.dtype.itemsize, field_itemsize
    )
    if bx_tot % bx:
        raise ValueError(f"block_x={bx} must divide shard depth {bx_tot}")
    need, pads, specs = _sharded_geometry(u, bx, mesh_shape, r_last)
    slab, lo, hi = specs["slab"], specs["lo"], specs["hi"]

    in_specs = [specs["smem"]]
    operands = [jnp.asarray(offsets, jnp.int32)]
    if has_field:
        in_specs.append(slab)
        operands.append(jnp.asarray(c2tau2_block, dtype=compute_dtype))
    in_specs += [slab, slab, lo, hi]
    operands += [u_prev, u, u, u]
    _append_ghosts(in_specs, operands, specs, need, ghosts)

    kernel = functools.partial(
        _sharded_kernel,
        alpha=alpha, beta=beta, coeff=coeff, has_field=has_field,
        need=need, pad=pads, n_global=n_global, block_x=bx,
        inv_h2=inv_h2, compute_dtype=compute_dtype,
    )
    return pl.pallas_call(
        kernel,
        grid=(bx_tot // bx,),
        in_specs=in_specs,
        out_specs=slab,
        out_shape=_out_struct(u),
        compiler_params=compat.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(*operands)


def sharded_compensated_step(u, v, carry, ghosts, offsets, n_global, *,
                             inv_h2, mesh_shape, r_last=None, coeff,
                             block_x=None, interpret=False,
                             compute_dtype=None):
    """Fused compensated (Kahan) leapfrog step of a shard block - the
    sharded counterpart of `compensated_step`, with ghosts/masking as in
    `sharded_fused_step`.  Returns (u', v', carry')."""
    bx_tot, by, bz = u.shape
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(u.dtype)
    bx = block_x or _choose_block_depth(
        bx_tot, by * bz, u.dtype.itemsize, slabs=6
    )
    if bx_tot % bx:
        raise ValueError(f"block_x={bx} must divide shard depth {bx_tot}")
    need, pads, specs = _sharded_geometry(u, bx, mesh_shape, r_last)
    slab, lo, hi = specs["slab"], specs["lo"], specs["hi"]

    in_specs = [specs["smem"], slab, slab, slab, lo, hi]
    operands = [jnp.asarray(offsets, jnp.int32), v, carry, u, u, u]
    _append_ghosts(in_specs, operands, specs, need, ghosts)

    kernel = functools.partial(
        _sharded_comp_kernel,
        coeff=coeff, need=need, pad=pads, n_global=n_global, block_x=bx,
        inv_h2=inv_h2, compute_dtype=compute_dtype,
    )
    out = _out_struct(u)
    return pl.pallas_call(
        kernel,
        grid=(bx_tot // bx,),
        in_specs=in_specs,
        out_specs=[slab, slab, slab],
        out_shape=[out, out, out],
        compiler_params=compat.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(*operands)


def _comp_step_kernel(v_ref, carry_ref, uc_ref, ulo_ref, uhi_ref,
                      u_out, v_out, carry_out,
                      *, coeff, inv_h2, compute_dtype):
    """Fused compensated (Kahan) incremental leapfrog slab.

    Semantics pinned to `stencil_ref.compensated_step`: the increment
    C*lap(u) accumulates in its own buffer and the u addition runs through
    a two-sum carry, keeping f32 rounding at the representation level (see
    that docstring for the measured numbers).  One kernel reads u (+2 halo
    planes), v, carry and writes all three successors - the whole step in
    a single HBM pass, where an unfused formulation would pay a second
    elementwise pass over four fields.
    """
    f = compute_dtype
    c = uc_ref[:].astype(f)
    lap = _slab_laplacian(c, ulo_ref, uhi_ref, inv_h2, f)
    d = jnp.asarray(coeff, f) * lap
    # Dirichlet mask on the increment only: u/v/carry start masked and
    # sums of masked fields stay masked (stencil_ref.compensated_step).
    ym = lax.broadcasted_iota(jnp.int32, d.shape, 1) != 0
    zm = lax.broadcasted_iota(jnp.int32, d.shape, 2) != 0
    d = jnp.where(ym & zm, d, jnp.asarray(0.0, f))
    v_next = v_ref[:].astype(f) + d
    y = v_next - carry_ref[:].astype(f)
    t = c + y
    carry_next = (t - c) - y
    u_out[:] = t.astype(u_out.dtype)
    v_out[:] = v_next.astype(v_out.dtype)
    carry_out[:] = carry_next.astype(carry_out.dtype)


def compensated_step(u, v, carry, problem: Problem, coeff=None, *,
                     block_x=None, interpret=False):
    """Fused (u, v, carry) -> (u', v', carry') compensated leapfrog step.

    Drop-in for `stencil_ref.compensated_step` (same signature semantics);
    `coeff` defaults to a2tau2, the layer-1 bootstrap passes a2tau2/2 with
    v = carry = 0.
    """
    n = u.shape[0]
    f = stencil_ref.compute_dtype(u.dtype)
    bx = block_x or _choose_block_depth(n, n * n, u.dtype.itemsize, slabs=6)
    if n % bx:
        raise ValueError(f"block_x={bx} must divide N={n}")
    slab, lo, hi = _specs(n, bx)
    kernel = functools.partial(
        _comp_step_kernel,
        coeff=problem.a2tau2 if coeff is None else coeff,
        inv_h2=problem.inv_h2, compute_dtype=f,
    )
    out = jax.ShapeDtypeStruct(u.shape, u.dtype)
    return pl.pallas_call(
        kernel,
        grid=(n // bx,),
        in_specs=[slab, slab, slab, lo, hi],
        out_specs=[slab, slab, slab],
        out_shape=[out, out, out],
        compiler_params=compat.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(v, carry, u, u, u)


def make_compensated_step_fn(block_x=None, interpret=False):
    """A `(u, v, carry, problem, coeff) -> (u', v', carry')` closure for
    `leapfrog.make_compensated_solver(comp_step_fn=...)`."""

    def step(u, v, carry, problem, coeff=None):
        return compensated_step(
            u, v, carry, problem, coeff,
            block_x=block_x, interpret=interpret,
        )

    return step


# --------------------------------------------------------------------------
# Temporally fused k-step kernel.
#
# The 1-step kernel above is HBM-streaming-bound: one step reads u_prev + u
# and writes u_next (~1.75 GB at N=512 f32), and measured pure-copy pallas
# pipelines on this v5e sustain only ~250 GB/s, so ~7 ms/step is the wall
# for ANY 1-step formulation (measured: the jnp-roll step, the fused kernel,
# and a bare out=2u-uprev axpy all land within 15% of it).  The classical
# stencil answer is temporal blocking: march k substeps per HBM pass on a
# slab "onion" held in VMEM, reading k-plane halos and writing only the last
# two layers - traffic per step drops from 3 field-streams to (2 + 2 + 4k/bx)
# / k.  Measured on v5e at N=512/1000 steps, per-layer errors on:
# 20.3 Gcell/s (k=1) -> 35.8 (k=2, bx=8) -> 43.8 (k=4, bx=4).
#
# The reference has no analog (its CUDA kernel is one-layer-per-launch,
# cuda_sol_kernels.cu:24-47, with a device-wide sync between layers); this
# is a TPU-first redesign enabled by the 128 MB VMEM and the sequential
# pallas grid.
#
# Variable c(x, y, z) rides the onion too (round 6): the c^2tau^2 field
# is time-invariant, so it enters as ONE onion-extent operand (slab +
# k-plane halos; ghost-overridden at shard edges like the state) and each
# substep s multiplies the Laplacian by the static slice C2[s : L0 - s] -
# the planes the shrinking update still writes.  Same summation order as
# the 1-step `_var_step_kernel`, so variable-c layers keep the bitwise
# mixing contract.  The field onion costs (bx + 2k) extra f32 planes in
# the pipeline plus one onion temp, which is what caps the block choice
# (`choose_kstep_block(field=True)`).
#
# Per-layer L-inf errors stay EXACTLY as observable as the reference's
# (mpi_new.cpp:335-345) even though intermediate layers never reach HBM:
# the analytic solution is separable (verify/oracle.py), so
#   abs_layer = max_x [ max_{y,z} |u - sxct[x]*syz| ]          (x != 0)
#   rel_layer = max_x [ max_{y,z} |u - f| / |syz| ] / |sx[x]*ct|
# and the kernel only needs per-x-plane maxes of diff and diff/|syz| -
# two SMEM scalar rows per substep, the tiny per-plane rescale happens
# outside.  (1/|syz| rides in as a precomputed plane with 0 at syz==0:
# those cells have u = f = 0 exactly, contributing 0 like the reference's
# NaN-skip, oracle.layer_errors.)
# --------------------------------------------------------------------------

_KSTEP_VMEM_LIMIT = 127 * 1024 * 1024
_KSTEP_VMEM_BUDGET = 122 * 1024 * 1024
# The comp (velocity-form) onion at N=512 k=4 bx=4 f32 needs 127.72 MB -
# 728 KB over the standard onion ceiling but still inside the v5e's
# 128 MiB physical VMEM; Mosaic accepts it with the ceiling at 127.9 MB
# (measured on chip; 33.1 Gcell/s, no spill cliff).
_KSTEP_COMP_VMEM_LIMIT = int(127.9 * 1024 * 1024)


def choose_kstep_block(
    n: int, k: int, itemsize: int = 4, depth: Optional[int] = None,
    ghosts: bool = False, plane_elems: Optional[int] = None,
    field: bool = False,
) -> Optional[int]:
    """Largest slab depth bx (multiple of k, power-of-two steps, <= 8,
    dividing `depth`) whose k-step pipeline fits VMEM; None if even bx=k
    does not.  `n` sets the (y, z) plane size; `depth` the x extent being
    blocked (= n single-device, the shard depth N/P sharded); `ghosts`
    adds the sharded variant's 4 single-fetched k-plane ghost buffers.

    Working-set model (validated against Mosaic's scoped-vmem accounting at
    N=512: est 120 MB vs actual 114 MB for k=2/bx=8): the double-buffered
    pipeline holds 2 state slabs in + 4 k-plane halos + 2 slabs out, the
    kernel body another ~3 onion-sized f32 temporaries, plus the two
    (N,N) oracle planes.

    `field=True` adds the variable-c working set: the c^2tau^2 onion rides
    as its own slab + k-plane halo fetch (f32 - the COMPUTE width, like the
    1-step field slab) plus one onion-sized concat temp in the body.  At
    N=512 f32 that admits k=2/bx=4 under the calibrated budget; k=4/bx=4
    models at ~134 MB against the 128 MiB physical - outside what this
    model will bless, but close enough to the measured ~5% overestimate
    that `block_x=4` stays exposed for explicit on-chip attempts
    (bench.py's kfused_varc row tries it and records the outcome).
    """
    if depth is None:
        depth = n
    if plane_elems is None:
        plane_elems = n * n
    pb_state = plane_elems * itemsize
    pb_f32 = plane_elems * 4
    best = None
    bx = k
    while bx <= 8 and bx <= depth:
        if depth % bx == 0:
            pipeline = 2 * (4 * bx + 4 * k) * pb_state
            if ghosts:
                pipeline += 4 * k * pb_state
            planes = 4 * pb_f32
            temps = 3 * (bx + 2 * k) * pb_f32
            if field:
                pipeline += 2 * (bx + 2 * k) * pb_f32
                if ghosts:
                    pipeline += 2 * k * pb_f32
                temps += (bx + 2 * k) * pb_f32
            if pipeline + planes + temps <= _KSTEP_VMEM_BUDGET:
                best = bx
        bx *= 2
    return best


def _field_onion(it, f, has_field):
    """Assemble the c^2tau^2 onion from the next three refs (slab + the two
    k-plane wraparound halos) when a field rides this call; None otherwise.

    The field is time-invariant, so unlike prev/cur its onion never
    shrinks: substep s reads the static slice C2[s : L0 - s] (the planes
    the shrinking update still writes).
    """
    if not has_field:
        return None
    c2_ref, c2lo_ref, c2hi_ref = next(it), next(it), next(it)
    return jnp.concatenate(
        [c2lo_ref[:].astype(f), c2_ref[:].astype(f), c2hi_ref[:].astype(f)],
        0)


def _substep_coeff(c2_onion, coeff, s, f):
    """Per-substep Laplacian coefficient: the matching field-onion slice,
    or the scalar a^2tau^2."""
    if c2_onion is None:
        return jnp.asarray(coeff, f)
    return c2_onion[s: c2_onion.shape[0] - s]


def _kstep_kernel(*refs, k, bx, coeff, inv_h2, compute_dtype, with_errors,
                  has_field=False):
    """March k leapfrog substeps on a slab onion held in VMEM.

    The prev/cur onions start at bx+2k planes (slab + k-plane wraparound
    halos, periodic x) and shrink by one plane per side per substep -
    after k substeps exactly the central slab remains.  Each substep is
    op-for-op the 1-step `_step_kernel` update (same laplacian summation
    order, same fused y/z Dirichlet mask), so a k-fused solve is bitwise
    identical to the 1-step pallas solve and the two can be mixed freely
    across checkpoint/resume boundaries (tests/test_kfused.py).

    With `has_field` the c^2tau^2 onion rides three extra input refs and
    each substep multiplies the Laplacian by its slice of the field
    instead of the scalar coefficient - the same summation order as the
    1-step `_var_step_kernel`, so variable-c layers keep the bitwise
    mixing contract (tests/test_kfused_varc.py).

    With `with_errors`, per-substep per-x-plane error maxes are stored as
    SMEM scalars (see the section comment for the factorization).
    """
    it = iter(refs)
    sxct_ref = next(it)
    f = compute_dtype
    c2_onion = _field_onion(it, f, has_field)
    uprev_ref, uc_ref = next(it), next(it)
    plo_ref, phi_ref = next(it), next(it)
    lo_ref, hi_ref = next(it), next(it)
    syz_ref, rsyz_ref = next(it), next(it)
    out_refs = list(it)
    if with_errors:
        out_prev_ref, out_ref, dmax_ref, rmax_ref = out_refs
    else:
        out_prev_ref, out_ref = out_refs
    i = pl.program_id(0)
    ix, iy, iz = (jnp.asarray(v, f) for v in inv_h2)
    prev = jnp.concatenate(
        [plo_ref[:].astype(f), uprev_ref[:].astype(f), phi_ref[:].astype(f)],
        0)
    cur = jnp.concatenate(
        [lo_ref[:].astype(f), uc_ref[:].astype(f), hi_ref[:].astype(f)], 0)
    syz = syz_ref[:]
    rsyz = rsyz_ref[:]
    ny, nz = syz.shape

    ym = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 1) != 0
    zm = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 2) != 0
    mask = ym & zm

    for s in range(1, k + 1):
        c = cur[1:-1]
        lap = (cur[:-2] + cur[2:] - 2.0 * c) * ix
        lap = lap + (
            pltpu.roll(c, 1, 1) + pltpu.roll(c, ny - 1, 1) - 2.0 * c
        ) * iy
        lap = lap + (
            pltpu.roll(c, 1, 2) + pltpu.roll(c, nz - 1, 2) - 2.0 * c
        ) * iz
        new = 2.0 * c + _substep_coeff(c2_onion, coeff, s, f) * lap \
            - prev[1:-1]
        new = jnp.where(mask, new, jnp.asarray(0.0, f))
        if out_ref.dtype != f:
            # A narrower state dtype (bf16) quantizes every stored layer on
            # the 1-step path; round-trip each substep so the k-fused
            # dynamics (and the observed errors) stay bitwise identical.
            new = new.astype(out_ref.dtype).astype(f)
        if with_errors:
            # Central bx planes of substep s sit at onion offset k - s.
            ctr = new[k - s: k - s + bx]
            for j in range(bx):
                diff = jnp.abs(ctr[j] - sxct_ref[s - 1, i * bx + j] * syz)
                dmax_ref[s - 1, i * bx + j] = jnp.max(diff)
                rmax_ref[s - 1, i * bx + j] = jnp.max(diff * rsyz)
        prev, cur = c, new

    out_prev_ref[:] = prev.astype(out_prev_ref.dtype)
    out_ref[:] = cur.astype(out_ref.dtype)


def fused_kstep(u_prev, u, syz, rsyz, sxct, *, k, coeff, inv_h2,
                c2tau2_field=None, block_x=None, interpret=False,
                with_errors=True, compute_dtype=None):
    """k temporally fused leapfrog steps of the full (N,N,N) state.

    Returns `(u_{n+k-1}, u_{n+k}, dmax, rmax)` where dmax/rmax are (k, N)
    per-substep per-x-plane error maxes (None, None without `with_errors`).
    `syz`/`rsyz` are the (N, N) oracle planes sy*sz and 1/|sy*sz| (0 at 0);
    `sxct` the (k, N) per-substep sx*ct row (any (k, N) f32 array when
    errors are off).  Requires N % k == 0 (wraparound halo blocks).

    With `c2tau2_field` (an (N,N,N) tau^2 c^2(x,y,z) array) the variable-c
    substep runs and `coeff` is ignored; the field rides its own slab +
    k-plane wraparound halos, matching the state onions' x extent.  Pair
    it with with_errors=False (the analytic oracle is constant-c only).
    """
    n = u.shape[0]
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(u.dtype)
    if n % k:
        raise ValueError(f"k={k} must divide N={n}")
    has_field = c2tau2_field is not None
    bx = block_x or choose_kstep_block(
        n, k, u.dtype.itemsize, field=has_field
    )
    if bx is None:
        raise ValueError(
            f"k={k} does not fit VMEM at N={n} (choose_kstep_block)"
        )
    if n % bx or bx % k:
        raise ValueError(f"block_x={bx} must divide N={n} and be a "
                         f"multiple of k={k}")
    slab = pl.BlockSpec((bx, n, n), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    # k-plane wraparound halos, indexed in units of k planes: the lower
    # halo starts at plane i*bx - k = k*(i*bx/k - 1), the upper at
    # (i+1)*bx; both divisible by k because k | bx.
    nb = n // k
    lo = pl.BlockSpec((k, n, n),
                      lambda i, _bk=bx // k, _nb=nb:
                      ((i * _bk - 1) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    hi = pl.BlockSpec((k, n, n),
                      lambda i, _bk=bx // k, _nb=nb:
                      (((i + 1) * _bk) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    plane = pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    kern = functools.partial(
        _kstep_kernel, k=k, bx=bx, coeff=coeff, inv_h2=inv_h2,
        compute_dtype=compute_dtype, with_errors=with_errors,
        has_field=has_field,
    )
    in_specs = [smem]
    operands = [sxct]
    if has_field:
        fld = jnp.asarray(c2tau2_field, dtype=compute_dtype)
        in_specs += [slab, lo, hi]
        operands += [fld, fld, fld]
    in_specs += [slab, slab, lo, hi, lo, hi, plane, plane]
    operands += [u_prev, u, u_prev, u_prev, u, u, syz, rsyz]
    state = jax.ShapeDtypeStruct(u.shape, u.dtype)
    out_specs = [slab, slab]
    out_shape = [state, state]
    if with_errors:
        out_specs += [smem, smem]
        out_shape += [jax.ShapeDtypeStruct((k, n), jnp.float32)] * 2
    out = pl.pallas_call(
        kern,
        grid=(n // bx,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=compat.CompilerParams(
            vmem_limit_bytes=_KSTEP_VMEM_LIMIT
        ),
        interpret=interpret,
    )(*operands)
    if with_errors:
        return out
    return out[0], out[1], None, None


def choose_kstep_comp_block(
    n: int, k: int, u_itemsize: int = 4, v_itemsize: int = 4,
    carry_itemsize: Optional[int] = 4, depth: Optional[int] = None,
    ghosts: bool = False, plane_elems: Optional[int] = None,
    field: bool = False,
) -> Optional[int]:
    """Slab depth for the compensated/velocity-form k-step kernel.

    Same shape as `choose_kstep_block` with the comp kernel's working set:
    u and v onions ride with k-plane halos (each at its own storage
    itemsize), the carry (when present) slab-only in and out, and the body
    holds ~3.2 onion-sized f32 temporaries regardless of carry (Mosaic
    recycles the U/V/C/lap/Kahan buffers down to that; calibrated on v5e
    against two measured programs: all-f32 carry k=4 bx=4 N=512 actual
    127.72 MB, and carry-less f32+bf16 k=4 bx=8 actual 134.91 MB - the
    latter is why bx=8 must be rejected there).  The carry-less
    coefficient carries an extra safety margin (3.4) because its
    rejection boundary was measured, not its acceptance.

    `depth` is the x extent being blocked (the shard depth for the
    sharded variant, default n); `ghosts=True` adds the sharded
    variant's 4 k-plane ghost buffers (u/v lo+hi; measured cost on v5e
    at N=512 k=4 bx=4: +20.9 MB over the ghost-less 127.72, i.e.
    ~1.25x the naive 2*k*state estimate - Mosaic double-buffers part of
    the constant-index fetches).  At N=512 that correctly rejects k=4
    for the sharded comp kernel (148.6 MB measured > 128); k=2 fits.

    `field=True` adds the variable-c onion (f32 slab + k-plane halos in
    the pipeline, one onion concat temp in the body; ghost fetches carry
    the same 1.25x factor as the state ghosts).  At N=512 the carry-less
    f32+bf16 increment form then fits k=2 (bx=4); k=4 models over the
    ceiling, as for the standard field onion (`choose_kstep_block`).
    """
    if depth is None:
        depth = n
    if plane_elems is None:
        plane_elems = n * n
    pb_f32 = plane_elems * 4
    state = u_itemsize + v_itemsize
    has_carry = carry_itemsize is not None
    best = None
    bx = k
    while bx <= 8 and bx <= depth:
        if depth % bx == 0:
            onion = bx + 2 * k
            pipeline = 2 * (onion + bx) * state * plane_elems
            if has_carry:
                pipeline += 2 * 2 * bx * carry_itemsize * plane_elems
            if ghosts:
                pipeline += 5 * k * state * plane_elems // 2
            planes = 4 * pb_f32
            temps = (315 if has_carry else 340) * onion * pb_f32 // 100
            if field:
                pipeline += 2 * onion * pb_f32
                if ghosts:
                    pipeline += 5 * k * pb_f32 // 2
                temps += onion * pb_f32
            if pipeline + planes + temps <= _KSTEP_COMP_VMEM_LIMIT:
                best = bx
        bx *= 2
    return best


def _kstep_comp_kernel(*refs, k, bx, coeff, inv_h2, compute_dtype,
                       with_errors, has_carry, has_field=False):
    """March k compensated (velocity-form) leapfrog substeps on a VMEM
    slab onion.

    Each substep is the Kahan two-sum update of `_comp_step_kernel`
    (semantics: stencil_ref.compensated_step): the increment
    v' = v + C*lap(u) accumulates in its own small-magnitude onion and
    u' = u + v' runs through the carry.  u and v march as shrinking
    onions exactly like `_kstep_kernel`; the carry rides slab-only with
    its halo planes seeded to ZERO - the halo-cone planes are discarded
    after the block, and their missing compensation re-enters the kept
    central planes only through coeff*lap of an ~ulp-sized smooth field
    (measured: no observable error delta vs the 1-step compensated path
    at N=512/1000 on v5e, both ~5.7e-6).  That approximation is the whole
    reason this fits VMEM where a 3-field full-onion Kahan scheme does
    not (solver/kfused.py's round-4 dead-end note).

    `has_carry=False` drops the carry entirely (plain increment form):
    the mode for a bf16 increment stream, where bf16 quantization of v
    dwarfs what a carry would recover.

    `has_field` threads the c^2tau^2 onion through the increment:
    v' = v + c^2tau^2(x,y,z)*lap(u) - the field coefficient enters the
    velocity form at exactly one multiply, so variable-c composes with
    the carry AND the bf16-increment mode unchanged.

    No bitwise parity with the 1-step path is claimed (unlike
    `_kstep_kernel`): intermediate layers skip the storage-dtype
    round-trip and halo carries differ - the contract is tolerance parity
    vs f64 (tests/test_kfused_comp.py).
    """
    it = iter(refs)
    sxct_ref = next(it)
    c2_onion = _field_onion(it, compute_dtype, has_field)
    u_ref, ulo_ref, uhi_ref = next(it), next(it), next(it)
    v_ref, vlo_ref, vhi_ref = next(it), next(it), next(it)
    carry_ref = next(it) if has_carry else None
    syz_ref, rsyz_ref = next(it), next(it)
    out = list(it)
    u_out, v_out = out[0], out[1]
    carry_out = out[2] if has_carry else None
    if with_errors:
        dmax_ref, rmax_ref = out[-2], out[-1]

    i = pl.program_id(0)
    f = compute_dtype
    ix, iy, iz = (jnp.asarray(val, f) for val in inv_h2)
    U = jnp.concatenate(
        [ulo_ref[:].astype(f), u_ref[:].astype(f), uhi_ref[:].astype(f)], 0)
    V = jnp.concatenate(
        [vlo_ref[:].astype(f), v_ref[:].astype(f), vhi_ref[:].astype(f)], 0)
    ny, nz = U.shape[1], U.shape[2]
    if has_carry:
        zpad = jnp.zeros((k, ny, nz), f)
        C = jnp.concatenate([zpad, carry_ref[:].astype(f), zpad], 0)

    ym = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 1) != 0
    zm = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 2) != 0
    mask = ym & zm

    syz = syz_ref[:]
    rsyz = rsyz_ref[:]

    for s in range(1, k + 1):
        uc = U[1:-1]
        lap = (U[:-2] + U[2:] - 2.0 * uc) * ix
        lap = lap + (
            pltpu.roll(uc, 1, 1) + pltpu.roll(uc, ny - 1, 1) - 2.0 * uc
        ) * iy
        lap = lap + (
            pltpu.roll(uc, 1, 2) + pltpu.roll(uc, nz - 1, 2) - 2.0 * uc
        ) * iz
        d = jnp.where(mask, _substep_coeff(c2_onion, coeff, s, f) * lap,
                      jnp.asarray(0.0, f))
        vn = V[1:-1] + d
        if has_carry:
            y = vn - C[1:-1]
        else:
            y = vn
        t = uc + y
        if has_carry:
            C = (t - uc) - y
        if with_errors:
            ctr = t[k - s: k - s + bx]
            for j in range(bx):
                diff = jnp.abs(ctr[j] - sxct_ref[s - 1, i * bx + j] * syz)
                # Error rows are f32 diagnostics regardless of the state
                # dtype (an f64 run's ~1e-13 errors round at 1e-7 relative).
                dmax_ref[s - 1, i * bx + j] = jnp.max(diff).astype(
                    jnp.float32)
                rmax_ref[s - 1, i * bx + j] = jnp.max(diff * rsyz).astype(
                    jnp.float32)
        U, V = t, vn

    u_out[:] = U.astype(u_out.dtype)
    v_out[:] = V.astype(v_out.dtype)
    if has_carry:
        carry_out[:] = C.astype(carry_out.dtype)


def fused_kstep_comp(u, v, carry, syz, rsyz, sxct, *, k, coeff, inv_h2,
                     c2tau2_field=None, block_x=None, interpret=False,
                     with_errors=True, compute_dtype=None):
    """k temporally fused compensated (velocity-form) leapfrog steps.

    State is `(u_n, v_n = u_n - u_{n-1}, carry_n)` as in
    `stencil_ref.compensated_step`; `carry=None` runs the carry-less
    increment form (e.g. bf16 v with f32 u).  Each field keeps its own
    storage dtype; compute is f32.  Returns `(u_{n+k}, v_{n+k},
    carry_{n+k} | None, dmax, rmax)` with the same (k, N) per-substep
    per-x-plane error rows as `fused_kstep`.  Requires N % k == 0.

    With `c2tau2_field` the increment uses the spatially varying
    coefficient (v' = v + c^2tau^2(x)*lap(u)) and `coeff` is ignored;
    pair it with with_errors=False (no analytic oracle).
    """
    n = u.shape[0]
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(u.dtype)
    if n % k:
        raise ValueError(f"k={k} must divide N={n}")
    has_carry = carry is not None
    has_field = c2tau2_field is not None
    bx = block_x or choose_kstep_comp_block(
        n, k, u.dtype.itemsize, v.dtype.itemsize,
        carry.dtype.itemsize if has_carry else None, field=has_field,
    )
    if bx is None:
        raise ValueError(
            f"k={k} does not fit VMEM at N={n} (choose_kstep_comp_block)"
        )
    if n % bx or bx % k:
        raise ValueError(f"block_x={bx} must divide N={n} and be a "
                         f"multiple of k={k}")
    slab = pl.BlockSpec((bx, n, n), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    nb = n // k
    lo = pl.BlockSpec((k, n, n),
                      lambda i, _bk=bx // k, _nb=nb:
                      ((i * _bk - 1) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    hi = pl.BlockSpec((k, n, n),
                      lambda i, _bk=bx // k, _nb=nb:
                      (((i + 1) * _bk) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    plane = pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    kern = functools.partial(
        _kstep_comp_kernel, k=k, bx=bx, coeff=coeff, inv_h2=inv_h2,
        compute_dtype=compute_dtype, with_errors=with_errors,
        has_carry=has_carry, has_field=has_field,
    )
    in_specs = [smem]
    operands = [sxct]
    if has_field:
        fld = jnp.asarray(c2tau2_field, dtype=compute_dtype)
        in_specs += [slab, lo, hi]
        operands += [fld, fld, fld]
    in_specs += [slab, lo, hi, slab, lo, hi]
    operands += [u, u, u, v, v, v]
    if has_carry:
        in_specs.append(slab)
        operands.append(carry)
    in_specs += [plane, plane]
    operands += [syz, rsyz]
    out_specs = [slab, slab]
    out_shape = [jax.ShapeDtypeStruct(u.shape, u.dtype),
                 jax.ShapeDtypeStruct(v.shape, v.dtype)]
    if has_carry:
        out_specs.append(slab)
        out_shape.append(jax.ShapeDtypeStruct(carry.shape, carry.dtype))
    if with_errors:
        out_specs += [smem, smem]
        out_shape += [jax.ShapeDtypeStruct((k, n), jnp.float32)] * 2
    out = pl.pallas_call(
        kern,
        grid=(n // bx,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=compat.CompilerParams(
            vmem_limit_bytes=_KSTEP_COMP_VMEM_LIMIT
        ),
        interpret=interpret,
    )(*operands)
    u_o, v_o = out[0], out[1]
    c_o = out[2] if has_carry else None
    if with_errors:
        return u_o, v_o, c_o, out[-2], out[-1]
    return u_o, v_o, c_o, None, None


def _kstep_comp_sharded_kernel(*refs, k, bx, coeff, inv_h2,
                               compute_dtype, with_errors, has_carry,
                               has_field=False):
    """`_kstep_comp_kernel` for an x-sharded block: the k-plane u/v halos
    of the block's EDGE programs come from ppermute'd ghost operands
    instead of the in-block wraparound (the `pick` of
    `_kstep_sharded_kernel`).  Carry stays slab-only with zero-seeded
    halos - the same approximation as the single-device comp onion, so
    for a shared block_x the per-plane op sequence is identical across
    mesh shapes.  NO strict bitwise pin is claimed (unlike the standard
    sharded onion): sub-f32-ulp value noise at the representation-zero
    sx plane can flip rounding ties, so cross-mesh agreement is
    ulp-level, pinned at tolerance with bitwise-equal error rows
    (tests/test_kfused_comp.py) - within the scheme's tolerance-vs-f64
    contract."""
    it = iter(refs)
    sxct_ref = next(it)
    c2_refs = (
        [next(it) for _ in range(5)] if has_field else None
    )
    u_ref, ulo_ref, uhi_ref = next(it), next(it), next(it)
    uglo_ref, ughi_ref = next(it), next(it)
    v_ref, vlo_ref, vhi_ref = next(it), next(it), next(it)
    vglo_ref, vghi_ref = next(it), next(it)
    carry_ref = next(it) if has_carry else None
    syz_ref, rsyz_ref = next(it), next(it)
    out = list(it)
    u_out, v_out = out[0], out[1]
    carry_out = out[2] if has_carry else None
    if with_errors:
        dmax_ref, rmax_ref = out[-2], out[-1]

    i = pl.program_id(0)
    last = pl.num_programs(0) - 1
    f = compute_dtype
    ix, iy, iz = (jnp.asarray(val, f) for val in inv_h2)

    def pick(edge_is_lo, ghost_ref, wrap_ref):
        at_edge = (i == 0) if edge_is_lo else (i == last)
        return jnp.where(
            at_edge, ghost_ref[:].astype(f), wrap_ref[:].astype(f)
        )

    c2_onion = _sharded_field_onion(iter(c2_refs), pick, f, has_field) \
        if has_field else None
    U = jnp.concatenate([
        pick(True, uglo_ref, ulo_ref),
        u_ref[:].astype(f),
        pick(False, ughi_ref, uhi_ref),
    ], 0)
    V = jnp.concatenate([
        pick(True, vglo_ref, vlo_ref),
        v_ref[:].astype(f),
        pick(False, vghi_ref, vhi_ref),
    ], 0)
    ny, nz = U.shape[1], U.shape[2]
    if has_carry:
        zpad = jnp.zeros((k, ny, nz), f)
        C = jnp.concatenate([zpad, carry_ref[:].astype(f), zpad], 0)

    ym = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 1) != 0
    zm = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 2) != 0
    mask = ym & zm
    syz = syz_ref[:]
    rsyz = rsyz_ref[:]

    for s in range(1, k + 1):
        uc = U[1:-1]
        lap = (U[:-2] + U[2:] - 2.0 * uc) * ix
        lap = lap + (
            pltpu.roll(uc, 1, 1) + pltpu.roll(uc, ny - 1, 1) - 2.0 * uc
        ) * iy
        lap = lap + (
            pltpu.roll(uc, 1, 2) + pltpu.roll(uc, nz - 1, 2) - 2.0 * uc
        ) * iz
        d = jnp.where(mask, _substep_coeff(c2_onion, coeff, s, f) * lap,
                      jnp.asarray(0.0, f))
        vn = V[1:-1] + d
        if has_carry:
            y = vn - C[1:-1]
        else:
            y = vn
        t = uc + y
        if has_carry:
            C = (t - uc) - y
        if with_errors:
            ctr = t[k - s: k - s + bx]
            for j in range(bx):
                diff = jnp.abs(ctr[j] - sxct_ref[s - 1, i * bx + j] * syz)
                dmax_ref[s - 1, i * bx + j] = jnp.max(diff).astype(
                    jnp.float32)
                rmax_ref[s - 1, i * bx + j] = jnp.max(diff * rsyz).astype(
                    jnp.float32)
        U, V = t, vn

    u_out[:] = U.astype(u_out.dtype)
    v_out[:] = V.astype(v_out.dtype)
    if has_carry:
        carry_out[:] = C.astype(carry_out.dtype)


def fused_kstep_comp_sharded(u, v, carry, u_ghosts, v_ghosts, syz, rsyz,
                             sxct, *, k, coeff, inv_h2, c2tau2_block=None,
                             c2_ghosts=None, block_x=None,
                             interpret=False, with_errors=True,
                             compute_dtype=None):
    """k fused compensated (velocity-form) leapfrog steps of one
    x-sharded block - the distributed flagship scheme.

    Must run inside `shard_map` on a (P, 1, 1) mesh.  `u`/`v`/`carry`
    are local (N/P, N, N) blocks (carry=None for the carry-less
    increment form); `u_ghosts`/`v_ghosts` are ((k, N, N) lo, hi) pairs
    ppermute'd from the cyclic x-neighbours BEFORE the call, exactly as
    `fused_kstep_sharded`.  `sxct` is this shard's (k, N/P) oracle row
    slice.  Returns `(u', v', carry'|None, dmax, rmax)` with (k, N/P)
    local error rows.

    `c2tau2_block`/`c2_ghosts` thread this shard's tau^2 c^2 slice (and
    its once-per-solve k-plane ghost pair) through the increment, as
    `fused_kstep_sharded`.
    """
    nl = u.shape[0]
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(u.dtype)
    if nl % k:
        raise ValueError(f"k={k} must divide the shard depth {nl}")
    has_carry = carry is not None
    has_field = c2tau2_block is not None
    bx = block_x or choose_kstep_comp_block(
        u.shape[1], k, u.dtype.itemsize, v.dtype.itemsize,
        carry.dtype.itemsize if has_carry else None,
        depth=nl, ghosts=True, field=has_field,
    )
    if bx is None:
        raise ValueError(
            f"k={k} does not fit VMEM for {u.shape} shards "
            f"(choose_kstep_comp_block)"
        )
    if nl % bx or bx % k:
        raise ValueError(f"block_x={bx} must divide the shard depth {nl} "
                         f"and be a multiple of k={k}")
    ny, nz = u.shape[1], u.shape[2]
    slab = pl.BlockSpec((bx, ny, nz), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    nb = nl // k
    lo = pl.BlockSpec((k, ny, nz),
                      lambda i, _bk=bx // k, _nb=nb:
                      ((i * _bk - 1) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    hi = pl.BlockSpec((k, ny, nz),
                      lambda i, _bk=bx // k, _nb=nb:
                      (((i + 1) * _bk) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    ghost = pl.BlockSpec((k, ny, nz), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM)
    plane = pl.BlockSpec((ny, nz), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    kern = functools.partial(
        _kstep_comp_sharded_kernel, k=k, bx=bx, coeff=coeff,
        inv_h2=inv_h2, compute_dtype=compute_dtype,
        with_errors=with_errors, has_carry=has_carry,
        has_field=has_field,
    )
    in_specs = [smem]
    operands = [sxct]
    if has_field:
        fld = jnp.asarray(c2tau2_block, dtype=compute_dtype)
        in_specs += [slab, lo, hi, ghost, ghost]
        operands += [fld, fld, fld, c2_ghosts[0], c2_ghosts[1]]
    in_specs += [slab, lo, hi, ghost, ghost,
                 slab, lo, hi, ghost, ghost]
    operands += [u, u, u, u_ghosts[0], u_ghosts[1],
                 v, v, v, v_ghosts[0], v_ghosts[1]]
    if has_carry:
        in_specs.append(slab)
        operands.append(carry)
    in_specs += [plane, plane]
    operands += [syz, rsyz]
    out_specs = [slab, slab]
    out_shape = [_out_struct(u), _out_struct(v)]
    if has_carry:
        out_specs.append(slab)
        out_shape.append(_out_struct(carry))
    if with_errors:
        err = _out_struct(u, shape=(k, nl), dtype=jnp.float32)
        out_specs += [smem, smem]
        out_shape += [err, err]
    out = pl.pallas_call(
        kern,
        grid=(nl // bx,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=compat.CompilerParams(
            vmem_limit_bytes=_KSTEP_COMP_VMEM_LIMIT
        ),
        interpret=interpret,
    )(*operands)
    u_o, v_o = out[0], out[1]
    c_o = out[2] if has_carry else None
    if with_errors:
        return u_o, v_o, c_o, out[-2], out[-1]
    return u_o, v_o, c_o, None, None


def _kstep_comp_sharded_xy_kernel(*refs, k, bx, nl_y, n_global, coeff,
                                  inv_h2, compute_dtype, with_errors,
                                  has_carry, has_field=False):
    """`_kstep_comp_sharded_kernel` for blocks ALSO sharded along y.

    u and v arrive pre-extended with k ghost ROWS per side (width
    W = nl_y + 2k) and their x ghosts are ppermute'd FROM the extended
    blocks (corner data rides the sequencing, as in
    `_kstep_sharded_xy_kernel`); the carry stays central (nl_y rows),
    zero-seeded in both the x halo planes and the y ghost rows.  The
    increment mask tests the WRAPPED global row index ((y0 - k + row)
    mod N != 0) so evolved ghost copies of the global y=0 stored zero
    plane never leak nonzero increments.  Outputs and error rows slice
    the central y rows (callers pmax rows over the y mesh axis).
    """
    it = iter(refs)
    y0_ref = next(it)
    sxct_ref = next(it)
    c2_refs = (
        [next(it) for _ in range(5)] if has_field else None
    )
    u_ref, ulo_ref, uhi_ref = next(it), next(it), next(it)
    uglo_ref, ughi_ref = next(it), next(it)
    v_ref, vlo_ref, vhi_ref = next(it), next(it), next(it)
    vglo_ref, vghi_ref = next(it), next(it)
    carry_ref = next(it) if has_carry else None
    syzc_ref, rsyzc_ref = next(it), next(it)
    out = list(it)
    u_out, v_out = out[0], out[1]
    carry_out = out[2] if has_carry else None
    if with_errors:
        dmax_ref, rmax_ref = out[-2], out[-1]

    i = pl.program_id(0)
    last = pl.num_programs(0) - 1
    f = compute_dtype
    ix, iy, iz = (jnp.asarray(val, f) for val in inv_h2)

    def pick(edge_is_lo, ghost_ref, wrap_ref):
        at_edge = (i == 0) if edge_is_lo else (i == last)
        return jnp.where(
            at_edge, ghost_ref[:].astype(f), wrap_ref[:].astype(f)
        )

    c2_onion = _sharded_field_onion(iter(c2_refs), pick, f, has_field) \
        if has_field else None
    U = jnp.concatenate([
        pick(True, uglo_ref, ulo_ref),
        u_ref[:].astype(f),
        pick(False, ughi_ref, uhi_ref),
    ], 0)
    V = jnp.concatenate([
        pick(True, vglo_ref, vlo_ref),
        v_ref[:].astype(f),
        pick(False, vghi_ref, vhi_ref),
    ], 0)
    w, nz = U.shape[1], U.shape[2]
    if has_carry:
        cpad_x = jnp.zeros((k, w, nz), f)
        cc = carry_ref[:].astype(f)
        cpad_y = jnp.zeros((cc.shape[0], k, nz), f)
        C = jnp.concatenate([
            cpad_x,
            jnp.concatenate([cpad_y, cc, cpad_y], 1),
            cpad_x,
        ], 0)

    gy = (y0_ref[0] - k + lax.broadcasted_iota(jnp.int32, (1, w, nz), 1))
    gy = gy % n_global
    zm = lax.broadcasted_iota(jnp.int32, (1, w, nz), 2) != 0
    mask = (gy != 0) & zm

    for s in range(1, k + 1):
        uc = U[1:-1]
        lap = (U[:-2] + U[2:] - 2.0 * uc) * ix
        lap = lap + (
            pltpu.roll(uc, 1, 1) + pltpu.roll(uc, w - 1, 1) - 2.0 * uc
        ) * iy
        lap = lap + (
            pltpu.roll(uc, 1, 2) + pltpu.roll(uc, nz - 1, 2) - 2.0 * uc
        ) * iz
        d = jnp.where(mask, _substep_coeff(c2_onion, coeff, s, f) * lap,
                      jnp.asarray(0.0, f))
        vn = V[1:-1] + d
        if has_carry:
            y = vn - C[1:-1]
        else:
            y = vn
        t = uc + y
        if has_carry:
            C = (t - uc) - y
        if with_errors:
            ctr = t[k - s: k - s + bx, k: k + nl_y]
            syz = syzc_ref[:]
            rsyz = rsyzc_ref[:]
            for j in range(bx):
                diff = jnp.abs(ctr[j] - sxct_ref[s - 1, i * bx + j] * syz)
                dmax_ref[s - 1, i * bx + j] = jnp.max(diff).astype(
                    jnp.float32)
                rmax_ref[s - 1, i * bx + j] = jnp.max(diff * rsyz).astype(
                    jnp.float32)
        U, V = t, vn

    u_out[:] = U[:, k: k + nl_y].astype(u_out.dtype)
    v_out[:] = V[:, k: k + nl_y].astype(v_out.dtype)
    if has_carry:
        carry_out[:] = C[:, k: k + nl_y].astype(carry_out.dtype)


def fused_kstep_comp_sharded_xy(u_ext, v_ext, carry, u_ghosts, v_ghosts,
                                syz_c, rsyz_c, sxct, y0, n_global, *,
                                k, nl_y, coeff, inv_h2, c2tau2_ext=None,
                                c2_ghosts=None, block_x=None,
                                interpret=False, with_errors=True,
                                compute_dtype=None):
    """k fused compensated (velocity-form) steps of an (x, y)-sharded
    block - the distributed flagship on 2D meshes.

    Must run inside `shard_map` on a (P, Q, 1) mesh.  `u_ext`/`v_ext`
    are local blocks pre-extended with k ghost rows per y side;
    `carry` is the CENTRAL (nl_x, nl_y, nz) block (or None for the
    increment form); `u_ghosts`/`v_ghosts` are ((k, W, nz) lo, hi)
    x-ghost pairs ppermute'd from the extended blocks.  Returns central
    (nl_x, nl_y, nz) state + (k, nl_x) error rows (max over this
    shard's y range; callers pmax over the y axis).  y-sharding shrinks
    every VMEM plane by Q, which is what lets k=4 fit at N=512 where
    the x-only variant is VMEM-bound at k=2.

    `c2tau2_ext`/`c2_ghosts` thread the y-extended field block and its
    once-per-solve x-ghost pair through the increment
    (`fused_kstep_sharded_xy` semantics).
    """
    nl_x, w, nz = u_ext.shape
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(u_ext.dtype)
    if w != nl_y + 2 * k:
        raise ValueError(
            f"extended y width {w} != nl_y + 2k = {nl_y + 2 * k}"
        )
    if nl_x % k:
        raise ValueError(f"k={k} must divide the shard depth {nl_x}")
    has_carry = carry is not None
    has_field = c2tau2_ext is not None
    bx = block_x or choose_kstep_comp_block(
        nz, k, u_ext.dtype.itemsize, v_ext.dtype.itemsize,
        carry.dtype.itemsize if has_carry else None,
        depth=nl_x, ghosts=True, plane_elems=w * nz, field=has_field,
    )
    if bx is None:
        raise ValueError(
            f"k={k} does not fit VMEM for {u_ext.shape} blocks"
        )
    if nl_x % bx or bx % k:
        raise ValueError(f"block_x={bx} must divide the shard depth "
                         f"{nl_x} and be a multiple of k={k}")
    slab = pl.BlockSpec((bx, w, nz), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    nb = nl_x // k
    lo = pl.BlockSpec((k, w, nz),
                      lambda i, _bk=bx // k, _nb=nb:
                      ((i * _bk - 1) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    hi = pl.BlockSpec((k, w, nz),
                      lambda i, _bk=bx // k, _nb=nb:
                      (((i + 1) * _bk) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    ghost = pl.BlockSpec((k, w, nz), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM)
    cslab = pl.BlockSpec((bx, nl_y, nz), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
    plane = pl.BlockSpec((nl_y, nz), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    kern = functools.partial(
        _kstep_comp_sharded_xy_kernel, k=k, bx=bx, nl_y=nl_y,
        n_global=n_global, coeff=coeff, inv_h2=inv_h2,
        compute_dtype=compute_dtype, with_errors=with_errors,
        has_carry=has_carry, has_field=has_field,
    )
    in_specs = [smem, smem]
    operands = [jnp.asarray(y0, jnp.int32).reshape(1), sxct]
    if has_field:
        fld = jnp.asarray(c2tau2_ext, dtype=compute_dtype)
        in_specs += [slab, lo, hi, ghost, ghost]
        operands += [fld, fld, fld, c2_ghosts[0], c2_ghosts[1]]
    in_specs += [slab, lo, hi, ghost, ghost,
                 slab, lo, hi, ghost, ghost]
    operands += [u_ext, u_ext, u_ext, u_ghosts[0], u_ghosts[1],
                 v_ext, v_ext, v_ext, v_ghosts[0], v_ghosts[1]]
    if has_carry:
        in_specs.append(cslab)
        operands.append(carry)
    in_specs += [plane, plane]
    operands += [syz_c, rsyz_c]
    state = _out_struct(u_ext, shape=(nl_x, nl_y, nz))
    vstate = _out_struct(v_ext, shape=(nl_x, nl_y, nz),
                         dtype=v_ext.dtype)
    out_specs = [cslab, cslab]
    out_shape = [state, vstate]
    if has_carry:
        out_specs.append(cslab)
        out_shape.append(_out_struct(carry))
    if with_errors:
        err = _out_struct(u_ext, shape=(k, nl_x), dtype=jnp.float32)
        out_specs += [smem, smem]
        out_shape += [err, err]
    out = pl.pallas_call(
        kern,
        grid=(nl_x // bx,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=compat.CompilerParams(
            vmem_limit_bytes=_KSTEP_COMP_VMEM_LIMIT
        ),
        interpret=interpret,
    )(*operands)
    u_o, v_o = out[0], out[1]
    c_o = out[2] if has_carry else None
    if with_errors:
        return u_o, v_o, c_o, out[-2], out[-1]
    return u_o, v_o, c_o, None, None


def _sharded_field_onion(it, pick, f, has_field):
    """Assemble the c^2tau^2 onion for a sharded onion kernel from the
    next five refs (slab, wraparound lo/hi, ghost lo/hi), with the edge
    programs' halos ghost-overridden exactly like the state onions."""
    if not has_field:
        return None
    c2_ref = next(it)
    c2lo_ref, c2hi_ref = next(it), next(it)
    c2glo_ref, c2ghi_ref = next(it), next(it)
    return jnp.concatenate([
        pick(True, c2glo_ref, c2lo_ref),
        c2_ref[:].astype(f),
        pick(False, c2ghi_ref, c2hi_ref),
    ], 0)


def _kstep_sharded_kernel(*refs, k, bx, coeff, inv_h2, compute_dtype,
                          with_errors, has_field=False):
    """`_kstep_kernel` for an x-sharded block: the k-plane halos of the
    block's EDGE programs come from the ppermute'd ghost operands (the
    neighbouring shard's boundary planes) instead of the in-block
    wraparound - interior programs are untouched, so a 1-shard mesh
    compiles to the single-device onion's data path.  y/z stay full-domain
    per shard (x-only decomposition), so the in-VMEM rolls and the fused
    Dirichlet mask are exactly the single-device kernel's.  `has_field`
    adds the c^2tau^2 onion (slab + wraparound halos + edge ghosts) as in
    `_kstep_kernel`."""
    it = iter(refs)
    sxct_ref = next(it)
    i = pl.program_id(0)
    last = pl.num_programs(0) - 1
    f = compute_dtype
    ix, iy, iz = (jnp.asarray(v, f) for v in inv_h2)

    def pick(edge_is_lo, ghost_ref, wrap_ref):
        at_edge = (i == 0) if edge_is_lo else (i == last)
        return jnp.where(
            at_edge, ghost_ref[:].astype(f), wrap_ref[:].astype(f)
        )

    c2_onion = _sharded_field_onion(it, pick, f, has_field)
    uprev_ref, uc_ref = next(it), next(it)
    plo_ref, phi_ref = next(it), next(it)
    lo_ref, hi_ref = next(it), next(it)
    pglo_ref, pghi_ref = next(it), next(it)
    glo_ref, ghi_ref = next(it), next(it)
    syz_ref, rsyz_ref = next(it), next(it)
    out_refs = list(it)
    if with_errors:
        out_prev_ref, out_ref, dmax_ref, rmax_ref = out_refs
    else:
        out_prev_ref, out_ref = out_refs

    prev = jnp.concatenate([
        pick(True, pglo_ref, plo_ref),
        uprev_ref[:].astype(f),
        pick(False, pghi_ref, phi_ref),
    ], 0)
    cur = jnp.concatenate([
        pick(True, glo_ref, lo_ref),
        uc_ref[:].astype(f),
        pick(False, ghi_ref, hi_ref),
    ], 0)
    syz = syz_ref[:]
    rsyz = rsyz_ref[:]
    ny, nz = syz.shape

    ym = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 1) != 0
    zm = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 2) != 0
    mask = ym & zm

    for s in range(1, k + 1):
        c = cur[1:-1]
        lap = (cur[:-2] + cur[2:] - 2.0 * c) * ix
        lap = lap + (
            pltpu.roll(c, 1, 1) + pltpu.roll(c, ny - 1, 1) - 2.0 * c
        ) * iy
        lap = lap + (
            pltpu.roll(c, 1, 2) + pltpu.roll(c, nz - 1, 2) - 2.0 * c
        ) * iz
        new = 2.0 * c + _substep_coeff(c2_onion, coeff, s, f) * lap \
            - prev[1:-1]
        new = jnp.where(mask, new, jnp.asarray(0.0, f))
        if out_ref.dtype != f:
            new = new.astype(out_ref.dtype).astype(f)
        if with_errors:
            ctr = new[k - s: k - s + bx]
            for j in range(bx):
                diff = jnp.abs(ctr[j] - sxct_ref[s - 1, i * bx + j] * syz)
                dmax_ref[s - 1, i * bx + j] = jnp.max(diff)
                rmax_ref[s - 1, i * bx + j] = jnp.max(diff * rsyz)
        prev, cur = c, new

    out_prev_ref[:] = prev.astype(out_prev_ref.dtype)
    out_ref[:] = cur.astype(out_ref.dtype)


def fused_kstep_sharded(u_prev, u, prev_ghosts, cur_ghosts, syz, rsyz, sxct,
                        *, k, coeff, inv_h2, c2tau2_block=None,
                        c2_ghosts=None, block_x=None, interpret=False,
                        with_errors=True, compute_dtype=None):
    """k temporally fused leapfrog steps of one x-sharded block.

    Must run inside `shard_map` on a (P, 1, 1) mesh.  `u_prev`/`u` are the
    local (N/P, N, N) block; `prev_ghosts`/`cur_ghosts` are ((k, N, N)
    lo, hi) pairs ppermute'd from the cyclic x-neighbours BEFORE the call
    (the reference's per-rank exchange-then-kernel shape,
    mpi_new.cpp:327-352, with the exchange amortized over k layers).
    `sxct` is this shard's (k, N/P) oracle row slice.  Returns the same
    tuple as `fused_kstep` with (k, N/P)-local error rows.

    With `c2tau2_block` (this shard's tau^2 c^2 slice) and `c2_ghosts`
    (its (lo, hi) k-plane ghost pair - the field is time-invariant, so the
    solver exchanges these ONCE per solve, not per block) the variable-c
    substep runs and `coeff` is ignored.
    """
    nl = u.shape[0]
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(u.dtype)
    if nl % k:
        raise ValueError(f"k={k} must divide the shard depth {nl}")
    has_field = c2tau2_block is not None
    bx = block_x or choose_kstep_block(
        u.shape[1], k, u.dtype.itemsize, depth=nl, ghosts=True,
        field=has_field,
    )
    if bx is None:
        raise ValueError(
            f"k={k} does not fit VMEM for {u.shape} shards"
        )
    if nl % bx or bx % k:
        raise ValueError(f"block_x={bx} must divide the shard depth {nl} "
                         f"and be a multiple of k={k}")
    ny, nz = u.shape[1], u.shape[2]
    slab = pl.BlockSpec((bx, ny, nz), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    nb = nl // k
    lo = pl.BlockSpec((k, ny, nz),
                      lambda i, _bk=bx // k, _nb=nb:
                      ((i * _bk - 1) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    hi = pl.BlockSpec((k, ny, nz),
                      lambda i, _bk=bx // k, _nb=nb:
                      (((i + 1) * _bk) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    # Ghost operands: constant index map, so the pipeline fetches them once.
    ghost = pl.BlockSpec((k, ny, nz), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM)
    plane = pl.BlockSpec((ny, nz), lambda i: (0, 0), memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    kern = functools.partial(
        _kstep_sharded_kernel, k=k, bx=bx, coeff=coeff, inv_h2=inv_h2,
        compute_dtype=compute_dtype, with_errors=with_errors,
        has_field=has_field,
    )
    in_specs = [smem]
    operands = [sxct]
    if has_field:
        fld = jnp.asarray(c2tau2_block, dtype=compute_dtype)
        in_specs += [slab, lo, hi, ghost, ghost]
        operands += [fld, fld, fld, c2_ghosts[0], c2_ghosts[1]]
    in_specs += [slab, slab, lo, hi, lo, hi, ghost, ghost, ghost, ghost,
                 plane, plane]
    operands += [u_prev, u, u_prev, u_prev, u, u,
                 prev_ghosts[0], prev_ghosts[1],
                 cur_ghosts[0], cur_ghosts[1], syz, rsyz]
    state = _out_struct(u)
    out_specs = [slab, slab]
    out_shape = [state, state]
    if with_errors:
        err = _out_struct(u, shape=(k, nl), dtype=jnp.float32)
        out_specs += [smem, smem]
        out_shape += [err, err]
    out = pl.pallas_call(
        kern,
        grid=(nl // bx,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=compat.CompilerParams(
            vmem_limit_bytes=_KSTEP_VMEM_LIMIT
        ),
        interpret=interpret,
    )(*operands)
    if with_errors:
        return out
    return out[0], out[1], None, None


def _kstep_padded_kernel(*refs, k, bx, bk, coeff, inv_h2, compute_dtype,
                         with_errors, has_field=False):
    """k leapfrog substeps of an x-sharded block with UNEVEN real depth.

    Operands are pre-assembled extended arrays (see
    `fused_kstep_padded`): ext = [k lo-ghost planes | D local planes |
    k junk planes], with the k hi-ghost planes written INTO the array at
    offset k + n_real - so the x-neighbour chain of every real plane is
    gap-free (the pad planes that would sit between the last real plane
    and the ghosts in HBM layout are displaced past the ghosts, where no
    real plane's k-cone reaches; junk beyond k + n_real + k is never
    consumed).  Each program fetches its onion window as bk + 2
    contiguous k-plane blocks of ext per field.

    Consequences vs `_kstep_sharded_kernel`: no edge `pick` (ghosts are
    baked into ext), no mid-onion x-mask (ghost slots hold REAL planes
    that must keep evolving; the junk zone is never read by real cones),
    and the store masks pad planes (local index >= n_real) to keep the
    zero-pad carry invariant.  Per-plane op order is identical to
    `_kstep_kernel`, so real planes stay bitwise equal to the 1-step
    pallas path (tests/test_sharded_kfused.py uneven cases).

    `has_field` adds bk+2 c^2tau^2 parts assembled IDENTICALLY to the
    state ext (lo ghosts | D planes | hi spliced at the real boundary,
    zero junk - a zero coefficient keeps the junk zone finite), read as
    the static per-substep onion slice.
    """
    it = iter(refs)
    nreal_ref = next(it)                       # SMEM (1,) int32
    sxct_ref = next(it)                        # SMEM (k, D)
    prev_parts = [next(it) for _ in range(bk + 2)]
    cur_parts = [next(it) for _ in range(bk + 2)]
    f = compute_dtype
    if has_field:
        c2_onion = jnp.concatenate(
            [next(it)[:].astype(f) for _ in range(bk + 2)], 0
        )
    else:
        c2_onion = None
    syz_ref, rsyz_ref = next(it), next(it)
    out = list(it)
    out_prev_ref, out_ref = out[0], out[1]
    if with_errors:
        dmax_ref, rmax_ref = out[2], out[3]

    i = pl.program_id(0)
    n_real = nreal_ref[0]
    ix, iy, iz = (jnp.asarray(v, f) for v in inv_h2)
    prev = jnp.concatenate([p[:].astype(f) for p in prev_parts], 0)
    cur = jnp.concatenate([p[:].astype(f) for p in cur_parts], 0)
    ny, nz = cur.shape[1], cur.shape[2]
    syz = syz_ref[:]
    rsyz = rsyz_ref[:]

    ym = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 1) != 0
    zm = lax.broadcasted_iota(jnp.int32, (1, ny, nz), 2) != 0
    mask = ym & zm

    for s in range(1, k + 1):
        c = cur[1:-1]
        lap = (cur[:-2] + cur[2:] - 2.0 * c) * ix
        lap = lap + (
            pltpu.roll(c, 1, 1) + pltpu.roll(c, ny - 1, 1) - 2.0 * c
        ) * iy
        lap = lap + (
            pltpu.roll(c, 1, 2) + pltpu.roll(c, nz - 1, 2) - 2.0 * c
        ) * iz
        new = 2.0 * c + _substep_coeff(c2_onion, coeff, s, f) * lap \
            - prev[1:-1]
        new = jnp.where(mask, new, jnp.asarray(0.0, f))
        if out_ref.dtype != f:
            new = new.astype(out_ref.dtype).astype(f)
        if with_errors:
            ctr = new[k - s: k - s + bx]
            for j in range(bx):
                col = i * bx + j
                # Pad columns must emit 0: their mid-onion values hold
                # displaced ghost planes (real data at the wrong x), and
                # their sxct is zero-padded.
                real = col < n_real
                diff = jnp.abs(ctr[j] - sxct_ref[s - 1, col] * syz)
                dmax_ref[s - 1, col] = jnp.where(
                    real, jnp.max(diff), 0.0
                ).astype(jnp.float32)
                rmax_ref[s - 1, col] = jnp.where(
                    real, jnp.max(diff * rsyz), 0.0
                ).astype(jnp.float32)
        prev, cur = c, new

    px = (
        i * bx + lax.broadcasted_iota(jnp.int32, (bx, 1, 1), 0)
    ) < n_real
    out_prev_ref[:] = jnp.where(
        px, prev, jnp.asarray(0.0, f)
    ).astype(out_prev_ref.dtype)
    out_ref[:] = jnp.where(
        px, cur, jnp.asarray(0.0, f)
    ).astype(out_ref.dtype)


def fused_kstep_padded(ext_prev, ext_cur, n_real, syz, rsyz, sxct, *,
                       k, coeff, inv_h2, ext_c2=None, block_x,
                       interpret=False, with_errors=True,
                       compute_dtype=None):
    """k fused leapfrog steps of an uneven (pad-and-mask) x-sharded block.

    Must run inside `shard_map` on an (MX, 1, 1) mesh (MX = 1 works too:
    the caller assembles ghosts from local slices).  `ext_prev`/`ext_cur`
    are (D + 2k, ny, nz) extended blocks: k exchanged lo-ghost planes,
    the D-plane padded local block with the k hi-ghost planes written at
    offset k + n_real (comm assembly in solver/sharded_kfused.py), and k
    trailing junk planes.  `n_real` is this shard's real-plane count as
    an int32 scalar array; `sxct` the (k, D) local oracle rows
    (zero-padded columns).  Returns (u_prev, u) as (D, ny, nz) blocks
    with pad planes zeroed, plus (k, D) error rows (zero at pad
    columns).  `block_x` is required (the caller owns the D/bx/VMEM
    trade; k must divide block_x, block_x must divide D).

    This is the remainder-folding analog of the reference
    (mpi_sol.cpp:417-421) for the temporally blocked path; the even-N
    point-to-point path (`fused_kstep_sharded`) remains the flagship
    fast path.  k=1 degenerates to a 1-step padded update (used for the
    bootstrap and the remainder tail).

    `ext_c2` is the c^2tau^2 field assembled exactly like `ext_prev`
    (same lo-ghost/hi-splice layout; the field is time-invariant, so the
    solver builds it once per solve); with it the variable-c substep runs
    and `coeff` is ignored.
    """
    dtot, ny, nz = ext_cur.shape
    bx = block_x
    d = dtot - 2 * k
    has_field = ext_c2 is not None
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(ext_cur.dtype)
    if d % bx or bx % k:
        raise ValueError(f"block_x={bx} must divide the padded depth {d} "
                         f"and be a multiple of k={k}")
    bk = bx // k
    parts = [
        pl.BlockSpec((k, ny, nz),
                     (lambda t: (lambda i, _bk=bk, _t=t:
                                 (i * _bk + _t, 0, 0)))(t),
                     memory_space=pltpu.VMEM)
        for t in range(bk + 2)
    ]
    out_slab = pl.BlockSpec((bx, ny, nz), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    plane = pl.BlockSpec((ny, nz), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    kern = functools.partial(
        _kstep_padded_kernel, k=k, bx=bx, bk=bk, coeff=coeff,
        inv_h2=inv_h2, compute_dtype=compute_dtype,
        with_errors=with_errors, has_field=has_field,
    )
    state = _out_struct(ext_cur, shape=(d, ny, nz))
    out_specs = [out_slab, out_slab]
    out_shape = [state, state]
    if with_errors:
        err = _out_struct(ext_cur, shape=(k, d), dtype=jnp.float32)
        out_specs += [smem, smem]
        out_shape += [err, err]
    in_specs = [smem, smem] + parts + parts
    operands = (
        [jnp.asarray(n_real, jnp.int32).reshape(1), sxct]
        + [ext_prev] * (bk + 2) + [ext_cur] * (bk + 2)
    )
    if has_field:
        fld = jnp.asarray(ext_c2, dtype=compute_dtype)
        in_specs += parts
        operands += [fld] * (bk + 2)
    in_specs += [plane, plane]
    operands += [syz, rsyz]
    out = pl.pallas_call(
        kern,
        grid=(d // bx,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=compat.CompilerParams(
            vmem_limit_bytes=_KSTEP_VMEM_LIMIT
        ),
        interpret=interpret,
    )(*operands)
    if with_errors:
        return out
    return out[0], out[1], None, None


def _kstep_sharded_xy_kernel(*refs, k, bx, nl_y, n_global, coeff, inv_h2,
                             compute_dtype, with_errors, has_field=False):
    """`_kstep_sharded_kernel` for blocks ALSO sharded along y.

    The solver hands in blocks pre-extended in y by k ghost rows per side
    (width W = nl_y + 2k), so the in-VMEM y rolls behave exactly as on the
    full domain for every row the onion still considers valid: staleness
    creeps inward one row per substep from the ghost edges and never
    reaches the central nl_y rows that are written back.  Two deltas vs
    the x-only kernel:

     * the y Dirichlet mask tests the WRAPPED global row index
       ((y0 - k + row) mod N != 0): the global y=0 stored zero plane must
       be re-zeroed wherever it appears, including inside a ghost strip,
       or its evolved copy would leak nonzero values into real rows;
     * outputs and error maxes slice the central y rows.

    `has_field` adds the c^2tau^2 onion: pre-extended in y like the state
    (its ghost ROWS hold the real neighbour's coefficients, which the
    onion-valid ghost-row updates genuinely consume), x ghosts from the
    extended field.
    """
    it = iter(refs)
    off_ref = next(it)
    sxct_ref = next(it)
    i = pl.program_id(0)
    last = pl.num_programs(0) - 1
    f = compute_dtype
    ix, iy, iz = (jnp.asarray(v, f) for v in inv_h2)

    def pick(edge_is_lo, ghost_ref, wrap_ref):
        at_edge = (i == 0) if edge_is_lo else (i == last)
        return jnp.where(
            at_edge, ghost_ref[:].astype(f), wrap_ref[:].astype(f)
        )

    c2_onion = _sharded_field_onion(it, pick, f, has_field)
    uprev_ref, uc_ref = next(it), next(it)
    plo_ref, phi_ref = next(it), next(it)
    lo_ref, hi_ref = next(it), next(it)
    pglo_ref, pghi_ref = next(it), next(it)
    glo_ref, ghi_ref = next(it), next(it)
    syzc_ref, rsyzc_ref = next(it), next(it)
    out_refs = list(it)
    if with_errors:
        out_prev_ref, out_ref, dmax_ref, rmax_ref = out_refs
    else:
        out_prev_ref, out_ref = out_refs

    prev = jnp.concatenate([
        pick(True, pglo_ref, plo_ref),
        uprev_ref[:].astype(f),
        pick(False, pghi_ref, phi_ref),
    ], 0)
    cur = jnp.concatenate([
        pick(True, glo_ref, lo_ref),
        uc_ref[:].astype(f),
        pick(False, ghi_ref, hi_ref),
    ], 0)
    w, nz = cur.shape[1], cur.shape[2]

    gy = (off_ref[0] - k + lax.broadcasted_iota(jnp.int32, (1, w, nz), 1))
    gy = gy % n_global
    zm = lax.broadcasted_iota(jnp.int32, (1, w, nz), 2) != 0
    mask = (gy != 0) & zm

    for s in range(1, k + 1):
        c = cur[1:-1]
        lap = (cur[:-2] + cur[2:] - 2.0 * c) * ix
        lap = lap + (
            pltpu.roll(c, 1, 1) + pltpu.roll(c, w - 1, 1) - 2.0 * c
        ) * iy
        lap = lap + (
            pltpu.roll(c, 1, 2) + pltpu.roll(c, nz - 1, 2) - 2.0 * c
        ) * iz
        new = 2.0 * c + _substep_coeff(c2_onion, coeff, s, f) * lap \
            - prev[1:-1]
        new = jnp.where(mask, new, jnp.asarray(0.0, f))
        if out_ref.dtype != f:
            new = new.astype(out_ref.dtype).astype(f)
        if with_errors:
            ctr = new[k - s: k - s + bx, k: k + nl_y]
            syz = syzc_ref[:]
            rsyz = rsyzc_ref[:]
            for j in range(bx):
                diff = jnp.abs(ctr[j] - sxct_ref[s - 1, i * bx + j] * syz)
                dmax_ref[s - 1, i * bx + j] = jnp.max(diff)
                rmax_ref[s - 1, i * bx + j] = jnp.max(diff * rsyz)
        prev, cur = c, new

    out_prev_ref[:] = prev[:, k: k + nl_y].astype(out_prev_ref.dtype)
    out_ref[:] = cur[:, k: k + nl_y].astype(out_ref.dtype)


def fused_kstep_sharded_xy(u_prev_ext, u_ext, prev_ghosts, cur_ghosts,
                           syz_c, rsyz_c, sxct, y0, n_global, *,
                           k, nl_y, coeff, inv_h2, c2tau2_ext=None,
                           c2_ghosts=None, block_x=None,
                           interpret=False, with_errors=True,
                           compute_dtype=None):
    """k fused leapfrog steps of an (x, y)-sharded block.

    Must run inside `shard_map` on a (P, Q, 1) mesh.  `u_prev_ext`/`u_ext`
    are the local blocks pre-extended along y with k ghost rows per side
    (comm: one cyclic y-ppermute pair per field); `prev_ghosts`/`cur_ghosts`
    are ((k, W, nz) lo, hi) x-ghost pairs ppermute'd FROM THE EXTENDED
    blocks - which is what makes the diagonal corner regions arrive for
    free.  `syz_c`/`rsyz_c` are the central (nl_y, nz) oracle plane
    slices, `sxct` this shard's (k, nl_x) oracle rows, `y0` the shard's
    global y offset as an int32 scalar array.  Returns central
    (nl_x, nl_y, nz) layers + (k, nl_x) error rows (max over this shard's
    y range; callers pmax over the y mesh axis).

    With `c2tau2_ext` (the field block y-extended exactly like the state)
    and `c2_ghosts` (its (lo, hi) x-ghost pair, exchanged once per solve)
    the variable-c substep runs and `coeff` is ignored.
    """
    nl_x, w, nz = u_ext.shape
    if compute_dtype is None:
        compute_dtype = stencil_ref.compute_dtype(u_ext.dtype)
    if w != nl_y + 2 * k:
        raise ValueError(
            f"extended y width {w} != nl_y + 2k = {nl_y + 2 * k}"
        )
    if nl_x % k:
        raise ValueError(f"k={k} must divide the shard depth {nl_x}")
    has_field = c2tau2_ext is not None
    bx = block_x or choose_kstep_block(
        nz, k, u_ext.dtype.itemsize, depth=nl_x, ghosts=True,
        plane_elems=w * nz, field=has_field,
    )
    if bx is None:
        raise ValueError(f"k={k} does not fit VMEM for {u_ext.shape}")
    if nl_x % bx or bx % k:
        raise ValueError(f"block_x={bx} must divide the shard depth "
                         f"{nl_x} and be a multiple of k={k}")
    slab = pl.BlockSpec((bx, w, nz), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    nb = nl_x // k
    lo = pl.BlockSpec((k, w, nz),
                      lambda i, _bk=bx // k, _nb=nb:
                      ((i * _bk - 1) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    hi = pl.BlockSpec((k, w, nz),
                      lambda i, _bk=bx // k, _nb=nb:
                      (((i + 1) * _bk) % _nb, 0, 0),
                      memory_space=pltpu.VMEM)
    ghost = pl.BlockSpec((k, w, nz), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM)
    out_slab = pl.BlockSpec((bx, nl_y, nz), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    plane = pl.BlockSpec((nl_y, nz), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    kern = functools.partial(
        _kstep_sharded_xy_kernel, k=k, bx=bx, nl_y=nl_y,
        n_global=n_global, coeff=coeff, inv_h2=inv_h2,
        compute_dtype=compute_dtype, with_errors=with_errors,
        has_field=has_field,
    )
    in_specs = [smem, smem]
    operands = [jnp.asarray(y0, jnp.int32).reshape(1), sxct]
    if has_field:
        fld = jnp.asarray(c2tau2_ext, dtype=compute_dtype)
        in_specs += [slab, lo, hi, ghost, ghost]
        operands += [fld, fld, fld, c2_ghosts[0], c2_ghosts[1]]
    in_specs += [slab, slab, lo, hi, lo, hi, ghost, ghost, ghost, ghost,
                 plane, plane]
    operands += [u_prev_ext, u_ext, u_prev_ext, u_prev_ext, u_ext, u_ext,
                 prev_ghosts[0], prev_ghosts[1],
                 cur_ghosts[0], cur_ghosts[1], syz_c, rsyz_c]
    state = _out_struct(u_ext, shape=(nl_x, nl_y, nz))
    out_specs = [out_slab, out_slab]
    out_shape = [state, state]
    if with_errors:
        err = _out_struct(u_ext, shape=(k, nl_x), dtype=jnp.float32)
        out_specs += [smem, smem]
        out_shape += [err, err]
    out = pl.pallas_call(
        kern,
        grid=(nl_x // bx,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=compat.CompilerParams(
            vmem_limit_bytes=_KSTEP_VMEM_LIMIT
        ),
        interpret=interpret,
    )(*operands)
    if with_errors:
        return out
    return out[0], out[1], None, None


def make_step_fn(block_x=None, interpret=False, c2tau2_field=None):
    """A `(u_prev, u, problem) -> u_next` closure for `make_solver(step_fn=)`
    with the kernel tuning parameters bound.

    With `c2tau2_field` (see `stencil_ref.make_c2tau2_field`) the update uses
    the spatially varying wave speed kernel and returns a `ParamStep` so the
    field is a runtime argument of the jitted program, not a baked-in
    constant (see solver.leapfrog.ParamStep); the analytic oracle only holds
    for constant speed, so pair it with compute_errors=False.
    """
    if c2tau2_field is None:
        def step(u_prev, u, problem):
            return leapfrog_step(u_prev, u, problem,
                                 block_x=block_x, interpret=interpret)
        return step

    from wavetpu.solver.leapfrog import ParamStep

    def var_step(u_prev, u, problem, field):
        return _fused_step(
            u_prev, u, c2tau2_field=field, inv_h2=problem.inv_h2,
            block_x=block_x, interpret=interpret,
        )

    return ParamStep(var_step, ParamStep.materialize(c2tau2_field))
