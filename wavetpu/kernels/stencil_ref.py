"""Pure-jnp leapfrog + 7-point Laplacian stencil (the semantic reference).

This is the XLA-fused counterpart of the reference's hot loops
(openmp_sol.cpp:157-163 interior leapfrog, openmp_sol.cpp:56-63 `Grid::laplace`,
cuda_sol_kernels.cu:24-47 `calculate_layer`).  Everything is expressed as
cyclic rolls, which is exact because of the state representation documented in
`wavetpu.core.problem`:

 * x is the fundamental periodic domain, so rolls ARE the boundary condition
   (the reference's seam `prepare_layer` update, openmp_sol.cpp:114-120, is
   the same formula with a wrapped neighbour).
 * y/z hold the Dirichlet invariant u[:,0,:] = u[:,:,0] = 0, so a cyclic roll
   delivers the correct zero neighbour for the j = N-1 / k = N-1 planes, and
   the j=0 / k=0 planes themselves are re-zeroed after each update (the
   counterpart of the reference zeroing all four y/z faces each step,
   openmp_sol.cpp:104-112).

This module is the semantic reference for any fused kernel implementation:
a Pallas kernel substituted via `make_solver(step_fn=...)` must agree with
it to rounding error on identical inputs.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from wavetpu.core.problem import Problem


def compute_dtype(dtype):
    """bf16 state computes in f32 (the BASELINE.md stretch contract:
    bf16 storage + fp32 accumulation); everything else computes as stored."""
    return jnp.float32 if dtype == jnp.bfloat16 else dtype


def laplacian(u, inv_h2):
    """7-point Laplacian with cyclic shifts on all three axes."""
    ix, iy, iz = inv_h2
    lap = (jnp.roll(u, 1, 0) + jnp.roll(u, -1, 0) - 2.0 * u) * ix
    lap = lap + (jnp.roll(u, 1, 1) + jnp.roll(u, -1, 1) - 2.0 * u) * iy
    lap = lap + (jnp.roll(u, 1, 2) + jnp.roll(u, -1, 2) - 2.0 * u) * iz
    return lap


def apply_dirichlet(u):
    """Re-impose the Dirichlet invariant: zero the stored y=0 and z=0 planes.

    (The y=N / z=N planes are not stored; see problem.py.)
    """
    u = u.at[:, 0, :].set(0.0)
    u = u.at[:, :, 0].set(0.0)
    return u


def leapfrog_step(u_prev, u, problem: Problem):
    """u_next = 2u - u_prev + a^2 tau^2 lap(u), Dirichlet re-imposed.

    The uniform interior update of the reference (openmp_sol.cpp:160) which,
    on the fundamental domain, also covers the periodic seam.  bf16 state
    computes in f32 and stores back in bf16.
    """
    f = compute_dtype(u.dtype)
    uc = u.astype(f)
    c = jnp.asarray(problem.a2tau2, dtype=f)
    u_next = 2.0 * uc - u_prev.astype(f) + c * laplacian(uc, problem.inv_h2)
    return apply_dirichlet(u_next).astype(u.dtype)


def taylor_half_step(u0, problem: Problem):
    """Layer-1 bootstrap: u1 = u0 + (a^2 tau^2 / 2) lap(u0)  (uses u_t(0)=0).

    Reference: openmp_sol.cpp:137-144 and the seam's n==1 coefficients at
    openmp_sol.cpp:117 (factor 1 on u0, none on u^{-1}, half on the Laplacian),
    which are exactly this formula.
    """
    f = compute_dtype(u0.dtype)
    uc = u0.astype(f)
    c = jnp.asarray(0.5 * problem.a2tau2, dtype=f)
    u1 = uc + c * laplacian(uc, problem.inv_h2)
    return apply_dirichlet(u1).astype(u0.dtype)


def make_c2tau2_field(
    problem: Problem, c2_fn: Callable[[np.ndarray, np.ndarray, np.ndarray], np.ndarray]
) -> np.ndarray:
    """Evaluate tau^2 * c^2(x, y, z) on the fundamental grid, host-side f64.

    `c2_fn` takes broadcastable (x, y, z) coordinate arrays and returns the
    squared wave speed.  The constant-speed problem is `c2_fn = lambda
    x, y, z: problem.a2`; the result then equals `problem.a2tau2` everywhere
    (pinned by tests/test_variable_c.py).

    Variable wave speed is a capability extension over the reference (its
    a^2 is hardcoded, openmp_sol.cpp:207); the analytic oracle only holds
    for constant speed, so variable-c runs should pass compute_errors=False.
    """
    n = problem.N
    x = (np.arange(n, dtype=np.float64) * problem.hx)[:, None, None]
    y = (np.arange(n, dtype=np.float64) * problem.hy)[None, :, None]
    z = (np.arange(n, dtype=np.float64) * problem.hz)[None, None, :]
    c2 = np.broadcast_to(
        np.asarray(c2_fn(x, y, z), dtype=np.float64), (n, n, n)
    )
    return c2 * problem.tau**2


C2_PRESET_NAMES = ("constant", "gaussian-lens", "two-layer")


def make_preset_c2tau2_field(problem: Problem, name: str) -> np.ndarray:
    """The named tau^2 c^2(x,y,z) presets - ONE source of truth shared by
    the CLI (`--c2-field`) and the serving API (`c2_field`), so the same
    preset name always means the same physics on both surfaces.

    constant: c^2 = a^2 everywhere (collapses to a2tau2; pinned by
    tests/test_variable_c.py).  gaussian-lens: a slow-speed lens dipping
    to a^2/2 at the domain centre.  two-layer: a discontinuous interface
    with the far z half running at DOUBLE c^2 (note: Courant-unstable at
    configs whose constant-c C is already near the bound - the serving
    watchdog tests rely on exactly that).
    """
    a2 = problem.a2

    def _gaussian_lens(x, y, z):
        s2 = 2.0 * (problem.Lx / 8.0) ** 2
        r2 = (
            (x - problem.Lx / 2) ** 2
            + (y - problem.Ly / 2) ** 2
            + (z - problem.Lz / 2) ** 2
        )
        return a2 * (1.0 - 0.5 * np.exp(-r2 / s2))

    presets = {
        "constant": lambda x, y, z: a2 * np.ones_like(x + y + z),
        "gaussian-lens": _gaussian_lens,
        "two-layer": lambda x, y, z: np.where(
            z < problem.Lz / 2, a2, 2.0 * a2
        ) + 0.0 * x + 0.0 * y,
    }
    if name not in presets:
        raise ValueError(
            f"c2 preset must be one of {sorted(presets)}, got {name!r}"
        )
    return make_c2tau2_field(problem, presets[name])


def make_variable_c_step(c2tau2_field):
    """A solver step with spatially varying speed:
    u_next = 2u - u_prev + tau^2 c^2(x,y,z) lap(u).

    Returns a `ParamStep`: the field rides through the jitted program as a
    runtime argument (closing over it would embed an N^3 HLO literal -
    512 MB at N=512; see solver.leapfrog.ParamStep).  Slots into
    `make_solver(step_fn=...)` like any other kernel, or call it directly
    as `(u_prev, u, problem)`.
    """
    from wavetpu.solver.leapfrog import ParamStep

    def step(u_prev, u, problem: Problem, field):
        f = compute_dtype(u.dtype)
        uc = u.astype(f)
        coeff = jnp.asarray(field, dtype=f)
        u_next = (
            2.0 * uc - u_prev.astype(f) + coeff * laplacian(uc, problem.inv_h2)
        )
        return apply_dirichlet(u_next).astype(u.dtype)

    return ParamStep(step, ParamStep.materialize(np.asarray(c2tau2_field)))


def compensated_step(u, v, carry, problem: Problem, coeff=None):
    """One step of the compensated (Kahan) incremental leapfrog.

    Algebraically identical to `leapfrog_step` via the increment form
    v_n = u_n - u_{n-1}:

        v_{n+1} = v_n + C*lap(u_n)
        u_{n+1} = u_n + v_{n+1}          (compensated two-sum)

    but numerically far better in f32: the standard form adds the tiny
    update C*lap(u) (~1e-5 at N=512) into O(1) state and loses its low
    bits every step - measured 1.09e-3 L-inf error at N=512/1000 vs the
    ~4e-6 discretization bound (BENCH_r03).  Here the increment
    accumulates in its own small-magnitude buffer and the u addition runs
    Kahan-compensated through `carry`, so rounding stays at the one-time
    f32 representation level (measured ~2e-7 vs f64 at N=128/1000 - a
    ~7000x reduction; the analytic error then equals f64's).

    The Dirichlet mask is applied to the increment only: u, v, carry all
    start masked and sums of masked fields stay masked.

    `coeff` defaults to a2tau2; the layer-1 bootstrap is this same step
    with v = carry = 0 and coeff = a2tau2/2 (then u1 = u0 + (C/2)lap(u0),
    the Taylor half-step, openmp_sol.cpp:137-144).
    """
    c = jnp.asarray(
        problem.a2tau2 if coeff is None else coeff, dtype=u.dtype
    )
    d = apply_dirichlet(c * laplacian(u, problem.inv_h2))
    v_next = v + d
    # Kahan two-sum: u_next = u + v_next with error fed back via carry.
    y = v_next - carry
    t = u + y
    carry_next = (t - u) - y
    return t, v_next, carry_next


def laplacian_ext(ext, inv_h2):
    """7-point Laplacian of the interior of a halo-extended block.

    `ext` has one ghost cell on each side of each axis: shape (bx+2, by+2,
    bz+2); the result has shape (bx, by, bz).  Used by the sharded solver
    where ghost planes arrive via `ppermute` instead of rolls.
    """
    ix, iy, iz = inv_h2
    c = ext[1:-1, 1:-1, 1:-1]
    lap = (ext[:-2, 1:-1, 1:-1] + ext[2:, 1:-1, 1:-1] - 2.0 * c) * ix
    lap = lap + (ext[1:-1, :-2, 1:-1] + ext[1:-1, 2:, 1:-1] - 2.0 * c) * iy
    lap = lap + (ext[1:-1, 1:-1, :-2] + ext[1:-1, 1:-1, 2:] - 2.0 * c) * iz
    return lap
