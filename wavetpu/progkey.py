"""ProgramKey: the ONE compiled-program identity, shared by every tier.

The serve engine caches compiled programs under a `ProgramKey`, the
compile ledger persists the same key as a JSON dict, the progcache names
disk entries by its canonical form - and the fleet router (fleet/) must
derive the SAME identity from a raw request body to land it on the
replica that already holds the program.  Before the fleet tier this key
logic lived in `serve/engine.py` (the NamedTuple), `serve/api.py` (body
-> identity validation), and `obs/ledger.py` (JSON canonicalization);
three copies one router away from drifting.  This module is the single
home; the old locations re-export for compatibility.

Imports only `core.problem` (itself import-free) - NEVER jax: the
router and the ledger tools run on hosts with no accelerator stack.
Anything that genuinely needs a backend (device-count checks, c2-field
preset construction, lane validation) stays in `serve/api.py` on top of
the shared identity derived here.

Affinity keys: the router's warm-key table is keyed by the program
identity MINUS the `batch` bucket (the replica picks the bucket at
batch-assembly time; any bucket of a tier shares compiled ancestry and
the same breaker, see `ServeEngine.breaker_key`) and MINUS
`compute_errors` (a server-side config flag a request body cannot see).
"""

from __future__ import annotations

import hashlib
import json
from typing import Callable, List, NamedTuple, Optional, Tuple, Union

from wavetpu.core.problem import Problem, parse_length

# The ProgramKey field order - also the JSON-dict shape the ledger,
# warmup manifests, and the /metrics warm_keys block use.
KEY_FIELDS = (
    "N", "Lx", "Ly", "Lz", "T", "timesteps", "scheme", "path", "k",
    "dtype", "with_field", "compute_errors", "batch", "mesh",
)

# The routing identity: everything a request body determines.  `batch`
# is the replica's bucketing decision and `compute_errors` its config;
# neither is visible to (or stable for) the router.
AFFINITY_FIELDS = tuple(
    f for f in KEY_FIELDS if f not in ("batch", "compute_errors")
)


class ProgramKey(NamedTuple):
    """Identity of one compiled batched program (the cache key).

    `mesh` is None for single-device programs, or the (MX, MY, MZ) mesh
    shape of a sharded x batched program (ensemble/sharded.py) - a
    (mesh, batch-bucket) pair is its own compiled executable."""

    N: int
    Lx: float
    Ly: float
    Lz: float
    T: float
    timesteps: int
    scheme: str
    path: str
    k: int
    dtype: str
    with_field: bool
    compute_errors: bool
    batch: int
    mesh: Optional[Tuple[int, int, int]] = None

    @classmethod
    def for_batch(cls, problem: Problem, scheme: str, path: str, k: int,
                  dtype_name: str, with_field: bool, compute_errors: bool,
                  batch: int,
                  mesh: Optional[Tuple[int, int, int]] = None
                  ) -> "ProgramKey":
        return cls(
            N=problem.N, Lx=problem.Lx, Ly=problem.Ly, Lz=problem.Lz,
            T=problem.T, timesteps=problem.timesteps, scheme=scheme,
            path=path, k=k if path == "kfused" else 1, dtype=dtype_name,
            with_field=with_field, compute_errors=compute_errors,
            batch=batch, mesh=None if mesh is None else tuple(mesh),
        )


def normalize_key(key: dict) -> dict:
    """A JSON-stable key dict: ProgramKey field order, mesh as a list
    (JSON has no tuples), unknown fields rejected loudly."""
    unknown = set(key) - set(KEY_FIELDS)
    if unknown:
        raise ValueError(f"unknown ProgramKey fields {sorted(unknown)}")
    out = {}
    for f in KEY_FIELDS:
        v = key.get(f)
        if f == "mesh" and v is not None:
            v = [int(x) for x in v]
        out[f] = v
    return out


def canonical_key(key: dict) -> str:
    return json.dumps(normalize_key(key), sort_keys=True)


def key_from_program_key(pk) -> dict:
    """A ProgramKey (duck-typed: any NamedTuple with `_asdict`) as the
    ledger's JSON key dict."""
    return normalize_key(dict(pk._asdict()))


def program_key_from_dict(d: dict) -> ProgramKey:
    """The round-trip half: a ledger/manifest/warm-keys key dict back
    into a `ProgramKey`."""
    d = normalize_key(d)
    if d["mesh"] is not None:
        d["mesh"] = tuple(d["mesh"])
    return ProgramKey(**d)


def affinity_key_from_dict(key: dict) -> str:
    """The router's warm-key-table key for a ProgramKey JSON dict: the
    AFFINITY_FIELDS projection as canonical JSON.  Every batch bucket of
    a tier maps to the same affinity key, so a replica that advertises
    {.., batch: 4} warmth attracts the tier's traffic at any occupancy."""
    out = {}
    for f in AFFINITY_FIELDS:
        v = key.get(f)
        if f == "mesh" and v is not None:
            v = [int(x) for x in v]
        out[f] = v
    return json.dumps(out, sort_keys=True)


def affinity_key(pk) -> str:
    """Affinity key of a ProgramKey (or any `_asdict` NamedTuple)."""
    return affinity_key_from_dict(dict(pk._asdict()))


def resolve_kernel(flag_value: str, platform: str) -> str:
    """Map --kernel {auto,roll,pallas} to the concrete kernel for
    `platform` (jax.default_backend()).  auto = pallas only where Mosaic
    compiles it natively; everywhere else the roll stencil is the fast
    path and interpret-mode pallas is opt-in."""
    if flag_value not in ("auto", "roll", "pallas"):
        raise ValueError(
            f"--kernel must be auto|roll|pallas, got {flag_value}"
        )
    if flag_value == "auto":
        return "pallas" if platform == "tpu" else "roll"
    return flag_value


class RequestIdentity(NamedTuple):
    """The program identity a /solve body determines - everything in
    ProgramKey except the server-chosen batch bucket and the server-
    config compute_errors flag."""

    problem: Problem
    scheme: str
    path: str
    k: int
    dtype: str
    with_field: bool
    mesh: Optional[Tuple[int, int, int]]

    def program_key(self, batch: int, compute_errors: bool) -> ProgramKey:
        return ProgramKey.for_batch(
            self.problem, self.scheme, self.path, self.k, self.dtype,
            self.with_field, compute_errors, batch, mesh=self.mesh,
        )

    def affinity_key(self) -> str:
        p = self.problem
        return affinity_key_from_dict({
            "N": p.N, "Lx": p.Lx, "Ly": p.Ly, "Lz": p.Lz, "T": p.T,
            "timesteps": p.timesteps, "scheme": self.scheme,
            "path": self.path, "k": self.k, "dtype": self.dtype,
            "with_field": self.with_field,
            "mesh": None if self.mesh is None else list(self.mesh),
        })


# `platform` for identity_from_body: a concrete backend name, or a
# callable resolved lazily ONLY when the body says kernel=auto (the
# serve path passes `lambda: jax.default_backend()` without paying the
# jax import for explicit-kernel requests).
PlatformSource = Union[str, Callable[[], str], None]


def identity_from_body(body: dict, default_kernel: str = "auto",
                       platform: PlatformSource = None) -> RequestIdentity:
    """The identity half of /solve body validation (ValueError on any
    bad field - HTTP 400 at the replica, route-anyway-and-let-it-400 at
    the router).  Validation that needs a backend (device-count for
    mesh, c2-field preset names, lane validation) is NOT done here -
    `serve/api.parse_solve_request` layers it on top."""
    if not isinstance(body, dict):
        raise ValueError("request body must be a JSON object")
    if "N" not in body:
        raise ValueError("missing required field N")
    problem = Problem(
        N=int(body["N"]),
        Np=int(body.get("Np", 1)),
        Lx=parse_length(body.get("Lx", 1.0)),
        Ly=parse_length(body.get("Ly", 1.0)),
        Lz=parse_length(body.get("Lz", 1.0)),
        T=float(body.get("T", 1.0)),
        timesteps=int(body.get("timesteps", 20)),
    )
    scheme = body.get("scheme", "standard")
    if scheme not in ("standard", "compensated"):
        raise ValueError(
            f"scheme must be standard|compensated, got {scheme!r}"
        )
    dtype_name = body.get("dtype", "f32")
    if dtype_name not in ("f32", "f64", "bf16"):
        raise ValueError(f"dtype must be f32|f64|bf16, got {dtype_name!r}")
    kernel = body.get("kernel", default_kernel)
    if kernel not in ("auto", "roll", "pallas"):
        raise ValueError(
            f"kernel must be auto|roll|pallas, got {kernel!r}"
        )
    fuse_steps = int(body.get("fuse_steps", 1))
    if fuse_steps < 1:
        raise ValueError(f"fuse_steps must be >= 1, got {fuse_steps}")
    if kernel == "auto":
        resolved = platform() if callable(platform) else platform
        kernel = resolve_kernel("auto", resolved or "cpu")
    if fuse_steps > 1:
        if kernel == "roll":
            raise ValueError("fuse_steps needs the pallas kernel")
        path = "kfused"
    else:
        path = kernel
    with_field = bool(body.get("c2_field"))
    if scheme == "compensated" and with_field:
        # Compensated batches are constant-speed only (the field is not
        # wired through the compensated vmapped core); reject here so
        # the client gets a 400, not a batch-time 500.  Shifted phases
        # DO batch on the compensated scheme (analytic bootstrap).
        raise ValueError(
            "scheme=compensated does not serve c2_field requests"
        )
    if scheme == "compensated" and dtype_name == "bf16":
        # Same 400-not-500 reasoning: the compensated scheme requires
        # an f32/f64 carrier (EnsembleSolver would refuse at build).
        raise ValueError(
            "scheme=compensated requires f32/f64 state (bf16 "
            "representation error dominates what compensation recovers)"
        )
    mesh = body.get("mesh")
    if mesh is not None:
        mesh = tuple(int(m) for m in mesh)
        if len(mesh) != 3 or any(m < 1 for m in mesh):
            raise ValueError(
                f"mesh must be three positive ints [MX, MY, MZ], "
                f"got {body.get('mesh')!r}"
            )
        if scheme == "compensated":
            raise ValueError(
                "sharded x batched serves the standard scheme only"
            )
        if fuse_steps > 1:
            raise ValueError(
                "sharded x batched does not take fuse_steps (the "
                "sharded lane marches the 1-step kernel)"
            )
        if with_field:
            raise ValueError(
                "sharded x batched does not serve c2_field requests"
            )
    return RequestIdentity(
        problem=problem, scheme=scheme, path=path,
        k=fuse_steps if path == "kfused" else 1, dtype=dtype_name,
        with_field=with_field, mesh=mesh,
    )


# Body fields beyond the program identity that change a deterministic
# solve's ANSWER (not just its routing): per-lane phase, the early-stop
# step, and the c2-field preset name.  `deadline_ms` / `priority` /
# QoS headers shape scheduling, never the payload, so they are NOT part
# of the result identity - two tenants replaying the same solve share
# one cache entry.
RESULT_FIELDS = ("phase", "steps", "c2_field")


def result_cache_eligible(body) -> bool:
    """Conservative result-cache eligibility: deterministic FULL solves
    only.  A resume-token request continues a specific checkpointed
    march (its answer depends on server-side state, not just the body),
    so it must never be served from - or stored into - the result
    cache."""
    return isinstance(body, dict) and not body.get("resume_token")


def result_key(body: dict, default_kernel: str = "auto",
               platform: PlatformSource = None) -> str:
    """The content-addressed RESULT identity of a /solve body: a sha256
    hex digest over the canonical `RequestIdentity` projection plus the
    answer-shaping RESULT_FIELDS.  Derived through the SAME
    `identity_from_body` normalization the engine caches programs under
    and the router routes by, so the replica result cache and the
    router edge cache hash a body identically - the progcache/resume-
    token discipline, extended to results.  Raises ValueError on a body
    that yields no identity (the caller treats that as ineligible)."""
    ident = identity_from_body(body, default_kernel, platform=platform)
    p = ident.problem
    payload = {
        "N": p.N, "Np": p.Np, "Lx": p.Lx, "Ly": p.Ly, "Lz": p.Lz,
        "T": p.T, "timesteps": p.timesteps, "scheme": ident.scheme,
        "path": ident.path, "k": ident.k, "dtype": ident.dtype,
        "with_field": ident.with_field,
        "mesh": None if ident.mesh is None else list(ident.mesh),
    }
    for f in RESULT_FIELDS:
        payload[f] = body.get(f)
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()
    ).hexdigest()


def warm_keys_to_affinity(warm_keys: dict) -> List[str]:
    """Flatten a /metrics `program_cache.warm_keys` block ({"memory":
    [keydict..], "disk": [keydict..]}) into affinity keys, ignoring
    malformed entries (a half-written cache dir must not poison the
    router's table)."""
    out: List[str] = []
    seen = set()
    for tier in ("memory", "disk"):
        for kd in warm_keys.get(tier, ()) or ():
            if not isinstance(kd, dict):
                continue
            if any(kd.get(f) is None
                   for f in ("N", "timesteps", "path", "dtype")):
                continue  # not a ProgramKey dict; don't poison the table
            try:
                ak = affinity_key_from_dict(kd)
            except (ValueError, TypeError):
                continue
            if ak not in seen:
                seen.add(ak)
                out.append(ak)
    return out
