"""Analytic-solution oracle and fused error accounting.

The reference validates every run against the closed-form solution and reports
per-layer L-infinity absolute and relative error over the *interior* points
global (i,j,k) in [1, N-1]^3 (openmp_sol.cpp:169-190, mpi_new.cpp:335-345).
Layer 0 is initialised from the analytic solution, so its reported error is
exactly zero.

TPU-native formulation: the analytic solution is separable,

    u(t,x,y,z) = Sx(x) * Sy(y) * Sz(z) * cos(a_t*t + 2*pi),

so instead of evaluating three sines per grid point per step (the reference
does exactly that in its fused error path, mpi_new.cpp:340), we precompute the
three 1-D spatial factors once and form the analytic field per step with two
broadcast multiplies and one scalar cosine.  XLA fuses those broadcasts into
the consumer, so the per-step analytic field costs no HBM traffic at all.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax.numpy as jnp
import numpy as np

from wavetpu.core.problem import Problem

TWO_PI = 2.0 * math.pi


def spatial_factors_np(problem: Problem, n_points: int):
    """Host-f64 1-D spatial factors over indices 0..n_points-1 (numpy).

    sx[i] = sin(2*pi*(i*hx)/Lx), sy[j] = sin(pi*(j*hy)/Ly),
    sz[k] = sin(pi*(k*hz)/Lz).  The single source of truth for the
    analytic solution's spatial part; every other helper pads/casts this.
    """
    i = np.arange(n_points, dtype=np.float64)
    sx = np.sin(2.0 * np.pi * (i * problem.hx) / problem.Lx)
    sy = np.sin(np.pi * (i * problem.hy) / problem.Ly)
    sz = np.sin(np.pi * (i * problem.hz) / problem.Lz)
    return sx, sy, sz


def spatial_factors(problem: Problem, dtype=jnp.float32):
    """1-D spatial factors (sx, sy, sz) on the fundamental (N,N,N) grid.

    Computed in float64 on host and cast once, so low-precision runs still
    compare against a well-rounded oracle.
    """
    sx, sy, sz = spatial_factors_np(problem, problem.N)
    return (
        jnp.asarray(sx, dtype=dtype),
        jnp.asarray(sy, dtype=dtype),
        jnp.asarray(sz, dtype=dtype),
    )


def time_factor(problem: Problem, n: int, dtype=jnp.float32,
                phase: float = TWO_PI):
    """cos(a_t * tau * n + phase) for a *static* layer n, computed on host.

    Deliberately numpy, not jnp: XLA's device `cos` is a fast-math
    approximation (measured ~3e-8 absolute error for f64 on CPU), which would
    pollute the error oracle.  See `time_factor_table` for traced indices.

    `phase` defaults to the reference's 2*pi; the ensemble engine
    (wavetpu/ensemble) varies it per lane - the analytic solution solves
    the PDE for ANY time phase, so the oracle stays exact.
    """
    return jnp.asarray(
        np.cos(problem.a_t * problem.tau * float(n) + phase), dtype=dtype
    )


def time_factor_table(problem: Problem, dtype=jnp.float32,
                      phase: float = TWO_PI):
    """cos(a_t*tau*n + phase) for every layer n in [0, timesteps], exact f64
    on host, cast once.  Indexed by the traced step counter inside the scan -
    removes all transcendentals from the device program."""
    n = np.arange(problem.timesteps + 1, dtype=np.float64)
    return jnp.asarray(
        np.cos(problem.a_t * problem.tau * n + phase), dtype=dtype
    )


def time_factor_table_np(problem: Problem, phase: float = TWO_PI) -> np.ndarray:
    """Host-f64 time-factor table (no device transfer) - the per-lane form
    the ensemble engine stacks into its (B, timesteps+1) runtime argument."""
    n = np.arange(problem.timesteps + 1, dtype=np.float64)
    return np.cos(problem.a_t * problem.tau * n + phase)


def analytic_field(sx, sy, sz, ct):
    """Broadcast the separable analytic solution to a (N,N,N) field (lazy)."""
    return sx[:, None, None] * sy[None, :, None] * sz[None, None, :] * ct


def interior_masks_1d(n: int, start: int = 0):
    """Boolean 1-D masks selecting the error interior for a local block.

    The reference's error loops cover global indices 1..N-1 on every axis
    (openmp_sol.cpp:174-176); in the fundamental-domain (N,N,N) state that
    means "exclude global index 0" on each axis (index N is not stored in x,
    and is the zero Dirichlet plane in y/z, which the reference also skips).

    `start` is the block's global offset (0 for single device).
    """
    idx = np.arange(start, start + n)
    return idx != 0


def layer_errors(u, f, mask_x, mask_y, mask_z):
    """L-inf absolute and relative error of field `u` vs analytic field `f`.

    Matches the reference metric (mpi_new.cpp:340-344): abs = |u - f|,
    rel = |u - f| / |f|, max over the interior.  Points where both numerator
    and denominator vanish (the reference's fmax simply skips the resulting
    NaN because NaN comparisons are false) contribute 0 here.
    """
    mask = (
        mask_x[:, None, None] & mask_y[None, :, None] & mask_z[None, None, :]
    )
    diff = jnp.abs(u - f)
    abs_e = jnp.max(jnp.where(mask, diff, 0.0))
    rel = diff / jnp.abs(f)
    rel = jnp.where(jnp.isnan(rel), 0.0, rel)
    rel_e = jnp.max(jnp.where(mask, rel, 0.0))
    return abs_e, rel_e


def full_analytic_grid(problem: Problem, n: int, dtype=np.float64) -> np.ndarray:
    """Host-side (N+1)^3 analytic grid for layer n, reference indexing.

    Used by tests and the history-mode post-hoc error path (the analog of the
    reference's precomputed `prec_sol` grid, openmp_sol.cpp:85-100).
    """
    sx, sy, sz = spatial_factors_np(problem, problem.N + 1)
    ct = math.cos(problem.a_t * problem.tau * n + TWO_PI)
    return (
        sx[:, None, None] * sy[None, :, None] * sz[None, None, :] * ct
    ).astype(dtype)
