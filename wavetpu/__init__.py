"""wavetpu - a TPU-native framework for the 3D acoustic wave equation.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of the reference
MPI+CUDA solver (aleksgri/3D-wave-equation-MPI-CUDA): explicit leapfrog with a
7-point Laplacian, periodic x / Dirichlet y-z boundaries, per-layer L-inf
validation against the closed-form analytic solution, 3D domain decomposition,
and halo exchange - expressed as one jitted program per chip with cyclic
`ppermute` halos over the ICI mesh instead of MPI messages.
"""

from wavetpu.core.problem import Problem, parse_length

__version__ = "0.1.0"

__all__ = ["Problem", "parse_length", "__version__"]
