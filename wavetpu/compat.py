"""Version shims for the jax API surface this codebase is written against.

The solvers target the current jax names (`jax.shard_map` with its
`check_vma` flag, `pltpu.CompilerParams`); older jaxlib images ship the
same functionality under the earlier names (`jax.experimental.shard_map`
with `check_rep`, `pltpu.TPUCompilerParams`).  Resolving the names once
here keeps every kernel/solver module version-agnostic without scattering
try/except at the call sites.
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as _pltpu

CompilerParams = getattr(
    _pltpu, "CompilerParams", getattr(_pltpu, "TPUCompilerParams", None)
)

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
        """`jax.shard_map` signature on the pre-unification API (where the
        varying-manual-axes check was called check_rep)."""
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )
