"""Ensemble-batched solves: many independent problems in ONE XLA program.

`batched.py` vmaps the existing step families (both schemes, incl. the
flagship compensated velocity form) over a leading lane axis - the
throughput model of the TPU fluid-flow framework (arXiv:2108.11076):
aggregate Gcell/s comes from keeping B independent simulations resident
as one batched program, not from more single-run tuning.  `sharded.py`
composes the lane axis with the device mesh (shard_map-of-vmap) so a
multi-chip host batches SHARDED solves.  The serve layer (wavetpu/serve)
sits on top.
"""

from wavetpu.ensemble.batched import (
    EnsembleResult,
    EnsembleSolver,
    LaneSpec,
    probe_results,
    solve_ensemble,
    vmap_capability,
)
from wavetpu.ensemble.sharded import (
    ShardedEnsembleSolver,
    solve_ensemble_sharded,
)

__all__ = [
    "EnsembleResult",
    "EnsembleSolver",
    "LaneSpec",
    "ShardedEnsembleSolver",
    "probe_results",
    "solve_ensemble",
    "solve_ensemble_sharded",
    "vmap_capability",
]
