"""Ensemble-batched solves: many independent problems in ONE XLA program.

`batched.py` vmaps the existing step families over a leading lane axis -
the throughput model of the TPU fluid-flow framework (arXiv:2108.11076):
aggregate Gcell/s comes from keeping B independent simulations resident
as one batched program, not from more single-run tuning.  The serve layer
(wavetpu/serve) sits on top.
"""

from wavetpu.ensemble.batched import (
    EnsembleResult,
    EnsembleSolver,
    LaneSpec,
    solve_ensemble,
    vmap_capability,
)

__all__ = [
    "EnsembleResult",
    "EnsembleSolver",
    "LaneSpec",
    "solve_ensemble",
    "vmap_capability",
]
